"""Native (C++) host kernels: build + ctypes bindings.

The reference's sequential algorithms run in third-party C/C++ (igraph's
``community_fastgreedy`` / ``community_infomap``, SURVEY.md §2.23); here they
are first-party C++ in ``src/``, compiled on first use into
``libfcnative.so`` and bound through :mod:`ctypes` (pybind11 is not available
in this environment).  The build is cached by source hash, so the compiler
runs once per source change.

Public API:

* :func:`cnm_labels`     — n_p randomized CNM fast-greedy partitions
* :func:`infomap_labels` — n_p Infomap (map equation) partitions
* :func:`parse_edgelist` — fast ``u v [w]`` file parser
* :func:`available`      — True if the toolchain produced a library
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_SRC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "src")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "build")
_SOURCES = ("fastgreedy.cpp", "infomap.cpp", "edgelist.cpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _source_hash() -> str:
    h = hashlib.sha256()
    for name in _SOURCES + ("common.hpp",):
        with open(os.path.join(_SRC_DIR, name), "rb") as fh:
            h.update(fh.read())
    return h.hexdigest()[:16]


def _build() -> Optional[ctypes.CDLL]:
    global _build_error
    os.makedirs(_BUILD_DIR, exist_ok=True)
    so_path = os.path.join(_BUILD_DIR, f"libfcnative-{_source_hash()}.so")
    lib = None
    for attempt in (0, 1):
        if not os.path.exists(so_path):
            cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared",
                   "-pthread", "-o", so_path + ".tmp"]
            cmd += [os.path.join(_SRC_DIR, s) for s in _SOURCES]
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               text=True, timeout=300)
            except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
                    FileNotFoundError) as e:
                _build_error = getattr(e, "stderr", str(e)) or str(e)
                return None
            os.replace(so_path + ".tmp", so_path)
        try:
            lib = ctypes.CDLL(so_path)
            break
        except OSError as e:
            # A prebuilt .so shipped in the repo may have been compiled
            # against a newer runtime than this host provides (observed:
            # GLIBCXX_3.4.29 absent).  Drop it and rebuild from src/ once;
            # if the freshly built library still fails to load, report
            # unavailability instead of letting the OSError escape into
            # callers (it used to kill pytest collection).
            try:
                os.remove(so_path)
            # fcheck: ok=swallowed-error (removing the broken .so is
            # itself best-effort; the build failure is reported via
            # _build_error just below)
            except OSError:
                pass
            if attempt == 1:
                _build_error = str(e)
                return None

    i32p = ctypes.POINTER(ctypes.c_int32)
    f32p = ctypes.POINTER(ctypes.c_float)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    for fn in (lib.fc_cnm, lib.fc_infomap):
        fn.argtypes = [i32p, i32p, f32p, ctypes.c_int64, ctypes.c_int32,
                       u64p, ctypes.c_int32, i32p]
        fn.restype = None
    lib.fc_parse_edgelist_count.argtypes = [ctypes.c_char_p, i32p]
    lib.fc_parse_edgelist_count.restype = ctypes.c_int64
    lib.fc_parse_edgelist_fill.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_double), ctypes.c_int64]
    lib.fc_parse_edgelist_fill.restype = None
    return lib


def _get_lib() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            if _build_error is not None:
                raise ImportError(f"native build failed: {_build_error}")
            # fcheck: ok=blocking-under-lock (the lock EXISTS to
            # serialize the one-time compiler run — concurrent first
            # callers must block until the single build lands; after
            # that the cached handle returns without ever blocking)
            _lib = _build()
            if _lib is None:
                raise ImportError(f"native build failed: {_build_error}")
        return _lib


def available() -> bool:
    try:
        _get_lib()
        return True
    except ImportError:
        return False


def _as_c(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def _run_detector(fn_name: str, src: np.ndarray, dst: np.ndarray,
                  weight: Optional[np.ndarray], n_nodes: int,
                  seeds: np.ndarray) -> np.ndarray:
    lib = _get_lib()
    src = np.ascontiguousarray(src, dtype=np.int32)
    dst = np.ascontiguousarray(dst, dtype=np.int32)
    if weight is None:
        weight = np.ones(src.shape[0], dtype=np.float32)
    weight = np.ascontiguousarray(weight, dtype=np.float32)
    seeds = np.ascontiguousarray(seeds, dtype=np.uint64)
    n_p = int(seeds.shape[0])
    out = np.empty((n_p, n_nodes), dtype=np.int32)
    getattr(lib, fn_name)(
        _as_c(src, ctypes.c_int32), _as_c(dst, ctypes.c_int32),
        _as_c(weight, ctypes.c_float), ctypes.c_int64(src.shape[0]),
        ctypes.c_int32(n_nodes), _as_c(seeds, ctypes.c_uint64),
        ctypes.c_int32(n_p), _as_c(out, ctypes.c_int32))
    return out


def cnm_labels(src, dst, weight, n_nodes: int, seeds) -> np.ndarray:
    """n_p randomized CNM fast-greedy partitions; int32[n_p, n_nodes]."""
    return _run_detector("fc_cnm", np.asarray(src), np.asarray(dst),
                         None if weight is None else np.asarray(weight),
                         int(n_nodes), np.asarray(seeds))


def infomap_labels(src, dst, weight, n_nodes: int, seeds) -> np.ndarray:
    """n_p Infomap (two-level map equation) partitions; int32[n_p, N]."""
    return _run_detector("fc_infomap", np.asarray(src), np.asarray(dst),
                         None if weight is None else np.asarray(weight),
                         int(n_nodes), np.asarray(seeds))


def parse_edgelist(path: str) -> Tuple[np.ndarray, np.ndarray,
                                       Optional[np.ndarray]]:
    """Fast native parse of a ``u v [w]`` edgelist.

    Returns raw ``(u int64[E], v int64[E], w float64[E] | None)`` —
    unvalidated original ids; compaction stays in utils/io.py.
    Raises ``ValueError`` on parse failure.
    """
    lib = _get_lib()
    saw = ctypes.c_int32(0)
    with _lock:
        n = lib.fc_parse_edgelist_count(path.encode(), ctypes.byref(saw))
        if n < 0:
            raise ValueError(f"native parse failed for {path}")
        u = np.empty(n, dtype=np.int64)
        v = np.empty(n, dtype=np.int64)
        w = np.empty(n, dtype=np.float64)
        lib.fc_parse_edgelist_fill(
            u.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            w.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.c_int64(n))
    return u, v, (w if saw.value else None)
