// Shared graph scaffolding for the native (host-side) detection kernels.
//
// The reference reaches its sequential community algorithms through igraph's
// C core (reference fast_consensus.py:41-52, :268, :270, :335); these are the
// first-party C++ equivalents.  The TPU compute path (JAX/XLA) never touches
// this code — it serves the two inherently sequential algorithms (CNM
// fast-greedy agglomeration, Infomap map-equation search; SURVEY.md §7 "hard
// parts" 4) plus fast file ingest.
#pragma once

#include <algorithm>
#include <cstdint>
#include <numeric>
#include <random>
#include <vector>

namespace fc {

// Immutable undirected weighted graph in CSR form (both edge orientations).
struct Csr {
  int32_t n = 0;
  std::vector<int64_t> off;   // size n+1
  std::vector<int32_t> nbr;   // size 2E
  std::vector<double> w;      // size 2E
  std::vector<double> strength;  // weighted degree incl. 2*self-loops
  std::vector<double> selfw;     // self-loop weight per node
  double total_w = 0.0;          // sum of edge weights (each edge once)

  static Csr build(const int32_t* src, const int32_t* dst, const float* wt,
                   int64_t n_edges, int32_t n_nodes) {
    Csr g;
    g.n = n_nodes;
    g.strength.assign(n_nodes, 0.0);
    g.selfw.assign(n_nodes, 0.0);
    std::vector<int64_t> deg(n_nodes, 0);
    for (int64_t e = 0; e < n_edges; ++e) {
      double w = wt ? static_cast<double>(wt[e]) : 1.0;
      g.total_w += w;
      if (src[e] == dst[e]) {
        g.selfw[src[e]] += w;
        g.strength[src[e]] += 2.0 * w;
        continue;
      }
      ++deg[src[e]];
      ++deg[dst[e]];
      g.strength[src[e]] += w;
      g.strength[dst[e]] += w;
    }
    g.off.assign(n_nodes + 1, 0);
    for (int32_t i = 0; i < n_nodes; ++i) g.off[i + 1] = g.off[i] + deg[i];
    g.nbr.resize(g.off[n_nodes]);
    g.w.resize(g.off[n_nodes]);
    std::vector<int64_t> cur(g.off.begin(), g.off.end() - 1);
    for (int64_t e = 0; e < n_edges; ++e) {
      if (src[e] == dst[e]) continue;
      double w = wt ? static_cast<double>(wt[e]) : 1.0;
      g.nbr[cur[src[e]]] = dst[e];
      g.w[cur[src[e]]++] = w;
      g.nbr[cur[dst[e]]] = src[e];
      g.w[cur[dst[e]]++] = w;
    }
    return g;
  }
};

// Compact labels to 0..k-1 by first occurrence.
inline void compact_labels(std::vector<int32_t>& lab) {
  std::vector<int32_t> remap(lab.size(), -1);
  int32_t next = 0;
  for (auto& l : lab) {
    if (remap[l] < 0) remap[l] = next++;
    l = remap[l];
  }
}

}  // namespace fc
