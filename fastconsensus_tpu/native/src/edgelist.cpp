// Fast edgelist ingest (the framework's native data loader).
//
// The reference parses input with `nx.read_edgelist` — a pure-Python
// line-by-line parse into dict-of-dicts (reference fast_consensus.py:434),
// which dominates startup on large graphs.  This is a single-pass mmap-free
// buffered C++ parser for the same format: `u v [w]` per line, `#` comments,
// blank lines.  It also fixes the reference's weighted-format crash
// (SURVEY.md §2.22.6): a third column parses as a float weight.
//
// Two-call ABI (count, then fill) keeps memory ownership in Python.

#include <cctype>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

struct Parsed {
  std::vector<int64_t> u, v;
  std::vector<double> w;
  bool saw_weight = false;
  bool ok = false;
};

Parsed parse(const char* path) {
  Parsed out;
  FILE* f = std::fopen(path, "rb");
  if (!f) return out;
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::vector<char> buf(static_cast<size_t>(size) + 1);
  size_t got = std::fread(buf.data(), 1, static_cast<size_t>(size), f);
  std::fclose(f);
  buf[got] = '\0';

  const char* p = buf.data();
  const char* end = p + got;
  while (p < end) {
    // one line
    const char* eol = static_cast<const char*>(memchr(p, '\n', end - p));
    if (!eol) eol = end;
    const char* q = p;
    auto skip_ws = [&]() {
      while (q < eol && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
    };
    skip_ws();
    if (q < eol && *q != '#') {
      char* next = nullptr;
      long long a = std::strtoll(q, &next, 10);
      if (next == q || next > eol) { out.ok = false; return out; }
      q = next;
      skip_ws();
      long long b = std::strtoll(q, &next, 10);
      if (next == q || next > eol) { out.ok = false; return out; }
      q = next;
      skip_ws();
      double wv = 1.0;
      if (q < eol && *q != '#' && *q != '\0') {
        wv = std::strtod(q, &next);
        // strict: the token must parse and be followed only by whitespace
        // or a comment (malformed weights must error, not default to 1.0,
        // matching the pure-Python parser's behavior)
        if (next == q || next > eol) { out.ok = false; return out; }
        q = next;
        skip_ws();
        if (q < eol && *q != '#') { out.ok = false; return out; }
        out.saw_weight = true;
      }
      out.u.push_back(a);
      out.v.push_back(b);
      out.w.push_back(wv);
    }
    p = eol + 1;
  }
  out.ok = true;
  return out;
}

Parsed g_last;  // single-slot cache between count and fill calls

}  // namespace

extern "C" {

// Returns edge count, or -1 on I/O/parse error.  saw_weight set to 0/1.
int64_t fc_parse_edgelist_count(const char* path, int32_t* saw_weight) {
  g_last = parse(path);
  if (!g_last.ok) return -1;
  *saw_weight = g_last.saw_weight ? 1 : 0;
  return static_cast<int64_t>(g_last.u.size());
}

// Fills caller-allocated arrays of length n (from the preceding count call).
void fc_parse_edgelist_fill(int64_t* u, int64_t* v, double* w, int64_t n) {
  if (n > static_cast<int64_t>(g_last.u.size()))
    n = static_cast<int64_t>(g_last.u.size());
  std::memcpy(u, g_last.u.data(), sizeof(int64_t) * n);
  std::memcpy(v, g_last.v.data(), sizeof(int64_t) * n);
  std::memcpy(w, g_last.w.data(), sizeof(double) * n);
  g_last = Parsed{};
}

}  // extern "C"
