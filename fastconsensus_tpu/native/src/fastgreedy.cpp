// CNM fast-greedy modularity agglomeration (Clauset–Newman–Moore 2004).
//
// First-party replacement for igraph's `community_fastgreedy` C routine that
// the reference calls per randomized relabeling (reference
// fast_consensus.py:319-335, :393-411).  The algorithm is inherently
// sequential (one best-pair merge at a time), which is why it lives here on
// the host rather than as a TPU kernel (SURVEY.md §2.23, §7).
//
// Agglomerates all the way to one community while recording the merge
// sequence, then replays the merges up to the modularity peak — the same
// "full dendrogram, cut at max Q" contract as igraph's
// `community_fastgreedy(...).as_clustering()`.
//
// Randomization: the reference randomizes the deterministic algorithm by
// shuffling node ids before each run (fc:326-332).  Here each seed applies a
// random node permutation that perturbs heap tie-breaking identically.
//
// Conventions: E_ij = (sum of A_uv over ordered pairs u in i, v in j) / 2m,
// a_i = strength_i / 2m, Q = sum_i (E_ii - a_i^2), merge gain
// dQ(i,j) = 2 (E_ij - a_i a_j).

#include <atomic>
#include <cstring>
#include <functional>
#include <queue>
#include <thread>
#include <unordered_map>

#include "common.hpp"

namespace {

struct HeapItem {
  double dq;
  int32_t a, b;     // community ids
  uint64_t stamp;   // lazy invalidation: per-community version sum
  bool operator<(const HeapItem& o) const { return dq < o.dq; }
};

// One full CNM run on a permuted view of the graph.
void cnm_single(const fc::Csr& g, uint64_t seed, int32_t* out) {
  const int32_t n = g.n;
  const double m2 = std::max(2.0 * g.total_w, 1e-12);

  std::mt19937_64 rng(seed);
  std::vector<int32_t> perm(n);
  std::iota(perm.begin(), perm.end(), 0);
  std::shuffle(perm.begin(), perm.end(), rng);  // orig -> permuted id

  std::vector<std::unordered_map<int32_t, double>> e(n);
  std::vector<double> a(n, 0.0);
  for (int32_t u = 0; u < n; ++u) {
    int32_t pu = perm[u];
    a[pu] = g.strength[u] / m2;
    if (g.selfw[u] > 0.0) e[pu][pu] += 2.0 * g.selfw[u] / m2;
    for (int64_t k = g.off[u]; k < g.off[u + 1]; ++k) {
      int32_t pv = perm[g.nbr[k]];
      e[pu][pv] += g.w[k] / m2;
    }
  }

  std::vector<uint64_t> version(n, 0);
  std::vector<bool> alive(n, true);
  std::priority_queue<HeapItem> heap;
  auto push_pair = [&](int32_t i, int32_t j) {
    if (i == j) return;
    auto it = e[i].find(j);
    if (it == e[i].end()) return;
    heap.push({2.0 * (it->second - a[i] * a[j]), i, j,
               version[i] + version[j]});
  };
  for (int32_t i = 0; i < n; ++i)
    for (const auto& kv : e[i])
      if (i < kv.first) push_pair(i, kv.first);

  std::vector<std::pair<int32_t, int32_t>> merges;
  merges.reserve(n > 0 ? n - 1 : 0);
  double q = 0.0;
  for (int32_t i = 0; i < n; ++i) {
    auto it = e[i].find(i);
    double eii = it == e[i].end() ? 0.0 : it->second;
    q += eii - a[i] * a[i];
  }
  double best_q = q;
  int64_t best_step = 0;

  while (!heap.empty()) {
    HeapItem top = heap.top();
    heap.pop();
    int32_t i = top.a, j = top.b;
    if (!alive[i] || !alive[j] || top.stamp != version[i] + version[j])
      continue;  // stale entry
    if (e[i].size() < e[j].size()) std::swap(i, j);  // i absorbs j
    alive[j] = false;
    ++version[i];
    ++version[j];
    double eij = 0.0, ejj = 0.0;
    for (const auto& kv : e[j]) {
      int32_t k = kv.first;
      if (k == j) {
        ejj = kv.second;
      } else if (k == i) {
        eij = kv.second;
      } else {
        e[i][k] += kv.second;
        auto& mk = e[k];
        mk.erase(j);
        mk[i] += kv.second;
      }
    }
    e[i][i] += ejj + 2.0 * eij;  // ordered-pair convention
    e[i].erase(j);
    e[j].clear();
    a[i] += a[j];
    q += top.dq;
    merges.emplace_back(i, j);
    if (q > best_q) {
      best_q = q;
      best_step = static_cast<int64_t>(merges.size());
    }
    for (const auto& kv : e[i])
      if (kv.first != i && alive[kv.first]) push_pair(i, kv.first);
  }

  // Replay merges up to the modularity peak with union-find.
  std::vector<int32_t> parent(n);
  std::iota(parent.begin(), parent.end(), 0);
  std::function<int32_t(int32_t)> find = [&](int32_t x) {
    while (parent[x] != x) x = parent[x] = parent[parent[x]];
    return x;
  };
  for (int64_t s = 0; s < best_step; ++s)
    parent[find(merges[s].second)] = find(merges[s].first);

  std::vector<int32_t> lab(n);
  for (int32_t u = 0; u < n; ++u) lab[u] = find(perm[u]);
  fc::compact_labels(lab);
  std::memcpy(out, lab.data(), sizeof(int32_t) * n);
}

}  // namespace

extern "C" void fc_cnm(const int32_t* src, const int32_t* dst,
                       const float* w, int64_t n_edges, int32_t n_nodes,
                       const uint64_t* seeds, int32_t n_p,
                       int32_t* out_labels /* n_p * n_nodes */) {
  fc::Csr g = fc::Csr::build(src, dst, w, n_edges, n_nodes);
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  int n_threads = std::max(1, std::min<int>(n_p, hw ? hw : 1));
  std::vector<std::thread> pool;
  std::atomic<int32_t> next{0};
  for (int t = 0; t < n_threads; ++t)
    pool.emplace_back([&]() {
      for (int32_t p; (p = next.fetch_add(1)) < n_p;)
        cnm_single(g, seeds[p],
                   out_labels + static_cast<int64_t>(p) * n_nodes);
    });
  for (auto& th : pool) th.join();
}
