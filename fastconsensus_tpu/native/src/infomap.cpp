// Two-level Infomap: map-equation minimization (Rosvall–Bergstrom 2008).
//
// First-party replacement for igraph's `community_infomap` (reference
// fast_consensus.py:268, :390).  Implements the core Infomap search — the
// map equation for undirected graphs optimized by Louvain-style local moves
// with aggregation passes — which is inherently sequential and therefore a
// host kernel, not a TPU one (SURVEY.md §2.23: "sequential — CPU fallback
// acceptable", §7 hard-part 4).
//
// Undirected map equation.  With node visit rates p_i = strength_i / 2m and
// module exit rates q_m = w_cross(m) / 2m:
//
//   L(M) = plogp(sum_m q_m) - 2 sum_m plogp(q_m)
//        + sum_m plogp(q_m + sum_{i in m} p_i) - sum_i plogp(p_i)
//
// (plogp(x) = x log2 x; the last term is partition-independent and dropped).
// Simplifications vs full Infomap: two-level codebook only (no hierarchy),
// no Markov-time / teleportation parameters — matching what the reference
// actually uses: `community_infomap()` with default arguments.

#include <atomic>
#include <cmath>
#include <cstring>
#include <thread>
#include <unordered_map>

#include "common.hpp"

namespace {

inline double plogp(double x) { return x > 0.0 ? x * std::log2(x) : 0.0; }

// Louvain-style local-move sweeps minimizing the map equation on graph g.
// labels: in/out module assignment.  Returns number of moves applied.
int64_t local_moves(const fc::Csr& g, std::vector<int32_t>& labels,
                    std::mt19937_64& rng, int max_sweeps) {
  const int32_t n = g.n;
  const double m2 = std::max(2.0 * g.total_w, 1e-12);

  std::vector<double> p(n, 0.0);   // module -> sum of visit rates
  std::vector<double> q(n, 0.0);   // module -> exit rate
  double sum_q = 0.0;
  for (int32_t u = 0; u < n; ++u) p[labels[u]] += g.strength[u] / m2;
  for (int32_t u = 0; u < n; ++u)
    for (int64_t k = g.off[u]; k < g.off[u + 1]; ++k)
      if (labels[g.nbr[k]] != labels[u]) q[labels[u]] += g.w[k] / m2;
  for (int32_t m = 0; m < n; ++m) sum_q += q[m];

  std::vector<int32_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::unordered_map<int32_t, double> wlink;  // module -> weight/2m from u
  int64_t total_moves = 0;

  for (int sweep = 0; sweep < max_sweeps; ++sweep) {
    std::shuffle(order.begin(), order.end(), rng);
    int64_t moves = 0;
    for (int32_t u : order) {
      const int32_t a = labels[u];
      const double pu = g.strength[u] / m2;
      const double ku_ext = (g.strength[u] - 2.0 * g.selfw[u]) / m2;
      wlink.clear();
      for (int64_t k = g.off[u]; k < g.off[u + 1]; ++k)
        wlink[labels[g.nbr[k]]] += g.w[k] / m2;
      auto ita = wlink.find(a);
      const double w_ua = ita == wlink.end() ? 0.0 : ita->second;

      // module a's aggregates with u removed
      const double qa2 = q[a] - ku_ext + 2.0 * w_ua;
      const double pa2 = p[a] - pu;
      const double old_a = -2.0 * plogp(q[a]) + plogp(q[a] + p[a]);
      const double new_a = -2.0 * plogp(qa2) + plogp(qa2 + pa2);

      double best_delta = -1e-12;  // strict improvement required
      int32_t best = a;
      double best_qb2 = 0.0;
      for (const auto& kv : wlink) {
        const int32_t b = kv.first;
        if (b == a) continue;
        const double qb2 = q[b] + ku_ext - 2.0 * kv.second;
        const double pb2 = p[b] + pu;
        const double old_b = -2.0 * plogp(q[b]) + plogp(q[b] + p[b]);
        const double new_b = -2.0 * plogp(qb2) + plogp(qb2 + pb2);
        const double sum_q2 = sum_q + (qa2 - q[a]) + (qb2 - q[b]);
        const double delta = plogp(sum_q2) - plogp(sum_q) +
                             (new_a - old_a) + (new_b - old_b);
        if (delta < best_delta) {
          best_delta = delta;
          best = b;
          best_qb2 = qb2;
        }
      }

      if (best != a) {
        sum_q += (qa2 - q[a]) + (best_qb2 - q[best]);
        q[a] = qa2;
        p[a] = pa2;
        q[best] = best_qb2;
        p[best] += pu;
        labels[u] = best;
        ++moves;
      }
    }
    total_moves += moves;
    if (moves == 0) break;
  }
  return total_moves;
}

void infomap_single(const fc::Csr& g, uint64_t seed, int32_t* out) {
  const int32_t n = g.n;
  std::mt19937_64 rng(seed);
  std::vector<int32_t> flat(n);
  std::iota(flat.begin(), flat.end(), 0);
  local_moves(g, flat, rng, /*max_sweeps=*/32);

  // Aggregation passes: collapse modules to supernodes and move again,
  // until a pass makes no further moves (Louvain-style outer loop, the same
  // structure Infomap's core search uses).
  for (int level = 0; level < 8; ++level) {
    fc::compact_labels(flat);
    int32_t k = *std::max_element(flat.begin(), flat.end()) + 1;
    if (k <= 1) break;
    std::unordered_map<int64_t, double> agg;
    for (int32_t u = 0; u < n; ++u) {
      for (int64_t e = g.off[u]; e < g.off[u + 1]; ++e) {
        int32_t v = g.nbr[e];
        if (u > v) continue;  // CSR holds both orientations
        int32_t cu = flat[u], cv = flat[v];
        int64_t key = static_cast<int64_t>(std::min(cu, cv)) * k +
                      std::max(cu, cv);
        agg[key] += g.w[e];
      }
      if (g.selfw[u] > 0.0)
        agg[static_cast<int64_t>(flat[u]) * k + flat[u]] += g.selfw[u];
    }
    std::vector<int32_t> asrc, adst;
    std::vector<float> aw;
    asrc.reserve(agg.size());
    for (const auto& kv : agg) {
      asrc.push_back(static_cast<int32_t>(kv.first / k));
      adst.push_back(static_cast<int32_t>(kv.first % k));
      aw.push_back(static_cast<float>(kv.second));
    }
    fc::Csr cg = fc::Csr::build(asrc.data(), adst.data(), aw.data(),
                                static_cast<int64_t>(asrc.size()), k);
    std::vector<int32_t> clab(k);
    std::iota(clab.begin(), clab.end(), 0);
    if (local_moves(cg, clab, rng, /*max_sweeps=*/32) == 0) break;
    for (int32_t u = 0; u < n; ++u) flat[u] = clab[flat[u]];
  }
  fc::compact_labels(flat);
  std::memcpy(out, flat.data(), sizeof(int32_t) * n);
}

}  // namespace

extern "C" void fc_infomap(const int32_t* src, const int32_t* dst,
                           const float* w, int64_t n_edges, int32_t n_nodes,
                           const uint64_t* seeds, int32_t n_p,
                           int32_t* out_labels /* n_p * n_nodes */) {
  fc::Csr g = fc::Csr::build(src, dst, w, n_edges, n_nodes);
  int hw = static_cast<int>(std::thread::hardware_concurrency());
  int n_threads = std::max(1, std::min<int>(n_p, hw ? hw : 1));
  std::vector<std::thread> pool;
  std::atomic<int32_t> next{0};
  for (int t = 0; t < n_threads; ++t)
    pool.emplace_back([&]() {
      for (int32_t p; (p = next.fetch_add(1)) < n_p;)
        infomap_single(g, seeds[p],
                       out_labels + static_cast<int64_t>(p) * n_nodes);
    });
  for (auto& th : pool) th.join();
}
