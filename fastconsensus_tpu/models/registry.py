"""Algorithm dispatch: the ``--alg`` seam.

The reference dispatches on a string through inline if/elif branches
(fast_consensus.py:141,204,260,312) or ``get_communities``
(merged_consensus.py:131-144).  Here it is an explicit registry so new
detectors (TPU kernels or host fallbacks) plug in without touching the
engine — the extension point BASELINE.json's north star names.
"""

from __future__ import annotations

from typing import Callable, Dict

from fastconsensus_tpu.models.base import Detector

_REGISTRY: Dict[str, Callable[[], Detector]] = {}


def register(name: str):
    def deco(factory: Callable[[], Detector]):
        _REGISTRY[name] = factory
        return factory
    return deco


def get_detector(name: str) -> Detector:
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown algorithm {name!r}; available: {sorted(_REGISTRY)}"
        ) from None
    try:
        return factory()
    except ImportError as e:
        raise NotImplementedError(
            f"algorithm {name!r} is registered but its kernel is not "
            f"available in this build: {e}") from e


def available() -> list:
    return sorted(_REGISTRY)


@register("lpm")
def _lpm() -> Detector:
    from fastconsensus_tpu.models.lpm import lpm
    return lpm


@register("louvain")
def _louvain() -> Detector:
    from fastconsensus_tpu.models.louvain import louvain
    return louvain


@register("leiden")
def _leiden() -> Detector:
    from fastconsensus_tpu.models.leiden import leiden
    return leiden


@register("cnm")
def _cnm() -> Detector:
    from fastconsensus_tpu import native
    if not native.available():
        raise ImportError("native C++ toolchain unavailable for the CNM "
                          "fast-greedy kernel")
    from fastconsensus_tpu.models.cnm import cnm
    return cnm


@register("infomap")
def _infomap() -> Detector:
    from fastconsensus_tpu import native
    if not native.available():
        raise ImportError("native C++ toolchain unavailable for the Infomap "
                          "kernel")
    from fastconsensus_tpu.models.infomap import infomap
    return infomap
