"""Algorithm dispatch: the ``--alg`` seam.

The reference dispatches on a string through inline if/elif branches
(fast_consensus.py:141,204,260,312) or ``get_communities``
(merged_consensus.py:131-144).  Here it is an explicit registry so new
detectors (TPU kernels or host fallbacks) plug in without touching the
engine — the extension point BASELINE.json's north star names.
"""

from __future__ import annotations

import functools as _functools
import inspect as _inspect
from typing import Callable, Dict

from fastconsensus_tpu.models.base import Detector

_REGISTRY: Dict[str, Callable[[], Detector]] = {}


def register(name: str):
    def deco(factory: Callable[[], Detector]):
        _REGISTRY[name] = factory
        return factory
    return deco


def supports_param(name: str, param: str) -> bool:
    """Whether ``name``'s registered factory accepts keyword ``param``
    (e.g. "gamma") — lets callers warn instead of silently dropping it."""
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown algorithm {name!r}; available: {sorted(_REGISTRY)}"
        ) from None
    return param in _inspect.signature(factory).parameters


def get_detector(name: str, gamma: float = 1.0) -> Detector:
    """Resolve a detector; memoized so repeated lookups return the same
    function object (jit caches key on it — see consensus._jitted_round).

    Extra parameters (currently ``gamma``, the resolution parameter) are
    forwarded to the registered factory when its signature accepts them, so
    new detectors opt in by declaring the keyword — no name lists here.
    The reference parses ``-g`` but never uses it
    (merged_consensus.py:284-285, SURVEY.md §2.22.10); here it works.
    """
    return _get_cached(name, float(gamma))


@_functools.lru_cache(maxsize=64)
def _get_cached(name: str, gamma: float) -> Detector:
    try:
        factory = _REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown algorithm {name!r}; available: {sorted(_REGISTRY)}"
        ) from None
    kwargs = {}
    if "gamma" in _inspect.signature(factory).parameters:
        kwargs["gamma"] = gamma
    try:
        return factory(**kwargs)
    except ImportError as e:
        raise NotImplementedError(
            f"algorithm {name!r} is registered but its kernel is not "
            f"available in this build: {e}") from e


def available() -> list:
    return sorted(_REGISTRY)


@register("lpm")
def _lpm() -> Detector:
    from fastconsensus_tpu.models.lpm import lpm
    return lpm


@register("louvain")
def _louvain(gamma: float = 1.0) -> Detector:
    from fastconsensus_tpu.models.louvain import louvain, make_louvain
    return louvain if gamma == 1.0 else make_louvain(gamma=gamma)


@register("leiden")
def _leiden(gamma: float = 1.0) -> Detector:
    from fastconsensus_tpu.models.leiden import leiden, make_leiden
    return leiden if gamma == 1.0 else make_leiden(gamma=gamma)


@register("cnm")
def _cnm() -> Detector:
    from fastconsensus_tpu import native
    if not native.available():
        raise ImportError("native C++ toolchain unavailable for the CNM "
                          "fast-greedy kernel")
    from fastconsensus_tpu.models.cnm import cnm
    return cnm


@register("infomap")
def _infomap() -> Detector:
    from fastconsensus_tpu import native
    if not native.available():
        raise ImportError("native C++ toolchain unavailable for the Infomap "
                          "kernel")
    from fastconsensus_tpu.models.infomap import infomap
    return infomap
