"""Infomap detector (native C++ host kernel).

The reference calls igraph's C ``community_infomap`` (reference
``fast_consensus.py:268``, ``:390``).  The map-equation search is the
hardest algorithm in the inventory to express data-parallel (SURVEY.md §7
hard-part 4: "no good data-parallel formulation; ship CPU fallback"), so the
kernel is first-party C++ — a two-level map-equation optimizer with
Louvain-style local moves and aggregation (``native/src/infomap.cpp``),
threaded over the n_p ensemble — reached through :func:`jax.pure_callback`
exactly like the CNM detector (see models/cnm.py for the boundary notes).
"""

from __future__ import annotations

from fastconsensus_tpu.models.cnm import _make_detector

infomap = _make_detector("infomap_labels")
