"""Label propagation (LPM) as a jitted fixed-point iteration.

Replaces igraph's C ``community_label_propagation`` (reference
``fast_consensus.py:270``).  igraph's implementation is asynchronous — nodes
update one at a time in random order until every node's label is a weighted
mode of its neighbors' labels.  Sequential sweeps don't map to a TPU, so this
kernel uses the standard data-parallel formulation:

* synchronous rounds: every node recomputes the weighted mode of its
  neighbors' labels via one sorted-run segment reduction
  (ops/segment.py), then
* a keyed random *update mask* keeps a random subset of nodes fixed each
  round (breaking the two-coloring oscillation synchronous LPA is prone to),
* keyed jitter randomizes ties (igraph breaks ties uniformly at random).

Termination: when no node wants to change its label, or ``max_iters``.
The fixed point is the same local criterion igraph converges to: every
updated node holds a maximal-weight neighbor label.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from fastconsensus_tpu.graph import GraphSlab
from fastconsensus_tpu.models.base import Detector, ensemble
from fastconsensus_tpu.ops import dense_adj as da
from fastconsensus_tpu.ops import segment as seg


def _vote_step(slab: GraphSlab, labels: jax.Array, key: jax.Array,
               update_prob: float) -> Tuple[jax.Array, jax.Array]:
    """One synchronous vote round.  Returns (new_labels, n_want_change)."""
    n = slab.n_nodes
    srcd, dstd, wd, ad = slab.directed()
    k_tie, k_mask = jax.random.split(key)
    runs = seg.node_label_runs(srcd, labels[dstd], wd, ad, n)
    # pair-keyed: position-based jitter would change tie-breaks when the
    # slab grows (segment.pair_jitter / graph.grow_slab)
    score = runs.total + seg.pair_jitter(k_tie, runs.node, runs.label, 0.5)
    best, _, has_any = seg.argmax_label_per_node(
        runs.node, score, runs.label, runs.valid, n)
    want = has_any & (best != labels)
    n_want = jnp.sum(want.astype(jnp.int32))
    mask = jax.random.bernoulli(k_mask, update_prob, (n,))
    new_labels = jnp.where(want & mask, best, labels)
    return new_labels, n_want


def _vote_step_dense(adj: da.DenseAdj, labels: jax.Array, key: jax.Array,
                     update_prob: float) -> Tuple[jax.Array, jax.Array]:
    """Dense-row vote (see ops/dense_adj.py).  A node's own zero-weight
    candidate never outscores a real neighbor vote (weights >= 1 vs jitter
    < 0.5), so the weighted-mode semantics match _vote_step."""
    n = adj.nbr.shape[0]
    k_tie, k_mask = jax.random.split(key)
    tot = da.row_label_totals(adj, labels)
    jitter = seg.uniform_jitter(k_tie, tot.total.shape, 0.5)
    # exclude the synthetic zero-weight own candidate unless it has real
    # neighbor weight — isolated-in-row nodes then keep their label
    score = jnp.where(tot.is_head & (tot.total > 0), tot.total + jitter,
                      -jnp.inf)
    best, want = da.best_candidate(tot, score, labels)
    n_want = jnp.sum(want.astype(jnp.int32))
    mask = jax.random.bernoulli(k_mask, update_prob, (n,))
    return jnp.where(want & mask, best, labels), n_want


def lpm_single(slab: GraphSlab, key: jax.Array,
               init_labels: jax.Array = None,
               max_iters: int = 64, update_prob: float = 0.7) -> jax.Array:
    """One label-propagation partition; labels int32[N] (not compacted).

    ``init_labels`` warm-starts the vote iteration (None = every node its
    own label, the igraph initial condition)."""
    n = slab.n_nodes
    if init_labels is None:
        init_labels = jnp.arange(n, dtype=jnp.int32)
    else:
        init_labels = init_labels.astype(jnp.int32)

    dense = slab.d_cap > 0
    if dense:
        adj = da.build_dense_adjacency(slab)

    def cond(state):
        labels, it, n_want = state
        return (n_want > 0) & (it < max_iters)

    def body(state):
        labels, it, _ = state
        k = jax.random.fold_in(key, it)
        if dense:
            new_labels, n_want = _vote_step_dense(adj, labels, k, update_prob)
        else:
            new_labels, n_want = _vote_step(slab, labels, k, update_prob)
        return new_labels, it + 1, n_want

    labels, _, _ = jax.lax.while_loop(
        cond, body, (init_labels, jnp.int32(0), jnp.int32(1)))
    return seg.compact_labels(labels, n)


def make_lpm(max_iters: int = 64, update_prob: float = 0.7) -> Detector:
    return ensemble(functools.partial(
        lpm_single, max_iters=max_iters, update_prob=update_prob))


lpm = make_lpm()
