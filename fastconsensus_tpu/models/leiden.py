"""Leiden-style seeded detector: local move + refinement + aggregation.

Replaces ``leidenalg.find_partition(..., ModularityVertexPartition, seed=s,
n_iterations=1)`` (reference ``fast_consensus.py:121-123``): one Leiden
iteration — modularity local move, a *refinement* phase that re-partitions
each community from singletons with moves constrained to stay inside the
community (Traag et al. 2019's guard against badly-connected communities),
aggregation over the refined partition with the aggregate initialized at the
unrefined communities, and a final local move — returning the flat partition.

Shares all machinery with models/louvain.py; the refinement constraint is an
edge mask (intra-community edges only), so the same jitted local-move kernel
runs all three phases.  Deviation from leidenalg (documented): refinement
merges greedily rather than sampling merges proportional to exp(gain/theta),
and the per-phase normalization uses the masked subgraph's weight.  Parity is
validated at the NMI level (SURVEY.md §7 "semantics fidelity").

Determinism: one partition per PRNG key — the ensemble analog of leidenalg's
``seed=range(n_p)`` (fc:125-127), the only reproducible path in the
reference.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from fastconsensus_tpu.graph import GraphSlab
from fastconsensus_tpu.models.base import Detector, ensemble
from fastconsensus_tpu.models.louvain import aggregate, local_move
from fastconsensus_tpu.ops import segment as seg


def refine(slab: GraphSlab, comm: jax.Array, key: jax.Array,
           max_sweeps: int = 16, gamma: float = 1.0) -> jax.Array:
    """Constrained local move: singletons may only merge within ``comm``."""
    n = slab.n_nodes
    intra = slab.alive & (comm[jnp.clip(slab.src, 0, n - 1)] ==
                          comm[jnp.clip(slab.dst, 0, n - 1)])
    masked = dataclasses.replace(slab, alive=intra)
    return local_move(masked, key, max_sweeps=max_sweeps, gamma=gamma)


def leiden_single(slab: GraphSlab, key: jax.Array,
                  init_labels: jax.Array = None,
                  max_sweeps: int = 32, gamma: float = 1.0) -> jax.Array:
    """``init_labels`` warm-starts the main move phase (the refinement and
    aggregate phases re-derive their own inits from it as usual)."""
    n = slab.n_nodes
    k0, k1, k2 = jax.random.split(key, 3)

    comm = local_move(slab, k0, init_labels=init_labels,
                      max_sweeps=max_sweeps, gamma=gamma)
    # refinement re-partitions *within* converged communities — a much
    # easier problem than the main move phase, so half the sweep budget
    # suffices (quality-checked in tests/test_louvain.py leiden tests)
    refined = seg.compact_labels(
        refine(slab, comm, k1, max_sweeps=max(max_sweeps // 2, 4),
               gamma=gamma), n)

    # aggregate over refined groups; initialize the aggregate's partition at
    # the unrefined communities (each refined group inherits its community).
    # The aggregate starts from an already-converged assignment, so it too
    # needs only the half budget.
    agg = aggregate(slab, refined)
    group_comm = jax.ops.segment_max(
        comm, jnp.clip(refined, 0, n - 1), num_segments=n)
    lvl = local_move(agg, k2, init_labels=group_comm.astype(jnp.int32),
                     max_sweeps=max(max_sweeps // 2, 4), gamma=gamma)
    lvl = seg.compact_labels(lvl, n)
    return lvl[jnp.clip(refined, 0, n - 1)]


def make_leiden(max_sweeps: int = 32, gamma: float = 1.0) -> Detector:
    return ensemble(functools.partial(leiden_single, max_sweeps=max_sweeps,
                                      gamma=gamma))


leiden = make_leiden()
