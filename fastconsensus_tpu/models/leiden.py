"""Leiden-style seeded detector: local move + refinement + aggregation.

Replaces ``leidenalg.find_partition(..., ModularityVertexPartition, seed=s,
n_iterations=1)`` (reference ``fast_consensus.py:121-123``): one Leiden
iteration — modularity local move, a *refinement* phase that re-partitions
each community from singletons with moves constrained to stay inside the
community (Traag et al. 2019's guard against badly-connected communities),
aggregation over the refined partition with the aggregate initialized at the
unrefined communities, and a final local move — returning the flat partition.

Shares all machinery with models/louvain.py; the refinement constraint is an
edge mask (intra-community edges only), so the same jitted local-move kernel
runs all three phases.  Refinement is theta-randomized like leidenalg's
(merges sampled proportional to exp(gain/theta) via the Gumbel-max trick,
restricted to sweep-start singletons) and therefore carries its
internal-connectivity guarantee — see :func:`refine`.  Remaining deviation
(documented): the per-phase normalization uses the masked subgraph's weight,
and moves are synchronous sweeps rather than sequential visits.  Parity is
validated at the NMI level (SURVEY.md §7 "semantics fidelity") plus the
connectivity property test.

Determinism: one partition per PRNG key — the ensemble analog of leidenalg's
``seed=range(n_p)`` (fc:125-127), the only reproducible path in the
reference.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from fastconsensus_tpu.graph import GraphSlab
from fastconsensus_tpu.models.base import Detector, ensemble
from fastconsensus_tpu.models.louvain import aggregate, local_move
from fastconsensus_tpu.ops import segment as seg


def refine(slab: GraphSlab, comm: jax.Array, key: jax.Array,
           max_sweeps: int = 16, gamma: float = 1.0,
           theta: float = 0.01) -> jax.Array:
    """Theta-randomized refinement within ``comm`` (Traag et al. 2019).

    Re-partitions each community from singletons on the intra-community
    edge mask.  Merges are restricted to sweep-start singletons and sampled
    proportional to ``exp(gain/theta)`` (louvain.local_move refinement
    mode, via the Gumbel-max trick) — matching leidenalg's randomized
    merge distribution and, because grouped nodes never move again, its
    internal-connectivity guarantee (property test:
    tests/test_louvain.py::test_leiden_refinement_connectivity).
    ``theta`` is in leidenalg's unnormalized-gain units (its default 1e-2).
    """
    n = slab.n_nodes
    intra = slab.alive & (comm[jnp.clip(slab.src, 0, n - 1)] ==
                          comm[jnp.clip(slab.dst, 0, n - 1)])
    masked = dataclasses.replace(slab, alive=intra)
    return local_move(masked, key, max_sweeps=max_sweeps, gamma=gamma,
                      theta=theta, singleton_only=True)


def leiden_single(slab: GraphSlab, key: jax.Array,
                  init_labels: jax.Array = None,
                  max_sweeps: int = 32, gamma: float = 1.0,
                  theta: float = 0.01) -> jax.Array:
    """``init_labels`` warm-starts the main move phase (the refinement and
    aggregate phases re-derive their own inits from it as usual)."""
    n = slab.n_nodes
    k0, k1, k2 = jax.random.split(key, 3)

    comm = local_move(slab, k0, init_labels=init_labels,
                      max_sweeps=max_sweeps, gamma=gamma)
    # refinement re-partitions *within* converged communities — a much
    # easier problem than the main move phase, so half the sweep budget
    # suffices (quality-checked in tests/test_louvain.py leiden tests)
    refined = seg.compact_labels(
        refine(slab, comm, k1, max_sweeps=max(max_sweeps // 2, 4),
               gamma=gamma, theta=theta), n)

    # aggregate over refined groups; initialize the aggregate's partition at
    # the unrefined communities (each refined group inherits its community).
    # The aggregate starts from an already-converged assignment, so it too
    # needs only the half budget.
    agg = aggregate(slab, refined)
    # Growth-stability: gate on the pack-time capacity hint, not live
    # capacity — labels must not change when auto-growth (or a generous
    # --capacity) resizes the slab mid-run (the louvain._cap_hint
    # contract; round-5 review).  Late-run agg_cap may exceed live
    # capacity by its 12.5% slack — a bounded waste, never a loss.
    # The gate is shared with the engine's n_agg_overflow accounting
    # (graph.agg_compaction_active), which surfaces any drop per round.
    from fastconsensus_tpu.graph import agg_compaction_active, compact_alive

    if agg_compaction_active(slab):
        # Compacted aggregate move: the hash path's per-sweep cost is
        # linear in the scanned capacity, and the aggregate uses only
        # ~the alive fraction of the consensus slab's slots (27.4 ->
        # ~11 ms/member/sweep measured, runs/kernel_profile/profile.json).
        # agg_cap >= the alive count at sizing time makes this lossless
        # (distinct aggregate pairs <= alive edges); the driver re-derives
        # agg_cap with the other budgets as closure densifies the slab.
        agg = compact_alive(agg, slab.agg_cap)
    group_comm = jax.ops.segment_max(
        comm, jnp.clip(refined, 0, n - 1), num_segments=n)
    lvl = local_move(agg, k2, init_labels=group_comm.astype(jnp.int32),
                     max_sweeps=max(max_sweeps // 2, 4), gamma=gamma)
    lvl = seg.compact_labels(lvl, n)
    return lvl[jnp.clip(refined, 0, n - 1)]


def make_leiden(max_sweeps: Optional[int] = None, gamma: float = 1.0,
                theta: float = 0.01) -> Detector:
    from fastconsensus_tpu.models.louvain import (cold_sweep_budget,
                                                  warm_sweep_budget)

    if max_sweeps is None:
        max_sweeps = cold_sweep_budget()

    det = ensemble(functools.partial(leiden_single, max_sweeps=max_sweeps,
                                     gamma=gamma, theta=theta))
    # Call-sizing hint (consensus._members_per_call): three move phases +
    # the aggregate's hash-path sweeps cost ~4x a plain louvain detection
    # (measured on the lfr10k config: 1.04 vs 0.24 s/member).
    det.cost_mult = 4.0
    # Warm consensus rounds run greedy singleton-accretion refinement
    # (theta=0: still connected by construction, but deterministic given
    # the structure).  Theta-resampling refinement *every* round injects
    # fresh cross-member variance that delta-convergence then has to fight
    # (measured on lfr10k: 31% vs 18% unconverged at round 5); the
    # user-visible leidenalg-parity surface — fresh detections and the
    # cold first round — keeps the theta-randomized distribution.
    det.warm_variant = ensemble(functools.partial(
        leiden_single, max_sweeps=min(warm_sweep_budget(), max_sweeps),
        gamma=gamma, theta=0.0))
    det.warm_variant.cost_mult = 4.0
    # Stagnation-refresh rounds (consensus.py round_mode "refresh") re-derive
    # every member from scratch on the current weights — with theta=0:
    # theta-resampling on every refresh re-injects exactly the
    # cross-member variance the refresh is trying to burn down (measured
    # round 3: lfr10k mu=0.5 diverges — consecutive theta-randomized cold
    # rounds RAISED the mid-weight count every round).  The user-visible
    # leidenalg-parity surface — fresh detections and the true round-0
    # cold start — keeps the theta-randomized distribution.
    det.refresh_variant = ensemble(functools.partial(
        leiden_single, max_sweeps=max_sweeps, gamma=gamma, theta=0.0))
    det.refresh_variant.cost_mult = 4.0
    # all three phases run louvain's move machinery, whose tie-break jitter
    # is content-keyed (louvain._community_reps) — see ConsensusConfig.align_frac
    det.supports_align = True
    return det


leiden = make_leiden()
