"""CNM fast-greedy detector (native C++ host kernel).

The reference runs igraph's C ``community_fastgreedy`` once per randomly
relabeled graph copy (reference ``fast_consensus.py:319-335``; the algorithm
is deterministic, so relabeling injects the ensemble's randomness).  Greedy
agglomeration is inherently sequential (SURVEY.md §2.23), so the kernel is
first-party C++ (``native/src/fastgreedy.cpp``, threaded over the ensemble)
reached through :func:`jax.pure_callback` — which keeps the detector
composable with the jitted consensus round: the slab stays on device, XLA
inserts the device→host→device transfer at the callback boundary.

The random relabeling lives inside the C++ kernel as a per-seed node
permutation (same mechanism, no host-side graph copies).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from fastconsensus_tpu.graph import GraphSlab


def _seeds_from_keys(keys: jax.Array) -> jax.Array:
    """Raw uint32 key words per ensemble member (combined to 64-bit seeds on
    the host — jax defaults to 32-bit dtypes)."""
    data = jax.random.key_data(keys).astype(jnp.uint32)
    return data.reshape(data.shape[0], -1)


def _host_call(fn_name):
    def host(src, dst, weight, alive, seed_words):
        from fastconsensus_tpu import native

        mask = np.asarray(alive)
        words = np.asarray(seed_words).astype(np.uint64)
        seeds = (words[:, 0] << np.uint64(32)) | words[:, -1]
        run = getattr(native, fn_name)
        return run(np.asarray(src)[mask], np.asarray(dst)[mask],
                   np.asarray(weight)[mask], host.n_nodes, seeds)
    return host


def _make_detector(fn_name: str):
    def detect(slab: GraphSlab, keys: jax.Array) -> jax.Array:
        n_p = keys.shape[0]
        host = _host_call(fn_name)
        host.n_nodes = slab.n_nodes
        out_shape = jax.ShapeDtypeStruct((n_p, slab.n_nodes), jnp.int32)
        return jax.pure_callback(
            host, out_shape, slab.src, slab.dst, slab.weight, slab.alive,
            _seeds_from_keys(keys), vmap_method="sequential")
    return detect


cnm = _make_detector("cnm_labels")
