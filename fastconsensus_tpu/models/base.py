"""Base-detection kernel interface.

A detector is a pure function

    detect(slab: GraphSlab, keys: uint32[n_p, ...]) -> labels int32[n_p, N]

running the base community-detection algorithm once per PRNG key — the
ensemble axis the reference executes as serial list comprehensions or a
multiprocessing pool (fast_consensus.py:148, :210-211, :268-270, :324-335)
and we execute as a vmapped batch axis, shardable over the device mesh.

Labels need not be compact; community ids only need to be equal within a
community (co-membership is an equality test, ops/consensus_ops.py).
"""

from __future__ import annotations

from typing import Callable, Protocol

import jax

from fastconsensus_tpu.graph import GraphSlab
from fastconsensus_tpu.utils.env import env_int


class Detector(Protocol):
    """``detect(slab, keys[n_p, ...]) -> labels int32[n_p, N]``.

    Implementations must be **per-key independent**: member i's labels may
    depend only on ``(slab, keys[i])``, never on other rows of ``keys``.
    The consensus driver relies on this to split detection into chunked
    device calls and to shard the ensemble axis over a mesh — a detector
    mixing information across the keys axis would give different results
    under different chunkings/shardings.  Every :func:`ensemble` lift
    satisfies the requirement by construction.
    """

    def __call__(self, slab: GraphSlab, keys: jax.Array) -> jax.Array: ...


def _sweep_bytes_per_member(slab: GraphSlab) -> int:
    """Rough peak of one member's per-sweep temporaries.

    Delegates to louvain's :func:`sweep_temp_bytes` (lazy import — louvain
    imports this module), which consults the same path selection
    :func:`local_move` will actually use, so the budget can't drift from the
    kernels.
    """
    from fastconsensus_tpu.models import louvain

    return louvain.sweep_temp_bytes(slab)


def ensemble_chunk(slab: GraphSlab, n_p: int) -> int:
    """How many ensemble members to run concurrently.

    vmapping all n_p members multiplies every per-sweep temporary by n_p —
    at LFR-10k shapes (N=10k, d_cap~1000) that is ~25 GB for n_p=100, past
    any single chip's HBM.  Bound the concurrent slice so temps fit a budget
    (FCTPU_ENSEMBLE_BUDGET_MB, default 2048), or force a chunk size with
    FCTPU_ENSEMBLE_CHUNK (<=0 disables chunking, e.g. on multi-chip meshes
    where the ensemble axis is already sharded across devices).
    """
    c = env_int("FCTPU_ENSEMBLE_CHUNK")
    if c is not None:
        return n_p if c <= 0 else min(c, n_p)
    budget = env_int("FCTPU_ENSEMBLE_BUDGET_MB", 2048) << 20
    return max(1, min(n_p, budget // max(1, _sweep_bytes_per_member(slab))))


def ensemble(single: Callable[[GraphSlab, jax.Array], jax.Array]) -> Detector:
    """Lift a one-partition kernel to the n_p ensemble axis.

    Plain vmap when all members' sweep temporaries fit the memory budget;
    otherwise ``lax.map(..., batch_size=chunk)`` — sequential chunks of a
    vmapped inner kernel, bounding peak HBM at chunk * per-member bytes
    while keeping each chunk wide enough to saturate the chip.

    If ``single`` accepts an ``init_labels`` keyword, the lifted detector
    exposes warm-starting: ``detect(slab, keys, init_labels=[n_p, N])``
    seeds member i's optimization from ``init_labels[i]`` (the consensus
    driver passes the previous round's labels — the reference re-runs each
    round's detections from scratch, fast_consensus.py:148, because its
    libraries offer no warm path).  The lifted function advertises this via
    ``detect.supports_init``.
    """
    import inspect

    has_init = "init_labels" in inspect.signature(single).parameters

    def detect(slab: GraphSlab, keys: jax.Array,
               init_labels: jax.Array = None) -> jax.Array:
        n_p = keys.shape[0]
        chunk = ensemble_chunk(slab, n_p)
        if init_labels is None or not has_init:
            if chunk >= n_p:
                return jax.vmap(lambda k: single(slab, k))(keys)
            return jax.lax.map(lambda k: single(slab, k), keys,
                               batch_size=chunk)
        fn = lambda ki: single(slab, ki[0], init_labels=ki[1])  # noqa: E731
        if chunk >= n_p:
            return jax.vmap(fn)((keys, init_labels))
        return jax.lax.map(fn, (keys, init_labels), batch_size=chunk)

    detect.supports_init = has_init
    return detect
