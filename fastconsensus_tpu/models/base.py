"""Base-detection kernel interface.

A detector is a pure function

    detect(slab: GraphSlab, keys: uint32[n_p, ...]) -> labels int32[n_p, N]

running the base community-detection algorithm once per PRNG key — the
ensemble axis the reference executes as serial list comprehensions or a
multiprocessing pool (fast_consensus.py:148, :210-211, :268-270, :324-335)
and we execute as a vmapped batch axis, shardable over the device mesh.

Labels need not be compact; community ids only need to be equal within a
community (co-membership is an equality test, ops/consensus_ops.py).
"""

from __future__ import annotations

from typing import Callable, Protocol

import jax

from fastconsensus_tpu.graph import GraphSlab


class Detector(Protocol):
    def __call__(self, slab: GraphSlab, keys: jax.Array) -> jax.Array: ...


def ensemble(single: Callable[[GraphSlab, jax.Array], jax.Array]) -> Detector:
    """Lift a one-partition kernel to the n_p ensemble axis via vmap."""

    def detect(slab: GraphSlab, keys: jax.Array) -> jax.Array:
        return jax.vmap(lambda k: single(slab, k))(keys)

    return detect
