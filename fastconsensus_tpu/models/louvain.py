"""Louvain as synchronous parallel modularity local-move.

Replaces python-louvain's ``generate_dendrogram(randomize=True)`` +
``partition_at_level(dend, 0)`` (reference ``fast_consensus.py:148`` — note
the reference uses the dendrogram's *finest* level, i.e. the partition after
the first local-move phase converges, not the top level).

python-louvain moves one node at a time in random sweep order.  On TPU the
move step is data-parallel (the GPU-Louvain formulation, arXiv:1805.10904):

* every node computes, in one sorted-run segment reduction
  (ops/segment.py), its modularity gain for joining each neighboring
  community:  gain(i -> C) = k_i_in(C) - k_i * (Sigma_tot(C) - [C = c_i] k_i) / 2m
* keyed jitter randomizes ties (the ``randomize=True`` analog), and a keyed
  bernoulli *move mask* applies only a random subset of the best moves each
  sweep — the standard cure for the swap oscillations synchronous moves
  cause;
* sweeps repeat until no node can improve, which is exactly python-louvain's
  level-0 convergence criterion.

``modularity_levels`` adds the aggregation phase (community graph built by
the same run machinery) for multi-level optimization — the backend of the
leiden detector and of final-quality-oriented uses; the louvain detector
itself returns level-0 labels for parity with the reference.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from fastconsensus_tpu.graph import GraphSlab
from fastconsensus_tpu.models.base import Detector, ensemble
from fastconsensus_tpu.ops import dense_adj as da
from fastconsensus_tpu.ops import segment as seg

# Tie-break jitter and the move margin are *relative to the gain quantum*
# 1/(2m): modularity gains differ by integer multiples of w_min/(2m), so an
# absolute jitter amplitude would dwarf real gain differences on large graphs
# (at 2m ~ 1e5 the quantum is ~1e-5) and vanish against them on tiny ones.
# Jitter in [0, 0.25/2m) can only reorder exact ties; a jittered best
# exceeding the unjittered stay score by 0.5/2m implies a true gain — without
# that margin, nodes at equilibrium flip-flop on jitter noise forever, the
# sweep loop never converges, and the churn degrades partition quality as
# sweeps accumulate (measured on LFR-10k: NMI 0.59 at 24 sweeps falling to
# 0.52 at 48).
_JITTER_REL = 0.25
_MARGIN_REL = 0.5

# Widest graph the full-matrix (MXU) move path materializes: per ensemble
# member the sweep holds a few N x N arrays, so n_p * N^2 * ~16B must fit in
# HBM (n_p=200 at N=1024 is ~3 GB).  Larger graphs take the padded-row or
# sorted-run paths.
MATMUL_MAX_N = 1024

# Dense padded-row sweeps beat hashed scatter-adds only while the row area
# N*(d_cap+1) stays comparable to the directed-edge count (low degree skew);
# past this ratio the rows are mostly padding and the per-sweep row sort
# loses to O(E) scatters.
DENSE_OVER_HASH = 8


def _community_reps(labels: jax.Array, n: int) -> jax.Array:
    """Canonical representative (minimum member node id) per community id.

    Tie-break jitter is keyed on ``(node, rep[candidate_label])`` instead of
    the raw label id: label ids are arbitrary per ensemble member (each
    member names communities differently), while the min-node-id
    representative is identical across members whenever the community is
    the same *node set*.  Within one member the mapping label -> rep is
    injective over live labels, so the jitter distribution is unchanged —
    but when the consensus driver shares one detection key across members
    (ConsensusConfig.align_frac endgame), members facing the same
    degenerate choice between the same candidate communities now draw the
    same noise and break the tie the same way.  That collapses exactly the
    modularity-degenerate disagreements that keep the last few percent of
    consensus edges mid-weight for rounds (round-1 measurement: 5 rounds on
    planted-100k where near-deterministic CPU louvain needs 1).
    Unused label ids map to the sentinel ``n``.
    """
    return jnp.full((n,), n, jnp.int32).at[
        jnp.clip(labels, 0, n - 1)].min(jnp.arange(n, dtype=jnp.int32))


def _theta_score(gain: jax.Array, noise_u: jax.Array, valid: jax.Array,
                 theta: float, m2: jax.Array) -> jax.Array:
    """Candidate scores for theta-randomized refinement (Leiden).

    Restricted to strictly-positive gains and Gumbel-perturbed: the argmax
    then samples a candidate with probability proportional to
    exp(gain * 2m / theta) — leidenalg's merge distribution
    (fast_consensus.py:121-123 semantics; theta in leidenalg's unnormalized
    gain units, our gains being /2m-normalized).
    """
    g = seg.gumbel_from_uniform(noise_u)
    return jnp.where(valid & (gain > 0),
                     gain + (jnp.float32(theta) / m2) * g, -jnp.inf)


def _gain_runs(slab: GraphSlab, labels: jax.Array
               ) -> Tuple[seg.Runs, jax.Array, jax.Array]:
    """Candidate runs (i, C, k_i_in(C)) + node strengths + community totals.

    Self-loops (present in aggregated graphs) are excluded from k_i_in — a
    node's self weight moves with it and cancels in gain comparisons — but
    included in strengths/Sigma_tot (each self-loop contributes twice, the
    standard convention).

    A zero-weight synthetic candidate (i, c_i) per node guarantees the "stay"
    option is always scored, even for nodes with no intra-community edge.
    """
    n = slab.n_nodes
    srcd, dstd, wd, ad = slab.directed()
    strength = slab.strengths()
    sigma_tot = jax.ops.segment_sum(
        strength, jnp.clip(labels, 0, n - 1), num_segments=n)

    not_loop = ad & (srcd != dstd)
    cand_node = jnp.concatenate([srcd, jnp.arange(n, dtype=jnp.int32)])
    cand_label = jnp.concatenate([labels[dstd], labels])
    cand_w = jnp.concatenate([wd, jnp.zeros((n,), jnp.float32)])
    cand_valid = jnp.concatenate([not_loop, jnp.ones((n,), bool)])
    runs = seg.node_label_runs(cand_node, cand_label, cand_w, cand_valid, n)
    return runs, strength, sigma_tot


def _move_step(slab: GraphSlab, labels: jax.Array, key: jax.Array,
               m2: jax.Array, gamma: float = 1.0, theta: float = 0.0
               ) -> Tuple[jax.Array, jax.Array]:
    """One synchronous sweep via the exact sorted-run reduction.

    Returns ``(best_label, want)``; the caller (local_move) decides which
    wanted moves to apply (swap-break masking).  ``theta > 0`` switches to
    refinement scoring (:func:`_theta_score`): positive-gain candidates
    only, Gumbel-sampled, no stay margin.
    """
    n = slab.n_nodes
    k_tie = key
    runs, strength, sigma_tot = _gain_runs(slab, labels)

    k_i = strength[jnp.clip(runs.node, 0, n - 1)]
    sig = sigma_tot[jnp.clip(runs.label, 0, n - 1)]
    own = runs.label == labels[jnp.clip(runs.node, 0, n - 1)]
    # gain of node i joining C (with i removed from its current community):
    # k_i_in(C) - k_i * (Sigma_tot(C) - [i in C] k_i) / 2m
    gain = runs.total - gamma * k_i * (sig - jnp.where(own, k_i, 0.0)) / m2
    rep = _community_reps(labels, n)[jnp.clip(runs.label, 0, n - 1)]
    if theta > 0.0:
        u = seg.pair_jitter(k_tie, runs.node, rep, 1.0)
        score = _theta_score(gain, u, runs.valid & ~own, theta, m2)
        best, _, has_any = seg.argmax_label_per_node(
            runs.node, score, runs.label, runs.valid, n)
        return best, has_any & (best >= 0) & (best != labels)
    # pair-keyed: tie-breaks must not depend on run positions, which shift
    # with slab capacity (segment.pair_jitter); rep-keyed for cross-member
    # alignment (_community_reps)
    score = gain + seg.pair_jitter(k_tie, runs.node, rep, _JITTER_REL / m2)

    best, best_score, has_any = seg.argmax_label_per_node(
        runs.node, score, runs.label, runs.valid, n)
    # unjittered stay score per node (the own-label run; nodes without one —
    # no intra-community edge — fall back to the synthetic zero-weight run's
    # gain, which _gain_runs guarantees exists)
    stay = jax.ops.segment_max(
        jnp.where(runs.valid & own, gain, -jnp.inf),
        jnp.where(runs.valid & own, runs.node, n),
        num_segments=n + 1)[:-1]
    want = has_any & (best != labels) & (best >= 0) & \
        (best_score > stay + _MARGIN_REL / m2)
    return best, want


def _dense_weights(slab: GraphSlab) -> jax.Array:
    """Dense symmetric weight matrix float32[N, N], zero diagonal.

    Input to the matmul move path.  Depends only on the slab, so under the
    ensemble vmap it is built once and shared by all n_p members.  Self-loop
    weight is excluded (it moves with the node and cancels in gain
    comparisons, same convention as _gain_runs).
    """
    n = slab.n_nodes
    srcd, dstd, wd, ad = slab.directed()
    w = jnp.where(ad & (srcd != dstd), wd, 0.0)
    return jnp.zeros((n, n), jnp.float32).at[
        jnp.clip(srcd, 0, n - 1), jnp.clip(dstd, 0, n - 1)].add(w)


def _move_step_matmul(W: jax.Array, labels: jax.Array, key: jax.Array,
                      m2: jax.Array, strength: jax.Array,
                      gamma: float = 1.0, theta: float = 0.0
                      ) -> Tuple[jax.Array, jax.Array]:
    """One synchronous sweep via one MXU matmul (graphs with N <= MATMUL_MAX_N).

    k_i_in(C) for *every* community at once is ``S = W @ onehot(labels)`` —
    a single [N,N]x[N,N] matmul — instead of the per-neighbor-run sort the
    other paths do; on TPU this is the difference between systolic-array
    FLOPs and VPU sort passes (~40x per sweep at N=1000, measured).

    Candidates are restricted to communities the node has positive in-weight
    to, plus its own (``(S > 0) | own``) — the same set the sorted-run path
    scores, minus neighbors connected only by weight-0 edges (documented
    deviation; such moves never have positive gain).
    """
    n = W.shape[0]
    k_tie = key
    sigma_tot = jax.ops.segment_sum(
        strength, jnp.clip(labels, 0, n - 1), num_segments=n)
    onehot = jax.nn.one_hot(labels, n, dtype=W.dtype)
    # HIGHEST keeps f32-accurate gains on aggregated graphs whose summed
    # weights exceed bf16's integer range; still MXU-bound and cheap.
    s = jax.lax.dot(W, onehot, precision=jax.lax.Precision.HIGHEST)
    own = labels[:, None] == jnp.arange(n, dtype=jnp.int32)[None, :]
    k_i = strength[:, None]
    gain = s - gamma * k_i * (
        sigma_tot[None, :] - jnp.where(own, k_i, 0.0)) / m2
    # column c = community id c; rep-keyed jitter (see _community_reps)
    nodes = jnp.arange(n, dtype=jnp.int32)
    rep_row = _community_reps(labels, n)[None, :]
    if theta > 0.0:
        u = seg.pair_jitter(k_tie, nodes[:, None], rep_row, 1.0)
        score = _theta_score(gain, u, (s > 0) & ~own, theta, m2)
        best = jnp.argmax(score, axis=1).astype(jnp.int32)
        best_score = jnp.take_along_axis(score, best[:, None], axis=1)[:, 0]
        has = jnp.isfinite(best_score)
        return jnp.where(has, best, labels), has & (best != labels)
    score = jnp.where((s > 0) | own,
                      gain + seg.pair_jitter(k_tie, nodes[:, None], rep_row,
                                             _JITTER_REL / m2),
                      -jnp.inf)
    best = jnp.argmax(score, axis=1).astype(jnp.int32)
    best_score = jnp.take_along_axis(score, best[:, None], axis=1)[:, 0]
    stay = jnp.take_along_axis(gain, jnp.clip(labels, 0, n - 1)[:, None],
                               axis=1)[:, 0]
    want = (best != labels) & (best_score > stay + _MARGIN_REL / m2)
    return best, want


def _move_step_hash(slab: GraphSlab, labels: jax.Array, key: jax.Array,
                    m2: jax.Array, strength: jax.Array, n_buckets: int,
                    gamma: float = 1.0, theta: float = 0.0
                    ) -> Tuple[jax.Array, jax.Array]:
    """One synchronous sweep via hashed scatter-adds — no sorts at all.

    Every directed edge IS a move candidate (node -> neighbor's community);
    its k_i_in(C) comes from two-table hashed accumulation
    (ops/segment.py:HashTables), and the per-node argmax is two scatter-max
    passes.  Work is O(E) per sweep regardless of degree skew — on
    hub-heavy graphs (LFR mu=0.5: mean degree 12, max 518) this replaces the
    dense path's [N, d_cap] sort over 99% padding.

    Approximation (documented): a candidate colliding with another live
    (node, label) pair in both tables reads an overstated k_i_in; probability
    ~(E/B)^2 per pair at load factor <= 0.25, and keyed jitter already
    randomizes near-ties, so move quality is unaffected in practice (NMI
    parity vs the exact paths: tests/test_louvain.py::test_move_path_parity).

    Every pair that is *looked up* must also be *inserted*: the stay lookup
    (i, c_i) therefore gets a synthetic zero-weight entry exactly like
    _gain_runs's synthetic run — an absent pair would otherwise read
    min(t1, t2) over buckets owned by other pairs, overstating the stay
    score without bound and freezing nodes in place (size n_buckets with
    :func:`segment.hash_buckets_for`(2*capacity + n_nodes)).
    """
    n = slab.n_nodes
    k_tie = key
    sigma_tot = jax.ops.segment_sum(
        strength, jnp.clip(labels, 0, n - 1), num_segments=n)
    srcd, dstd, wd, ad = slab.directed()
    valid = ad & (srcd != dstd)
    src_c = jnp.clip(srcd, 0, n - 1)
    lab_dst = labels[jnp.clip(dstd, 0, n - 1)]
    nodes = jnp.arange(n, dtype=jnp.int32)

    tables = seg.build_hash_totals(
        jnp.concatenate([srcd, nodes]),
        jnp.concatenate([lab_dst, labels]),
        jnp.concatenate([wd, jnp.zeros((n,), jnp.float32)]),
        jnp.concatenate([valid, jnp.ones((n,), bool)]),
        n_buckets)
    tot = seg.lookup_hash_totals(tables, srcd, lab_dst)
    k_i = strength[src_c]
    sig = sigma_tot[jnp.clip(lab_dst, 0, n - 1)]
    own = lab_dst == labels[src_c]
    gain = tot - gamma * k_i * (sig - jnp.where(own, k_i, 0.0)) / m2
    rep_dst = _community_reps(labels, n)[jnp.clip(lab_dst, 0, n - 1)]
    if theta > 0.0:
        u = seg.pair_jitter(k_tie, srcd, rep_dst, 1.0)
        score = _theta_score(gain, u, valid & ~own, theta, m2)
        best, _, has_any = seg.scatter_argmax_label(
            srcd, score, lab_dst, valid, n)
        return best, has_any & (best >= 0) & (best != labels)
    # pair-keyed jitter: position-independent, so slab growth cannot
    # reorder tie-breaks (segment.pair_jitter); rep-keyed for cross-member
    # alignment (_community_reps)
    score = jnp.where(valid, gain + seg.pair_jitter(
        k_tie, srcd, rep_dst, _JITTER_REL / m2), -jnp.inf)
    best, best_score, has_any = seg.scatter_argmax_label(
        srcd, score, lab_dst, valid, n)

    # the "stay" candidate (always present in the tables via the synthetic
    # zero-weight entry above); unjittered — see _MARGIN_REL
    stay_tot = seg.lookup_hash_totals(tables, nodes, labels)
    stay = stay_tot - gamma * strength * (sigma_tot[jnp.clip(labels, 0, n - 1)]
                                          - strength) / m2

    want = has_any & (best_score > stay + _MARGIN_REL / m2) & \
        (best != labels) & (best >= 0)
    return best, want


def _move_step_hybrid(hyb: da.HybridAdj, slab: GraphSlab, labels: jax.Array,
                      key: jax.Array, m2: jax.Array, strength: jax.Array,
                      n_buckets: int, gamma: float = 1.0, theta: float = 0.0
                      ) -> Tuple[jax.Array, jax.Array]:
    """One synchronous sweep on the degree-partitioned layout.

    Non-hub nodes (degree <= d_hyb, ~95% of nodes) run the dense-row
    lowering over narrow Pallas-friendly rows that are *complete* for them;
    hub nodes run the hashed lowering over the compacted hub-edge prefix
    (ops/dense_adj.py:HybridAdj).  Same gain formula as every other path;
    the per-sweep scatter volume drops from O(capacity) to O(hub_cap) —
    the hash path's measured bottleneck on skewed-degree graphs
    (~31M scatter-updates/s, BASELINE.md lfr10k).
    """
    n = slab.n_nodes
    k_dense, k_hub = jax.random.split(key)
    sigma_tot = jax.ops.segment_sum(
        strength, jnp.clip(labels, 0, n - 1), num_segments=n)

    # dense side — identical to _move_step_dense on the masked rows
    tot = da.row_label_totals(hyb.adj, labels)
    k_i = strength[:, None]
    sig = sigma_tot[jnp.clip(tot.label, 0, n - 1)]
    own = tot.label == labels[:, None]
    gain = tot.total - gamma * k_i * (sig - jnp.where(own, k_i, 0.0)) / m2
    reps = _community_reps(labels, n)
    nodes = jnp.arange(n, dtype=jnp.int32)
    rep_d = reps[jnp.clip(tot.label, 0, n - 1)]
    if theta > 0.0:
        u = seg.pair_jitter(k_dense, nodes[:, None], rep_d, 1.0)
        score = _theta_score(gain, u, tot.is_head & ~own, theta, m2)
        best_d, want_d = da.best_candidate(tot, score, labels)
    else:
        jitter = seg.pair_jitter(k_dense, nodes[:, None], rep_d,
                                 _JITTER_REL / m2)
        score = jnp.where(tot.is_head, gain + jitter, -jnp.inf)
        best_d, want_d = da.best_candidate(tot, score, labels)
        best_score_d = jnp.max(score, axis=1)
        stay_d = jnp.max(jnp.where(own & tot.is_head, gain, -jnp.inf),
                         axis=1)
        want_d = want_d & (best_score_d > stay_d + _MARGIN_REL / m2)

    # hub side — hashed aggregation over the compacted prefix; synthetic
    # zero-weight stay entries for hub nodes (same invariant as
    # _move_step_hash: every looked-up pair must be inserted)
    lab_hdst = labels[jnp.clip(hyb.hdst, 0, n - 1)]
    rep_h = reps[jnp.clip(lab_hdst, 0, n - 1)]
    tables = seg.build_hash_totals(
        jnp.concatenate([hyb.hsrc, nodes]),
        jnp.concatenate([lab_hdst, labels]),
        jnp.concatenate([hyb.hw, jnp.zeros((n,), jnp.float32)]),
        jnp.concatenate([hyb.hvalid, hyb.is_hub]),
        n_buckets)
    tot_h = seg.lookup_hash_totals(tables, hyb.hsrc, lab_hdst)
    src_c = jnp.clip(hyb.hsrc, 0, n - 1)
    k_i_h = strength[src_c]
    sig_h = sigma_tot[jnp.clip(lab_hdst, 0, n - 1)]
    own_h = lab_hdst == labels[src_c]
    gain_h = tot_h - gamma * k_i_h * (sig_h -
                                      jnp.where(own_h, k_i_h, 0.0)) / m2
    if theta > 0.0:
        u = seg.pair_jitter(k_hub, hyb.hsrc, rep_h, 1.0)
        score_h = _theta_score(gain_h, u, hyb.hvalid & ~own_h, theta, m2)
        best_h, _, has_h = seg.scatter_argmax_label(
            hyb.hsrc, score_h, lab_hdst, hyb.hvalid, n)
        want_h = has_h & (best_h >= 0) & (best_h != labels)
    else:
        score_h = jnp.where(hyb.hvalid, gain_h + seg.pair_jitter(
            k_hub, hyb.hsrc, rep_h, _JITTER_REL / m2), -jnp.inf)
        best_h, bs_h, has_h = seg.scatter_argmax_label(
            hyb.hsrc, score_h, lab_hdst, hyb.hvalid, n)
        stay_tot = seg.lookup_hash_totals(tables, nodes, labels)
        stay_h = stay_tot - gamma * strength * (
            sigma_tot[jnp.clip(labels, 0, n - 1)] - strength) / m2
        want_h = has_h & (bs_h > stay_h + _MARGIN_REL / m2) & \
            (best_h != labels) & (best_h >= 0)

    best = jnp.where(hyb.is_hub, best_h, best_d)
    want = jnp.where(hyb.is_hub, want_h, want_d)
    return best, want


def _move_step_dense(adj: da.DenseAdj, slab: GraphSlab, labels: jax.Array,
                     key: jax.Array, m2: jax.Array, strength: jax.Array,
                     gamma: float = 1.0, theta: float = 0.0
                     ) -> Tuple[jax.Array, jax.Array]:
    """One synchronous sweep on the padded dense adjacency.

    Same gain formula and semantics as _move_step, but the per-(node, label)
    aggregation is a minor-axis row sort (ops/dense_adj.py) instead of a
    global lexsort — the TPU-side difference is ~an order of magnitude per
    sweep (see dense_adj module docstring).
    """
    n = slab.n_nodes
    k_tie = key
    sigma_tot = jax.ops.segment_sum(
        strength, jnp.clip(labels, 0, n - 1), num_segments=n)

    tot = da.row_label_totals(adj, labels)
    k_i = strength[:, None]
    sig = sigma_tot[jnp.clip(tot.label, 0, n - 1)]
    own = tot.label == labels[:, None]
    gain = tot.total - gamma * k_i * (sig - jnp.where(own, k_i, 0.0)) / m2
    nodes = jnp.arange(n, dtype=jnp.int32)
    rep = _community_reps(labels, n)[jnp.clip(tot.label, 0, n - 1)]
    if theta > 0.0:
        u = seg.pair_jitter(k_tie, nodes[:, None], rep, 1.0)
        score = _theta_score(gain, u, tot.is_head & ~own, theta, m2)
        return da.best_candidate(tot, score, labels)
    jitter = seg.pair_jitter(k_tie, nodes[:, None], rep, _JITTER_REL / m2)
    score = jnp.where(tot.is_head, gain + jitter, -jnp.inf)

    best, want = da.best_candidate(tot, score, labels)
    best_score = jnp.max(score, axis=1)
    stay = jnp.max(jnp.where(own & tot.is_head, gain, -jnp.inf), axis=1)
    want = want & (best_score > stay + _MARGIN_REL / m2)
    return best, want


class _FusedRows:
    """Label-independent inputs of the fused dense sweep, built once per
    local_move: neighbor rows extended with the node's own zero-weight
    candidate slot and padded to lane width (see
    ops/pallas_kernels.py:fused_move_rows)."""

    def __init__(self, slab: GraphSlab, adj: "da.DenseAdj",
                 strength: jax.Array, m2: jax.Array, gamma: float):
        from fastconsensus_tpu.ops import pallas_kernels as pk

        n = slab.n_nodes
        d1 = slab.d_cap + 1
        pad = (-d1) % 128
        self.d_self = slab.d_cap
        self.n = n
        nbr = jnp.concatenate(
            [jnp.where(adj.valid, adj.nbr, n),
             jnp.arange(n, dtype=jnp.int32)[:, None]], axis=1)
        self.nbr = jnp.pad(nbr, ((0, 0), (0, pad)), constant_values=n)
        w = jnp.concatenate(
            [jnp.where(adj.valid, adj.w, 0.0), jnp.zeros((n, 1))], axis=1)
        self.w = jnp.pad(w, ((0, 0), (0, pad)))
        valid = jnp.concatenate([adj.valid, jnp.ones((n, 1), bool)], axis=1)
        self.valid = jnp.pad(valid, ((0, 0), (0, pad)))
        k_i = strength
        coef = gamma * strength / m2
        jscale = jnp.full((n,), _JITTER_REL) / m2
        margin = jnp.full((n,), _MARGIN_REL) / m2
        rid = jnp.arange(n, dtype=jnp.int32).astype(jnp.float32)
        zero = jnp.zeros((n,), jnp.float32)
        self.scal_base = jnp.stack(
            [k_i, coef, jscale, margin, zero, rid, zero, zero], axis=1)
        self.pk = pk

    def step(self, labels: jax.Array, sigma_tot: jax.Array,
             key: jax.Array) -> Tuple[jax.Array, jax.Array]:
        n = self.n
        lab = jnp.where(self.valid,
                        labels[jnp.clip(self.nbr, 0, n - 1)],
                        self.pk.SENTINEL)
        sig = sigma_tot[jnp.clip(lab, 0, n - 1)]
        # 24-bit salt: it round-trips through the float32 scalar pack exactly
        salt = (jax.random.bits(key, (), jnp.uint32)
                & jnp.uint32(0xFFFFFF)).astype(jnp.float32)
        scal = self.scal_base.at[:, 4].set(salt)
        return self.pk.fused_move_rows(lab, self.w, sig, scal, self.d_self)


def _move_step_dense_fused(fused: _FusedRows, labels: jax.Array,
                           key: jax.Array, strength: jax.Array
                           ) -> Tuple[jax.Array, jax.Array]:
    """Fused-kernel dense sweep: same semantics as _move_step_dense, but
    totals/gains/argmax never leave VMEM (parity test:
    tests/test_louvain.py::test_fused_dense_step_matches_unfused)."""
    n = fused.n
    sigma_tot = jax.ops.segment_sum(
        strength, jnp.clip(labels, 0, n - 1), num_segments=n)
    return fused.step(labels, sigma_tot, key)


def _swap_break(key: jax.Array, slab: GraphSlab, want: jax.Array,
                adj: "da.DenseAdj" = None,
                hyb: "da.HybridAdj" = None) -> jax.Array:
    """Keep each wanting node only if it out-prioritizes its wanting neighbors.

    Synchronous best-gain moves oscillate: adjacent node pairs that each
    improve by joining the other's community swap forever when both move in
    the same sweep (a bernoulli subsample only makes the swap *probable* per
    sweep, so n_want floors at a few percent and never reaches 0 — measured
    ~400/10k nodes after 48 sweeps on LFR-10k).  Random per-sweep priorities
    make adjacent simultaneous moves impossible — the standard
    independent-set cure from GPU Louvain (arXiv:1805.10904) — while nodes
    with no wanting neighbor still move every sweep, so convergence speed for
    the bulk is unchanged and n_want can actually hit 0.
    """
    n = slab.n_nodes
    k_pri, k_gate = jax.random.split(key)
    pri = jax.random.uniform(k_pri, (n,))
    wpri = jnp.where(want, pri, -1.0)
    if hyb is not None:
        # hybrid: non-hub rows are complete, hub edges live in the prefix;
        # together they cover every adjacency unless hub_cap overflowed
        nbrp = jnp.where(hyb.adj.valid,
                         wpri[jnp.clip(hyb.adj.nbr, 0, n - 1)], -1.0)
        nbr_best = jnp.max(nbrp, axis=1)
        hub_best = jnp.full((n + 1,), -1.0).at[
            jnp.where(hyb.hvalid, hyb.hsrc, n)].max(
            wpri[jnp.clip(hyb.hdst, 0, n - 1)], mode="drop")[:-1]
        nbr_best = jnp.maximum(nbr_best, hub_best)
        # overflow coin-gate (see the dense branch) only when the prefix
        # actually overflowed, and only on hub nodes
        gate = (hyb.n_hub_overflow == 0) | ~hyb.is_hub | \
            jax.random.bernoulli(k_gate, 0.5, (n,))
        return want & (wpri > nbr_best) & gate
    if adj is not None:
        # dense rows: per-row max over neighbor priorities — far cheaper
        # than the directed-edge scatter (measured 123 ms -> ~25 ms on the
        # 100k config).  Overflowed hub rows may miss a wanting neighbor
        # beyond d_cap (the same candidates the move step itself does not
        # see), so the priority comparison alone cannot break a swap cycle
        # riding an overflow edge (ADVICE round 1: bounded only by
        # max_sweeps).  Nodes whose row is full are the only ones that can
        # be overflowing; when any overflow exists, an extra keyed coin on
        # exactly those rows makes any surviving symmetric swap die off
        # geometrically (P(both move) <= 1/4 per sweep) while leaving the
        # 99%+ non-full rows untouched.
        nbrp = jnp.where(adj.valid,
                         wpri[jnp.clip(adj.nbr, 0, n - 1)], -1.0)
        nbr_best = jnp.max(nbrp, axis=1)
        full = jnp.all(adj.valid, axis=1)
        gate = (adj.n_overflow == 0) | ~full | \
            jax.random.bernoulli(k_gate, 0.5, (n,))
        return want & (wpri > nbr_best) & gate
    srcd, dstd, _, ad = slab.directed()
    valid = ad & (srcd != dstd)
    nbr_best = jnp.full((n + 1,), -1.0).at[
        jnp.where(valid, srcd, n)].max(
        wpri[jnp.clip(dstd, 0, n - 1)], mode="drop")[:-1]
    return want & (wpri > nbr_best)


def _cap_hint(slab: GraphSlab) -> int:
    """Growth-stable stand-in for ``slab.capacity`` in heuristics.

    Path selection and hash-table sizing must not change when the consensus
    driver auto-grows the slab mid-run (replay determinism, graph.grow_slab)
    or when a user pre-sizes ``--capacity`` generously — both would
    otherwise silently change detection results.
    """
    return slab.cap_hint or slab.capacity


def select_move_path(slab: GraphSlab) -> str:
    """Which per-sweep lowering :func:`local_move` will use for this slab.

    One of "matmul", "dense", "hybrid", "hash", "runs" — best first:
    full-matrix MXU matmul for graphs up to MATMUL_MAX_N nodes; padded
    dense rows when the slab carries a neighbor capacity (``d_cap > 0``)
    *and* the padded-row area is within DENSE_OVER_HASH of the
    directed-edge count (skewed degree distributions make the rows mostly
    padding, and the per-sweep row sort pays for the padding); the
    degree-partitioned hybrid when the slab carries hybrid sizing and its
    *narrow* rows pass the same area test (skewed graphs — the lfr10k
    regime where pure hash is scatter-bound); hashed scatter-add
    aggregation otherwise (the d_cap=0 aggregated multi-level graphs).
    All capacity-derived terms use :func:`_cap_hint` (growth-stable).

    FCTPU_MOVE_PATH forces a path, best-effort: a forced path that cannot
    serve this slab (dense needs d_cap; hybrid needs d_hyb/hub_cap; matmul
    needs the N^2 matrix to fit — capped at 8*MATMUL_MAX_N to keep a forced
    run from faulting the chip) falls through to the exact sorted-run step
    ("runs", kept as the oracle the approximate hash path is tested
    against).

    The single source of truth for path choice — memory budgeting
    (models/base.py:ensemble_chunk) consults it too.
    """
    n = slab.n_nodes
    hybrid_ok = slab.d_hyb > 0 and slab.hub_cap > 0
    forced = os.environ.get("FCTPU_MOVE_PATH", "")
    if forced:
        if forced == "matmul" and n <= 8 * MATMUL_MAX_N:
            return "matmul"
        if forced == "dense" and slab.d_cap > 0:
            return "dense"
        if forced == "hybrid" and hybrid_ok:
            return "hybrid"
        if forced == "hash":
            return "hash"
        return "runs"
    if n <= MATMUL_MAX_N:
        return "matmul"
    if slab.d_cap > 0 and \
            n * (slab.d_cap + 1) <= DENSE_OVER_HASH * 2 * _cap_hint(slab):
        return "dense"
    if hybrid_ok and \
            n * (slab.d_hyb + 1) <= DENSE_OVER_HASH * 2 * _cap_hint(slab):
        return "hybrid"
    return "hash"


def sweep_temp_bytes(slab: GraphSlab) -> int:
    """Rough peak of one ensemble member's per-sweep temporaries.

    Feeds the ensemble-width budget (models/base.py:ensemble_chunk); the
    constant factors are deliberately generous.
    """
    path = select_move_path(slab)
    n = slab.n_nodes
    if path == "matmul":
        return 4 * 4 * n * n
    if path == "dense":
        return 6 * 4 * n * (slab.d_cap + 1)
    if path == "hybrid":
        return 6 * 4 * n * (slab.d_hyb + 1) + 10 * 4 * slab.hub_cap + \
            2 * 4 * seg.hash_buckets_for(slab.hub_cap + n)
    # hash / runs: a handful of directed-edge-sized arrays (sort operands or
    # scatter sources) plus, for hash, the two bucket tables (sized from the
    # growth-stable hint, matching local_move)
    return 10 * 4 * 2 * slab.capacity + \
        2 * 4 * seg.hash_buckets_for(2 * _cap_hint(slab) + n)


def local_move(slab: GraphSlab, key: jax.Array,
               init_labels: jax.Array = None,
               max_sweeps: int = 32, update_prob: float = 0.5,
               gamma: float = 1.0, stop_frac: float = 0.0,
               theta: float = 0.0,
               singleton_only: bool = False) -> jax.Array:
    """Run sweeps until (almost) no node can improve, or max_sweeps.
    Labels are community ids in [0, N); not compacted.

    Per-sweep lowering: :func:`select_move_path`.  ``update_prob`` is the
    probability a wanted move is applied during the early chaotic phase
    (the endgame switches to swap-break masking; see the body comment).

    ``stop_frac``: sweeps stop once fewer than ``max(1, stop_frac*N)``
    nodes still want to move.  Default 0 = run to the (near-)fixpoint:
    looser thresholds make each run a bit cheaper (the final ~1-2% of
    wants are modularity-degenerate churn with NMI long plateaued) but the
    per-member inconsistency costs far more consensus rounds than the
    sweeps saved (measured on LFR-1k: stop_frac=0.02 turned a 4-round
    consensus into 16 rounds).  Exposed for single-shot detection uses.

    ``theta`` + ``singleton_only`` switch to Leiden refinement mode
    (models/leiden.py): candidates restricted to strictly-positive gains
    and Gumbel-sampled proportional to exp(gain/theta) (_theta_score), and
    only nodes whose community is a singleton at sweep start may move.
    Grouped nodes never move again, so every group grows purely by
    accretion of nodes with an edge into it — refined communities are
    internally connected *by construction* (leidenalg's guarantee,
    fast_consensus.py:121-123; property test in tests/test_louvain.py).
    """
    n = slab.n_nodes
    if init_labels is None:
        init_labels = jnp.arange(n, dtype=jnp.int32)
    else:
        init_labels = init_labels.astype(jnp.int32)
    srcd, _, wd, ad = slab.directed()
    m2 = jnp.maximum(jnp.sum(jnp.where(ad, wd, 0.0)), 1e-9)

    path = select_move_path(slab)
    matmul = path == "matmul"
    dense = path == "dense"
    hybrid = path == "hybrid"
    hashed = path == "hash"
    strength = slab.strengths()
    fused = None
    if matmul:
        W = _dense_weights(slab)
    elif dense:
        from fastconsensus_tpu.ops import pallas_kernels as pk

        adj = da.build_dense_adjacency(slab)
        d1p = (slab.d_cap + 1) + (-(slab.d_cap + 1)) % 128
        # Opt-in only (FCTPU_FUSED=1): measured ~30% slower than the
        # unfused pipeline on the 100k config — the sweep is VPU-bound on
        # the O(D^2) compare, so fusing away the intermediate HBM traffic
        # buys nothing and the kernel overheads cost.  Kept (with its
        # parity test) as the starting point for future in-kernel-gather
        # work.
        if os.environ.get("FCTPU_FUSED", "") == "1" and pk.fits_vmem(d1p) \
                and theta == 0.0:  # fused kernel has no refinement scoring
            fused = _FusedRows(slab, adj, strength, m2, gamma)
    elif hybrid:
        hyb = da.build_hybrid(slab)
        n_buckets = seg.hash_buckets_for(slab.hub_cap + n)
    elif hashed:
        # bucket count from the growth-stable hint: auto-growth must not
        # change the collision pattern (and thus labels) mid-run
        n_buckets = seg.hash_buckets_for(2 * _cap_hint(slab) + n)

    stop_at = jnp.int32(max(1, int(stop_frac * n)))

    def cond(state):
        _, it, n_want = state
        return (n_want >= stop_at) & (it < max_sweeps)

    def body(state):
        labels, it, _ = state
        k_step, k_pri, k_mask = jax.random.split(
            jax.random.fold_in(key, it), 3)
        if matmul:
            best, want = _move_step_matmul(
                W, labels, k_step, m2, strength, gamma, theta)
        elif dense and fused is not None:
            best, want = _move_step_dense_fused(
                fused, labels, k_step, strength)
        elif dense:
            best, want = _move_step_dense(
                adj, slab, labels, k_step, m2, strength, gamma, theta)
        elif hybrid:
            best, want = _move_step_hybrid(
                hyb, slab, labels, k_step, m2, strength, n_buckets, gamma,
                theta)
        elif hashed:
            best, want = _move_step_hash(
                slab, labels, k_step, m2, strength, n_buckets, gamma, theta)
        else:
            best, want = _move_step(slab, labels, k_step, m2, gamma, theta)
        if singleton_only:
            # refinement: grouped nodes are frozen — groups grow only by
            # accretion, which is what guarantees internal connectivity
            sizes = jnp.zeros((n + 1,), jnp.int32).at[
                jnp.clip(labels, 0, n)].add(1, mode="drop")
            lab_c = jnp.clip(labels, 0, n - 1)
            want = want & (sizes[lab_c] == 1)
        n_want = jnp.sum(want.astype(jnp.int32))
        # Adaptive masking: while many nodes want to move (early, chaotic
        # phase) a bernoulli(update_prob) subsample merges fastest — swap
        # collisions are rare and harmless among thousands of movers.  Near
        # convergence the same subsample lets adjacent pairs swap forever,
        # so the endgame switches to priority swap-breaking, which makes
        # adjacent simultaneous moves impossible and lets n_want actually
        # reach 0.
        if singleton_only:
            # Joiner/anchor coin split: only joiner-coined nodes may move,
            # and a singleton group whose member is joiner-coined may not
            # be joined — so a move's target group is guaranteed stationary
            # this sweep.  Without it, several joiners targeting a node
            # that simultaneously departs end up grouped but pairwise
            # disconnected (caught by the connectivity property test).
            # Symmetric merge pairs resolve in expected two sweeps.
            coin = jax.random.bernoulli(k_mask, 0.5, (n,))
            # `want` is already singleton-gated, so want & coin is exactly
            # the superset of nodes that may depart this sweep
            departing_label = jnp.zeros((n + 1,), bool).at[
                jnp.clip(labels, 0, n)].max(want & coin, mode="drop")[:-1]
            ok = want & coin & \
                ~departing_label[jnp.clip(best, 0, n - 1)]
            return jnp.where(ok, best, labels), it + 1, n_want
        endgame = n_want <= jnp.int32(max(1, int(0.05 * n)))
        # Both mask variants are computed and selected with where: a
        # lax.cond here gets batched into select_n under the ensemble vmap
        # (both branches execute regardless) and only adds overhead
        # (measured +70% on the 100k config).
        bern = jax.random.bernoulli(k_mask, update_prob, (n,))
        swap = _swap_break(k_pri, slab, want, adj if dense else None,
                           hyb if hybrid else None)
        mask = jnp.where(endgame, swap, bern)
        return jnp.where(want & mask, best, labels), it + 1, n_want

    labels, _, _ = jax.lax.while_loop(
        cond, body, (init_labels, jnp.int32(0), jnp.int32(n)))
    return labels


def aggregate(slab: GraphSlab, labels: jax.Array) -> GraphSlab:
    """Community graph: supernode per community, summed edge weights.

    Built with the same sorted-run reduction as the vote kernels; self-loops
    (intra-community weight) are kept — they carry Sigma_in through levels.
    Capacity is preserved, keeping every level jittable at the same shapes.
    """
    n = slab.n_nodes
    c = seg.compact_labels(labels, n)
    cu = c[jnp.clip(slab.src, 0, n - 1)]
    cv = c[jnp.clip(slab.dst, 0, n - 1)]
    u = jnp.minimum(cu, cv)
    v = jnp.maximum(cu, cv)
    runs = seg.node_label_runs(u, v, slab.weight, slab.alive, n)
    # d_cap/d_hyb = 0: supernode degrees can exceed any per-node cap, so
    # multi-level moves on aggregated graphs take the hash/sorted-run paths.
    import dataclasses

    return dataclasses.replace(
        slab, src=jnp.where(runs.valid, runs.node, 0),
        dst=jnp.where(runs.valid, runs.label, 0),
        weight=runs.total, alive=runs.valid, d_cap=0, d_hyb=0, hub_cap=0)


def modularity_levels(slab: GraphSlab, key: jax.Array, n_levels: int = 2,
                      max_sweeps: int = 32, update_prob: float = 0.5
                      ) -> jax.Array:
    """Multi-level optimization; returns the *flattened* final labels.

    Level 0 reproduces ``local_move``; each further level aggregates and
    moves supernodes, then projects back — the dendrogram "top level".
    """
    n = slab.n_nodes
    labels = local_move(slab, jax.random.fold_in(key, 0),
                        max_sweeps=max_sweeps, update_prob=update_prob)
    flat = seg.compact_labels(labels, n)       # original node -> community
    cur = slab
    cur_assign = flat                          # cur's nodes -> communities
    for level in range(1, n_levels):
        cur = aggregate(cur, cur_assign)
        lvl = local_move(cur, jax.random.fold_in(key, level),
                         max_sweeps=max_sweeps, update_prob=update_prob)
        cur_assign = seg.compact_labels(lvl, n)
        flat = cur_assign[jnp.clip(flat, 0, n - 1)]
    return flat


def louvain_single(slab: GraphSlab, key: jax.Array,
                   init_labels: jax.Array = None,
                   max_sweeps: int = 32, update_prob: float = 0.5,
                   gamma: float = 1.0) -> jax.Array:
    """Level-0 partition (parity with partition_at_level(dend, 0), fc:148).

    ``gamma`` is the resolution parameter (gain = k_i_in - gamma k_i
    Sigma_tot / 2m): the reference parses ``-g`` but never uses it
    (merged_consensus.py:284-285, SURVEY.md 2.22.10); here it works.

    ``init_labels`` warm-starts the sweeps (consensus rounds reuse the
    previous round's labels; None = singleton start, identical to the
    reference's from-scratch runs)."""
    return seg.compact_labels(
        local_move(slab, key, init_labels=init_labels, max_sweeps=max_sweeps,
                   update_prob=update_prob, gamma=gamma), slab.n_nodes)


def warm_sweep_budget(default: int = 12) -> int:
    """Sweep cap for warm-started rounds (FCTPU_WARM_SWEEPS overrides).

    Under the ensemble vmap the sweep while-loop runs until the *slowest*
    member exits, so warm-started members' early exits buy nothing while a
    single straggler churns to max_sweeps (measured: warm round-2 detection
    as slow as cold round-1 on lfr10k).  Rounds >= 1 therefore run a
    capped-sweep detector variant: warm members need only adapt the
    previous round's labels to a modestly-changed graph, and a member that
    genuinely needs more sweeps simply carries its progress into the next
    round's warm start.
    """
    from fastconsensus_tpu.utils.env import env_int

    return max(1, env_int("FCTPU_WARM_SWEEPS", default))


def cold_sweep_budget(default: int = 32) -> int:
    """Sweep cap for cold (from-singletons) detection
    (FCTPU_COLD_SWEEPS overrides).

    On modularity-degenerate graphs the sweep loop never reaches a
    fixpoint — measured on lfr10k/mu0.5 (hybrid path), n_want plateaus at
    ~10% of nodes under every masking variant, so cold detection always
    burns its whole budget; and the accumulated churn actively hurts:
    single-run NMI 0.50 at 8 sweeps vs 0.42 at 32 (round-4 measurement;
    round 1 saw the same shape at 24 vs 48 sweeps).  The default stays 32
    (well-separated graphs exit early and never pay it); the knob exists
    to A/B the consensus-level effect per config before changing any
    default.
    """
    from fastconsensus_tpu.utils.env import env_int

    return max(1, env_int("FCTPU_COLD_SWEEPS", default))


def make_louvain(max_sweeps: Optional[int] = None,
                 update_prob: float = 0.5,
                 gamma: float = 1.0) -> Detector:
    if max_sweeps is None:
        max_sweeps = cold_sweep_budget()
    det = ensemble(functools.partial(
        louvain_single, max_sweeps=max_sweeps, update_prob=update_prob,
        gamma=gamma))
    det.warm_variant = ensemble(functools.partial(
        louvain_single, max_sweeps=min(warm_sweep_budget(), max_sweeps),
        update_prob=update_prob, gamma=gamma))
    # tie-break jitter is content-keyed (_community_reps), so endgame key
    # sharing (ConsensusConfig.align_frac) collapses degenerate
    # disagreements instead of merely deleting ensemble randomness
    det.supports_align = True
    return det


louvain = make_louvain()
