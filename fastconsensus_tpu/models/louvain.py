"""Louvain as synchronous parallel modularity local-move.

Replaces python-louvain's ``generate_dendrogram(randomize=True)`` +
``partition_at_level(dend, 0)`` (reference ``fast_consensus.py:148`` — note
the reference uses the dendrogram's *finest* level, i.e. the partition after
the first local-move phase converges, not the top level).

python-louvain moves one node at a time in random sweep order.  On TPU the
move step is data-parallel (the GPU-Louvain formulation, arXiv:1805.10904):

* every node computes, in one sorted-run segment reduction
  (ops/segment.py), its modularity gain for joining each neighboring
  community:  gain(i -> C) = k_i_in(C) - k_i * (Sigma_tot(C) - [C = c_i] k_i) / 2m
* keyed jitter randomizes ties (the ``randomize=True`` analog), and a keyed
  bernoulli *move mask* applies only a random subset of the best moves each
  sweep — the standard cure for the swap oscillations synchronous moves
  cause;
* sweeps repeat until no node can improve, which is exactly python-louvain's
  level-0 convergence criterion.

``modularity_levels`` adds the aggregation phase (community graph built by
the same run machinery) for multi-level optimization — the backend of the
leiden detector and of final-quality-oriented uses; the louvain detector
itself returns level-0 labels for parity with the reference.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from fastconsensus_tpu.graph import GraphSlab
from fastconsensus_tpu.models.base import Detector, ensemble
from fastconsensus_tpu.ops import dense_adj as da
from fastconsensus_tpu.ops import segment as seg

_JITTER = 1e-5

# Widest graph the full-matrix (MXU) move path materializes: per ensemble
# member the sweep holds a few N x N arrays, so n_p * N^2 * ~16B must fit in
# HBM (n_p=200 at N=1024 is ~3 GB).  Larger graphs take the padded-row or
# sorted-run paths.
MATMUL_MAX_N = 1024


def _gain_runs(slab: GraphSlab, labels: jax.Array
               ) -> Tuple[seg.Runs, jax.Array, jax.Array]:
    """Candidate runs (i, C, k_i_in(C)) + node strengths + community totals.

    Self-loops (present in aggregated graphs) are excluded from k_i_in — a
    node's self weight moves with it and cancels in gain comparisons — but
    included in strengths/Sigma_tot (each self-loop contributes twice, the
    standard convention).

    A zero-weight synthetic candidate (i, c_i) per node guarantees the "stay"
    option is always scored, even for nodes with no intra-community edge.
    """
    n = slab.n_nodes
    srcd, dstd, wd, ad = slab.directed()
    strength = slab.strengths()
    sigma_tot = jax.ops.segment_sum(
        strength, jnp.clip(labels, 0, n - 1), num_segments=n)

    not_loop = ad & (srcd != dstd)
    cand_node = jnp.concatenate([srcd, jnp.arange(n, dtype=jnp.int32)])
    cand_label = jnp.concatenate([labels[dstd], labels])
    cand_w = jnp.concatenate([wd, jnp.zeros((n,), jnp.float32)])
    cand_valid = jnp.concatenate([not_loop, jnp.ones((n,), bool)])
    runs = seg.node_label_runs(cand_node, cand_label, cand_w, cand_valid, n)
    return runs, strength, sigma_tot


def _move_step(slab: GraphSlab, labels: jax.Array, key: jax.Array,
               m2: jax.Array, update_prob: float, gamma: float = 1.0
               ) -> Tuple[jax.Array, jax.Array]:
    """One synchronous sweep.  Returns (new_labels, n_want_move)."""
    n = slab.n_nodes
    k_tie, k_mask = jax.random.split(key)
    runs, strength, sigma_tot = _gain_runs(slab, labels)

    k_i = strength[jnp.clip(runs.node, 0, n - 1)]
    sig = sigma_tot[jnp.clip(runs.label, 0, n - 1)]
    own = runs.label == labels[jnp.clip(runs.node, 0, n - 1)]
    # gain of node i joining C (with i removed from its current community):
    # k_i_in(C) - k_i * (Sigma_tot(C) - [i in C] k_i) / 2m
    gain = runs.total - gamma * k_i * (sig - jnp.where(own, k_i, 0.0)) / m2
    score = gain + seg.uniform_jitter(k_tie, gain.shape, _JITTER)

    best, _, has_any = seg.argmax_label_per_node(
        runs.node, score, runs.label, runs.valid, n)
    want = has_any & (best != labels) & (best >= 0)
    n_want = jnp.sum(want.astype(jnp.int32))
    mask = jax.random.bernoulli(k_mask, update_prob, (n,))
    return jnp.where(want & mask, best, labels), n_want


def _dense_weights(slab: GraphSlab) -> jax.Array:
    """Dense symmetric weight matrix float32[N, N], zero diagonal.

    Input to the matmul move path.  Depends only on the slab, so under the
    ensemble vmap it is built once and shared by all n_p members.  Self-loop
    weight is excluded (it moves with the node and cancels in gain
    comparisons, same convention as _gain_runs).
    """
    n = slab.n_nodes
    srcd, dstd, wd, ad = slab.directed()
    w = jnp.where(ad & (srcd != dstd), wd, 0.0)
    return jnp.zeros((n, n), jnp.float32).at[
        jnp.clip(srcd, 0, n - 1), jnp.clip(dstd, 0, n - 1)].add(w)


def _move_step_matmul(W: jax.Array, labels: jax.Array, key: jax.Array,
                      m2: jax.Array, strength: jax.Array,
                      update_prob: float, gamma: float = 1.0
                      ) -> Tuple[jax.Array, jax.Array]:
    """One synchronous sweep via one MXU matmul (graphs with N <= MATMUL_MAX_N).

    k_i_in(C) for *every* community at once is ``S = W @ onehot(labels)`` —
    a single [N,N]x[N,N] matmul — instead of the per-neighbor-run sort the
    other paths do; on TPU this is the difference between systolic-array
    FLOPs and VPU sort passes (~40x per sweep at N=1000, measured).

    Candidates are restricted to communities the node has positive in-weight
    to, plus its own (``(S > 0) | own``) — the same set the sorted-run path
    scores, minus neighbors connected only by weight-0 edges (documented
    deviation; such moves never have positive gain).
    """
    n = W.shape[0]
    k_tie, k_mask = jax.random.split(key)
    sigma_tot = jax.ops.segment_sum(
        strength, jnp.clip(labels, 0, n - 1), num_segments=n)
    onehot = jax.nn.one_hot(labels, n, dtype=W.dtype)
    # HIGHEST keeps f32-accurate gains on aggregated graphs whose summed
    # weights exceed bf16's integer range; still MXU-bound and cheap.
    s = jax.lax.dot(W, onehot, precision=jax.lax.Precision.HIGHEST)
    own = labels[:, None] == jnp.arange(n, dtype=jnp.int32)[None, :]
    k_i = strength[:, None]
    gain = s - gamma * k_i * (
        sigma_tot[None, :] - jnp.where(own, k_i, 0.0)) / m2
    score = jnp.where((s > 0) | own,
                      gain + seg.uniform_jitter(k_tie, gain.shape, _JITTER),
                      -jnp.inf)
    best = jnp.argmax(score, axis=1).astype(jnp.int32)
    want = best != labels
    n_want = jnp.sum(want.astype(jnp.int32))
    mask = jax.random.bernoulli(k_mask, update_prob, (n,))
    return jnp.where(want & mask, best, labels), n_want


def _move_step_dense(adj: da.DenseAdj, slab: GraphSlab, labels: jax.Array,
                     key: jax.Array, m2: jax.Array, strength: jax.Array,
                     update_prob: float, gamma: float = 1.0
                     ) -> Tuple[jax.Array, jax.Array]:
    """One synchronous sweep on the padded dense adjacency.

    Same gain formula and semantics as _move_step, but the per-(node, label)
    aggregation is a minor-axis row sort (ops/dense_adj.py) instead of a
    global lexsort — the TPU-side difference is ~an order of magnitude per
    sweep (see dense_adj module docstring).
    """
    n = slab.n_nodes
    k_tie, k_mask = jax.random.split(key)
    sigma_tot = jax.ops.segment_sum(
        strength, jnp.clip(labels, 0, n - 1), num_segments=n)

    tot = da.row_label_totals(adj, labels)
    k_i = strength[:, None]
    sig = sigma_tot[jnp.clip(tot.label, 0, n - 1)]
    own = tot.label == labels[:, None]
    gain = tot.total - gamma * k_i * (sig - jnp.where(own, k_i, 0.0)) / m2
    jitter = seg.uniform_jitter(k_tie, gain.shape, _JITTER)
    score = jnp.where(tot.is_head, gain + jitter, -jnp.inf)

    best, want = da.best_candidate(tot, score, labels)
    n_want = jnp.sum(want.astype(jnp.int32))
    mask = jax.random.bernoulli(k_mask, update_prob, (n,))
    return jnp.where(want & mask, best, labels), n_want


def local_move(slab: GraphSlab, key: jax.Array,
               init_labels: jax.Array = None,
               max_sweeps: int = 48, update_prob: float = 0.5,
               gamma: float = 1.0) -> jax.Array:
    """Run sweeps until no node can improve (or max_sweeps).  Labels are
    community ids in [0, N); not compacted.

    Path selection, best first: full-matrix MXU matmul for graphs up to
    MATMUL_MAX_N nodes; padded dense rows when the slab carries a neighbor
    capacity (``d_cap > 0``, set by pack_edges); exact sorted-run reduction
    otherwise (aggregated multi-level graphs, hub-heavy degree
    distributions).
    """
    n = slab.n_nodes
    if init_labels is None:
        init_labels = jnp.arange(n, dtype=jnp.int32)
    srcd, _, wd, ad = slab.directed()
    m2 = jnp.maximum(jnp.sum(jnp.where(ad, wd, 0.0)), 1e-9)

    matmul = n <= MATMUL_MAX_N
    dense = not matmul and slab.d_cap > 0
    if matmul:
        W = _dense_weights(slab)
        strength = slab.strengths()
    elif dense:
        adj = da.build_dense_adjacency(slab)
        strength = slab.strengths()

    def cond(state):
        _, it, n_want = state
        return (n_want > 0) & (it < max_sweeps)

    def body(state):
        labels, it, _ = state
        k = jax.random.fold_in(key, it)
        if matmul:
            new_labels, n_want = _move_step_matmul(
                W, labels, k, m2, strength, update_prob, gamma)
        elif dense:
            new_labels, n_want = _move_step_dense(
                adj, slab, labels, k, m2, strength, update_prob, gamma)
        else:
            new_labels, n_want = _move_step(slab, labels, k, m2, update_prob,
                                            gamma)
        return new_labels, it + 1, n_want

    labels, _, _ = jax.lax.while_loop(
        cond, body, (init_labels, jnp.int32(0), jnp.int32(1)))
    return labels


def aggregate(slab: GraphSlab, labels: jax.Array) -> GraphSlab:
    """Community graph: supernode per community, summed edge weights.

    Built with the same sorted-run reduction as the vote kernels; self-loops
    (intra-community weight) are kept — they carry Sigma_in through levels.
    Capacity is preserved, keeping every level jittable at the same shapes.
    """
    n = slab.n_nodes
    c = seg.compact_labels(labels, n)
    cu = c[jnp.clip(slab.src, 0, n - 1)]
    cv = c[jnp.clip(slab.dst, 0, n - 1)]
    u = jnp.minimum(cu, cv)
    v = jnp.maximum(cu, cv)
    runs = seg.node_label_runs(u, v, slab.weight, slab.alive, n)
    # d_cap=0: supernode degrees can exceed any per-node cap, so multi-level
    # moves on aggregated graphs take the sorted-run path.
    return GraphSlab(src=jnp.where(runs.valid, runs.node, 0),
                     dst=jnp.where(runs.valid, runs.label, 0),
                     weight=runs.total, alive=runs.valid, n_nodes=n, d_cap=0)


def modularity_levels(slab: GraphSlab, key: jax.Array, n_levels: int = 2,
                      max_sweeps: int = 48, update_prob: float = 0.5
                      ) -> jax.Array:
    """Multi-level optimization; returns the *flattened* final labels.

    Level 0 reproduces ``local_move``; each further level aggregates and
    moves supernodes, then projects back — the dendrogram "top level".
    """
    n = slab.n_nodes
    labels = local_move(slab, jax.random.fold_in(key, 0),
                        max_sweeps=max_sweeps, update_prob=update_prob)
    flat = seg.compact_labels(labels, n)       # original node -> community
    cur = slab
    cur_assign = flat                          # cur's nodes -> communities
    for level in range(1, n_levels):
        cur = aggregate(cur, cur_assign)
        lvl = local_move(cur, jax.random.fold_in(key, level),
                         max_sweeps=max_sweeps, update_prob=update_prob)
        cur_assign = seg.compact_labels(lvl, n)
        flat = cur_assign[jnp.clip(flat, 0, n - 1)]
    return flat


def louvain_single(slab: GraphSlab, key: jax.Array,
                   max_sweeps: int = 48, update_prob: float = 0.5,
                   gamma: float = 1.0) -> jax.Array:
    """Level-0 partition (parity with partition_at_level(dend, 0), fc:148).

    ``gamma`` is the resolution parameter (gain = k_i_in - gamma k_i
    Sigma_tot / 2m): the reference parses ``-g`` but never uses it
    (merged_consensus.py:284-285, SURVEY.md 2.22.10); here it works."""
    return seg.compact_labels(
        local_move(slab, key, max_sweeps=max_sweeps,
                   update_prob=update_prob, gamma=gamma), slab.n_nodes)


def make_louvain(max_sweeps: int = 48, update_prob: float = 0.5,
                 gamma: float = 1.0) -> Detector:
    return ensemble(functools.partial(
        louvain_single, max_sweeps=max_sweeps, update_prob=update_prob,
        gamma=gamma))


louvain = make_louvain()
