from fastconsensus_tpu.parallel.sharding import (  # noqa: F401
    EDGE_AXIS,
    ENSEMBLE_AXIS,
    initialize_multihost,
    keys_sharding,
    labels_sharding,
    make_mesh,
    pad_n_p,
    shard_keys,
    shard_slab,
    slab_sharding,
)
