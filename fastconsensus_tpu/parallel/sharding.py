"""Device-mesh scale-out: ensemble- and edge-sharding for the consensus loop.

The reference's only parallelism is a ``multiprocessing.Pool`` on the leiden
path (``fast_consensus.py:210-211``) — full-graph broadcast by fork+pickle,
results gathered by pickle return (SURVEY.md §2.24).  The TPU-native design
replaces that with a ``jax.sharding.Mesh`` and lets XLA's SPMD partitioner
insert the collectives:

* **ensemble axis ``"p"`` (the DP analog)** — the ``n_p`` independent
  detection runs shard over chips: ``keys[n_p, ...]`` is split along axis 0,
  the graph slab is replicated, and each chip runs its shard of the ensemble.
  Co-membership counting then contracts the ``n_p`` axis, which XLA lowers to
  one ``psum`` over ICI — the only communication in the whole round.
* **edge axis ``"e"`` (the SP/TP analog)** — the COO slab itself shards
  along capacity, distributing the *resident* graph across chips' HBM.
  The consensus tail runs edge-LOCAL under an explicit ``jax.shard_map``
  (ops/sharded_tail.py): co-membership, thresholding, convergence,
  sort-free wedge sampling, hash-dedup insertion and singleton repair all
  operate on each device's local chunk, communicating [N]-sized node
  vectors, the closure insert's hash tables (edge-count-proportional but
  shard-count-independent), and scalars — the slab's per-edge arrays
  never cross the interconnect, and results are bit-identical to the
  unsharded tail
  (round-2's GSPMD tail re-gathered the slab 19x per round; measured
  round 3: 5 slab-sized all-gathers remain, all inside the detection's
  own per-call layout builds — tests/test_parallel.py pins this).

No hand-rolled communication backend exists or is needed (the reference has
none either): `jit` + `NamedSharding` over the mesh IS the distributed
backend, and it rides ICI on a real slice and DCN across hosts unchanged.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fastconsensus_tpu.graph import GraphSlab

ENSEMBLE_AXIS = "p"
EDGE_AXIS = "e"


def make_mesh(ensemble: Optional[int] = None,
              edge: int = 1,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a (ensemble, edge) mesh over the available devices.

    ``ensemble=None`` takes every device not claimed by the edge axis.  A
    1-sized axis still exists in the mesh (specs mentioning it are no-ops),
    so callers can always annotate with both axis names.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if ensemble is None:
        if n % edge:
            raise ValueError(f"{n} devices not divisible by edge={edge}")
        ensemble = n // edge
    if ensemble * edge > n:
        raise ValueError(
            f"mesh {ensemble}x{edge} needs {ensemble * edge} devices, "
            f"have {n}")
    grid = np.asarray(devices[: ensemble * edge]).reshape(ensemble, edge)
    return Mesh(grid, (ENSEMBLE_AXIS, EDGE_AXIS))


def keys_sharding(mesh: Mesh) -> NamedSharding:
    """Ensemble keys [n_p, ...] split along the ensemble axis."""
    return NamedSharding(mesh, P(ENSEMBLE_AXIS))


def labels_sharding(mesh: Mesh) -> NamedSharding:
    """Labels [n_p, N] split along the ensemble axis, nodes replicated."""
    return NamedSharding(mesh, P(ENSEMBLE_AXIS, None))


def slab_sharding(mesh: Mesh) -> NamedSharding:
    """Edge slab arrays [capacity] split along the edge axis.

    Used as a pytree-prefix sharding for every GraphSlab leaf (all leaves are
    capacity-length 1-D arrays; ``n_nodes`` is static metadata, not a leaf).
    With ``edge=1`` this replicates — the pure-ensemble configuration.
    """
    return NamedSharding(mesh, P(EDGE_AXIS))


def shard_slab(slab: GraphSlab, mesh: Mesh) -> GraphSlab:
    """Place a slab on the mesh (pads capacity to the edge-axis multiple)."""
    from fastconsensus_tpu.graph import grow_slab

    e = mesh.shape[EDGE_AXIS]
    padded = math.ceil(slab.capacity / e) * e
    slab = grow_slab(slab, padded)  # dead-slot tail; result-preserving
    return jax.device_put(slab, slab_sharding(mesh))


def pad_n_p(n_p: int, mesh: Mesh) -> int:
    """Round n_p up to a multiple of the ensemble axis size."""
    p = mesh.shape[ENSEMBLE_AXIS]
    return math.ceil(n_p / p) * p


def shard_keys(keys: jax.Array, mesh: Mesh) -> jax.Array:
    if keys.shape[0] % mesh.shape[ENSEMBLE_AXIS]:
        raise ValueError(
            f"n_p={keys.shape[0]} not divisible by ensemble axis "
            f"{mesh.shape[ENSEMBLE_AXIS]}; use pad_n_p")
    return put_keys(keys, keys_sharding(mesh))


def _key_data_sharding(keys: jax.Array, sharding: NamedSharding
                       ) -> NamedSharding:
    """Extend an ensemble-axis spec over the trailing key-data dims.

    Typed PRNG key arrays carry a hidden uint32 payload dim; GSPMD
    validates specs against the RAW shape, so ``P("p")`` on keys[n_p]
    (raw ``u32[n_p, 2]``) is a rank mismatch on jax 0.4.x (newer jax
    extends the spec itself).  Always spelling the payload dims out
    keeps both versions happy.
    """
    data = jax.random.key_data(keys)
    spec = P(*(tuple(sharding.spec) +
               (None,) * (data.ndim - len(sharding.spec))))
    return NamedSharding(sharding.mesh, spec)


def put_keys(keys: jax.Array, sharding: NamedSharding) -> jax.Array:
    """``device_put`` for typed PRNG key arrays (see _key_data_sharding)."""
    data = jax.device_put(jax.random.key_data(keys),
                          _key_data_sharding(keys, sharding))
    return jax.random.wrap_key_data(data)


def constrain_keys(keys: jax.Array, sharding: NamedSharding) -> jax.Array:
    """``with_sharding_constraint`` for typed PRNG key arrays (jittable)."""
    data = jax.lax.with_sharding_constraint(
        jax.random.key_data(keys), _key_data_sharding(keys, sharding))
    return jax.random.wrap_key_data(data)


def replicate_slab(slab: GraphSlab, mesh: Mesh) -> GraphSlab:
    """Constrain every slab leaf to replicated (detection-side view).

    Detection consumes the whole graph on every chip regardless — GSPMD
    re-gathers an edge-sharded slab inside the detection's layout builds
    (module notes above) — so pinning the gather to the jit boundary
    costs nothing it wasn't already paying.  It also sidesteps a
    measured XLA:CPU SPMD miscompile: a scatter/segment-sum whose
    operand stays sharded on ``"e"`` interleaves per-device partials
    instead of summing them (observed on jax 0.4.37's virtual CPU mesh;
    tests/test_parallel.py bitwise parity would catch a regression).
    The explicit shard_map tail keeps its ``P("e")`` view — shard_map
    reshards at its own boundary.
    """
    import dataclasses

    rep = NamedSharding(mesh, P())
    con = lambda x: jax.lax.with_sharding_constraint(x, rep)  # noqa: E731
    return dataclasses.replace(slab, src=con(slab.src), dst=con(slab.dst),
                               weight=con(slab.weight),
                               alive=con(slab.alive))


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> int:
    """Join a multi-host run; returns this process's index.

    The reference's only scale-out is a fork+pickle pool on one machine
    (fast_consensus.py:210-211).  Here multi-host needs no custom backend
    either: ``jax.distributed.initialize`` brings every host's chips into
    one global device set, ``make_mesh`` (which already uses the *global*
    ``jax.devices()``) lays both axes across them, and the same
    ``NamedSharding`` annotations that ride ICI within a slice ride DCN
    across hosts — XLA's SPMD partitioner picks the transport, not us.

    Args default from the standard cluster-env variables
    (COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID, or the TPU pod
    metadata on Cloud TPU).  Call once, before any jax computation.
    Single-process runs may skip this entirely.
    """
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return jax.process_index()
