"""Static-shape, device-resident graph substrate.

The reference keeps all graph state in a mutable ``networkx.Graph``
(dict-of-dicts; see reference ``fast_consensus.py:131-136``) and crosses into
igraph's C structure per detection run (``fast_consensus.py:41-52``).  On TPU
that design is untenable: XLA wants static shapes and pure functions.

Here the graph is a **fixed-capacity COO edge slab**:

* ``src``/``dst``     int32[capacity]  canonical endpoints (src < dst),
* ``weight``          float32[capacity],
* ``alive``           bool[capacity]   validity mask.

"Edge deletion" (tau-thresholding, reference ``fast_consensus.py:163-168``) is
mask-out; "edge insertion" (triadic closure, ``fast_consensus.py:175-191``)
writes into free slots.  The edge universe grows by at most L edges per
consensus round, so a capacity of ``E0 + slack`` keeps every round jittable
with static shapes.  The host touches the graph exactly twice: one
``device_put`` of the packed slab at the start, one readback of final
memberships at the end (BASELINE.json north star).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# Widest per-node dense candidate row pack_edges will configure; beyond this
# the sorted-run kernels are the better (and exact) choice.
DENSE_D_MAX = 1024


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class GraphSlab:
    """Fixed-capacity undirected weighted graph in COO form.

    Edges are stored once in canonical orientation (``src < dst``).  Padding /
    dead slots have ``alive == False``; their ``src``/``dst`` content is
    meaningless and must never be read unmasked.

    ``n_nodes`` is static metadata (part of the jit cache key), not a traced
    array.
    """

    src: jax.Array     # int32[capacity]
    dst: jax.Array     # int32[capacity]
    weight: jax.Array  # float32[capacity]
    alive: jax.Array   # bool[capacity]
    n_nodes: int = dataclasses.field(metadata=dict(static=True))
    # Static per-node neighbor capacity for the dense (padded-row) kernels in
    # ops/dense_adj.py; 0 = "not computed" (kernels fall back to the
    # sorted-run path).  pack_edges sets it from the input degree histogram
    # with slack for triadic-closure growth.
    d_cap: int = dataclasses.field(default=0, metadata=dict(static=True))
    # Capacity at pack time, preserved across grow_slab: every
    # capacity-derived *heuristic* (move-path selection, hash-table sizing —
    # models/louvain.py) keys off this instead of the live capacity, so
    # mid-run auto-growth (and generous pre-sizing relative to a grown run)
    # can never flip a detection lowering and change results.  0 = "use
    # capacity" (hand-built slabs).
    cap_hint: int = dataclasses.field(default=0, metadata=dict(static=True))
    # Hybrid-path sizing (ops/dense_adj.py:build_hybrid): row width covering
    # ~p95 of input degrees (nodes above it are "hubs" whose move candidates
    # go through hashed aggregation instead of padded rows), and the static
    # budget for the compacted hub directed-edge prefix.  0 = hybrid
    # unavailable (aggregated supernode graphs, hand-built slabs).
    d_hyb: int = dataclasses.field(default=0, metadata=dict(static=True))
    hub_cap: int = dataclasses.field(default=0, metadata=dict(static=True))
    # Static capacity for the compacted aggregate-level slab
    # (models/leiden.py): the aggregate move otherwise runs the hash path
    # over every slot of THIS slab while only the alive fraction holds
    # aggregate edges — measured 18.3 -> 9.5 ms/member/sweep at half
    # capacity on lfr10k (runs/kernel_profile/profile.json, round 5).
    # Distinct aggregate pairs never exceed the alive edge count, so
    # agg_cap >= n_alive guarantees a lossless compaction; the driver
    # re-derives it from the live alive count alongside the other budgets
    # (derive_agg_sizing).  0 = compaction off (pre-r5 semantics).
    agg_cap: int = dataclasses.field(default=0, metadata=dict(static=True))

    @property
    def capacity(self) -> int:
        return self.src.shape[0]

    def num_alive(self) -> jax.Array:
        return jnp.sum(self.alive.astype(jnp.int32))

    def with_weights(self, weight: jax.Array, alive: Optional[jax.Array] = None
                     ) -> "GraphSlab":
        return dataclasses.replace(
            self, weight=weight, alive=self.alive if alive is None else alive)

    def directed(self) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
        """Both orientations of every edge: (srcd, dstd, weightd, alived).

        Shape 2*capacity.  This is the view all per-node reductions consume
        (neighbor votes, degrees, community statistics).
        """
        srcd = jnp.concatenate([self.src, self.dst])
        dstd = jnp.concatenate([self.dst, self.src])
        wd = jnp.concatenate([self.weight, self.weight])
        ad = jnp.concatenate([self.alive, self.alive])
        return srcd, dstd, wd, ad

    def degrees(self) -> jax.Array:
        """Alive-degree (edge count) per node, int32[n_nodes]."""
        srcd, _, _, ad = self.directed()
        seg = jnp.where(ad, srcd, self.n_nodes)
        return jax.ops.segment_sum(
            ad.astype(jnp.int32), seg, num_segments=self.n_nodes + 1)[:-1]

    def strengths(self) -> jax.Array:
        """Weighted degree per node, float32[n_nodes]."""
        srcd, _, wd, ad = self.directed()
        seg = jnp.where(ad, srcd, self.n_nodes)
        return jax.ops.segment_sum(
            jnp.where(ad, wd, 0.0), seg, num_segments=self.n_nodes + 1)[:-1]


def derive_dense_sizing(degree: np.ndarray, n_nodes: int) -> int:
    """Neighbor-row capacity for the dense kernels, from a degree histogram.

    The max degree plus 25% closure-growth slack, rounded to a
    lane-friendly multiple of 8.  (A 2x cap was tried first; the dense
    kernels' per-sweep cost is quadratic in the padded width, and on the
    100k stress config the extra headroom doubled the width for padding
    that was ~76% dead.)  When even this exceeds DENSE_D_MAX (hub/
    star-like degree distributions, where a dense [N, max_deg] adjacency
    would waste or exhaust memory), d_cap is 0 and the detection kernels
    take the hash/sorted-run paths instead — the cap never silently
    truncates *input* neighborhoods.  Nodes that triadic closure later
    grows past d_cap keep all edges in the slab (counts/convergence
    exact) and only lose the overflow from *move candidate* rows;
    consensus_round reports that count per round (RoundStats.n_overflow),
    and the driver re-derives the sizing from the live degree histogram
    when the overflow breaches policy.budgets_stale (round-4: static
    budgets starved under densification — n_hub_overflow hit 3.26M on
    lfr100k, VERDICT r3 Weak #4).
    """
    max_deg = int(degree.max(initial=0))
    want = min((5 * max_deg) // 4 + 8, max(n_nodes - 1, 1))
    want = int(((want + 7) // 8) * 8)
    return want if want <= DENSE_D_MAX else 0


def derive_hybrid_sizing(degree: np.ndarray, n_nodes: int,
                         n_edges: int) -> Tuple[int, int]:
    """Hybrid-path sizing (d_hyb, hub_cap) from a degree histogram.

    The partition point is chosen by a per-sweep COST MODEL, not a degree
    quantile: every row slot costs ~3 random-access ops per sweep (the
    labels/sigma/rep gathers — and random access is the hot sweep's
    binding resource, at ~100% of the measured scatter ceiling:
    BASELINE.md round-5 kernel profile), and every hub directed edge
    costs ~6 (two-table hash build + lookup + argmax scatters, over a
    1.5x-slack prefix).  Minimizing

        cost(d) = 3 * N * (d + 1) + 6 * hub_mass(d)

    over lane-multiples of 8 replaces round 2's p95-quantile rule, which
    ignored the row side entirely: on the densified lfr100k slab (mean
    degree ~46 after closure) p95 drove d_hyb to 168 — 50M row-gather
    ops/sweep — where the cost optimum serves the same graph several
    times cheaper by widening the hub prefix instead.  Hubs above the cut
    get hashed aggregation (ops/dense_adj.py:build_hybrid); 1.5x growth
    slack on the prefix as before.  Degenerate (0, 0) when no cut beats
    the pure-hash cost baseline (~8 random ops per directed edge slot) —
    near-uniform degree distributions, where the dense or hash paths
    already serve every node.  Shared by pack_edges and the driver's
    mid-run budget re-derivation — the sizing must be a pure function of
    the degree histogram so replays and resumes reproduce it (same
    contract as cap_hint).
    """
    if n_nodes <= 0 or n_edges <= 0:
        return 0, 0
    max_deg = int(degree.max(initial=0))
    hi = min(max(((max_deg + 7) // 8) * 8, 8), DENSE_D_MAX,
             max(n_nodes - 1, 1))
    cands = np.arange(8, hi + 1, 8, dtype=np.int64)
    if cands.size == 0:
        return 0, 0
    # hub_mass(d) = sum of degrees strictly above d, for every candidate
    # at once: sorted degrees + prefix sums + one searchsorted
    srt = np.sort(degree.astype(np.int64))
    csum = np.concatenate([[0], np.cumsum(srt)])
    total = int(csum[-1])
    idx = np.searchsorted(srt, cands, side="right")
    hub_mass = total - csum[idx]
    cost = 3 * n_nodes * (cands + 1) + 6 * hub_mass
    best = int(np.argmin(cost))
    # pure-hash baseline (~8 random ops per directed edge slot, round-5
    # kernel accounting): when no cut beats it the hybrid layout only
    # adds work — return degenerate and let select_move_path fall through
    if int(cost[best]) >= 8 * 2 * n_edges:
        return 0, 0
    d_hyb = int(cands[best])
    hub_cap = int((((3 * int(hub_mass[best])) // 2 + 64 + 7) // 8) * 8)
    return d_hyb, hub_cap


def derive_agg_sizing(n_alive: int) -> int:
    """Compacted-aggregate capacity from the live alive-edge count.

    ``n_alive`` bounds the distinct aggregate pairs (each alive edge maps
    to exactly one community pair), so this is lossless until closure
    densifies the slab past the slack; 12.5% + one lane-multiple covers
    ~1-2 rounds of measured closure growth (lfr10k: ~25k inserts/round on
    ~60-300k alive), and the driver refreshes it together with every
    d_cap/d_hyb/hub_cap re-derivation so agg growth rarely costs its own
    recompile.  Slack is deliberately tight: the per-sweep hash cost is
    linear in this capacity (the round-5 kernel profile), while a regrow
    is one (batched) recompile.
    """
    if n_alive <= 0:
        return 0
    want = n_alive + n_alive // 8 + 1024
    return ((want + 4095) // 4096) * 4096


def agg_compaction_active(slab: GraphSlab) -> bool:
    """Static gate: will the aggregate level run :func:`compact_alive`?

    Single source of truth shared by models/leiden.py (which compacts
    under exactly this condition) and the engine's per-round
    ``n_agg_overflow`` accounting (RoundStats), which bounds how many
    alive aggregate edges the compaction could silently drop.  Gated on
    the pack-time ``cap_hint``, not live capacity — the growth-stability
    contract (labels must not change when auto-growth resizes the slab).
    """
    return 0 < slab.agg_cap < (slab.cap_hint or slab.capacity)


def compact_alive(slab: GraphSlab, cap: int) -> GraphSlab:
    """Pack the alive edges into a fresh slab of static capacity ``cap``.

    Traced (jit/vmap-safe): one cumsum + four scatters over the source
    capacity, amortized across every subsequent per-sweep scan of the
    compact slab.  Alive slot order is preserved.  Alive edges ranked
    beyond ``cap`` are DROPPED — callers size ``cap`` with
    :func:`derive_agg_sizing` (>= the alive count at derivation time),
    and drops only ever affect move *candidates* of the aggregate level
    (the consensus slab itself is untouched).  Once closure grows the
    alive count past the slack, mild drops can PERSIST for several
    rounds: the driver refreshes agg_cap for free whenever any dense/hub
    budget regrows, but the standalone agg trigger is deliberately loose
    (25% past budget — policy.budgets_stale) so agg staleness alone
    rarely costs a recompile.  The stale window is no longer silent:
    every round reports ``n_agg_overflow`` (an upper bound on the drop,
    0 = provably lossless) in RoundStats / ``rounds.jsonl`` — see
    :func:`agg_compaction_active`.

    The result carries no dense/hybrid sizing (aggregate supernode degrees
    are unbounded) and ``cap_hint = cap`` so hash-bucket sizing tracks the
    compact shape.
    """
    pos = jnp.cumsum(slab.alive.astype(jnp.int32)) - 1
    ok = slab.alive & (pos < cap)
    tgt = jnp.where(ok, pos, cap)

    def scat(x, dtype):
        buf = jnp.zeros((cap + 1,), dtype)
        # not-ok lanes all write 0 to the spill slot `cap`, sliced off
        return buf.at[tgt].set(jnp.where(ok, x, 0))[:cap]

    n_keep = jnp.minimum(slab.num_alive(), cap)
    return GraphSlab(
        src=scat(slab.src, jnp.int32),
        dst=scat(slab.dst, jnp.int32),
        weight=scat(slab.weight, jnp.float32),
        alive=jnp.arange(cap, dtype=jnp.int32) < n_keep,
        n_nodes=slab.n_nodes, d_cap=0, cap_hint=cap,
        d_hyb=0, hub_cap=0, agg_cap=0)


def pack_edges(edges: np.ndarray,
               n_nodes: int,
               weights: Optional[np.ndarray] = None,
               capacity: Optional[int] = None) -> GraphSlab:
    """Host-side: canonicalize, dedupe and pad an edge array into a GraphSlab.

    ``edges`` is int[E, 2] with compact 0-based node ids.  Self-loops are
    dropped (the reference's input graphs are simple).  Duplicate edges are
    merged keeping the first weight.  Default capacity is ``2 * E + 16``:
    triadic closure adds at most L = E0 edges per round net of thresholding
    (reference ``fast_consensus.py:175``), and insertion drops overflow with a
    reported counter rather than crashing.
    """
    edges = np.asarray(edges, dtype=np.int64)
    if weights is None:
        weights = np.ones(edges.shape[0], dtype=np.float32)
    weights = np.asarray(weights, dtype=np.float32)
    u = np.minimum(edges[:, 0], edges[:, 1])
    v = np.maximum(edges[:, 0], edges[:, 1])
    keep = u != v
    u, v, weights = u[keep], v[keep], weights[keep]
    key = u * np.int64(n_nodes) + v
    _, first = np.unique(key, return_index=True)
    first.sort()
    u, v, weights = u[first], v[first], weights[first]
    n_edges = u.shape[0]
    if capacity is None:
        capacity = 2 * n_edges + 16
    if capacity < n_edges:
        raise ValueError(f"capacity {capacity} < edge count {n_edges}")
    src = np.zeros(capacity, dtype=np.int32)
    dst = np.zeros(capacity, dtype=np.int32)
    w = np.zeros(capacity, dtype=np.float32)
    alive = np.zeros(capacity, dtype=bool)
    src[:n_edges] = u
    dst[:n_edges] = v
    w[:n_edges] = weights
    alive[:n_edges] = True
    degree = np.zeros(max(n_nodes, 1) + 1, dtype=np.int64)
    np.add.at(degree, u, 1)
    np.add.at(degree, v, 1)
    d_cap = derive_dense_sizing(degree[:n_nodes], n_nodes)
    d_hyb, hub_cap = derive_hybrid_sizing(degree[:n_nodes], n_nodes,
                                          n_edges)
    # cap_hint is the *default* capacity formula regardless of the caller's
    # requested capacity: heuristics keyed off it (move path, hash buckets —
    # models/louvain.py) then depend only on graph content, so a tight pack
    # that auto-grows, a default pack, and a generous pre-size all take
    # identical detection lowerings and produce identical results.
    return GraphSlab(src=jnp.asarray(src), dst=jnp.asarray(dst),
                     weight=jnp.asarray(w), alive=jnp.asarray(alive),
                     n_nodes=int(n_nodes), d_cap=d_cap,
                     cap_hint=2 * n_edges + 16,
                     d_hyb=d_hyb, hub_cap=hub_cap,
                     agg_cap=derive_agg_sizing(n_edges))


def grow_slab(slab: GraphSlab, new_capacity: int) -> GraphSlab:
    """Extend capacity with dead slots at the tail (device-side, no repack).

    Growth is *result-preserving*: free-slot fill order (insert_edges) visits
    pre-existing dead slots before the new tail, CSR construction sorts dead
    entries past the alive ones, and co-membership/threshold/convergence
    ignore dead slots entirely — so replaying a round after growth produces
    the identical alive-edge content, except that candidates previously
    dropped for capacity now land in the new slots.  The consensus driver
    uses this to self-size the slab at round boundaries (the reference's
    networkx graph grows unboundedly, fast_consensus.py:175-191; a fixed
    slab that silently sheds edges would be its crash dressed up —
    VERDICT round 1).
    """
    pad = new_capacity - slab.capacity
    if pad < 0:
        raise ValueError(
            f"cannot shrink slab: {new_capacity} < {slab.capacity}")
    if pad == 0:
        return slab
    return dataclasses.replace(
        slab,
        src=jnp.pad(slab.src, (0, pad)),
        dst=jnp.pad(slab.dst, (0, pad)),
        weight=jnp.pad(slab.weight, (0, pad)),
        alive=jnp.pad(slab.alive, (0, pad)),
        cap_hint=slab.cap_hint or slab.capacity)


def stack_slabs(slabs) -> GraphSlab:
    """Stack B same-shaped slabs along a new leading batch axis.

    The result is a GraphSlab whose array fields are ``[B, capacity]`` —
    the operand of the batch-vmapped consensus path (engine.
    _jitted_rounds_batch).  Every STATIC field (n_nodes, capacity and the
    sizing metadata) must be identical across the batch: statics are jit
    cache keys, and the whole point of batching is that same-bucket
    graphs share one executable (serve/bucketer.py canonicalizes them).
    """
    if not slabs:
        raise ValueError("stack_slabs needs at least one slab")
    base = slabs[0]
    statics = lambda s: (s.n_nodes, s.capacity, s.d_cap, s.cap_hint,  # noqa: E731
                         s.d_hyb, s.hub_cap, s.agg_cap)
    for i, s in enumerate(slabs[1:], start=1):
        if statics(s) != statics(base):
            raise ValueError(
                f"cannot batch slabs with differing static shapes: slab 0 "
                f"has {statics(base)}, slab {i} has {statics(s)} "
                f"(n_nodes, capacity, d_cap, cap_hint, d_hyb, hub_cap, "
                f"agg_cap); pad through one serve/bucketer bucket first")
    return jax.tree.map(lambda *xs: jnp.stack(xs), *slabs)


def host_edges(slab: GraphSlab) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Readback: alive (u, v, w) triples as numpy arrays."""
    src = np.asarray(slab.src)
    dst = np.asarray(slab.dst)
    w = np.asarray(slab.weight)
    alive = np.asarray(slab.alive)
    return src[alive], dst[alive], w[alive]


def to_networkx(slab: GraphSlab):
    """Debug/interop boundary: materialize a networkx.Graph on host."""
    import networkx as nx

    g = nx.Graph()
    g.add_nodes_from(range(slab.n_nodes))
    u, v, w = host_edges(slab)
    g.add_weighted_edges_from(zip(u.tolist(), v.tolist(), w.tolist()))
    return g


def from_networkx(g, capacity: Optional[int] = None) -> GraphSlab:
    """Interop: pack a networkx graph whose nodes are hashable ids.

    Node ids are compacted to 0..N-1 by sorted order; the caller keeps the
    mapping if it needs original ids (see utils/io.py for file-level I/O).
    """
    nodes = sorted(g.nodes())
    index = {n: i for i, n in enumerate(nodes)}
    edges = np.array([[index[a], index[b]] for a, b in g.edges()],
                     dtype=np.int64).reshape(-1, 2)
    wts = np.array([d.get("weight", 1.0) for _, _, d in g.edges(data=True)],
                   dtype=np.float32)
    return pack_edges(edges, len(nodes), weights=wts, capacity=capacity)
