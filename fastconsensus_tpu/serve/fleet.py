"""fcfleet manager: spawn, watch, and retire fcserve replica processes.

serve/router.py routes traffic across replicas it is GIVEN; this module
is what gives it them — a jax-free manager that launches N
``python -m fastconsensus_tpu.serve`` subprocesses (each one a full
ConsensusService with its own worker pool, result cache and flight
recorder), fronts them with a :class:`~.router.FleetRouter`, and owns
the fleet's lifecycle stories:

* **spawn + readiness** — each replica gets its own port, cache spill
  file, and flight-bundle directory; ``wait_healthy`` polls
  ``/healthz`` until the replica answers (pre-warm included), so the
  router never routes into a replica that is still compiling;
* **chaos hooks** — a replica can be spawned with an
  ``FCTPU_FAULT_INJECT`` site armed in ITS environment only (the
  fleet-level use of the PR 15 harness: one replica misbehaves, the
  fleet must not), killed hard (SIGKILL — the crash story the periodic
  cache spill exists for) or drained (SIGTERM — the rolling-restart
  story, exit 0 means every admitted job finished);
* **death inheritance** — when a replica dies, its groups re-home via
  the router's cordon machinery, and :meth:`inherit_cache` tells the
  ring successor to load the dead replica's spilled cache file
  (``POST /cachez/load``), so resubmissions of the dead replica's work
  answer from cache instead of recomputing;
* **prewarm shipping** — :meth:`add_replica` asks the router which
  current member the joiner will inherit groups from, copies that
  donor's warm-bucket residency into the joiner's ``--warm`` flags,
  and ships the donor's cached results (``GET /cachez`` +
  ``GET /cachez/<hash>`` -> ``POST /cachez``) before the ring add —
  the new replica takes its first request warm;
* **bundle collection** — :meth:`snapshot_bundles` SIGQUITs every live
  replica (the fcflight "dump and keep serving" signal) and gathers
  the per-replica post-mortem bundle paths; :meth:`collect_bundles`
  goes one step further (fctrace) and copies every replica's bundles —
  dead ones included — into ONE ``<replica>__<bundle>`` directory that
  ``python -m fastconsensus_tpu.obs.fleettrace render`` merges into a
  clock-aligned fleet incident timeline.

Like the router, this module never imports jax: the replicas pay the
engine cost in their own processes, the manager is pure stdlib.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import signal
import subprocess
import sys
import time
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple

from fastconsensus_tpu.obs import counters as obs_counters
from fastconsensus_tpu.serve.router import (FleetRouter, _http_json,
                                            make_router_server)

_logger = logging.getLogger("fastconsensus_tpu")

# How many cached results prewarm shipping copies donor -> joiner.  A
# bounded snapshot: shipping is a warm-start optimization, not a
# replication protocol, and an unbounded copy of a large donor cache
# would stall the join it is supposed to speed up.
SHIP_CACHE_MAX_ENTRIES = 64


class ReplicaSpawnError(RuntimeError):
    """A replica process exited or never answered /healthz in time."""


class ReplicaProc:
    """One managed fcserve subprocess."""

    def __init__(self, name: str, port: int, proc: subprocess.Popen,
                 cache_path: str, flight_dir: str,
                 warm: Tuple[str, ...]) -> None:
        self.name = name
        self.port = port
        self.proc = proc
        self.cache_path = cache_path
        self.flight_dir = flight_dir
        self.warm = warm

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def alive(self) -> bool:
        return self.proc.poll() is None

    def bundles(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self.flight_dir,
                                             "fcflight_*")))


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class FleetManager:
    """Own a replica fleet + its router, end to end.

    Typical use (bench.py serve_fleet / the CI fcfleet stage)::

        fleet = FleetManager(workdir, warm=("n64_e96:2",))
        fleet.spawn("r0"); fleet.spawn("r1", fault="...:ValueError")
        url = fleet.start_router()
        ... drive traffic at url ...
        fleet.kill("r1", graceful=False)   # chaos
        fleet.on_death("r1")               # cordon + cache inheritance
        ... burst completes with zero failed jobs ...
        fleet.stop_all()
    """

    def __init__(self, workdir: str,
                 warm: Sequence[str] = (),
                 replica_args: Sequence[str] = (),
                 cache_spill_s: Optional[float] = 1.0,
                 spawn_timeout_s: float = 240.0,
                 poll_s: float = 0.5) -> None:
        self.workdir = os.path.abspath(workdir)
        os.makedirs(self.workdir, exist_ok=True)
        self.warm = tuple(warm)
        self.replica_args = tuple(replica_args)
        self.cache_spill_s = cache_spill_s
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.replicas: Dict[str, ReplicaProc] = {}
        self.router = FleetRouter({}, poll_s=poll_s)
        self._httpd = None
        self._http_thread = None
        self._reg = obs_counters.get_registry()

    # -- spawning -----------------------------------------------------

    def _spawn_proc(self, name: str, warm: Tuple[str, ...],
                    fault: Optional[str] = None,
                    fault_count: Optional[int] = None,
                    env_extra: Optional[Dict[str, str]] = None
                    ) -> ReplicaProc:
        port = _free_port()
        cache_path = os.path.join(self.workdir, f"{name}_cache.npz")
        flight_dir = os.path.join(self.workdir, f"{name}_flight")
        log_path = os.path.join(self.workdir, f"{name}.log")
        cmd = [sys.executable, "-m", "fastconsensus_tpu.serve",
               "--port", str(port),
               "--cache-file", cache_path,
               "--flight-dir", flight_dir]
        if self.cache_spill_s:
            cmd += ["--cache-spill-s", str(self.cache_spill_s)]
        for spec in warm:
            cmd += ["--warm", spec]
        cmd += list(self.replica_args)
        env = dict(os.environ)
        if fault:
            env["FCTPU_FAULT_INJECT"] = fault
            if fault_count is not None:
                env["FCTPU_FAULT_INJECT_COUNT"] = str(fault_count)
        env.update(env_extra or {})
        log = open(log_path, "ab")
        try:
            proc = subprocess.Popen(cmd, stdout=log, stderr=log, env=env)
        finally:
            log.close()   # the child holds its own fd now
        return ReplicaProc(name, port, proc, cache_path, flight_dir,
                           warm)

    def wait_healthy(self, rep: ReplicaProc,
                     timeout_s: Optional[float] = None) -> None:
        """Poll the replica's /healthz until it answers with pre-warm
        finished; raise :class:`ReplicaSpawnError` on process death or
        timeout (with the tail of the replica's log — the spawn
        failure is otherwise invisible in the parent)."""
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self.spawn_timeout_s)
        while time.monotonic() < deadline:
            if not rep.alive():
                raise ReplicaSpawnError(
                    f"replica {rep.name} exited rc={rep.proc.returncode} "
                    f"before serving: {self._log_tail(rep.name)}")
            try:
                with urllib.request.urlopen(rep.base_url + "/healthz",
                                            timeout=2.0) as resp:
                    body = json.loads(resp.read() or b"{}")
                prewarm = body.get("prewarm") or {}
                if prewarm.get("finished", True):
                    return
            # fcheck: ok=swallowed-error (not listening YET is the
            # expected state this loop exists to wait out; death and
            # timeout are both surfaced above/below)
            except (OSError, ValueError):
                pass
            time.sleep(0.2)
        raise ReplicaSpawnError(
            f"replica {rep.name} not healthy after "
            f"{timeout_s or self.spawn_timeout_s:.0f}s: "
            f"{self._log_tail(rep.name)}")

    def _log_tail(self, name: str, n: int = 12) -> str:
        path = os.path.join(self.workdir, f"{name}.log")
        try:
            with open(path, "r", encoding="utf-8",
                      errors="replace") as fh:
                return " | ".join(fh.read().splitlines()[-n:])
        except OSError:
            return "<no log>"

    def spawn(self, name: str, fault: Optional[str] = None,
              fault_count: Optional[int] = None,
              env_extra: Optional[Dict[str, str]] = None,
              warm: Optional[Sequence[str]] = None,
              register: bool = True) -> ReplicaProc:
        """Launch a replica, wait for it to serve, and (by default)
        join it to the router's ring."""
        if name in self.replicas:
            raise ValueError(f"replica {name!r} already exists")
        rep = self._spawn_proc(name,
                               tuple(warm if warm is not None
                                     else self.warm),
                               fault=fault, fault_count=fault_count,
                               env_extra=env_extra)
        self.replicas[name] = rep
        try:
            self.wait_healthy(rep)
        except ReplicaSpawnError:
            self.replicas.pop(name, None)
            if rep.alive():
                rep.proc.kill()
                rep.proc.wait(timeout=10)
            raise
        if register:
            self.router.add_replica(name, rep.base_url)
        self._reg.inc("serve.fleet.spawns")
        return rep

    # -- elastic join (prewarm shipping) ------------------------------

    def add_replica(self, name: str,
                    env_extra: Optional[Dict[str, str]] = None
                    ) -> ReplicaProc:
        """Grow the fleet by one WARM replica: before the ring add, the
        joiner inherits its donor's warm-spec (spawned with the
        donor's resident buckets as ``--warm`` flags) and a bounded
        snapshot of the donor's cached results — so the ~1/N of groups
        that re-home onto it arrive on a replica that has already
        compiled their buckets and already holds their recent answers.
        """
        donor_name = self.router.preview_donor(name)
        warm = list(self.warm)
        donor = self.replicas.get(donor_name) if donor_name else None
        if donor is not None:
            try:
                _, health, _ = _http_json(donor.base_url + "/healthz",
                                          timeout=5.0)
                for bucket in (health.get("buckets") or {}):
                    spec = f"{bucket}:1"
                    if bucket not in {w.split(":")[0] for w in warm}:
                        warm.append(spec)
            except (OSError, ValueError):
                donor = None   # unreachable donor: join cold
        rep = self.spawn(name, env_extra=env_extra, warm=warm,
                         register=False)
        if donor is not None:
            shipped = self.ship_cache(donor.name, name)
            self._reg.inc("serve.fleet.prewarm_shipped", 1 if shipped
                          else 0)
        self.router.add_replica(name, rep.base_url)
        return rep

    def ship_cache(self, donor: str, target: str,
                   max_entries: int = SHIP_CACHE_MAX_ENTRIES) -> int:
        """Copy up to ``max_entries`` cached results donor -> target
        over the /cachez endpoints; returns the number shipped."""
        d, t = self.replicas[donor], self.replicas[target]
        try:
            _, listing, _ = _http_json(d.base_url + "/cachez",
                                       timeout=10.0)
        except (OSError, ValueError):
            return 0
        shipped = 0
        for key in (listing.get("keys") or [])[:max_entries]:
            try:
                status, res, _ = _http_json(
                    d.base_url + f"/cachez/{key}", timeout=10.0)
                if status != 200:
                    continue
                status, _, _ = _http_json(
                    t.base_url + "/cachez",
                    json.dumps(res).encode("utf-8"), timeout=10.0)
            # fcheck: ok=swallowed-error (one unshippable entry must
            # not abort the whole shipment; the cache_shipped counter
            # vs the donor's listing carries the shortfall)
            except (OSError, ValueError):
                continue
            if status == 200:
                shipped += 1
        if shipped:
            self._reg.inc("serve.fleet.cache_shipped", shipped)
        return shipped

    # -- chaos / retirement -------------------------------------------

    def kill(self, name: str, graceful: bool = True,
             timeout_s: float = 120.0) -> Optional[int]:
        """Stop a replica: SIGTERM (graceful=True — the rolling-drain
        path; returns its exit code, 0 = every admitted job finished)
        or SIGKILL (the crash drill; returns None immediately after
        reaping).  Either way the caller follows with
        :meth:`on_death` to cordon + inherit."""
        rep = self.replicas[name]
        if not rep.alive():
            return rep.proc.returncode
        if graceful:
            rep.proc.send_signal(signal.SIGTERM)
            try:
                return rep.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                _logger.warning("fcfleet: %s drain timed out; killing",
                                name)
                rep.proc.kill()
                rep.proc.wait(timeout=10)
                return rep.proc.returncode
        rep.proc.kill()
        rep.proc.wait(timeout=10)
        return None

    def on_death(self, name: str) -> Optional[str]:
        """A replica is gone: cordon it (re-home + replay its
        in-flight jobs) and tell the successor that inherits its
        groups to load its spilled cache file.  Returns the successor
        name (None when nothing could inherit)."""
        self.router.cordon(name, "replica process death")
        rep = self.replicas.get(name)
        successor = self._successor_of(name)
        if successor is None or rep is None:
            return None
        if os.path.exists(rep.cache_path):
            srep = self.replicas[successor]
            try:
                status, out, _ = _http_json(
                    srep.base_url + "/cachez/load",
                    json.dumps({"path": rep.cache_path}).encode("utf-8"),
                    timeout=30.0)
                if status == 200:
                    self._reg.inc("serve.fleet.cache_inherited",
                                  int(out.get("loaded", 0)))
                    for h in out.get("content_hashes") or ():
                        # re-point the content-hash index at the
                        # inheritor so fetch-on-miss can source from it
                        self.router.note_holder(str(h), successor)
                    _logger.info(
                        "fcfleet: %s inherited %s cached result(s) "
                        "from dead replica %s", successor,
                        out.get("loaded"), name)
            except (OSError, ValueError):
                self._reg.inc("serve.fleet.cache_inherit_failed")
        return successor

    def _successor_of(self, dead: str) -> Optional[str]:
        """The live replica that now owns the plurality of the dead
        replica's route-key assignments — the cache-inheritance
        target."""
        stats = self.router.fleet_stats()
        owned = {k for k, owner in (stats.get("assignments") or {}
                                    ).items() if owner == dead}
        excluded = frozenset({dead})
        for r in stats["replicas"]:
            if r["name"] == dead:
                # the poll loop usually cordons the dead replica before
                # on_death runs, and live traffic then overwrites its
                # _assignments entries with the new homes — the
                # cordon-time rehomed_keys snapshot is the authoritative
                # record of what it owned
                owned.update(r.get("rehomed_keys") or ())
            elif r["state"] == "cordoned":
                excluded |= {r["name"]}
        live = [r["name"] for r in stats["replicas"]
                if r["state"] == "up" and r["name"] != dead]
        if not live:
            return None
        if not owned:
            return live[0]
        counts: Dict[str, int] = {}
        for key in sorted(owned):
            try:
                # exclude every cordoned replica, not just the dead one:
                # the successor must be where live routing actually
                # sends these keys, or the inherited cache is useless
                new_owner = self.router.ring.route(key, excluded)
            except Exception:  # noqa: BLE001 — an all-cordoned ring has
                # no successor; cache inheritance is then moot
                return None
            counts[new_owner] = counts.get(new_owner, 0) + 1
        return max(sorted(counts), key=lambda n: counts[n])

    def snapshot_bundles(self, timeout_s: float = 30.0) -> List[str]:
        """SIGQUIT every live replica (fcflight: dump a post-mortem
        bundle, keep serving) and collect the bundle paths that
        appear.  A bundle counts only once its MANIFEST.json exists —
        the dump writes the manifest LAST, so a bare fresh directory
        is still mid-write and a collector that took it would skip it
        as a partial."""
        live = [r for r in self.replicas.values() if r.alive()]
        before = {r.name: set(r.bundles()) for r in live}
        for r in live:
            r.proc.send_signal(signal.SIGQUIT)
        deadline = time.monotonic() + timeout_s
        collected: List[str] = []
        pending = set(r.name for r in live)
        while pending and time.monotonic() < deadline:
            for r in live:
                if r.name not in pending:
                    continue
                fresh = {
                    b for b in set(r.bundles()) - before[r.name]
                    if os.path.isfile(os.path.join(b, "MANIFEST.json"))}
                if fresh:
                    collected += sorted(fresh)
                    pending.discard(r.name)
            if pending:
                time.sleep(0.2)
        return collected

    def all_bundles(self) -> List[str]:
        out: List[str] = []
        for r in self.replicas.values():
            out += r.bundles()
        return out

    def collect_bundles(self, dest_dir: Optional[str] = None,
                        snapshot: bool = True,
                        timeout_s: float = 30.0) -> Dict[str, List[str]]:
        """Gather EVERY replica's bundles into one directory — the
        fctrace incident-merge input.  ``snapshot=True`` first SIGQUITs
        the live replicas (:meth:`snapshot_bundles`) so the collection
        includes a fresh dump of each survivor; dead replicas
        contribute whatever their flight dirs already hold (the
        watchdog/death bundles written before they went).

        Each bundle lands as ``<replica>__<bundle_name>`` (the
        :data:`~fastconsensus_tpu.obs.fleettrace.REPLICA_SEP` layout
        ``fleettrace render`` splits its replica tracks on); returns
        replica name -> collected paths.  Collection is copy-based so
        the replicas' own flight dirs stay intact for any later reader.
        """
        import shutil

        from fastconsensus_tpu.obs import flight as obs_flight
        from fastconsensus_tpu.obs.fleettrace import REPLICA_SEP

        if snapshot:
            self.snapshot_bundles(timeout_s=timeout_s)
        dest = os.path.abspath(dest_dir or os.path.join(
            self.workdir, "collected_bundles"))
        os.makedirs(dest, exist_ok=True)
        out: Dict[str, List[str]] = {}
        for name, rep in sorted(self.replicas.items()):
            collected: List[str] = []
            for bundle in rep.bundles():
                if not os.path.isfile(os.path.join(bundle,
                                                   "MANIFEST.json")):
                    continue   # manifest-less partial: incomplete dump
                target = os.path.join(
                    dest, f"{name}{REPLICA_SEP}"
                          f"{os.path.basename(bundle)}")
                try:
                    if not os.path.isdir(target):
                        shutil.copytree(bundle, target)
                    collected.append(target)
                # fcheck: ok=swallowed-error (one uncopyable bundle
                # must not abort the fleet collection; the per-replica
                # counts in the return value carry the shortfall)
                except OSError:
                    continue
            out[name] = collected
            self._reg.inc("serve.fleet.bundles_collected",
                          len(collected))
            obs_flight.record("fleet_bundle", replica=name,
                              n_bundles=len(collected))
        return out

    # -- router front end ---------------------------------------------

    def start_router(self, host: str = "127.0.0.1", port: int = 0) -> str:
        """Start the router's poll loop + HTTP front end; returns the
        fleet's base URL."""
        import threading

        self.router.start()
        self._httpd = make_router_server(self.router, host, port)
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, name="fcfleet-http",
            daemon=True)
        self._http_thread.start()
        h, p = self._httpd.server_address[:2]
        return f"http://{h}:{p}"

    def stop_all(self, graceful: bool = True) -> Dict[str, Optional[int]]:
        """Retire the fleet: stop the router front end, then drain (or
        kill) every live replica; returns name -> exit code."""
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.router.stop()
        codes: Dict[str, Optional[int]] = {}
        for name, rep in self.replicas.items():
            if rep.alive():
                codes[name] = self.kill(name, graceful=graceful)
            else:
                codes[name] = rep.proc.returncode
        return codes


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m fastconsensus_tpu.serve.fleet`` — run a local fleet:
    N replicas + the router, drained as a fleet on SIGTERM/SIGINT."""
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m fastconsensus_tpu.serve.fleet",
        description="fcfleet: N fcserve replicas behind a "
                    "consistent-hash router")
    p.add_argument("--replicas", type=int, default=2, metavar="N",
                   help="fleet size (default 2)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8770,
                   help="router port (0 picks a free one; default 8770)")
    p.add_argument("--workdir", default="./fcfleet",
                   help="per-replica cache/flight/log directory")
    p.add_argument("--warm", action="append", default=[],
                   metavar="BUCKET[:B]",
                   help="pre-warm spec passed to every replica")
    p.add_argument("--cache-spill-s", type=float, default=5.0,
                   metavar="S",
                   help="periodic replica cache spill interval "
                        "(default 5; 0 disables)")
    p.add_argument("--replica-arg", action="append", default=[],
                   metavar="ARG", help="extra flag passed to every "
                                       "replica CLI; repeatable")
    args = p.parse_args(argv)
    if args.replicas < 1:
        print("error: --replicas must be >= 1", file=sys.stderr)
        return 2
    fleet = FleetManager(args.workdir, warm=args.warm,
                         replica_args=args.replica_arg,
                         cache_spill_s=args.cache_spill_s or None)
    import threading

    stop = threading.Event()
    try:
        for i in range(args.replicas):
            name = f"r{i}"
            print(f"[fcfleet] spawning replica {name}...",
                  file=sys.stderr, flush=True)
            fleet.spawn(name)
        url = fleet.start_router(args.host, args.port)
        print(f"[fcfleet] routing {args.replicas} replica(s) at {url}",
              file=sys.stderr, flush=True)
    except (ReplicaSpawnError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        fleet.stop_all(graceful=False)
        return 2

    def _on_signal(signum, frame) -> None:
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    stop.wait()
    print("[fcfleet] draining fleet...", file=sys.stderr, flush=True)
    codes = fleet.stop_all(graceful=True)
    bad = {n: c for n, c in codes.items() if c not in (0, None)}
    for name, code in sorted(codes.items()):
        print(f"[fcfleet] {name}: exit {code}", file=sys.stderr,
              flush=True)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
