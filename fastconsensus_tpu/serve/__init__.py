"""fcserve: the request-serving layer over the consensus engine.

Turns the one-shot engine (cli.py / bench.py pay graph load + executable
warm-up per invocation and throw the compiled state away) into a
long-lived service that amortizes everything amortizable:

* **serve/bucketer.py** — shape buckets: incoming graphs pad onto a
  small ``{2^k, 3*2^k}`` ladder of canonical (n_nodes, n_edges) classes
  (sizing.grid_up) with every content-derived static slab field
  canonicalized, so distinct graphs in one bucket reuse the same jitted
  executables — warm-bucket requests compile zero times.
* **serve/cache.py** — content-addressed result cache (LRU + TTL):
  identical (graph, config) work — keyed by serve/jobs.py's canonical
  content hash — is answered from memory, no device time at all.
* **serve/queue.py** — bounded thread-safe priority queue with explicit
  admission control: overload is rejected with backpressure (HTTP 429),
  never absorbed into unbounded growth.  ``pop_batch`` coalesces queued
  same-bucket jobs for the cross-request batch path (one vmapped device
  call per ladder rung, ``consensus.run_consensus_batch``).
* **serve/jobs.py** — job spec / states / priorities + the content hash.
* **serve/server.py** — the service core (single device-driving worker)
  and the stdlib HTTP front end: ``POST /submit``, ``GET /status/<id>``,
  ``/result/<id>``, ``/healthz``, ``/metricsz`` (the fcobs registry —
  cache hit rate, per-job compiles, queue depth — as JSON).
* **serve/client.py** — stdlib urllib client (``cli.py --server`` uses
  it to submit without importing jax).

Run one: ``python -m fastconsensus_tpu.serve --port 8765``; SIGTERM
drains gracefully (finish admitted work, export the server's fcobs
trace with ``--trace-dir``, exit 0).  See README "Serving".
"""

# Lazy re-exports (PEP 562), mirroring the package root: importing
# fastconsensus_tpu.serve.client (the THIN-CLIENT path — cli.py
# --server) must stay jax-free, and eager submodule imports here would
# pull bucketer -> graph -> jax into every client process.
_EXPORTS = {
    "Bucket": "bucketer", "BucketTooLarge": "bucketer",
    "bucket_for": "bucketer", "pad_to_bucket": "bucketer",
    "ResultCache": "cache",
    "Job": "jobs", "JobSpec": "jobs", "content_hash": "jobs",
    "SLO_CLASSES": "jobs",
    "AdmissionQueue": "queue", "QueueClosed": "queue",
    "QueueFull": "queue", "DeadlineShed": "queue",
    "ShapingConfig": "shaping", "TrafficShaper": "shaping",
    "ConsensusService": "server", "GraphTooLarge": "server",
    "ServeConfig": "server", "make_http_server": "server",
    "DeviceWorker": "pool", "MeshWorker": "pool", "WorkerPool": "pool",
    "NoEligibleWorker": "scheduler", "StickyScheduler": "scheduler",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        mod = importlib.import_module(
            f"fastconsensus_tpu.serve.{_EXPORTS[name]}")
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
