"""fcserve shape buckets: pad incoming graphs onto a canonical ladder.

Every static shape and static slab field is part of a jitted
executable's cache key (graph.GraphSlab metadata, engine._jitted_round
arguments), so a naive server would compile a fresh multi-minute
executable set for every distinct (n_nodes, n_edges) it ever sees.  This
module folds the infinite input space onto a small ladder of **size
classes** — the ``{2^k, 3*2^k}`` grid from :func:`sizing.grid_up`, the
same quantization the engine already applies to detect-call member
counts — and pads each graph to its class:

* ``n_class``  — node count padded up (extra nodes are isolated: they
  contribute no edges, no strength, and fall out as singleton
  communities the server slices off the returned partitions);
* ``e_class``  — canonical (deduped) edge count padded up; it sizes the
  slab capacity exactly as ``pack_edges`` would (``2*E + 16`` closure
  headroom) and serves as the bucket-canonical wedge-sample count L
  (``run_consensus(n_closure=...)``).

Crucially, the *content-derived* static slab fields are *canonicalized
away*: ``d_cap``/``d_hyb``/``hub_cap`` are pinned to 0 (two same-bucket
graphs with different degree histograms would otherwise derive different
dense/hybrid row widths — different static fields, different
executables) and ``agg_cap``/``cap_hint`` are pure functions of the
bucket.  Detection therefore takes the matmul path for buckets up to
``MATMUL_MAX_N`` nodes and the hash path above it — both
content-shape-independent.  The cost is forgoing the dense/hybrid
lowerings; the win is the serving contract: **any two graphs in one
bucket run the same executables, so every request after the bucket's
first compiles nothing** (asserted with ``analysis.CompileGuard`` in
tests/test_serve.py and the CI smoke).

Padding changes results only through the sample-count semantics above
(documented deviation: a served run of graph G may differ from a
one-shot ``cli.py`` run of G in tie-degenerate choices), but it is
deterministic: same graph + same config -> same bucket -> same
partitions, which is what the content-addressed cache requires.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from fastconsensus_tpu import sizing
from fastconsensus_tpu.graph import GraphSlab, derive_agg_sizing, pack_edges

# Floors keep tiny interactive graphs (karate-sized probes) in ONE
# bucket instead of one per size, at negligible padding cost.
MIN_NODE_CLASS = 64
MIN_EDGE_CLASS = 64

# Cross-request batch ladder: coalesced batches execute only at these
# widths (serve/server.py splits a coalesced pop into ladder rungs; B=1
# is the solo path's executables).  The batch width is a leading shape
# of every batched executable, so an unquantized width would compile a
# fresh executable per burst size — exactly the hazard the (n, e) grid
# above exists to prevent, one axis up.  Powers of two cap the split
# overhead at one extra sub-batch per burst.
BATCH_LADDER = (1, 2, 4, 8)


def batch_rung(n: int) -> int:
    """Largest batch-ladder rung <= n (>= 1): a coalesced group of n
    jobs executes as rung-sized sub-batches (8, 4, 2, 1), so the
    resident executable set stays at most ``len(BATCH_LADDER)`` wide per
    (bucket, config) and CompileGuard can pin it."""
    n = max(int(n), 1)
    rung = BATCH_LADDER[0]
    for b in BATCH_LADDER:
        if b <= n:
            rung = b
    return rung


class BucketTooLarge(ValueError):
    """Admission refused: the graph exceeds the configured ladder top
    (HTTP 413 — oversized payloads are rejected, not queued)."""


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One rung of the ladder: canonical (node, edge) size class."""

    n_class: int
    e_class: int

    @property
    def capacity(self) -> int:
        """Slab capacity: pack_edges' default headroom at the class."""
        return 2 * self.e_class + 16

    @property
    def agg_cap(self) -> int:
        # Sized from the slab CAPACITY, not e_class: alive edges are
        # bounded by capacity, so this compaction budget can never
        # starve (policy.budgets_stale's agg term needs alive >
        # 1.25*agg_cap, and derive_agg_sizing(capacity) > capacity) —
        # a mid-run budget re-derivation would re-size a shared bucket
        # executable, the exact compile hazard the canonical statics
        # exist to prevent, and it forces the batch path to split jobs
        # off to solo tails.  Costs a ~2x-generous aggregate hash slab
        # vs content-derived sizing; serving trades that for executable
        # stability.  (e_class-derived sizing starved in practice:
        # lfr1k-density graphs run alive ~10.3k against the old 8192.)
        return derive_agg_sizing(self.capacity)

    @property
    def n_closure(self) -> int:
        """Bucket-canonical wedge-sample count L (run_consensus)."""
        return self.e_class

    def key(self) -> str:
        return f"n{self.n_class}_e{self.e_class}"

    def describe(self) -> dict:
        return {"n_class": self.n_class, "e_class": self.e_class,
                "capacity": self.capacity, "key": self.key()}


def bucket_for(n_nodes: int, n_edges: int,
               max_nodes: Optional[int] = None,
               max_edges: Optional[int] = None) -> Bucket:
    """The bucket serving a graph of ``n_nodes`` / ``n_edges``
    (canonical edge count), or raise :class:`BucketTooLarge`."""
    if n_nodes < 1 or n_edges < 1:
        raise ValueError(
            f"graph must have >= 1 node and >= 1 edge, got "
            f"n_nodes={n_nodes}, n_edges={n_edges}")
    if max_nodes is not None and n_nodes > max_nodes:
        raise BucketTooLarge(
            f"graph has {n_nodes} nodes; this server admits at most "
            f"{max_nodes}")
    if max_edges is not None and n_edges > max_edges:
        raise BucketTooLarge(
            f"graph has {n_edges} edges; this server admits at most "
            f"{max_edges}")
    return Bucket(n_class=sizing.grid_up(n_nodes, MIN_NODE_CLASS),
                  e_class=sizing.grid_up(n_edges, MIN_EDGE_CLASS))


def bucket_from_key(key: str) -> Bucket:
    """Parse a bucket key back into its Bucket (``"n64_e96"`` — the
    ``--warm`` flag's operand).  Classes must sit exactly on the ladder
    grid: a typo'd class would pre-warm executables no request can ever
    land on, silently."""
    try:
        n_part, e_part = key.split("_")
        if not (n_part.startswith("n") and e_part.startswith("e")):
            raise ValueError
        n_class, e_class = int(n_part[1:]), int(e_part[1:])
    except ValueError:
        raise ValueError(
            f"bad bucket key {key!r}; expected the form n<N>_e<E>, e.g. "
            f"n64_e96") from None
    want = bucket_for(n_class, e_class)
    got = Bucket(n_class=n_class, e_class=e_class)
    if want != got:
        raise ValueError(
            f"bucket key {key!r} is not on the ladder grid; the "
            f"nearest real bucket is {want.key()}")
    return got


def probe_edges(bucket: Bucket, variant: int = 0) -> np.ndarray:
    """A deterministic synthetic graph landing EXACTLY in ``bucket``:
    ``n_class`` nodes, ``e_class`` canonical edges (a path over the
    first nodes plus chord families).  ``variant`` shifts the chords so
    pre-warm batches carry genuinely distinct graphs per batch lane —
    the shapes are what compile, but distinct content keeps the probe
    honest about the per-job PRNG/cache paths."""
    n, e = bucket.n_class, bucket.e_class
    seen = set()
    rows = []

    def add(u: int, v: int) -> None:
        if u == v:
            return
        k = (min(u, v), max(u, v))
        if k in seen:
            return
        seen.add(k)
        rows.append(k)

    # chord-less buckets (e <= n-1) vary by shifting the path's start
    # node instead; chordful ones keep the path fixed and shift chords
    off = (variant % n) if e <= n - 1 else 0
    for i in range(min(e, n - 1)):
        add((off + i) % n, (off + i + 1) % n)
    shift, i = 2 + (variant % max(n - 3, 1)), 0
    while len(rows) < e:
        add(i, (i + shift) % n)
        i += 1
        if i >= n:
            i, shift = 0, shift + 1
            if shift >= n:  # pragma: no cover — e_class <= n*(n-1)/2
                raise ValueError(f"cannot realize {e} edges on {n} nodes")
    return np.asarray(rows, dtype=np.int64)


def pad_to_bucket(edges: np.ndarray, n_nodes: int,
                  weights: Optional[np.ndarray] = None,
                  max_nodes: Optional[int] = None,
                  max_edges: Optional[int] = None,
                  canonical: Optional[Tuple[np.ndarray, np.ndarray,
                                            Optional[np.ndarray]]] = None
                  ) -> Tuple[GraphSlab, Bucket]:
    """Pack a graph into its bucket's canonical slab shape.

    The returned slab's every static field is a pure function of the
    BUCKET (see module docstring), so jit caches key identically for all
    graphs the bucket serves.  Alive-edge content still belongs to the
    input graph — padding adds dead slots and isolated nodes only.

    ``canonical``: an already-computed ``jobs.canonical_edges`` result
    for these exact inputs (``JobSpec.canonical()`` memoizes it at
    hash time), skipping a second sort/dedupe pass here.
    """
    if canonical is None:
        from fastconsensus_tpu.serve.jobs import canonical_edges

        canonical = canonical_edges(edges, n_nodes, weights)
    u, v, w = canonical
    if w is not None and not np.all(np.isfinite(w)):
        # A NaN/inf weight is malformed input, not a computable job —
        # reject it HERE (per graph, before any batch is stacked) so a
        # coalesced batch fails only the poisoned member, never its
        # batchmates (serve/server.py failure isolation).
        raise ValueError("graph carries non-finite edge weights")
    bucket = bucket_for(n_nodes, int(u.shape[0]),
                        max_nodes=max_nodes, max_edges=max_edges)
    slab = pack_edges(np.stack([u, v], axis=1), bucket.n_class,
                      weights=w, capacity=bucket.capacity)
    # Canonicalize the content-derived statics (pack_edges set them from
    # THIS graph's degree histogram; the bucket contract requires them
    # identical across the bucket).
    slab = dataclasses.replace(
        slab, d_cap=0, d_hyb=0, hub_cap=0,
        cap_hint=bucket.capacity, agg_cap=bucket.agg_cap)
    return slab, bucket
