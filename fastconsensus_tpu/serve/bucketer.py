"""fcserve shape buckets: pad incoming graphs onto a canonical ladder.

Every static shape and static slab field is part of a jitted
executable's cache key (graph.GraphSlab metadata, engine._jitted_round
arguments), so a naive server would compile a fresh multi-minute
executable set for every distinct (n_nodes, n_edges) it ever sees.  This
module folds the infinite input space onto a small ladder of **size
classes** — the ``{2^k, 3*2^k}`` grid from :func:`sizing.grid_up`, the
same quantization the engine already applies to detect-call member
counts — and pads each graph to its class:

* ``n_class``  — node count padded up (extra nodes are isolated: they
  contribute no edges, no strength, and fall out as singleton
  communities the server slices off the returned partitions);
* ``e_class``  — canonical (deduped) edge count padded up; it sizes the
  slab capacity exactly as ``pack_edges`` would (``2*E + 16`` closure
  headroom) and serves as the bucket-canonical wedge-sample count L
  (``run_consensus(n_closure=...)``).

Crucially, the *content-derived* static slab fields are *canonicalized
away*: ``d_cap``/``d_hyb``/``hub_cap`` are pinned to 0 (two same-bucket
graphs with different degree histograms would otherwise derive different
dense/hybrid row widths — different static fields, different
executables) and ``agg_cap``/``cap_hint`` are pure functions of the
bucket.  Detection therefore takes the matmul path for buckets up to
``MATMUL_MAX_N`` nodes and the hash path above it — both
content-shape-independent.  The cost is forgoing the dense/hybrid
lowerings; the win is the serving contract: **any two graphs in one
bucket run the same executables, so every request after the bucket's
first compiles nothing** (asserted with ``analysis.CompileGuard`` in
tests/test_serve.py and the CI smoke).

Padding changes results only through the sample-count semantics above
(documented deviation: a served run of graph G may differ from a
one-shot ``cli.py`` run of G in tie-degenerate choices), but it is
deterministic: same graph + same config -> same bucket -> same
partitions, which is what the content-addressed cache requires.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from fastconsensus_tpu import sizing
from fastconsensus_tpu.graph import GraphSlab, derive_agg_sizing, pack_edges

# Floors keep tiny interactive graphs (karate-sized probes) in ONE
# bucket instead of one per size, at negligible padding cost.
MIN_NODE_CLASS = 64
MIN_EDGE_CLASS = 64


class BucketTooLarge(ValueError):
    """Admission refused: the graph exceeds the configured ladder top
    (HTTP 413 — oversized payloads are rejected, not queued)."""


@dataclasses.dataclass(frozen=True)
class Bucket:
    """One rung of the ladder: canonical (node, edge) size class."""

    n_class: int
    e_class: int

    @property
    def capacity(self) -> int:
        """Slab capacity: pack_edges' default headroom at the class."""
        return 2 * self.e_class + 16

    @property
    def agg_cap(self) -> int:
        return derive_agg_sizing(self.e_class)

    @property
    def n_closure(self) -> int:
        """Bucket-canonical wedge-sample count L (run_consensus)."""
        return self.e_class

    def key(self) -> str:
        return f"n{self.n_class}_e{self.e_class}"

    def describe(self) -> dict:
        return {"n_class": self.n_class, "e_class": self.e_class,
                "capacity": self.capacity, "key": self.key()}


def bucket_for(n_nodes: int, n_edges: int,
               max_nodes: Optional[int] = None,
               max_edges: Optional[int] = None) -> Bucket:
    """The bucket serving a graph of ``n_nodes`` / ``n_edges``
    (canonical edge count), or raise :class:`BucketTooLarge`."""
    if n_nodes < 1 or n_edges < 1:
        raise ValueError(
            f"graph must have >= 1 node and >= 1 edge, got "
            f"n_nodes={n_nodes}, n_edges={n_edges}")
    if max_nodes is not None and n_nodes > max_nodes:
        raise BucketTooLarge(
            f"graph has {n_nodes} nodes; this server admits at most "
            f"{max_nodes}")
    if max_edges is not None and n_edges > max_edges:
        raise BucketTooLarge(
            f"graph has {n_edges} edges; this server admits at most "
            f"{max_edges}")
    return Bucket(n_class=sizing.grid_up(n_nodes, MIN_NODE_CLASS),
                  e_class=sizing.grid_up(n_edges, MIN_EDGE_CLASS))


def pad_to_bucket(edges: np.ndarray, n_nodes: int,
                  weights: Optional[np.ndarray] = None,
                  max_nodes: Optional[int] = None,
                  max_edges: Optional[int] = None,
                  canonical: Optional[Tuple[np.ndarray, np.ndarray,
                                            Optional[np.ndarray]]] = None
                  ) -> Tuple[GraphSlab, Bucket]:
    """Pack a graph into its bucket's canonical slab shape.

    The returned slab's every static field is a pure function of the
    BUCKET (see module docstring), so jit caches key identically for all
    graphs the bucket serves.  Alive-edge content still belongs to the
    input graph — padding adds dead slots and isolated nodes only.

    ``canonical``: an already-computed ``jobs.canonical_edges`` result
    for these exact inputs (``JobSpec.canonical()`` memoizes it at
    hash time), skipping a second sort/dedupe pass here.
    """
    if canonical is None:
        from fastconsensus_tpu.serve.jobs import canonical_edges

        canonical = canonical_edges(edges, n_nodes, weights)
    u, v, w = canonical
    bucket = bucket_for(n_nodes, int(u.shape[0]),
                        max_nodes=max_nodes, max_edges=max_edges)
    slab = pack_edges(np.stack([u, v], axis=1), bucket.n_class,
                      weights=w, capacity=bucket.capacity)
    # Canonicalize the content-derived statics (pack_edges set them from
    # THIS graph's degree histogram; the bucket contract requires them
    # identical across the bucket).
    slab = dataclasses.replace(
        slab, d_cap=0, d_hyb=0, hub_cap=0,
        cap_hint=bucket.capacity, agg_cap=bucket.agg_cap)
    return slab, bucket
