"""fcserve admission queue: bounded, thread-safe, priority-ordered.

The serving layer's backpressure contract lives here: the queue has a
**hard depth bound** and :meth:`AdmissionQueue.submit` on a full queue
raises :class:`QueueFull` immediately — it never blocks the submitting
HTTP thread and never grows without bound.  An overloaded server
therefore answers "429, retry later" in microseconds instead of
accepting work it cannot finish (the failure mode that turns overload
into OOM or timeout storms; the north-star "heavy traffic" posture is
*reject early, finish what you accepted*).

Ordering is a min-heap on ``(priority, seq)``: lower priority values pop
first (jobs.PRIORITY_INTERACTIVE before PRIORITY_BATCH) and equal
priorities pop FIFO by admission order (``seq`` is assigned under the
queue lock, so FIFO holds across concurrently submitting threads).

Drain: :meth:`close` stops admissions (submit raises
:class:`QueueClosed` -> HTTP 503) while :meth:`pop` keeps handing out
already-admitted jobs until the heap is empty, then returns ``None`` —
the worker's signal that a graceful SIGTERM drain is complete
(serve/server.py).
"""

from __future__ import annotations

import heapq
import threading
from typing import Callable, List, Optional, Tuple

from fastconsensus_tpu.obs import counters as obs_counters
from fastconsensus_tpu.serve.jobs import Job


class QueueFull(RuntimeError):
    """Admission refused: the queue is at its depth bound (backpressure,
    not an internal error — HTTP maps it to 429 with Retry-After)."""

    def __init__(self, depth: int, max_depth: int) -> None:
        super().__init__(
            f"queue full ({depth}/{max_depth} jobs); retry later")
        self.depth = depth
        self.max_depth = max_depth


class QueueClosed(RuntimeError):
    """Admission refused: the service is draining (HTTP 503)."""


class AdmissionQueue:
    """Bounded thread-safe priority queue of :class:`Job`s."""

    def __init__(self, max_depth: int) -> None:
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = int(max_depth)
        self._heap: List[Tuple[int, int, Job]] = []
        self._seq = 0
        self._closed = False
        self._cond = threading.Condition()
        self._extra_depth: Optional[Callable[[], int]] = None
        self._reg = obs_counters.get_registry()

    def set_extra_depth(self, fn: Callable[[], int]) -> None:
        """Count admitted-but-undispatched jobs parked OUTSIDE the heap
        toward the depth bound.  The fcpool dispatcher (serve/pool.py)
        eagerly moves popped batches into per-worker deques; without
        this hook that would hollow out the backpressure contract — the
        heap would drain in microseconds and a depth-1 queue would
        absorb an unbounded burst into worker backlogs.  ``fn`` is
        called under the queue lock and must not take the queue lock
        itself (worker deque locks are always acquired after it)."""
        with self._cond:
            self._extra_depth = fn

    def submit(self, job: Job) -> None:
        """Admit ``job`` or raise :class:`QueueFull` /
        :class:`QueueClosed` — never blocks, never exceeds the bound."""
        with self._cond:
            if self._closed:
                self._reg.inc("serve.queue.rejected_draining")
                raise QueueClosed("service is draining; not accepting jobs")
            depth = len(self._heap) + (self._extra_depth()
                                       if self._extra_depth else 0)
            if depth >= self.max_depth:
                self._reg.inc("serve.queue.rejected_full")
                raise QueueFull(depth, self.max_depth)
            self._seq += 1
            heapq.heappush(self._heap, (job.spec.priority, self._seq, job))
            self._reg.inc("serve.queue.admitted")
            self._reg.gauge("serve.queue.depth", len(self._heap))
            self._cond.notify()

    def pop(self, timeout: Optional[float] = None) -> Optional[Job]:
        """Next job by (priority, admission order).

        Blocks until a job is available or the queue is closed *and*
        empty (returns ``None`` — drain complete).  With ``timeout``,
        also returns ``None`` if nothing arrived in time; callers that
        need to distinguish check :meth:`draining`.
        """
        with self._cond:
            while True:
                if self._heap:
                    _, _, job = heapq.heappop(self._heap)
                    self._reg.gauge("serve.queue.depth", len(self._heap))
                    # fclat queue_wait closes HERE — the moment the job
                    # leaves the admission heap (Job.stamp is a leaf
                    # lock; no cycle with _cond)
                    job.stamp("dispatched")
                    return job
                if self._closed:
                    return None
                if not self._cond.wait(timeout):
                    return None

    def pop_batch(self, max_b: int,
                  group_key: Callable[[Job], str],
                  timeout: Optional[float] = None
                  ) -> Optional[List[Job]]:
        """The next job plus up to ``max_b - 1`` already-queued jobs of
        the same batch group (serve/jobs.JobSpec.batch_group) — the
        cross-request coalescing pop.

        Priority is never starved: the HEAD is always the strict
        ``(priority, seq)`` front of the queue, coalescing only pulls
        *ride-along* jobs that would otherwise run later, and it never
        waits for more work to arrive — a lone job pops immediately as a
        batch of one.  A job skipped over by a ride-along is delayed by
        at most the one coalesced device call, which costs about what
        the head job alone would have (that amortization is the whole
        point); it pops next.

        Same drain semantics as :meth:`pop`: ``None`` once the queue is
        closed *and* empty (or on ``timeout`` with nothing queued).
        """
        with self._cond:
            while True:
                if self._heap:
                    _, _, head = heapq.heappop(self._heap)
                    taken = [head]
                    if max_b > 1 and self._heap:
                        g = group_key(head)
                        rest: List[Tuple[int, int, Job]] = []
                        # sorted() of a heap is a valid heap, and gives
                        # ride-alongs in strict (priority, seq) order
                        for entry in sorted(self._heap):
                            if len(taken) < max_b and \
                                    group_key(entry[2]) == g:
                                taken.append(entry[2])
                            else:
                                rest.append(entry)
                        self._heap = rest
                        if len(taken) > 1:
                            self._reg.inc("serve.queue.coalesced_pops")
                    self._reg.gauge("serve.queue.depth", len(self._heap))
                    for t in taken:
                        # queue_wait closes at the coalesced pop for the
                        # head AND every ride-along (they leave the heap
                        # together)
                        t.stamp("dispatched")
                    return taken
                if self._closed:
                    return None
                if not self._cond.wait(timeout):
                    return None

    def close(self) -> None:
        """Stop admissions; wake blocked poppers so they can drain."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def depth(self) -> int:
        with self._cond:
            return len(self._heap)

    def draining(self) -> bool:
        with self._cond:
            return self._closed
