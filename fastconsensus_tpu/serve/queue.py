"""fcserve admission queue: bounded, thread-safe, deadline-ordered.

The serving layer's backpressure contract lives here: the queue has a
**hard depth bound** and :meth:`AdmissionQueue.submit` on a full queue
raises :class:`QueueFull` immediately — it never blocks the submitting
HTTP thread and never grows without bound.  An overloaded server
therefore answers "429, retry later" in microseconds instead of
accepting work it cannot finish (the failure mode that turns overload
into OOM or timeout storms; the north-star "heavy traffic" posture is
*reject early, finish what you accepted*).  Since fcshape the 429 is
also HONEST: the raised :class:`QueueFull` carries a derived
``retry_after_s`` (serve/shaping.py) instead of a literal guess, and
:class:`DeadlineShed` refuses — at submit — work that provably cannot
meet its deadline at the current depth.

Ordering is a min-heap on ``(priority, deadline, seq)``: lower priority
values pop first (jobs.PRIORITY_INTERACTIVE before PRIORITY_BATCH), and
within a priority jobs pop **earliest-deadline-first** —
``Job.deadline_mono`` = admit + the job's SLO target — so a
tight-deadline job never starves behind earlier-admitted loose ones
(each reordering EDF actually performs counts into
``serve.shape.edf_promotions``).  Jobs of one SLO class share a target,
so their deadlines increase with admission time and equal-class traffic
stays FIFO (``seq`` is assigned under the queue lock, breaking exact
ties deterministically).  ``edf=False`` restores pure
(priority, seq) FIFO — the CI deadline-inversion probe runs against
exactly that posture to prove the check can fail.

Coalescing: :meth:`pop_batch` pops the EDF head plus same-group
ride-alongs, and — when a :class:`serve.shaping.TrafficShaper` is
installed — may **hold** for a few milliseconds when the head bucket's
arrival rate predicts a larger batch rung will fill within the
deadline slack (the adaptive hold-for-coalesce window; every decision
is the shaper's, the queue only enforces it).  A hold ends early the
moment the rung fills or the queue closes, and every popped job gets a
``hold_start`` stamp so the window shows up as the fclat ``hold``
phase, never smeared into ``queue_wait``.

Drain: :meth:`close` stops admissions (submit raises
:class:`QueueClosed` -> HTTP 503) while :meth:`pop` keeps handing out
already-admitted jobs until the heap is empty, then returns ``None`` —
the worker's signal that a graceful SIGTERM drain is complete
(serve/server.py).  A closed queue never holds: drain latency beats
rung occupancy.
"""

from __future__ import annotations

import heapq
import time
import threading
from typing import Callable, List, Optional, Tuple

from fastconsensus_tpu.obs import counters as obs_counters
from fastconsensus_tpu.obs import flight as obs_flight
from fastconsensus_tpu.serve.jobs import Job


class QueueFull(RuntimeError):
    """Admission refused: the queue is at its depth bound (backpressure,
    not an internal error — HTTP maps it to 429 with a Retry-After
    derived from the observed service rate when a shaper is present; a
    bucket with no service history yet derives it from the static cost
    prior the shaper seeds (analysis/cost.py), so even the FIRST 429 a
    cold bucket ever sends carries model-derived honesty rather than
    the configured constant.  ``retry_after_s`` stays None only without
    a shaper, and the handler falls back to the default)."""

    retry_after_s: Optional[float] = None

    def __init__(self, depth: int, max_depth: int) -> None:
        super().__init__(
            f"queue full ({depth}/{max_depth} jobs); retry later")
        self.depth = depth
        self.max_depth = max_depth


class DeadlineShed(QueueFull):
    """Admission refused: at the current queued depth this job provably
    cannot meet its SLO deadline (serve/shaping.py ``should_shed``), so
    it is rejected at submit instead of occupying a slot just to miss.
    Maps to HTTP 429 like :class:`QueueFull` — from the client's side
    both mean "retry after the queue drains" — but the message names
    the deadline math."""

    def __init__(self, depth: int, max_depth: int, reason: str) -> None:
        RuntimeError.__init__(self, reason)
        self.depth = depth
        self.max_depth = max_depth


class QueueClosed(RuntimeError):
    """Admission refused: the service is draining (HTTP 503)."""


class AdmissionQueue:
    """Bounded thread-safe deadline-ordered priority queue of
    :class:`Job`s."""

    def __init__(self, max_depth: int, edf: bool = True) -> None:
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = int(max_depth)
        self.edf = bool(edf)
        # entries: (priority, deadline-or-0, seq, job); the deadline
        # slot is 0.0 under edf=False so ordering degrades to the
        # pre-fcshape (priority, seq) FIFO without a second heap shape
        self._heap: List[Tuple[int, float, int, Job]] = []
        self._seq = 0
        self._closed = False
        self._cond = threading.Condition()
        self._extra_depth: Optional[Callable[[], int]] = None
        self._shaper = None   # serve/shaping.TrafficShaper, optional
        self._reg = obs_counters.get_registry()

    def set_extra_depth(self, fn: Callable[[], int]) -> None:
        """Count admitted-but-undispatched jobs parked OUTSIDE the heap
        toward the depth bound.  The fcpool dispatcher (serve/pool.py)
        eagerly moves popped batches into per-worker deques; without
        this hook that would hollow out the backpressure contract — the
        heap would drain in microseconds and a depth-1 queue would
        absorb an unbounded burst into worker backlogs.  ``fn`` is
        called under the queue lock and must not take the queue lock
        itself (worker deque locks are always acquired after it)."""
        with self._cond:
            self._extra_depth = fn

    def set_shaper(self, shaper) -> None:
        """Install the traffic shaper consulted by :meth:`pop_batch`
        for hold-for-coalesce decisions (None disables holding — the
        pre-fcshape never-waits posture).  The shaper is called under
        the queue lock; its own locks (estimate cache, fclat registry)
        are leaves that never take the queue's, keeping the
        acquisition graph acyclic."""
        with self._cond:
            self._shaper = shaper

    def submit(self, job: Job) -> None:
        """Admit ``job`` or raise :class:`QueueFull` /
        :class:`QueueClosed` — never blocks, never exceeds the bound."""
        with self._cond:
            if self._closed:
                self._reg.inc("serve.queue.rejected_draining")
                raise QueueClosed("service is draining; not accepting jobs")
            depth = len(self._heap) + (self._extra_depth()
                                       if self._extra_depth else 0)
            if depth >= self.max_depth:
                self._reg.inc("serve.queue.rejected_full")
                # fcflight: 429s are exactly the events a post-incident
                # timeline needs next to the hangs that caused them
                obs_flight.record("reject_429", job=job.job_id,
                                  depth=depth)
                raise QueueFull(depth, self.max_depth)
            self._seq += 1
            heapq.heappush(
                self._heap,
                (job.spec.priority,
                 job.deadline_mono if self.edf else 0.0,
                 self._seq, job))
            self._reg.inc("serve.queue.admitted")
            depth = len(self._heap)
            self._reg.gauge("serve.queue.depth", depth)
            self._cond.notify()
        # flight append outside _cond: admits race the dispatcher's
        # pop for this lock, and the timeline doesn't need the
        # critical section — only the depth observed inside it
        trace = getattr(job.spec, "trace", None)
        obs_flight.record("admit", job=job.job_id,
                          priority=job.spec.priority, depth=depth,
                          **({"trace": trace} if trace else {}))

    def _note_promotion(self, heap, popped_seq: int,
                        priority: int) -> None:
        """Count one EDF reordering: the popped head left behind a
        same-priority job admitted EARLIER (smaller seq) — under FIFO
        that job would have popped first, so EDF provably promoted a
        tighter deadline past it.  ``heap`` is the caller's
        lock-guarded heap (passed explicitly — the caller holds
        ``_cond`` for the whole pop); depth-bounded, so the scan is
        O(max_depth)."""
        if not self.edf:
            return
        for prio, _, seq, _ in heap:
            if prio == priority and seq < popped_seq:
                self._reg.inc("serve.shape.edf_promotions")
                return

    def pop(self, timeout: Optional[float] = None) -> Optional[Job]:
        """Next job by (priority, deadline, admission order).

        Blocks until a job is available or the queue is closed *and*
        empty (returns ``None`` — drain complete).  With ``timeout``,
        also returns ``None`` if nothing arrived in time; callers that
        need to distinguish check :meth:`draining`.
        """
        with self._cond:
            while True:
                if self._heap:
                    prio, _, seq, job = heapq.heappop(self._heap)
                    self._note_promotion(self._heap, seq, prio)
                    self._reg.gauge("serve.queue.depth", len(self._heap))
                    # fclat queue_wait closes HERE — the moment the job
                    # leaves the admission heap (Job.stamp is a leaf
                    # lock; no cycle with _cond).  The solo pop never
                    # holds, so hold_start == the pop instant (hold=0).
                    t_pop = time.monotonic()
                    job.stamp_hold(t_pop)
                    job.stamp("dispatched", at=t_pop)
                    obs_flight.record("pop", job=job.job_id,
                                      depth=len(self._heap))
                    return job
                if self._closed:
                    return None
                if not self._cond.wait(timeout):
                    return None

    def pop_batch(self, max_b: int,
                  group_key: Callable[[Job], str],
                  timeout: Optional[float] = None
                  ) -> Optional[List[Job]]:
        """The next job plus up to ``max_b - 1`` already-queued jobs of
        the same batch group (serve/jobs.JobSpec.batch_group) — the
        cross-request coalescing pop, with an optional adaptive
        hold-for-coalesce window (serve/shaping.py).

        Priority is never starved: the HEAD is always the strict
        ``(priority, deadline, seq)`` front of the queue, coalescing
        only pulls *ride-along* jobs that would otherwise run later,
        in that same EDF order.  Without a shaper a lone job pops
        immediately as a batch of one (the pre-fcshape contract, and
        still the test posture).  With a shaper, the pop may wait —
        bounded by the shaper's decision, which is itself bounded by
        the tightest queued deadline minus the measured service time —
        for the head bucket's predicted arrivals to fill a larger
        batch rung; the wait ends the instant the rung fills, the hold
        window expires, or the queue closes.

        Same drain semantics as :meth:`pop`: ``None`` once the queue is
        closed *and* empty (or on ``timeout`` with nothing queued).
        """
        with self._cond:
            hold_began: Optional[float] = None   # first episode start
            hold_until: Optional[float] = None   # active episode end
            hold_target = 0
            held_group: Optional[str] = None
            while True:
                if self._heap:
                    head = self._heap[0][3]
                    g = group_key(head)
                    shaper = self._shaper
                    if shaper is not None and max_b > 1 \
                            and not self._closed:
                        now = time.monotonic()
                        have = 0
                        tightest = None
                        blocks_solo = False
                        for _, _, _, j in self._heap:
                            if group_key(j) == g:
                                have += 1
                            if tightest is None \
                                    or j.deadline_mono < tightest:
                                tightest = j.deadline_mono
                            if not blocks_solo and j is not head:
                                # a queued mesh-tier job: holding the
                                # head parks it behind the window while
                                # its own (separate) tier may be idle
                                try:
                                    blocks_solo = shaper.runs_solo(
                                        j.spec.bucket().key())
                                # fcheck: ok=swallowed-error (a probe, not an action:
                                # blocks_solo just stays False and the hold window
                                # proceeds on the conservative default)
                                except Exception:  # noqa: BLE001
                                    pass
                        if held_group is not None and held_group != g:
                            # a tighter-deadline job of another group
                            # took the head mid-hold: the old episode
                            # is moot, decide afresh for the new head —
                            # and the new head's pop must not inherit
                            # the aborted episode's start stamp (its
                            # group never held)
                            hold_until = None
                            held_group = None
                            hold_began = None
                        if hold_until is not None \
                                and have >= hold_target:
                            # rung filled early: close this episode and
                            # re-decide (the shaper may chase the next
                            # rung, still deadline-bounded, or pop)
                            hold_until = None
                            held_group = None
                            continue
                        if hold_until is None:
                            try:
                                bucket = head.spec.bucket().key()
                            except Exception:  # noqa: BLE001 — an
                                bucket = None  # unbucketable spec pops
                            decision = shaper.hold_decision(
                                bucket, have=have, max_b=max_b,
                                slack_s=tightest - now, now=now,
                                group=g, blocks_solo=blocks_solo)
                            if decision.hold_s > 0.0:
                                hold_until = now + decision.hold_s
                                hold_target = decision.target
                                held_group = g
                                if hold_began is None:
                                    hold_began = now
                        if hold_until is not None:
                            if now >= hold_until \
                                    or not shaper.hold_is_free():
                                # window expired — or a worker went
                                # idle mid-hold, making every further
                                # held millisecond real latency: pop
                                hold_until = None
                                held_group = None
                            else:
                                # short wait slices so the idle check
                                # above re-runs every few ms, not only
                                # on submit wakeups
                                self._cond.wait(
                                    min(hold_until - now, 0.005))
                                continue
                    prio, _, head_seq, head = heapq.heappop(self._heap)
                    self._note_promotion(self._heap, head_seq, prio)
                    taken = [head]
                    if max_b > 1 and self._heap:
                        rest: List[Tuple[int, float, int, Job]] = []
                        # sorted() of a heap is a valid heap, and gives
                        # ride-alongs in strict (priority, deadline,
                        # seq) order — EDF order
                        for entry in sorted(self._heap):
                            if len(taken) < max_b and \
                                    group_key(entry[3]) == g:
                                taken.append(entry[3])
                            else:
                                rest.append(entry)
                        self._heap = rest
                        if len(taken) > 1:
                            self._reg.inc("serve.queue.coalesced_pops")
                    self._reg.gauge("serve.queue.depth", len(self._heap))
                    t_pop = time.monotonic()
                    t_hold = hold_began if hold_began is not None \
                        else t_pop
                    for t in taken:
                        # queue_wait closes at the hold start (or the
                        # pop, when nothing held) for the head AND
                        # every ride-along; the hold phase then spans
                        # to the coalesced pop they leave the heap in
                        t.stamp_hold(t_hold)
                        t.stamp("dispatched", at=t_pop)
                        obs_flight.record("pop", job=t.job_id,
                                          n=len(taken))
                    if hold_began is not None:
                        obs_flight.record(
                            "hold", job=head.job_id,
                            held_s=round(t_pop - hold_began, 6),
                            n=len(taken))
                    return taken
                if self._closed:
                    return None
                if not self._cond.wait(timeout):
                    return None

    def close(self) -> None:
        """Stop admissions; wake blocked poppers so they can drain."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()

    def depth(self) -> int:
        with self._cond:
            return len(self._heap)

    def total_depth(self) -> int:
        """Heap depth plus the dispatched-but-unstarted backlog the
        ``extra_depth`` hook tracks — the depth the admission bound
        (and the shaping Retry-After / shed math) actually judges."""
        with self._cond:
            return len(self._heap) + (self._extra_depth()
                                      if self._extra_depth else 0)

    def draining(self) -> bool:
        with self._cond:
            return self._closed
