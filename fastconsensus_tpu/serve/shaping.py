"""fcshape: SLO-aware traffic shaping for the serving stack.

The fclat substrate (PR 9) measured the problem this module solves: the
committed ``runs/bench_serve_load_r09.json`` curve shows p95 growing
16 -> 80 ms from 2 -> 32 rps with **deque-wait, not device time, as the
growth driver** — the queue fragments steady traffic into small batch
rungs because ``pop_batch`` never waits, and batching only wins when the
heap happens to be deep.  fcshape turns the observed SLO classes,
arrival rates and phase histograms into a control loop with three arms:

* **earliest-deadline-first admission ordering** — every job carries an
  absolute monotonic deadline (``Job.deadline_mono`` = admit +
  ``JobSpec.slo_target()``), the admission heap orders by
  ``(priority, deadline, seq)``, and ``pop_batch`` pops in that order,
  so within a priority a tight-deadline job is never starved behind
  earlier-admitted loose ones (``serve.shape.edf_promotions`` counts
  each reordering EDF actually performed);

* **adaptive hold-for-coalesce** (:meth:`TrafficShaper.hold_decision`)
  — when the head-of-queue's bucket shows an arrival rate that predicts
  a larger batch rung will fill *within the deadline slack*, the pop
  holds for ``hold_margin x`` the expected time-to-fill (Poisson
  arrivals are noisy; a bare mean-fill hold would abandon half its
  rungs one arrival short) and coalesces the stragglers into one
  device call.  The hold is bounded by the **tightest queued deadline
  minus the measured service-time estimate** — never by hope — and a
  rung that cannot fill inside ``min(max_hold_s, slack)`` bypasses
  instantly (``serve.shape.{holds,bypass}``), so a lone tight-deadline
  job dispatches with zero added latency;

* **honest backpressure** — Retry-After on a 429 derives from queued
  depth x the per-bucket observed service time over the pool's live
  parallelism (:meth:`TrafficShaper.retry_after_s`), replacing the old
  literal ``"1"``; and a job that *provably* cannot meet its deadline
  at the current depth is shed at submit (:meth:`should_shed`,
  ``serve.shape.deadline_sheds``) instead of occupying a slot just to
  miss — the client learns in microseconds what the queue would have
  told it after the whole SLO window.

Everything here is stdlib-only (jax-free: the predictor and estimator
must be loadable by the report tooling and testable under a fake
clock) and lock-light: the shaper's only mutable state is a small
estimate cache guarded by one leaf lock that never nests another, so
``fcheck-concurrency`` runs clean with zero pragmas.  The queue calls
:meth:`hold_decision` while holding its own condition — the resulting
acquisition edges (queue cond -> shaper cache -> fclat registry locks)
are one-directional by construction.
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from fastconsensus_tpu.obs import counters as obs_counters
from fastconsensus_tpu.obs import latency as obs_latency

# Mirror of serve/bucketer.py BATCH_LADDER, kept import-light so the
# shaper stays jax-free (bucketer pulls graph -> jax); the mirror is
# pinned against the real ladder in tests/test_shaping.py, exactly like
# the footprint analyzer's jax-free grid mirror.
BATCH_LADDER: Tuple[int, ...] = (1, 2, 4, 8)

# How long a computed per-bucket service estimate is reused before the
# histograms are re-read: hold_decision runs under the admission
# queue's condition on EVERY pop, and re-merging every phase histogram
# there would make the queue lock's hold time grow with metric
# cardinality.  Estimates move on the time scale of traffic shifts,
# not pops.
ESTIMATE_TTL_S = 0.25


@dataclasses.dataclass(frozen=True)
class ShapingConfig:
    """Operator knobs for the traffic-shaping control loop.

    Each arm degrades independently to the pre-shaping posture:
    ``edf=False`` restores FIFO-within-priority ordering,
    ``hold=False`` restores the never-waits ``pop_batch``, and
    ``shed=False`` restores depth-only 429s (Retry-After stays derived
    — honesty costs nothing).
    """

    edf: bool = True
    hold: bool = True
    shed: bool = True
    # Hard cap on one hold episode.  The principled bound is the
    # deadline slack; this cap exists so a batch-class queue (120 s
    # slack) still cannot park the dispatcher for seconds chasing a
    # rung — past ~50 ms the coalescing win is already amortized away
    # by the wait itself at interactive service times.
    max_hold_s: float = 0.050
    # Hold for margin x expected fill: inter-arrival times are
    # exponential, so the expected-fill point leaves ~half of rungs one
    # arrival short; 1.5x trades a little worst-case latency (still
    # slack-bounded) for most of that tail.
    hold_margin: float = 1.5
    # Estimates with fewer service samples than this never shed work or
    # shape Retry-After (cold start must not reject traffic on noise);
    # hold decisions use whatever exists — a hold's worst case is
    # bounded latency, a shed's is a wrongly refused job.
    min_estimate_count: int = 8
    retry_after_default_s: float = 1.0
    retry_after_max_s: float = 600.0


@dataclasses.dataclass(frozen=True)
class HoldDecision:
    """One ``pop_batch`` hold verdict: wait ``hold_s`` (0 = dispatch
    now) for the batch rung ``target`` to fill; ``reason`` names why."""

    hold_s: float
    target: int
    reason: str


def next_rung(have: int, max_b: int,
              ladder: Tuple[int, ...] = BATCH_LADDER) -> Optional[int]:
    """The next batch-ladder rung above ``have`` reachable under
    ``max_b``, or None when ``have`` already fills the top rung."""
    for rung in ladder:
        if have < rung <= max_b:
            return rung
    return None


def expected_fill_s(have: int, target: int, rate_per_s: float) -> float:
    """Predicted seconds until ``target - have`` more same-group jobs
    arrive at ``rate_per_s`` (the per-bucket arrival tracker's view).
    ``inf`` when the rate is unknown or zero — an idle bucket predicts
    no ride-alongs, so the caller must bypass, never hold on hope.
    Pure arithmetic: the fake-clock predictor unit drives it with
    :meth:`obs.latency.RateTracker.rate` values stamped at explicit
    times."""
    need = max(int(target) - int(have), 0)
    if need == 0:
        return 0.0
    if rate_per_s <= 0.0:
        return math.inf
    return need / float(rate_per_s)


def find_deadline_inversions(pop_log: Iterable[Any]) -> List[str]:
    """EDF-order findings over a completed pop sequence; [] = clean.

    ``pop_log`` is the jobs (or ``(priority, deadline, seq)`` tuples)
    in the order they were popped from a fully loaded queue.  Within a
    priority the deadlines must be non-decreasing — a later pop with an
    earlier deadline means a tight-deadline job waited behind a loose
    one, the starvation EDF exists to prevent.  Each finding names the
    check (``deadline-inversion``) so the CI negative probe can assert
    the failure is THIS check firing, not an unrelated crash.
    """
    problems: List[str] = []
    last: Dict[int, Tuple[float, Any]] = {}
    for item in pop_log:
        if hasattr(item, "deadline_mono"):
            prio = item.spec.priority
            deadline = item.deadline_mono
            tag = item.job_id
        else:
            prio, deadline, tag = item[0], item[1], item[2]
        prev = last.get(prio)
        if prev is not None and deadline < prev[0] - 1e-9:
            problems.append(
                f"deadline-inversion: priority {prio} popped {tag!r} "
                f"(deadline {deadline:.6f}) after {prev[1]!r} "
                f"(deadline {prev[0]:.6f}) — EDF ordering violated")
        last[prio] = (deadline, tag)
    return problems


def _no_prior(bucket: str) -> Optional[float]:
    """Fallback cost-prior when the analyzer cannot load: no seeding."""
    return None


class TrafficShaper:
    """The shaping control loop shared by queue, admission and HTTP.

    Reads the fclat signals (per-bucket arrival rates marked at submit,
    per-bucket phase histograms folded per finished job) and answers
    three questions: *should this pop wait* (:meth:`hold_decision`),
    *should this submit be shed* (:meth:`should_shed`), and *when
    should a rejected client retry* (:meth:`retry_after_s`).  All
    decisions are recorded into ``serve.shape.*`` counters so
    ``/metricsz`` exposes the loop's behavior, not just its outcome.
    """

    def __init__(self, config: Optional[ShapingConfig] = None,
                 lat: Optional[obs_latency.LatencyRegistry] = None,
                 reg=None,
                 parallelism: Optional[Callable[[], int]] = None,
                 cost_prior: Optional[
                     Callable[[str], Optional[float]]] = None) -> None:
        self.config = config or ShapingConfig()
        self._lat = lat if lat is not None \
            else obs_latency.get_latency_registry()
        self._reg = reg if reg is not None \
            else obs_counters.get_registry()
        self._parallelism = parallelism
        self._busy_probe: Optional[Callable[[], bool]] = None
        self._solo_probe: Optional[Callable[[str], bool]] = None
        # Cold-start device-seconds model: bucket key -> est seconds or
        # None.  Default resolves lazily to the fcheck-cost jax-free
        # mirror (analysis/cost.py static_service_prior) on first cold
        # lookup; tests inject a fake, and ``lambda b: None`` disables
        # seeding outright.
        self._cost_prior = cost_prior
        self._lock = threading.Lock()
        # bucket key (or None = all buckets) -> (computed_at, estimate)
        self._est_cache: Dict[Optional[str],
                              Tuple[float, Optional[dict]]] = {}
        # buckets whose estimate has been prior-seeded at least once
        # (the serve.shape.prior_seeded counter counts BUCKETS, not
        # lookups — service_estimate runs on every pop)
        self._prior_seeded: set = set()

    def set_parallelism(self, fn: Callable[[], int]) -> None:
        """Install the live-worker counter (the pool's eligible chip
        count) once the pool exists — Retry-After and shed math divide
        the queued work across the devices actually draining it."""
        self._parallelism = fn

    def set_busy_probe(self, fn: Callable[[], bool]) -> None:
        """Install the pool's all-chips-busy probe.  This is the hold
        economics in one bit: while every eligible worker is occupied a
        held job would only have waited in a worker deque anyway, so
        the hold is FREE latency-wise and pure occupancy gain; the
        moment a worker sits idle, holding trades real latency for
        predicted occupancy — a bad trade at interactive service
        times, so the decision bypasses.  Without a probe (unit tests,
        embedded use) holding is assumed free."""
        self._busy_probe = fn

    def hold_is_free(self) -> bool:
        """True while holding costs nothing (see set_busy_probe); the
        queue also re-checks this mid-hold so a worker going idle ends
        the episode within one wait slice instead of at the window."""
        if self._busy_probe is None:
            return True
        try:
            return bool(self._busy_probe())
        except Exception:  # noqa: BLE001 — a mid-drain pool must not
            return True    # wedge the pop path

    def set_solo_probe(self, fn: Callable[[str], bool]) -> None:
        """Install the pool's bucket-runs-solo probe (True for buckets
        the mesh/huge tier serves): those jobs execute one at a time
        regardless of coalescing, so a hold buys a bigger pop that
        still runs solo — pure added latency.  hold_decision bypasses
        them."""
        self._solo_probe = fn

    def runs_solo(self, bucket: Optional[str]) -> bool:
        """Whether this bucket's jobs execute solo (mesh/huge tier) —
        the queue also consults it for the heap it would delay."""
        if bucket is None or self._solo_probe is None:
            return False
        try:
            return bool(self._solo_probe(bucket))
        except Exception:  # noqa: BLE001 — an unparseable key routes
            return False   # chip-tier; the pop will sort it out

    def _workers(self) -> int:
        if self._parallelism is None:
            return 1
        try:
            return max(int(self._parallelism()), 1)
        except Exception:  # noqa: BLE001 — a mid-drain pool must not
            return 1       # break admission math

    # -- the service-time estimate ------------------------------------

    def service_estimate(self, bucket: Optional[str],
                         now: Optional[float] = None,
                         fallback: bool = True) -> Optional[dict]:
        """Cached :meth:`LatencyRegistry.service_estimate` for one
        bucket.  With ``fallback`` (the default), a bucket with no
        history yet borrows the all-bucket estimate — fine for hold
        bounds and Retry-After, where overestimating only shortens a
        hold or delays a retry; the shed path passes ``fallback=False``
        because refusing a job on ANOTHER bucket's service time is not
        "provably late".  Cached for :data:`ESTIMATE_TTL_S` because the
        queue consults it under its condition on every pop.

        A bucket with NO measured history anywhere in the chain is
        seeded from the static cost prior (the fcheck-cost mirrored
        roofline): ``{"count": 0, "mean_s": prior, "p95_s": prior,
        "prior": True}`` — so cold hold bounds, Retry-After and shed
        math start from the model instead of a constant guess.  Any
        measured sample beats the model (the prior only fills
        ``est is None``), and ``retry_after_s`` / ``should_shed``
        accept a seeded estimate in place of their
        ``min_estimate_count`` history gate (the ``"prior"`` marker)."""
        est = self._cached_estimate(bucket, now)
        if est is None and fallback and bucket is not None:
            est = self._cached_estimate(None, now)
        if est is None and bucket is not None:
            prior = self._static_prior(bucket)
            if prior is not None and prior > 0:
                with self._lock:
                    if bucket not in self._prior_seeded:
                        self._prior_seeded.add(bucket)
                        seed_new = True
                    else:
                        seed_new = False
                if seed_new:
                    self._reg.inc("serve.shape.prior_seeded")
                est = {"count": 0, "mean_s": round(float(prior), 9),
                       "p95_s": round(float(prior), 9), "prior": True}
        return est

    def _static_prior(self, bucket: str) -> Optional[float]:
        fn = self._cost_prior
        if fn is None:
            # analysis/cost.py is jax-free by contract (its own
            # poisoned-jax subprocess test); the import is deferred so
            # embedded shapers with an injected prior never load it
            try:
                from fastconsensus_tpu.analysis import cost as _cost
                fn = _cost.static_service_prior
            except Exception:  # noqa: BLE001 — a broken analyzer must
                fn = _no_prior  # not take down admission
            self._cost_prior = fn
        try:
            return fn(bucket)
        except Exception:  # noqa: BLE001 — ditto: an unparseable key
            return None    # just means "no prior"

    def _cached_estimate(self, which: Optional[str],
                         now: Optional[float]) -> Optional[dict]:
        """TTL-cached per-bucket (None = all-bucket) estimate read."""
        t = time.monotonic() if now is None else float(now)
        with self._lock:
            hit = self._est_cache.get(which)
            if hit is not None and t - hit[0] <= ESTIMATE_TTL_S:
                return hit[1]
        est = self._lat.service_estimate(which)
        with self._lock:
            self._est_cache[which] = (t, est)
        return est

    # -- arm 2: adaptive hold-for-coalesce ----------------------------

    def hold_decision(self, bucket: Optional[str], have: int,
                      max_b: int, slack_s: float,
                      now: Optional[float] = None,
                      group: Optional[str] = None,
                      blocks_solo: bool = False) -> HoldDecision:
        """Should ``pop_batch`` wait for a larger rung?

        ``have`` is the same-group jobs already queued, ``slack_s`` the
        tightest queued deadline minus now (across the WHOLE heap — a
        hold delays every queued job, not just its own group), and
        ``group`` the head's batch group: the fill prediction prefers
        the GROUP arrival rate, because only same-group arrivals can
        join the rung — the bucket rate is just the fallback for a
        group with no history yet.  ``blocks_solo`` means a mesh-tier
        job is queued behind the head: its idle tier cannot be probed
        cheaply, so the decision bypasses rather than park work a
        separate tier could be running.  The hold window is
        ``min(hold_margin x expected fill, max_hold_s, slack - service
        estimate)``; when the expected fill cannot complete inside that
        bound the decision is an instant bypass — holding a doomed rung
        would buy occupancy with missed SLOs.
        """
        cfg = self.config
        if not cfg.hold or max_b <= 1:
            return HoldDecision(0.0, max(have, 1), "disabled")
        target = next_rung(have, max_b)
        if target is None:
            return HoldDecision(0.0, have, "rung_full")
        if self.runs_solo(bucket):
            # mesh/huge-tier buckets execute solo whatever the pop
            # size: a bigger rung gains nothing, the wait is pure loss
            self._reg.inc("serve.shape.bypass")
            return HoldDecision(0.0, target, "solo_tier")
        if blocks_solo:
            self._reg.inc("serve.shape.bypass")
            return HoldDecision(0.0, target, "blocks_solo_tier")
        if not self.hold_is_free():
            # an idle worker means a held job pays the wait for real
            # (it could be running RIGHT NOW); dispatch immediately —
            # coalescing under light load is the deque re-merge's job
            self._reg.inc("serve.shape.bypass")
            return HoldDecision(0.0, target, "worker_idle")
        est = self.service_estimate(bucket, now=now)
        est_s = (est or {}).get("p95_s") or 0.0
        slack = float(slack_s) - est_s
        if slack <= 0.0:
            self._reg.inc("serve.shape.bypass")
            return HoldDecision(0.0, target, "deadline")
        rate = self._lat.group_arrivals.rate(group, now=now) \
            if group is not None else 0.0
        if rate <= 0.0:
            rate = self._lat.arrivals.rate(bucket, now=now) \
                if bucket is not None else 0.0
        fill = expected_fill_s(have, target, rate)
        bound = min(cfg.max_hold_s, slack)
        if fill > bound:
            self._reg.inc("serve.shape.bypass")
            return HoldDecision(0.0, target, "fill_exceeds_slack")
        hold = min(fill * cfg.hold_margin, bound)
        self._reg.inc("serve.shape.holds")
        return HoldDecision(hold, target, "hold")

    # -- arm 3: honest backpressure -----------------------------------

    def retry_after_s(self, depth: int,
                      bucket: Optional[str] = None) -> float:
        """Seconds until the queue has plausibly drained ``depth``
        jobs: depth x the observed per-job service time over the live
        worker count.  Until the estimate has ``min_estimate_count``
        samples, a prior-seeded estimate (the static cost model — see
        :meth:`service_estimate`) still derives the answer; only a
        bucket with neither history nor a prior falls back to
        ``retry_after_default_s`` — an honest guess beats a precise
        fabrication."""
        cfg = self.config
        est = self.service_estimate(bucket)
        if est is None or not est["mean_s"] or (
                est["count"] < cfg.min_estimate_count
                and not est.get("prior")):
            return cfg.retry_after_default_s
        v = max(int(depth), 1) * est["mean_s"] / self._workers()
        return min(max(v, 0.001), cfg.retry_after_max_s)

    def should_shed(self, bucket: Optional[str], deadline_mono: float,
                    depth: int,
                    now: Optional[float] = None) -> Optional[str]:
        """A shed reason when the job provably cannot meet its deadline
        at the current queued depth, else None (admit it).

        "Provably" is held to an OPTIMISTIC service model: the drain
        rate is the better of the per-bucket observed dispatch rate
        (which already includes every batching win) and ``workers /
        mean service time``; only when even that model lands the job
        past its deadline is it refused.  Anything less conservative
        would shed traffic the pool could have served — a 429 storm is
        the failure mode, not the feature.
        """
        cfg = self.config
        if not cfg.shed or depth <= 0:
            return None
        # per-bucket history ONLY (no cross-bucket fallback): "provably
        # late" judged on another bucket's service time is a guess, and
        # the estimator already excludes cold-compile samples — both
        # are real false-shed modes tier-1 caught.  A prior-seeded
        # estimate (this bucket's OWN static model) is admissible where
        # a borrowed measurement is not: it is conservative (worst-case
        # sweep counts) and bucket-specific, so "provably late" against
        # it errs toward admitting.
        est = self.service_estimate(bucket, now=now, fallback=False)
        if est is None or not est["mean_s"] or (
                est["count"] < cfg.min_estimate_count
                and not est.get("prior")):
            return None
        t = time.monotonic() if now is None else float(now)
        per_worker = self._workers() / est["mean_s"]
        dispatch = self._lat.dispatches.rate(bucket, now=t) \
            if bucket is not None else 0.0
        drain = max(per_worker, dispatch)
        eta = t + depth / drain + est["p95_s"]
        if eta <= deadline_mono:
            return None
        self._reg.inc("serve.shape.deadline_sheds")
        late_ms = (eta - deadline_mono) * 1000.0
        return (f"deadline shed: {depth} queued job(s) at "
                f"~{est['mean_s'] * 1000.0:.1f} ms/job across "
                f"{self._workers()} worker(s) put completion "
                f"~{late_ms:.0f} ms past the "
                f"{(deadline_mono - t) * 1000.0:.0f} ms deadline slack; "
                f"retry later or relax the SLO class")

    # -- introspection ------------------------------------------------

    def describe(self, depth: int = 0,
                 buckets: Iterable[str] = ()) -> Dict[str, Any]:
        """The ``/metricsz`` ``shaping`` block: the live config, the
        ``serve.shape.*`` counters, per-bucket service estimates for
        every bucket with arrival history, and the Retry-After a 429
        issued right now would carry."""
        cfg = self.config
        counters = self._reg.counters()
        estimates = {}
        for b in buckets:
            # through the TTL cache (fallback off: a borrowed estimate
            # would render as the bucket's own) — a metrics scraper
            # polling /metricsz must not re-merge every histogram per
            # bucket per poll
            est = self.service_estimate(b, fallback=False)
            if est is not None:
                estimates[b] = est
        return {
            "config": {
                "edf": cfg.edf, "hold": cfg.hold, "shed": cfg.shed,
                "max_hold_s": cfg.max_hold_s,
                "hold_margin": cfg.hold_margin,
                "min_estimate_count": cfg.min_estimate_count,
            },
            "counters": {
                name: counters.get(f"serve.shape.{name}", 0)
                for name in ("holds", "bypass", "edf_promotions",
                             "deadline_sheds", "prior_seeded")},
            "estimates": estimates,
            "retry_after_hint_s": round(self.retry_after_s(depth), 6),
        }
