"""fcflight hang watchdog: detect a wedged device call, cordon, dump.

``utils/supervise.py`` already survives a wedged PROCESS (progress-file
watchdog, SIGKILL, relaunch), but inside a serving replica that is the
wrong granularity: one stuck device call — a pathological graph, a
wedged transport, an XLA bug — would freeze one worker while seven
healthy chips keep serving, and killing the process throws away all
eight.  The hang watchdog is the per-worker version of the same idea:

* **Heartbeats, not progress files.**  Workers stamp a heartbeat at
  batch dequeue, device dispatch and device done
  (:meth:`HangWatchdog.beat` — the pool and the service's device-call
  sites call it; each beat is one uncontended lock take, O(1)).
* **A measured threshold, not a constant.**  A device call is "hung"
  when it exceeds ``k ×`` the bucket's measured service p95
  (``LatencyRegistry.service_estimate`` — the fcshape estimator, which
  already excludes cache hits and cold-compile-tagged timelines), with
  a floor (``floor_s``) so sub-millisecond buckets don't trip on
  scheduler jitter.  Two guards keep false positives structural, not
  tuned: a dispatch the server expects to COMPILE (bucket not warm on
  that worker) is exempt — XLA legitimately takes minutes — and a
  bucket with fewer than ``min_history`` completed device calls never
  trips at all (no distribution, no verdict).
* **Cordon-on-stall.**  A trip marks the worker *suspect*, writes a
  post-mortem bundle (obs/postmortem.py), and cordons the worker
  through the same machinery a worker death uses (PR 6): the deque
  backlog requeues onto surviving devices with the suspect excluded,
  so the fleet keeps serving while the stuck call either returns late
  (the worker finishes its job but takes no new work) or never does.
  Surfaced in ``/healthz`` (``suspect_devices``, ``last_bundle``) and
  the ``serve.flight.*`` counters.

Everything here is stdlib-only (jax-free) and fake-clock testable:
:meth:`check` is a pure function of the heartbeat table, the latency
registry and ``now``.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

_logger = logging.getLogger("fastconsensus_tpu")


@dataclasses.dataclass(frozen=True)
class WatchdogConfig:
    """Hang-watchdog knobs (``ServeConfig.watchdog``).

    ``k``            trip at ``k x`` the bucket's service p95
    ``floor_s``      never trip below this elapsed time (absorbs
                     scheduler jitter on microsecond buckets)
    ``min_history``  minimum completed device calls in the bucket
                     before its p95 is trusted (the min-history guard)
    ``poll_s``       watchdog thread wake interval
    ``cordon``       False = observe-only (trip counters + bundle, no
                     cordon) — the cautious first-deploy posture
    """

    enabled: bool = True
    k: float = 8.0
    floor_s: float = 30.0
    min_history: int = 8
    poll_s: float = 0.5
    cordon: bool = True

    def validate(self) -> None:
        if self.k <= 0:
            raise ValueError(f"watchdog k={self.k} must be > 0")
        if self.floor_s < 0:
            raise ValueError(
                f"watchdog floor_s={self.floor_s} must be >= 0")
        if self.min_history < 1:
            raise ValueError(
                f"watchdog min_history={self.min_history} must be >= 1")
        if self.poll_s <= 0:
            raise ValueError(
                f"watchdog poll_s={self.poll_s} must be > 0")


class _Beat:
    """One worker's latest heartbeat (all fields guarded by the
    watchdog lock — instances never leave :class:`HangWatchdog`)."""

    __slots__ = ("state", "since", "job", "bucket", "cold", "n_jobs",
                 "seq", "tripped")

    def __init__(self) -> None:
        self.state = "idle"
        self.since = 0.0
        self.job: Optional[str] = None
        self.bucket: Optional[str] = None
        self.cold = False
        self.n_jobs = 0
        self.seq = 0
        self.tripped = False


class HangWatchdog:
    """The per-pool hang watchdog; see the module docstring.

    ``latency`` is anything with a ``service_estimate(bucket=...,
    min_count=...)`` method (the fclat registry in production, a stub
    in tests); ``clock`` defaults to ``time.monotonic`` and is
    injectable for fake-clock units; ``on_trip`` receives each trip
    dict exactly once per suspect episode.
    """

    def __init__(self, latency: Any,
                 config: Optional[WatchdogConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_trip: Optional[Callable[[Dict[str, Any]],
                                            None]] = None) -> None:
        self.config = config or WatchdogConfig()
        self.config.validate()
        self.latency = latency
        self.clock = clock
        self.on_trip = on_trip
        self._lock = threading.Lock()
        self._beats: Dict[int, _Beat] = {}
        self._suspects: Dict[int, Dict[str, Any]] = {}
        self._trips = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the hot path (workers) ---------------------------------------

    def beat(self, idx: int, state: str, job: Optional[str] = None,
             bucket: Optional[str] = None, cold: bool = False,
             n_jobs: int = 0) -> None:
        """Stamp worker ``idx``'s heartbeat: ``state`` is one of
        ``dequeue`` / ``device`` / ``device_done`` / ``idle``.  A beat
        ends any suspect episode for the worker — the stuck call
        returned after all — so the next hang trips (and bundles)
        afresh."""
        now = self.clock()
        with self._lock:
            b = self._beats.get(idx)
            if b is None:
                b = self._beats[idx] = _Beat()
            b.state = state
            b.since = now
            b.job = job
            b.bucket = bucket
            b.cold = cold
            b.n_jobs = n_jobs
            b.seq += 1
            b.tripped = False
            if state in ("device_done", "idle"):
                self._suspects.pop(idx, None)

    # -- the verdict --------------------------------------------------

    def check(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Evaluate every heartbeat; returns the NEW trips (each suspect
        episode trips once).  Estimates are read outside the watchdog
        lock — the latency registry has its own locks and the beat
        table must stay O(1) to stamp."""
        t_now = self.clock() if now is None else float(now)
        with self._lock:
            candidates = [
                (idx, b.seq, b.job, b.bucket, t_now - b.since)
                for idx, b in self._beats.items()
                if b.state == "device" and not b.tripped and not b.cold]
        trips: List[Dict[str, Any]] = []
        for idx, seq, job, bucket, elapsed in candidates:
            est = self.latency.service_estimate(
                bucket=bucket, min_count=self.config.min_history)
            if est is None:
                continue   # min-history guard: no distribution yet
            p95 = float(est.get("p95_s") or 0.0)
            threshold = max(self.config.k * p95, self.config.floor_s)
            if elapsed <= threshold:
                continue
            trip = {
                "device": idx,
                "job": job,
                "bucket": bucket,
                "elapsed_s": round(elapsed, 6),
                "threshold_s": round(threshold, 6),
                "service_p95_s": round(p95, 9),
                "history": est.get("count"),
            }
            with self._lock:
                b = self._beats.get(idx)
                if b is None or b.seq != seq:
                    continue   # the call finished while we deliberated
                b.tripped = True
                self._trips += 1
                self._suspects[idx] = trip
            trips.append(trip)
        return trips

    def suspects(self) -> List[Dict[str, Any]]:
        """Current suspect episodes (cleared when the worker beats
        again) — the ``/healthz`` ``suspect_devices`` payload."""
        with self._lock:
            return [dict(t) for _, t in sorted(self._suspects.items())]

    def trips(self) -> int:
        with self._lock:
            return self._trips

    def describe(self) -> Dict[str, Any]:
        with self._lock:
            beats = {
                idx: {"state": b.state, "job": b.job, "bucket": b.bucket,
                      "cold": b.cold, "n_jobs": b.n_jobs,
                      "since_mono": round(b.since, 6),
                      "tripped": b.tripped}
                for idx, b in sorted(self._beats.items())}
            trips = self._trips
            suspects = [dict(t) for _, t in sorted(self._suspects.items())]
        return {"config": dataclasses.asdict(self.config),
                "trips": trips, "suspects": suspects, "beats": beats}

    # -- the thread ---------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._poll_loop, name="fcflight-watchdog", daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)

    def _poll_loop(self) -> None:
        from fastconsensus_tpu.obs import counters as obs_counters

        reg = obs_counters.get_registry()
        while not self._stop.wait(self.config.poll_s):
            try:
                trips = self.check()
            except Exception:  # noqa: BLE001 — a poisoned estimate must
                # not kill the only thread that detects hangs; count it
                # so /metricsz shows a watchdog that polls but cannot
                # judge
                reg.inc("serve.watchdog.poll_errors")
                _logger.exception("fcflight: watchdog check failed")
                continue
            for trip in trips:
                cb = self.on_trip
                if cb is not None:
                    try:
                        cb(trip)
                    except Exception:  # noqa: BLE001 — the trip handler
                        # writes bundles and cordons; a bug there must
                        # not kill the watchdog itself
                        reg.inc("serve.watchdog.trip_errors")
                        _logger.exception(
                            "fcflight: watchdog trip handler failed")


class DisabledWatchdog:
    """No-op watchdog (``watchdog.enabled=False``): call sites stay
    unconditional, like the disabled tracer singleton."""

    config = WatchdogConfig(enabled=False)

    def beat(self, idx: int, state: str, job: Optional[str] = None,
             bucket: Optional[str] = None, cold: bool = False,
             n_jobs: int = 0) -> None:
        pass

    def check(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        return []

    def suspects(self) -> List[Dict[str, Any]]:
        return []

    def trips(self) -> int:
        return 0

    def describe(self) -> Dict[str, Any]:
        return {"config": {"enabled": False}, "trips": 0,
                "suspects": [], "beats": {}}

    def start(self) -> None:
        pass

    def stop(self, timeout: float = 5.0) -> None:
        pass


DISABLED_WATCHDOG = DisabledWatchdog()
