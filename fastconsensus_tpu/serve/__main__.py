"""CLI: ``python -m fastconsensus_tpu.serve`` — run one fcserve instance.

Binds the stdlib HTTP front end, launches the device worker, and waits
for SIGTERM/SIGINT; on signal the server **drains**: admissions close
(submits answer 503), every already-admitted job finishes, the server's
own fcobs trace artifacts are exported (``--trace-dir``), and the
process exits 0.  A non-zero exit means the drain timed out with work
still in flight.
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    from fastconsensus_tpu.serve.server import ServeConfig

    d = ServeConfig()
    p = argparse.ArgumentParser(
        prog="python -m fastconsensus_tpu.serve",
        description="fcserve: long-lived consensus-clustering service "
                    "(shape-bucketed batching, content-addressed result "
                    "cache, admission control).")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (default: loopback only)")
    p.add_argument("--port", type=int, default=8765,
                   help="TCP port (0 picks a free one; default 8765)")
    p.add_argument("--queue-depth", type=int, default=d.queue_depth,
                   help="admission bound: jobs beyond this are rejected "
                        f"with HTTP 429 (default {d.queue_depth})")
    p.add_argument("--cache-entries", type=int, default=d.cache_entries,
                   help="result-cache LRU capacity "
                        f"(default {d.cache_entries})")
    p.add_argument("--cache-ttl", type=float, default=d.cache_ttl_s,
                   metavar="SECONDS",
                   help="result-cache TTL "
                        f"(default {d.cache_ttl_s:.0f}s)")
    p.add_argument("--max-nodes", type=int, default=d.max_nodes,
                   help="largest admissible graph, nodes (HTTP 413 above)")
    p.add_argument("--max-edges", type=int, default=d.max_edges,
                   help="largest admissible graph, edges (HTTP 413 above)")
    p.add_argument("--drain-timeout", type=float, default=d.drain_timeout_s,
                   metavar="SECONDS",
                   help="max seconds to finish admitted work on SIGTERM "
                        f"(default {d.drain_timeout_s:.0f})")
    p.add_argument("--max-batch", type=int, default=d.max_batch,
                   metavar="B",
                   help="coalesce up to B queued same-bucket jobs into "
                        "one batched device call (executed at ladder "
                        "rungs 1/2/4/8; 1 disables coalescing; default "
                        f"{d.max_batch})")
    p.add_argument("--warm", action="append", default=[],
                   metavar="BUCKET[:B]",
                   help="pre-warm a bucket's executables before serving "
                        "(e.g. n64_e96:4 compiles the solo path and the "
                        "batch ladder up to rung 4); repeatable")
    p.add_argument("--warm-config", type=str, default=None,
                   metavar="JSON",
                   help="ConsensusConfig overrides for --warm probes, "
                        "e.g. '{\"n_p\": 50, \"algorithm\": \"leiden\"}' "
                        "(default: louvain with its default tau)")
    p.add_argument("--cache-file", type=str, default=None, metavar="PATH",
                   help="persist the result cache across restarts: "
                        "loaded at startup, spilled (npz) on graceful "
                        "drain — a restarted server answers repeats of "
                        "pre-restart work without touching the device")
    p.add_argument("--cache-spill-s", type=float, default=None,
                   metavar="SECONDS",
                   help="ALSO spill --cache-file every SECONDS while "
                        "serving (skipped when nothing changed) so a "
                        "crashed replica's cache survives for fleet "
                        "inheritance (serve/fleet.py); default: drain-"
                        "time only")
    p.add_argument("--devices", type=int, default=None, metavar="N",
                   help="drive N local devices with one worker each "
                        "(default: all of them; 1 = single-worker). "
                        "Same-bucket traffic sticks to the device that "
                        "compiled the bucket (serve/scheduler.py)")
    p.add_argument("--huge-devices", type=int, default=d.huge_devices,
                   metavar="K",
                   help="reserve the last K devices as a mesh group for "
                        "the huge tier (graphs past --chip-max-edges run "
                        "edge-sharded across it; default "
                        f"{d.huge_devices} = tier off)")
    p.add_argument("--chip-max-edges", default=d.chip_max_edges,
                   metavar="E|auto",
                   help="single-chip bucket ceiling: buckets with edge "
                        "class > E route to the huge tier (requires "
                        "--huge-devices >= 1).  'auto' derives the "
                        "largest ladder bucket whose executables fit "
                        "--hbm-bytes from the fcheck-footprint memory "
                        "model (analysis/footprint.py), priced at the "
                        "--warm-config ensemble width (default n_p 20)")
    p.add_argument("--hbm-bytes", type=int, default=None, metavar="BYTES",
                   help="per-chip device-memory budget for "
                        "'--chip-max-edges auto' and for validating an "
                        "explicit ceiling at startup (default: the "
                        "local device's advertised memory, else the "
                        "model's synthetic CI budget)")
    p.add_argument("--spill-backlog", type=int, default=d.spill_backlog,
                   metavar="J",
                   help="sticky-affinity spill threshold: a bucket's "
                        "work leaves its home device only when more "
                        "than J jobs are queued there (default "
                        f"{d.spill_backlog})")
    p.add_argument("--hold-ms", type=float, default=None, metavar="MS",
                   help="cap on the adaptive hold-for-coalesce window "
                        "(serve/shaping.py): pop_batch may wait up to "
                        "MS ms — never past the tightest queued "
                        "deadline's slack — for predicted same-bucket "
                        "arrivals to fill a larger batch rung "
                        "(default 50; 0 disables holding)")
    p.add_argument("--no-edf", action="store_true",
                   help="order the admission queue FIFO within a "
                        "priority instead of earliest-deadline-first")
    p.add_argument("--no-hold", action="store_true",
                   help="disable the hold-for-coalesce window "
                        "(pop_batch never waits — the pre-fcshape "
                        "posture)")
    p.add_argument("--no-shed", action="store_true",
                   help="disable deadline-aware shedding (jobs that "
                        "provably cannot meet their SLO at the current "
                        "depth are queued anyway; 429s still carry the "
                        "derived Retry-After)")
    p.add_argument("--no-pin-sizing", action="store_true",
                   help="let the engine re-size executables adaptively "
                        "per request (default: pinned — stable bucket "
                        "executables; see serve/server.py)")
    p.add_argument("--trace-dir", type=str, default=None, metavar="DIR",
                   help="export the server's fcobs trace artifacts "
                        "(fcserve_trace.json + .jsonl) to DIR on drain")
    p.add_argument("--flight-dir", type=str, default=None, metavar="DIR",
                   help="where fcflight post-mortem bundles land "
                        "(SIGQUIT / watchdog trip / worker death / "
                        "drain timeout; default: FCTPU_FLIGHT_DIR, "
                        "else ./fcflight)")
    wd = ServeConfig().watchdog
    p.add_argument("--watchdog-k", type=float, default=wd.k, metavar="K",
                   help="hang watchdog: a device call is suspect past "
                        "K x the bucket's measured service p95 "
                        f"(default {wd.k:g})")
    p.add_argument("--watchdog-floor-s", type=float, default=wd.floor_s,
                   metavar="S",
                   help="hang watchdog: never trip below S seconds "
                        f"elapsed (default {wd.floor_s:g})")
    p.add_argument("--no-watchdog", action="store_true",
                   help="disable the hang watchdog (no suspect "
                        "detection, no cordon-on-stall)")
    p.add_argument("--watchdog-observe-only", action="store_true",
                   help="watchdog trips count and write bundles but "
                        "never cordon the worker (first-deploy posture)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress startup/drain log lines")
    return p


def _device_hbm_bytes() -> Optional[int]:
    """The local accelerator's advertised memory, when it advertises one
    (CPU backends do not — callers fall back to the model's synthetic
    budget)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
        return int(stats["bytes_limit"]) if stats else None
    except Exception:  # noqa: BLE001 — absent stats are a normal backend
        return None


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    # imports deferred so -h never pays the jax/engine import
    from fastconsensus_tpu.serve.server import (ConsensusService,
                                                ServeConfig,
                                                make_http_server)
    from fastconsensus_tpu.utils.env import setup_compile_cache

    setup_compile_cache()

    def say(msg: str) -> None:
        if not args.quiet:
            print(f"[fcserve] {msg}", file=sys.stderr, flush=True)

    logging.basicConfig(level=logging.WARNING)
    warm_config = None
    if args.warm_config:
        import json

        try:
            warm_config = json.loads(args.warm_config)
            if not isinstance(warm_config, dict):
                raise ValueError("expected a JSON object")
        except ValueError as e:
            print(f"error: bad --warm-config: {e}", file=sys.stderr)
            return 2
    if args.max_batch < 1:
        print("error: --max-batch must be >= 1", file=sys.stderr)
        return 2
    if args.cache_spill_s is not None:
        if args.cache_spill_s <= 0:
            print("error: --cache-spill-s must be > 0", file=sys.stderr)
            return 2
        if not args.cache_file:
            print("error: --cache-spill-s needs --cache-file (there is "
                  "nowhere to spill to)", file=sys.stderr)
            return 2
    chip_max_edges = args.chip_max_edges
    if isinstance(chip_max_edges, str):
        if chip_max_edges.lower() == "auto":
            chip_max_edges = "auto"
        else:
            try:
                chip_max_edges = int(chip_max_edges)
            except ValueError:
                print(f"error: --chip-max-edges {chip_max_edges!r}: "
                      f"expected an integer or 'auto'", file=sys.stderr)
                return 2
    if chip_max_edges is not None and args.huge_devices < 1:
        print("error: --chip-max-edges needs --huge-devices >= 1 (the "
              "huge tier is what runs graphs past the ceiling)",
              file=sys.stderr)
        return 2
    if args.huge_devices >= 1 and chip_max_edges is None:
        print("error: --huge-devices without --chip-max-edges reserves "
              "a mesh group no bucket can ever route to; set the "
              "single-chip ceiling too", file=sys.stderr)
        return 2
    if chip_max_edges == "auto" or (chip_max_edges is not None
                                    and args.hbm_bytes is not None):
        # the fcheck-footprint memory model: derive the largest ladder
        # bucket whose worst-case executable set fits one chip, and
        # hold an explicit ceiling to the same standard (failing fast
        # at startup beats OOM-ing on first traffic)
        from fastconsensus_tpu.analysis import footprint

        budget = args.hbm_bytes
        if budget is None:
            budget = _device_hbm_bytes() or footprint.CHIP_HBM_BYTES_DEFAULT
        spec = footprint.SurfaceSpec(
            max_nodes=args.max_nodes, max_edges=args.max_edges,
            max_batch=args.max_batch,
            n_p=int((warm_config or {}).get("n_p", 20)),
            algorithm=str((warm_config or {}).get("algorithm",
                                                  "louvain")))
        say(f"deriving the single-chip ceiling from the footprint "
            f"model (budget {budget / 2**30:.1f} GiB)...")
        derived = footprint.derive_chip_ceiling(budget, spec)
        if derived is None:
            print(f"error: no ladder bucket fits --hbm-bytes {budget} "
                  f"under this posture; lower --max-nodes/--max-batch "
                  f"or raise the budget", file=sys.stderr)
            return 2
        if chip_max_edges == "auto":
            if derived >= footprint.grid_up(
                    args.max_edges, footprint.MIN_EDGE_CLASS):
                # the whole admissible ladder fits one chip, so nothing
                # would ever route to the mandatory huge tier — the
                # same idle-mesh-group misconfiguration the explicit
                # validation above exits 2 on, reached via auto
                print(f"error: --chip-max-edges auto derived {derived} "
                      f"edges, which covers every admissible bucket "
                      f"(--max-edges {args.max_edges}); the reserved "
                      f"--huge-devices group would idle forever — drop "
                      f"the huge tier, raise --max-edges, or set an "
                      f"explicit lower ceiling", file=sys.stderr)
                return 2
            chip_max_edges = derived
            say(f"--chip-max-edges auto -> {derived} edges")
        elif chip_max_edges > derived:
            print(f"error: --chip-max-edges {chip_max_edges} exceeds "
                  f"the derived single-chip ceiling {derived} for "
                  f"--hbm-bytes {budget}: buckets between them would "
                  f"OOM on first traffic (footprint model)",
                  file=sys.stderr)
            return 2
    from fastconsensus_tpu.serve.shaping import ShapingConfig

    shaping_defaults = ShapingConfig()
    if args.hold_ms is not None and args.hold_ms < 0:
        print("error: --hold-ms must be >= 0", file=sys.stderr)
        return 2
    shaping = ShapingConfig(
        edf=not args.no_edf,
        hold=not args.no_hold and args.hold_ms != 0,
        shed=not args.no_shed,
        max_hold_s=(args.hold_ms / 1000.0 if args.hold_ms
                    else shaping_defaults.max_hold_s))
    from fastconsensus_tpu.serve.watchdog import WatchdogConfig

    wd_defaults = WatchdogConfig()
    watchdog = WatchdogConfig(
        enabled=not args.no_watchdog,
        k=args.watchdog_k,
        floor_s=args.watchdog_floor_s,
        min_history=wd_defaults.min_history,
        poll_s=wd_defaults.poll_s,
        cordon=not args.watchdog_observe_only)
    try:
        watchdog.validate()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    cfg = ServeConfig(queue_depth=args.queue_depth,
                      cache_entries=args.cache_entries,
                      cache_ttl_s=args.cache_ttl,
                      max_nodes=args.max_nodes,
                      max_edges=args.max_edges,
                      drain_timeout_s=args.drain_timeout,
                      pin_sizing=not args.no_pin_sizing,
                      trace_dir=args.trace_dir,
                      max_batch=args.max_batch,
                      cache_path=args.cache_file,
                      cache_spill_s=args.cache_spill_s,
                      prewarm=tuple(args.warm),
                      prewarm_config=warm_config,
                      devices=args.devices,
                      huge_devices=args.huge_devices,
                      chip_max_edges=chip_max_edges,
                      spill_backlog=args.spill_backlog,
                      shaping=shaping,
                      watchdog=watchdog,
                      flight_dir=args.flight_dir)
    from fastconsensus_tpu.serve import faultinject

    try:
        # fcfault: arm the FCTPU_FAULT_INJECT site (if any) BEFORE the
        # pool starts, so worker threads capture the injected callable;
        # a bad site id fails startup loudly instead of injecting
        # nothing silently
        site = faultinject.maybe_install_from_env()
    except (ValueError, ImportError, AttributeError) as e:
        print(f"error: bad {faultinject.ENV_VAR}: {e}", file=sys.stderr)
        return 2
    if site is not None:
        say(f"fault injection armed: {site}")
    try:
        service = ConsensusService(cfg).start()
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    n_workers = len(service.pool.workers)
    n_mesh = len(service.pool.mesh_workers)
    say(f"worker pool: {n_workers - n_mesh} chip worker(s)"
        + (f" + 1 mesh group of {len(service.pool.mesh_workers[0].devices)}"
           f" device(s) (huge tier, bucket ceiling "
           f"{cfg.chip_max_edges} edges)" if n_mesh else ""))
    if args.warm:
        say(f"pre-warming {len(args.warm)} bucket(s): "
            f"{', '.join(args.warm)}")
    try:
        httpd = make_http_server(service, args.host, args.port)
    except OSError as e:
        print(f"error: cannot bind {args.host}:{args.port}: {e}",
              file=sys.stderr)
        return 2
    host, port = httpd.server_address[:2]
    say(f"listening on http://{host}:{port} "
        f"(queue depth {cfg.queue_depth}, cache {cfg.cache_entries} "
        f"entries / {cfg.cache_ttl_s:.0f}s TTL)")

    stop = threading.Event()

    def _on_signal(signum, frame) -> None:
        say(f"signal {signum}: draining")
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    if hasattr(signal, "SIGQUIT"):
        # fcflight: SIGQUIT = "dump a post-mortem bundle and KEEP
        # serving" — the live-incident snapshot (contrast SIGTERM's
        # drain).  Routed through the service so the bundle carries the
        # full serving state and /healthz learns the path.
        def _on_sigquit(signum, frame) -> None:
            path = service.write_bundle("sigquit")
            say(f"SIGQUIT: flight bundle "
                f"{'failed' if path is None else path}")

        signal.signal(signal.SIGQUIT, _on_sigquit)
    http_thread = threading.Thread(target=httpd.serve_forever,
                                   name="fcserve-http", daemon=True)
    http_thread.start()
    stop.wait()
    # Drain order: stop admissions FIRST (in-flight handler threads get
    # 503 from the closed queue), then stop the listener, then finish
    # every admitted job.
    service.begin_drain()
    httpd.shutdown()
    ok = service.drain(cfg.drain_timeout_s)
    httpd.server_close()
    say("drained cleanly" if ok
        else f"drain timed out after {cfg.drain_timeout_s:.0f}s")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
