"""fcserve: a long-lived consensus service over ``run_consensus``.

Every pre-existing entry point (cli.py, bench.py) is one-shot: each
invocation pays process start, graph load and executable warm-up, then
throws the compiled state away.  The serving layer keeps ONE resident
process whose jitted executables are reused across requests:

* requests are padded onto canonical shape buckets (serve/bucketer.py),
  so distinct graphs share executables and warm-bucket requests compile
  **zero** times (counted live by ``analysis.CompileGuard`` into the
  fcobs registry — ``/metricsz`` shows it);
* queued same-bucket jobs COALESCE: the worker pops up to ``max_batch``
  same-group jobs at once (serve/queue.pop_batch) and drives them as
  ONE batched device call (consensus.run_consensus_batch) at batch-
  ladder rungs {1, 2, 4, 8}, bit-identical per job to solo execution;
  ``--warm`` pre-compiles a bucket's ladder before the first request;
* identical work is answered from a content-addressed LRU+TTL result
  cache (serve/cache.py) without touching the device at all;
* admission control is explicit: a bounded priority queue
  (serve/queue.py) rejects overload with backpressure (HTTP 429),
  oversized graphs are refused up front (413), and a draining server
  says so (503) — accepted work always finishes;
* admission is SLO-shaped (serve/shaping.py): the queue orders
  earliest-deadline-first within a priority, ``pop_batch`` may hold a
  few deadline-bounded milliseconds for predicted same-bucket arrivals
  so steady traffic coalesces into larger batch rungs, 429s carry a
  Retry-After derived from the observed service rate, and a job that
  provably cannot meet its deadline at the current depth is shed at
  submit instead of queued to miss.

Threading model: HTTP handler threads (stdlib ``ThreadingHTTPServer``)
only touch the queue / cache / jobs table; the device side is the
**fcpool worker pool** (serve/pool.py) — one device-pinned worker
thread per chip, fed by a dispatcher that pops coalesced batches and
routes them by sticky bucket->device affinity (serve/scheduler.py), so
executable reuse survives the fan-out (a bucket's executables live on
the device that compiled them; round-robin would recompile every bucket
on every chip).  Each worker owns a thread-filtered CompileGuard and
``device=i`` span/counter tags, so ``/metricsz`` attributes compiles,
jobs and busy-time per device.  Buckets past the single-chip ceiling
(``chip_max_edges``) route to a reserved mesh group and run
edge-sharded via ``shard_map`` (the "huge" tier) instead of 413-ing.
A worker that dies mid-batch is cordoned (visible in ``/healthz``) and
its jobs requeue with that device excluded.  ``devices=1`` (or a
single-chip machine) reproduces the former single-worker behavior
exactly.

Shutdown: SIGTERM (serve/__main__.py) closes the queue, finishes every
admitted job, optionally exports the server's own fcobs trace artifacts
(``--trace-dir``), and exits 0 — a graceful drain, never dropped work.

The whole front end is stdlib-only (http.server / json / urllib on the
client side): no new dependencies ride in with the subsystem.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import math
import os
import threading
import time
from collections import OrderedDict, deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from fastconsensus_tpu.cli import ALGORITHMS, DEFAULT_TAU
from fastconsensus_tpu.obs import counters as obs_counters
from fastconsensus_tpu.obs import flight as obs_flight
from fastconsensus_tpu.obs import latency as obs_latency
from fastconsensus_tpu.obs import postmortem as obs_postmortem
from fastconsensus_tpu.obs.tracer import get_tracer
from fastconsensus_tpu.serve import bucketer
from fastconsensus_tpu.serve.jobs import (PRIORITY_BATCH,
                                          PRIORITY_INTERACTIVE,
                                          PRIORITY_NAMES, PRIORITY_NORMAL,
                                          SLO_CLASSES,
                                          STATE_DONE, STATE_FAILED,
                                          STATE_QUEUED, STATE_RUNNING, Job,
                                          JobSpec, hash_canonical)
from fastconsensus_tpu.serve.queue import (AdmissionQueue, DeadlineShed,
                                           QueueClosed, QueueFull)
from fastconsensus_tpu.serve.cache import ResultCache
from fastconsensus_tpu.serve.delta import (DeltaError, DeltaPolicy,
                                           ParentNotCached)
from fastconsensus_tpu.serve.shaping import ShapingConfig, TrafficShaper
from fastconsensus_tpu.serve.watchdog import WatchdogConfig

_logger = logging.getLogger("fastconsensus_tpu")

# Finished-job retention (status/result remain queryable this long after
# completion); bounded so the jobs table cannot grow without limit.
MAX_RETAINED_JOBS = 4096
# Resident-memory bound on the server's own tracer (--trace-dir): spans
# stream to the .jsonl continuously; once this many have streamed, the
# in-memory list resets (the drain-time Perfetto blob then covers the
# recent window — the full history lives in the .jsonl).
TRACE_EVENT_WINDOW = 20_000


class GraphTooLarge(ValueError):
    """Admission refused before queueing (HTTP 413)."""


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Operator knobs for one service instance."""

    queue_depth: int = 64
    cache_entries: int = 256
    cache_ttl_s: float = 3600.0
    max_nodes: int = 1 << 20
    max_edges: int = 1 << 22
    drain_timeout_s: float = 300.0
    # Pin the engine's adaptive executable sizing while serving (applied
    # as env defaults in start(); an operator-set env var wins): member
    # splitting off, fused block fixed.  Rate-adaptive sizing is right
    # for one long run; for a resident server cycling heterogeneous
    # requests it re-sizes (recompiles) shared bucket executables on
    # measurement drift — exactly the cost serving exists to amortize.
    pin_sizing: bool = True
    # Where drain() writes the server's own fcobs artifacts
    # (fcserve_trace.json + .jsonl); None = no server-side tracing.
    trace_dir: Optional[str] = None
    # Most-recent-samples window applied to the process-global fcobs
    # series registry at start() (ObsRegistry.set_series_limit): a
    # resident server observes per-job/per-round latencies forever, and
    # unbounded sample lists are a slow leak.  0/None disables.
    series_window: Optional[int] = 4096
    # Cross-request batching: the worker coalesces up to this many
    # queued same-group jobs (same bucket, same config-but-seed —
    # jobs.JobSpec.batch_group) into ONE batched device call
    # (consensus.run_consensus_batch), executed at batch-ladder rungs
    # (bucketer.BATCH_LADDER) so the executable set stays pinnable.
    # 1 disables coalescing (every job runs solo).
    max_batch: int = 8
    # Persist the content-addressed result cache across restarts: loaded
    # at start(), spilled on graceful drain (ResultCache.spill/load).
    # A restarted server answers repeats of pre-restart work as cache
    # hits without touching the device.  None = in-memory only.
    cache_path: Optional[str] = None
    # fcfleet: ALSO spill the cache every this-many seconds while
    # serving (ResultCache.spill_if_dirty — skipped when nothing
    # changed, never concurrent with the drain spill).  A drain-only
    # spill means a SIGKILLed replica's cache dies with it; the
    # periodic spill is what lets a fleet successor inherit it
    # (serve/fleet.py on_death -> POST /cachez/load).  Requires
    # cache_path; None/0 disables (the pre-fcfleet posture).
    cache_spill_s: Optional[float] = None
    # Pre-warm bucket specs ("n64_e96" or "n64_e96:4"): before serving,
    # the worker compiles each bucket's solo executables and its batch
    # ladder up to the given rung (default: max_batch) by driving
    # deterministic probe graphs through the real paths — the first
    # request into a warmed bucket compiles nothing.
    prewarm: Tuple[str, ...] = ()
    # ConsensusConfig field overrides for the pre-warm probes (e.g.
    # {"n_p": 50, "algorithm": "leiden"}).  Executable identity includes
    # n_p / tau / delta / algorithm / gamma / warm_start / align_frac /
    # closure_sampler / closure_tau, so pre-warm only pays off when
    # these match the traffic; seed and max_rounds are traced and free.
    prewarm_config: Optional[Dict[str, Any]] = None
    # Multi-device serving (serve/pool.py): how many local devices the
    # pool drives (None = all of them; 1 = the single-worker posture).
    devices: Optional[int] = None
    # Devices reserved (off the END of the device list) for the
    # mesh-sharded "huge" tier.  0 disables the tier.
    huge_devices: int = 0
    # Single-chip bucket ceiling: buckets whose edge class exceeds this
    # route to the huge tier (edge-sharded across the reserved mesh
    # group) instead of a single chip.  Requires huge_devices >= 1.
    # None = every admitted bucket runs single-chip (the max_edges 413
    # bound still applies either way).
    chip_max_edges: Optional[int] = None
    # Sticky-affinity spill threshold (serve/scheduler.py): a bucket's
    # batches leave their home device only when the home has more than
    # this many jobs queued.
    spill_backlog: int = 8
    # SLO-aware traffic shaping (serve/shaping.py): EDF admission
    # ordering, the adaptive hold-for-coalesce window, and
    # deadline-aware shedding with derived Retry-After.  The default
    # config enables all three arms; ShapingConfig is frozen, so the
    # shared default instance is safe.
    shaping: ShapingConfig = ShapingConfig()
    # fcflight hang watchdog (serve/watchdog.py): heartbeat-based
    # wedged-device detection with cordon-on-stall.  None or
    # enabled=False disables the watchdog thread entirely (the
    # DISABLED_WATCHDOG no-op keeps every call site unconditional).
    watchdog: Optional[WatchdogConfig] = WatchdogConfig()
    # Where post-mortem bundles land (obs/postmortem.py): None falls
    # back to $FCTPU_FLIGHT_DIR, else ./fcflight.
    flight_dir: Optional[str] = None
    # fcdelta warm-start vs full-run thresholds (serve/delta.py): the
    # delta-size ceiling and the parent-quality floors an incremental
    # submission must clear; every tripped rule stamps its name as the
    # fallback ``reason``.  Frozen, so the shared default is safe.
    delta_policy: DeltaPolicy = DeltaPolicy()


def _trace_aux(job) -> Dict[str, Any]:
    """fctrace: the flight-event aux carrying a job's trace id — empty
    when the submission carried none, so untraced traffic's events stay
    byte-identical to before this field existed."""
    trace = getattr(job.spec, "trace", None)
    return {"trace": trace} if trace else {}


def validate_warm_specs(config: ServeConfig) -> None:
    """Fail fast on ``--warm`` specs the running server could never use.

    Checked at server START (raises ValueError -> the CLI exits 2)
    rather than logged at warm time, because each bad shape silently
    wastes multi-minute compiles or pre-warms executables no request
    can reach: a malformed/off-grid key, a rung < 1, a bucket outside
    the admission bounds (no request can ever land there), or a bucket
    past the single-chip ceiling — the mesh tier serves those SOLO and
    edge-sharded, so a single-chip ladder pre-warm for them compiles
    executables the scheduler will never route a job to.
    """
    from fastconsensus_tpu import sizing

    n_cap = sizing.grid_up(config.max_nodes, bucketer.MIN_NODE_CLASS)
    e_cap = sizing.grid_up(config.max_edges, bucketer.MIN_EDGE_CLASS)
    for spec in config.prewarm:
        key, _, b = spec.partition(":")
        if b and (not b.isdigit() or int(b) < 1):
            raise ValueError(
                f"--warm {spec!r}: rung must be an integer >= 1")
        bucket = bucketer.bucket_from_key(key)   # off-grid -> ValueError
        if bucket.n_class > n_cap or bucket.e_class > e_cap:
            raise ValueError(
                f"--warm {spec!r}: bucket {bucket.key()} is outside the "
                f"admission bounds (max_nodes={config.max_nodes}, "
                f"max_edges={config.max_edges} admit buckets up to "
                f"n{n_cap}_e{e_cap}); no request can ever land in it")
        if config.chip_max_edges is not None and \
                bucket.e_class > config.chip_max_edges:
            raise ValueError(
                f"--warm {spec!r}: bucket {bucket.key()} exceeds the "
                f"single-chip ceiling ({config.chip_max_edges} edges); "
                f"its traffic routes to the mesh tier, which runs solo "
                f"edge-sharded executables — a single-chip ladder "
                f"pre-warm there compiles executables no job will hit")


class ConsensusService:
    """The queue -> bucket -> cache -> ``run_consensus`` pipeline."""

    def __init__(self, config: Optional[ServeConfig] = None) -> None:
        self.config = config or ServeConfig()
        self.queue = AdmissionQueue(self.config.queue_depth,
                                    edf=self.config.shaping.edf)
        self.shaper = TrafficShaper(self.config.shaping)
        if self.config.shaping.hold and self.config.max_batch > 1:
            self.queue.set_shaper(self.shaper)
        self.cache = ResultCache(max_entries=self.config.cache_entries,
                                 ttl_seconds=self.config.cache_ttl_s)
        self._jobs: "OrderedDict[str, Job]" = OrderedDict()
        self._lock = threading.Lock()
        self.pool = None   # serve/pool.WorkerPool, built in start()
        self._tracer = None
        self._trace_lock = threading.Lock()
        self._trace_jsonl: Optional[str] = None
        self._streamed_events = 0
        self._buckets: Dict[str, int] = {}
        self._started_at = time.time()
        self._reg = obs_counters.get_registry()
        self._lat = obs_latency.get_latency_registry()
        self._batch_seq = itertools.count(1)
        self._prewarm_total = len(self.config.prewarm)
        self._prewarm_done = 0
        self._prewarm_finished = self._prewarm_total == 0
        # fcflight: last post-mortem bundle path (guarded by self._lock
        # — the watchdog thread writes it, /healthz handlers read it)
        self._last_bundle: Optional[str] = None
        # Hang-injection test hook (tests + the CI fcflight smoke): the
        # FCTPU_TEST_HANG_AFTER-th device dispatch sleeps
        # FCTPU_TEST_HANG_S seconds inside the watchdog's "device"
        # heartbeat window, exactly once per process — a deterministic
        # wedge the watchdog must catch while earlier traffic builds
        # the service-time history it judges against.
        self._hang_s = float(os.environ.get("FCTPU_TEST_HANG_S", "0")
                             or 0.0)
        self._hang_after = int(os.environ.get("FCTPU_TEST_HANG_AFTER",
                                              "0") or 0)
        self._hang_seq = itertools.count()
        # fcfleet periodic cache spill (cache_spill_s): stopped by
        # drain() before the final drain-time spill
        self._spill_stop = threading.Event()
        self._spill_thread: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------

    def start(self) -> "ConsensusService":
        """Build the device worker pool and launch it (idempotent).

        Raises ValueError on a config the server could never serve
        correctly — including ``--warm`` specs past the admission
        bounds or the single-chip ceiling (fail at start, not as an
        OOM or a wasted compile on first traffic)."""
        if self.pool is not None:
            return self
        validate_warm_specs(self.config)
        if self.config.pin_sizing:
            os.environ.setdefault("FCTPU_DETECT_CALL_MEMBERS", "0")
            os.environ.setdefault("FCTPU_ROUNDS_BLOCK", "8")
        if self.config.series_window:
            self._reg.set_series_limit(self.config.series_window)
        if self.config.trace_dir:
            from fastconsensus_tpu.obs import Tracer, set_tracer

            os.makedirs(self.config.trace_dir, exist_ok=True)
            self._trace_jsonl = os.path.join(self.config.trace_dir,
                                             "fcserve_trace.json.jsonl")
            open(self._trace_jsonl, "w", encoding="utf-8").close()
            self._tracer = Tracer()
            set_tracer(self._tracer)
        if self.config.cache_path and \
                os.path.exists(self.config.cache_path):
            n = self.cache.load(self.config.cache_path)
            _logger.info("fcserve: reloaded %d cached result(s) from %s",
                         n, self.config.cache_path)
        if self.config.cache_path and self.config.cache_spill_s:
            self._spill_thread = threading.Thread(
                target=self._spill_loop, name="fcserve-cache-spill",
                daemon=True)
            self._spill_thread.start()
        from fastconsensus_tpu.serve.pool import WorkerPool

        self.pool = WorkerPool(self)
        self.pool.start()
        # Retry-After / shed math divides queued work across the chips
        # actually draining it; the callable re-counts per decision so
        # cordoned workers stop flattering the estimate.
        pool = self.pool
        self.shaper.set_parallelism(
            lambda: sum(1 for w in pool.chip_workers if w.eligible()))
        # hold economics: pop_batch may hold only while every chip is
        # already occupied (the held job would have waited in a deque
        # anyway) — an idle chip turns every held millisecond into
        # real added latency, so the shaper bypasses then
        self.shaper.set_busy_probe(pool.chips_all_busy)
        if self.config.chip_max_edges is not None:
            # huge-tier buckets run SOLO on the mesh group whatever the
            # pop size — holding them coalesces nothing
            self.shaper.set_solo_probe(
                lambda key: pool._is_huge(bucketer.bucket_from_key(key)))
        return self

    def _spill_loop(self) -> None:
        """fcfleet periodic cache persistence (``cache_spill_s``): the
        crash-survival complement to the drain-time spill — a replica
        killed without a drain still leaves a recent npz for the ring
        successor to inherit.  ``spill_if_dirty`` makes the idle loop
        free (no write when nothing changed) and yields to a
        concurrent drain spill instead of double-writing."""
        while not self._spill_stop.wait(self.config.cache_spill_s):
            try:
                n = self.cache.spill_if_dirty(self.config.cache_path)
                if n > 0:
                    _logger.debug("fcserve: periodic spill wrote %d "
                                  "cached result(s)", n)
            except OSError:
                # same contract as the drain spill: persistence is an
                # optimization and a full disk must not kill serving
                self._reg.inc("serve.cache.persist_write_failed")
                _logger.exception(
                    "fcserve: periodic cache spill failed; continuing")

    def begin_drain(self) -> None:
        """Stop admissions; already-admitted jobs keep running."""
        self.queue.close()

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Graceful shutdown: close intake, finish every admitted job on
        every worker, export ONE merged trace with per-device tracks
        (``trace_dir``).  True = fully drained."""
        self.begin_drain()
        # stop the periodic spill loop BEFORE the final spill below: the
        # drain-time write must be the last one (spill_if_dirty would
        # skip on the shared lock anyway, but a loop outliving drain
        # could resurrect the file after an operator removed it)
        self._spill_stop.set()
        if self._spill_thread is not None:
            self._spill_thread.join(timeout=5.0)
            self._spill_thread = None
        ok = True
        if self.pool is not None:
            ok = self.pool.drain(timeout if timeout is not None
                                 else self.config.drain_timeout_s)
        if ok:
            if self.config.cache_path:
                try:
                    n = self.cache.spill(self.config.cache_path)
                    _logger.info(
                        "fcserve: spilled %d cached result(s) to %s",
                        n, self.config.cache_path)
                except OSError:
                    # a full/unwritable disk must not turn a clean drain
                    # into exit 1 — the cache is an optimization, the
                    # drain contract is the product
                    self._reg.inc("serve.cache.persist_write_failed")
                    _logger.exception(
                        "fcserve: cache spill failed; draining anyway")
            self._export_trace()
        else:
            # some worker is STILL RUNNING a job: exporting now would
            # race its per-batch _flush_trace on the stream index and
            # the .jsonl file (duplicate/desynced records); the streamed
            # .jsonl up to the last finished batch is already on disk
            _logger.warning(
                "fcserve drain timed out with a job in flight; "
                "skipping trace export (streamed .jsonl is intact)")
            # a drain that refuses to finish IS an incident: dump the
            # in-flight table, thread stacks and event rings while the
            # wedged state still exists to photograph
            self.write_bundle("drain_timeout")
        return ok

    # -- fcflight incident hooks --------------------------------------

    def bundle_sections(self) -> Dict[str, Any]:
        """The serving layer's post-mortem sections (obs/postmortem.py
        adds flight/counters/latency/stacks on top): the resolved
        config, the in-flight jobs table with per-job phase timelines,
        and the pool / scheduler / queue / watchdog / shaping state."""
        sections: Dict[str, Any] = {
            "config": dataclasses.asdict(self.config),
            "jobs": self._jobs_section(),
        }
        try:
            sections["queue"] = {
                "depth": self.queue.depth(),
                "total_depth": self.queue.total_depth(),
                "max_depth": self.queue.max_depth,
                "draining": self.queue.draining(),
            }
            if self.pool is not None:
                sections["pool"] = self.pool.describe()
                sections["scheduler"] = {
                    "affinity": self.pool.scheduler.affinity()}
                sections["watchdog"] = self.pool.watchdog.describe()
            sections["shaping"] = self.shaping_stats()
        except Exception as exc:  # noqa: BLE001 — a half-wedged server
            # must still dump what it can collect
            sections["sections_error"] = {"error": repr(exc)}
        return sections

    def _jobs_section(self) -> Dict[str, Any]:
        """In-flight jobs with open-ended phase timelines — the bundle
        row the reader prints as 'where is this job's lifetime
        accumulating' (a wedged job shows device=312.4s)."""
        with self._lock:
            jobs = list(self._jobs.values())
        rows: List[Dict[str, Any]] = []
        for j in jobs:
            if j.state not in (STATE_QUEUED, STATE_RUNNING):
                continue
            try:
                bucket = j.spec.bucket().key()
            except Exception:  # noqa: BLE001 — unbucketable specs
                bucket = "-"   # still belong in the incident table
            rows.append({
                "job_id": j.job_id,
                "state": j.state,
                "trace": j.spec.trace,
                "bucket": bucket,
                "priority": j.spec.priority,
                "device": j.device,
                "batch_id": j.batch_id,
                "requeues": j.requeues,
                "phases_s": {k: round(v, 6)
                             for k, v in j.phases_so_far().items()},
            })
        return {"tracked": len(jobs), "in_flight": len(rows),
                "jobs": rows}

    def write_bundle(self, reason: str) -> Optional[str]:
        """Dump one post-mortem bundle (never raises — an incident dump
        that throws during the incident is worse than none)."""
        try:
            path = obs_postmortem.write_bundle(
                reason, self.bundle_sections(),
                base_dir=self.config.flight_dir)
        except Exception:  # noqa: BLE001
            _logger.exception("fcflight: bundle write failed (reason=%s)",
                              reason)
            return None
        self._reg.inc("serve.flight.bundles")
        obs_flight.record("bundle", reason=reason, path=path)
        with self._lock:
            self._last_bundle = path
        _logger.warning("fcflight: post-mortem bundle written to %s "
                        "(reason=%s)", path, reason)
        return path

    def _on_watchdog_trip(self, trip: Dict[str, Any]) -> None:
        """Watchdog-thread callback, once per suspect episode: count,
        record, bundle, then cordon through the PR 6 machinery (unless
        ``watchdog.cordon=False`` — observe-only)."""
        wd = self.config.watchdog
        cordon = wd is not None and wd.cordon
        self._reg.inc("serve.flight.watchdog_trips")
        obs_flight.record("watchdog_trip", job=trip.get("job"),
                          device=trip.get("device"),
                          bucket=trip.get("bucket"),
                          elapsed_s=trip.get("elapsed_s"),
                          threshold_s=trip.get("threshold_s"))
        _logger.error(
            "fcflight watchdog: device %s wedged %.1fs inside a device "
            "call (threshold %.1fs, job %s)%s", trip.get("device"),
            trip.get("elapsed_s") or 0.0, trip.get("threshold_s") or 0.0,
            trip.get("job"), "; cordoning" if cordon else
            " (observe-only: cordon disabled)")
        self.write_bundle(f"watchdog_d{trip.get('device')}")
        if cordon and self.pool is not None:
            worker = self.pool.worker_for(trip["device"])
            if worker is not None:
                worker.cordon(
                    f"hang watchdog: device call exceeded "
                    f"{trip.get('threshold_s')}s (job {trip.get('job')})")

    def _on_worker_death(self, worker, exc: Exception) -> None:
        """Pool callback after a worker's ``_die`` cordoned it and
        requeued its backlog: photograph the process while the broken
        state is fresh."""
        self.write_bundle(f"worker_death_d{worker.idx}")

    def slowest(self, limit: int = 8) -> Dict[str, Any]:
        """The ``/debugz/slowest`` payload: the worst ``serve.e2e``
        exemplars (job id + latency, per bucket/rung/device tags)
        joined to their retained flight-recorder timelines and — while
        the jobs table still holds them — their phase breakdowns.  The
        answer to "why was THIS request the p99"."""
        snap = self._lat.snapshot()
        rows: List[Dict[str, Any]] = []
        for h in snap.get("histograms", ()):
            if h.get("name") != "serve.e2e":
                continue
            tags = h.get("tags") or {}
            for slots in (h.get("exemplars") or {}).values():
                for job_id, secs in slots:
                    rows.append({
                        "job_id": job_id,
                        "e2e_s": secs,
                        "bucket": tags.get("bucket"),
                        "rung": tags.get("rung"),
                        "priority": tags.get("priority"),
                        "device": tags.get("device"),
                    })
        rows.sort(key=lambda r: -float(r["e2e_s"]))
        del rows[max(int(limit), 1):]
        recorder = obs_flight.get_flight_recorder()
        for r in rows:
            r["events"] = recorder.events(job=r["job_id"], limit=64)
            job = self.job(r["job_id"])
            if job is not None:
                r["timing"] = job.timing()
        return {"slowest": rows}

    # -- fcflight device-call instrumentation -------------------------

    def _device_begin(self, worker, job_id: Optional[str],
                      bucket_name: str, n_jobs: int = 1) -> bool:
        """Open the watchdog's "device" heartbeat window and record the
        flight event; returns the cold-compile prediction (bucket not
        warm on that worker — the watchdog exemption, and the honest
        tag for the flight timeline)."""
        cold = worker is not None and not worker.is_warm(bucket_name)
        obs_flight.record("device", job=job_id,
                          device=None if worker is None else worker.idx,
                          bucket=bucket_name, cold=cold, n_jobs=n_jobs)
        if worker is not None and self.pool is not None:
            self.pool.watchdog.beat(worker.idx, "device", job=job_id,
                                    bucket=bucket_name, cold=cold,
                                    n_jobs=n_jobs)
        self._maybe_test_hang()
        return cold

    def _device_end(self, worker, job_id: Optional[str],
                    bucket_name: str) -> None:
        if worker is not None and self.pool is not None:
            self.pool.watchdog.beat(worker.idx, "device_done")
        obs_flight.record("device_done", job=job_id,
                          device=None if worker is None else worker.idx,
                          bucket=bucket_name)

    def _maybe_test_hang(self) -> None:
        if self._hang_s <= 0.0:
            return
        if next(self._hang_seq) == self._hang_after:
            _logger.warning(
                "fcflight TEST hook: injecting a %.1fs hang inside the "
                "device window (FCTPU_TEST_HANG_S)", self._hang_s)
            time.sleep(self._hang_s)

    def _flush_trace(self) -> None:
        """Stream newly finished spans to the .jsonl (once per batch)
        and bound resident span memory: past TRACE_EVENT_WINDOW streamed
        spans the in-memory list resets — the history is already on
        disk, and a heavy-traffic server must not retain every span of
        every request until drain.  Every pool worker calls this between
        batches, so the stream index and the reset are serialized under
        their own lock."""
        # local bindings: tracer/jsonl are written once in start() and
        # never change after the workers exist — hoisting them out of
        # the locked region keeps the lock covering only the state it
        # actually guards (the stream index + the file append)
        tracer, jsonl = self._tracer, self._trace_jsonl
        if tracer is None or jsonl is None:
            return
        with self._trace_lock:
            new = tracer.events_since(self._streamed_events)
            self._streamed_events += len(new)
            if self._streamed_events > TRACE_EVENT_WINDOW:
                # atomic snapshot+clear (Tracer.drain_since): a span
                # another worker closes between a separate read and
                # clear() would vanish from memory AND the stream
                new = new + tracer.drain_since(self._streamed_events)
                self._streamed_events = 0
            if new:
                with open(jsonl, "a", encoding="utf-8") as fh:
                    for ev in new:
                        fh.write(json.dumps({"kind": "span", **ev})
                                 + "\n")

    def _export_trace(self) -> None:
        if self._tracer is None or not self.config.trace_dir:
            return
        from fastconsensus_tpu.obs import export as obs_export
        from fastconsensus_tpu.obs import set_tracer

        set_tracer(None)
        self._flush_trace()
        snapshot = self._reg.snapshot()
        # Perfetto blob from the retained (recent-window) spans; the
        # complete stream is the .jsonl next to it.  Worker threads map
        # to named per-device tracks ("device-0", "mesh-6", ...).
        events = self._tracer.events()
        path = os.path.join(self.config.trace_dir, "fcserve_trace.json")
        thread_names = self.pool.thread_names() if self.pool else None
        obs_export.write_perfetto(path, events, snapshot,
                                  process_name="fcserve",
                                  thread_names=thread_names)
        with open(self._trace_jsonl, "a", encoding="utf-8") as fh:
            fh.write(json.dumps({"kind": "counters", **snapshot}) + "\n")
        _logger.info("fcserve trace written to %s (+.jsonl)", path)

    # -- submission --------------------------------------------------

    def submit(self, spec: JobSpec, key: Optional[str] = None) -> Job:
        """Admit a job (or answer it from the cache immediately).

        Raises :class:`GraphTooLarge` (413), :class:`queue.QueueFull`
        (429) or :class:`queue.QueueClosed` (503); on success the
        returned job is either queued, or already DONE when its content
        hash hit the cache — a cache hit costs no queue slot, so cached
        traffic flows even through a saturated queue.

        ``key`` overrides the cache key (fcdelta: incremental results
        live under :func:`serve.delta.delta_cache_key`, never under the
        child graph's own content hash — approximate answers must not
        shadow the exact-dedup promise).
        """
        n_raw = spec.n_edges_raw()
        if n_raw < 1:
            raise ValueError("graph has no edges")
        if spec.n_nodes > self.config.max_nodes:
            raise GraphTooLarge(
                f"graph has {spec.n_nodes} nodes; this server admits at "
                f"most {self.config.max_nodes}")
        if n_raw > self.config.max_edges:
            raise GraphTooLarge(
                f"graph has {n_raw} edges; this server admits at most "
                f"{self.config.max_edges}")
        job = Job(self._normalize_spec(spec), key=key)
        bucket_key = None
        try:
            # fclat per-bucket arrival rate: offered load, marked for
            # EVERY admissible request (cache hits included — the
            # adaptive coalescing window must see the true arrival
            # process, not the cache-filtered one).  canonical() is
            # already memoized by the content hash above, so bucket()
            # is just the grid lookup.
            bucket_key = job.spec.bucket().key()
            self._lat.arrivals.mark(bucket_key)
        # fcheck: ok=swallowed-error (deliberate: the arrival
        # mark is telemetry; an unbucketable spec still fails
        # as its own job at pack time, visibly)
        except Exception:  # noqa: BLE001 — rate tracking must never
            pass           # reject a job the bucketer will judge later
        cached = self.cache.get(job.key)
        if cached is not None:
            job.mark(STATE_DONE, result=dict(cached, cached=True))
            self._remember(job)
            self._reg.inc("serve.jobs.cached")
            obs_flight.record("cache_hit", job=job.job_id,
                              bucket=bucket_key, **_trace_aux(job))
            self._record_timeline(job, cached=True)
            return job
        # fcshape deadline-aware shedding: a job the measured service
        # rate provably cannot finish inside its SLO at the current
        # depth is refused NOW — same 429 class as QueueFull, but the
        # client learns in microseconds what the queue would have told
        # it after the whole SLO window.  Cache hits never reach here
        # (they cost no slot); cold-start estimates never shed.
        if bucket_key is not None:
            depth = self.queue.total_depth()
            reason = self.shaper.should_shed(bucket_key,
                                             job.deadline_mono, depth)
            if reason is not None:
                self._reg.inc("serve.queue.rejected_shed")
                obs_flight.record("shed", job=job.job_id,
                                  bucket=bucket_key, depth=depth,
                                  **_trace_aux(job))
                shed = DeadlineShed(depth, self.queue.max_depth, reason)
                shed.retry_after_s = self.shaper.retry_after_s(
                    depth, bucket_key)
                raise shed
        try:
            # Pre-compute (memoize) the coalescing group HERE, on the
            # submitting thread: pop_batch evaluates group_key under
            # the queue lock, and a first evaluation there would run
            # the O(E log E) canonicalization for every heap entry
            # while all submits block.  (canonical() is already warm —
            # the content hash above computed it.)  The GROUP arrival
            # mark is the hold predictor's preferred fill signal: only
            # same-group arrivals can join a rung, so the per-bucket
            # rate alone would predict fills mixed-config traffic can
            # never deliver.
            self._lat.group_arrivals.mark(job.spec.batch_group())
        # fcheck: ok=swallowed-error (deliberate: the group
        # mark is telemetry; _group_key independently falls
        # back to solo for the same spec)
        except Exception:  # noqa: BLE001 — grouping must never reject
            pass           # a job; _group_key falls back to solo
        try:
            self.queue.submit(job)   # QueueClosed propagates as-is
        except QueueFull as e:
            # honest backpressure: the 429 tells the client when the
            # depth it bounced off should actually have drained
            try:
                e.retry_after_s = self.shaper.retry_after_s(
                    e.depth, bucket_key)
            # fcheck: ok=swallowed-error (the 429 re-raises right
            # below — only the optional retry-after refinement is
            # dropped, and the client has its static default)
            except Exception:  # noqa: BLE001 — estimator trouble must
                pass           # never mask the backpressure signal
            raise
        self._remember(job)
        return job

    def submit_delta(self, payload: Dict[str, Any]) -> Job:
        """fcdelta admission: resolve ``payload['parent']`` from the
        result cache, apply the canonical edge delta, and submit the
        child graph — warm-started from the parent's partitions with
        the move phase restricted to the changed edges' neighborhood
        when the policy allows, else as a plain full run with
        ``mode="fallback"`` stamped.

        The parent entry is PINNED for exactly the resolve window
        (``serve.cache.parent_pins``): between reading the hash and
        copying the warm-start labels out, an LRU eviction or TTL
        expiry would otherwise turn an admissible delta into a
        spurious 404 under cache contention.

        Raises :class:`serve.delta.ParentNotCached` (404),
        :class:`serve.delta.DeltaError` (400), plus everything
        :meth:`submit` raises.
        """
        from fastconsensus_tpu.consensus import ConsensusConfig
        from fastconsensus_tpu.models.registry import get_detector
        from fastconsensus_tpu.serve import delta as fcdelta

        parent_hash = payload.get("parent")
        if not isinstance(parent_hash, str) or not parent_hash:
            raise DeltaError("parent must be a content-hash string")
        pinned = self.cache.pin(parent_hash)
        try:
            parent = self.cache.get(parent_hash, count_miss=False) \
                if pinned else None
            if parent is None:
                self._reg.inc("serve.delta.parent_miss")
                raise ParentNotCached(
                    f"parent {parent_hash[:16]}… is not in this "
                    f"replica's result cache (expired, evicted, or "
                    f"never ran here)")
            graph = parent.get("graph")
            cfg_dict = parent.get("config")
            if graph is None or cfg_dict is None:
                self._reg.inc("serve.delta.parent_miss")
                raise ParentNotCached(
                    "parent result carries no graph/config block "
                    "(cached before fcdelta); resubmit the full graph "
                    "once to refresh it")
            n_nodes = int(parent["n_nodes"])
            adds, removes = fcdelta.parse_delta(payload, n_nodes)
            pu = np.asarray(graph["u"], dtype=np.int64)
            pv = np.asarray(graph["v"], dtype=np.int64)
            pw = graph.get("w")
            cu, cv, cw = fcdelta.apply_delta(pu, pv, pw, n_nodes,
                                             adds, removes)
            config = ConsensusConfig(**cfg_dict)
            child_hash = hash_canonical((cu, cv, cw), n_nodes, config)
            parent_bucket = bucketer.bucket_for(
                n_nodes, max(int(pu.shape[0]), 1))
            child_bucket = bucketer.bucket_for(
                n_nodes, max(int(cu.shape[0]), 1))
            detect = get_detector(config.algorithm, gamma=config.gamma)
            warm_capable = bool(config.warm_start and
                                getattr(detect, "supports_init", False))
            huge = self.config.chip_max_edges is not None and \
                child_bucket.e_class > self.config.chip_max_edges
            decision = self.config.delta_policy.decide(
                int(adds.shape[0] + removes.shape[0]),
                int(pu.shape[0]), parent, config,
                parent_bucket.key(), child_bucket.key(),
                warm_capable, huge=huge)
            warm_labels = warm_active = key = None
            if decision.mode == "incremental":
                # copies — nothing below may reference the cache entry
                # once the pin releases
                warm_labels = np.stack(
                    [np.asarray(p, dtype=np.int32)
                     for p in parent["partitions"]])
                warm_active = fcdelta.neighborhood_mask(
                    cu, cv, n_nodes, adds, removes)
                key = fcdelta.delta_cache_key(child_hash, parent_hash)
            self._reg.inc(f"serve.delta.{decision.mode}")
        finally:
            if pinned:
                self.cache.unpin(parent_hash)
        spec = JobSpec(
            edges=np.stack([cu, cv], axis=1), n_nodes=n_nodes,
            config=config, weights=cw,
            priority=_parse_priority(payload),
            slo=_parse_slo(payload, default="delta"),
            slo_target_ms=_parse_slo_target(payload),
            trace=_parse_trace(payload),
            delta=fcdelta.describe_payload(
                parent_hash, decision,
                int(adds.shape[0]), int(removes.shape[0])),
            warm_labels=warm_labels, warm_active=warm_active)
        # (cu, cv, cw) is already canonical ascending edge-key order —
        # pre-seed the memo so hashing/packing skip the O(E log E) pass
        object.__setattr__(spec, "_canonical", (cu, cv, cw))
        job = self.submit(spec, key=key)
        obs_flight.record("delta", job=job.job_id,
                          parent=parent_hash[:16], mode=decision.mode,
                          reason=decision.reason,
                          delta_frac=decision.delta_frac,
                          **_trace_aux(job))
        return job

    def job(self, job_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(job_id)

    def _normalize_spec(self, spec: JobSpec) -> JobSpec:
        """Drop an ignored gamma before hashing.

        Detectors without a gamma parameter compute identical results
        at every gamma, so letting it into the content hash would
        fragment the cache with distinct keys for provably identical
        work — the same fingerprint poisoning cli.py normalizes away
        for checkpoints/detect caches.
        """
        if spec.config.gamma != 1.0:
            from fastconsensus_tpu.models.registry import supports_param

            if not supports_param(spec.config.algorithm, "gamma"):
                spec = dataclasses.replace(
                    spec, config=dataclasses.replace(spec.config,
                                                     gamma=1.0))
        return spec

    def _remember(self, job: Job) -> None:
        with self._lock:
            self._jobs[job.job_id] = job
            while len(self._jobs) > MAX_RETAINED_JOBS:
                # evict the oldest FINISHED job only: an admitted
                # (queued/running) job must stay queryable for its whole
                # lifetime even while cache-hit traffic churns the table
                for jid, j in self._jobs.items():
                    if j.state in (STATE_DONE, STATE_FAILED):
                        del self._jobs[jid]
                        break
                else:
                    break  # everything retained is live work

    # -- fclat timeline recording -------------------------------------

    def _record_timeline(self, job: Job, rung: int = 1, worker=None,
                         cached: bool = False,
                         failed: bool = False) -> None:
        """Fold one finished job's phase timeline into the fclat
        histograms (per-phase + end-to-end, tagged by bucket / batch
        rung / priority / device) and its SLO verdict into the
        ``serve.slo.*`` attainment counters.  Cache hits record under
        rung 0 — a genuine serve whose latency profile must not blend
        into the device-path distributions.  FAILED jobs always count
        as an SLO miss (a 500 is the worst possible latency from the
        user's side — attainment must crater during an outage, not
        read 1.0 off the surviving successes) and record end-to-end
        only, into ``serve.e2e.failed``, so failure latencies never
        blend into the served distributions."""
        ph = job.phase_seconds()
        if ph is None:
            return
        phases, e2e = ph
        try:
            bucket_key = job.spec.bucket().key()
        except Exception:  # noqa: BLE001 — unbucketable specs fail as
            bucket_key = "-"  # their own job and still count here
        device = worker.idx if worker is not None else (
            job.device if job.device is not None else "-")
        cls = job.spec.slo_class()
        if failed:
            self._lat.hist("serve.e2e.failed", bucket=bucket_key,
                           priority=job.spec.priority).record(e2e)
            self._reg.inc("serve.slo.missed")
            self._reg.inc(f"serve.slo.{cls}.missed")
            obs_flight.record("fail", job=job.job_id, bucket=bucket_key,
                              **_trace_aux(job))
            return
        tags = dict(bucket=bucket_key, rung=0 if cached else int(rung),
                    priority=job.spec.priority, device=device)
        if not cached and (job.result or {}).get("compiles"):
            # a cold job's device phase is mostly XLA compile time, not
            # service: tag it so the shaping service-time estimator
            # (obs/latency.py) can exclude it — one 50 s compile in the
            # mean would make the deadline-shed math refuse jobs a warm
            # bucket serves in 20 ms
            tags["cold"] = 1
        for name, secs in phases.items():
            if name == "hold" and secs <= 0.0:
                # every popped job carries a hold stamp (jobs.py), but
                # only actual hold-for-coalesce episodes belong in the
                # serve.phase.hold histogram — a distribution that is
                # 99% synthetic zeros measures nothing
                continue
            self._lat.hist(f"serve.phase.{name}", **tags).record(secs)
        # fcflight: the job id rides the e2e observation as a bounded
        # per-bucket exemplar — /debugz/slowest joins the bucket's worst
        # latencies back to their flight timelines by exactly this id
        self._lat.hist("serve.e2e", **tags).record(
            e2e, exemplar=job.job_id)
        obs_flight.record("finish", job=job.job_id, bucket=bucket_key,
                          e2e_s=round(e2e, 6),
                          rung=0 if cached else int(rung),
                          **_trace_aux(job))
        verdict = "met" if e2e * 1000.0 <= job.spec.slo_target() \
            else "missed"
        self._reg.inc(f"serve.slo.{verdict}")
        self._reg.inc(f"serve.slo.{cls}.{verdict}")

    def latency_stats(self) -> Dict[str, Any]:
        """The ``/metricsz`` ``latency`` block: fclat histogram
        exposition (per-phase/e2e, JSON form), per-bucket arrival and
        dispatch rates, and the per-class SLO attainment summary."""
        snap = self._lat.snapshot()
        counters = self._reg.counters()
        slo: Dict[str, Any] = {}
        for cls, target in SLO_CLASSES.items():
            met = counters.get(f"serve.slo.{cls}.met", 0)
            missed = counters.get(f"serve.slo.{cls}.missed", 0)
            if met or missed:
                slo[cls] = {
                    "met": met, "missed": missed,
                    "attainment": round(met / (met + missed), 4),
                    "target_default_ms": target,
                }
        snap["slo"] = slo
        return snap

    def shaping_stats(self) -> Dict[str, Any]:
        """The ``/metricsz`` ``shaping`` block (typed by the jax-free
        client): the live shaping config, the ``serve.shape.*``
        counters, per-bucket service-time estimates for every bucket
        with arrival history, and the Retry-After a 429 issued at the
        current depth would carry."""
        buckets = sorted(self._lat.arrivals.rates())
        return self.shaper.describe(depth=self.queue.total_depth(),
                                    buckets=buckets)

    # -- the worker paths (driven by serve/pool.py workers) -----------

    def _group_key(self, job: Job) -> str:
        try:
            return job.spec.batch_group()
        except Exception:  # noqa: BLE001 — a spec the bucketer rejects
            # must still pop (and fail as ITS OWN job, solo); a unique
            # group key guarantees it never coalesces
            return f"solo:{job.job_id}"

    def _drain_group(self, pending: "deque[Job]", worker=None) -> None:
        """Run one coalesced pop: answer cache hits, then execute the
        rest at batch-ladder rungs (one batched device call per rung,
        solo for a rung of 1).  On a mesh (huge-tier) worker every job
        runs solo — the batch path is single-chip only, and huge jobs
        are device-bound, not dispatch-bound."""
        runnable: List[Job] = []
        for job in pending:
            cached = self.cache.get(job.key, count_miss=False)
            if cached is not None:
                # an identical job finished while this one queued — a
                # genuine serve, same accounting as the solo re-probe
                job.mark(STATE_DONE, result=dict(cached, cached=True))
                self._reg.inc("serve.jobs.completed")
                self._record_timeline(job, worker=worker, cached=True)
            else:
                runnable.append(job)
        solo_only = worker is not None and worker.kind == "mesh"
        while runnable:
            rung = 1 if solo_only else bucketer.batch_rung(
                min(len(runnable), self.config.max_batch))
            chunk, runnable = runnable[:rung], runnable[rung:]
            if len(chunk) == 1:
                self._run_solo_job(chunk[0], worker=worker)
            else:
                self._run_batch(chunk, worker=worker)

    def _run_solo_job(self, job: Job, worker=None) -> None:
        job.mark(STATE_RUNNING)
        if worker is not None:
            job.set_device(worker.idx)
        try:
            result = self.run_spec(job.spec, key=job.key, worker=worker,
                                   job=job)
            job.stamp("fanned_out")
            job.mark(STATE_DONE, result=result)
            self._reg.inc("serve.jobs.completed")
            if worker is not None:
                self._reg.inc(f"serve.device.{worker.idx}.jobs")
            self._record_timeline(job, rung=1, worker=worker,
                                  cached=bool(result.get("cached")))
        except Exception as e:  # noqa: BLE001 — one bad job must
            # never take down the worker (and with it every queued
            # job behind it); the failure is the job's result
            job.mark(STATE_FAILED, error=f"{type(e).__name__}: {e}")
            self._reg.inc("serve.jobs.failed")
            self._record_timeline(job, worker=worker, failed=True)
            _logger.warning("fcserve job %s failed: %s", job.job_id,
                            job.error)

    def _run_batch(self, jobs: List[Job], worker=None) -> None:
        """Execute >= 2 same-group jobs as ONE batched device call.

        Failure isolation, in order: a job whose graph fails to pack
        (e.g. non-finite weights) fails alone at pack time, before any
        batch exists; if the batched call itself raises, every member
        falls back to solo execution so one poison job cannot fail its
        batchmates.  Per-job spans, cache fills and counters fan out of
        the shared call.
        """
        packed: List[Tuple] = []  # (job, normalized spec, slab, bucket)
        for job in jobs:
            job.mark(STATE_RUNNING)
            if worker is not None:
                job.set_device(worker.idx)
            spec = self._normalize_spec(job.spec)
            try:
                slab, bucket = bucketer.pad_to_bucket(
                    spec.edges, spec.n_nodes, spec.weights,
                    max_nodes=self.config.max_nodes,
                    max_edges=self.config.max_edges,
                    canonical=spec.canonical())
            except Exception as e:  # noqa: BLE001 — pack-time rejects
                job.mark(STATE_FAILED,
                         error=f"{type(e).__name__}: {e}")
                self._reg.inc("serve.jobs.failed")
                self._record_timeline(job, worker=worker, failed=True)
                _logger.warning("fcserve job %s failed at pack: %s",
                                job.job_id, job.error)
                continue
            job.stamp("packed")
            packed.append((job, spec, slab, bucket))
        # pack failures can leave an off-ladder width; re-split so
        # every device call stays on a BATCH_LADDER rung (the
        # executable-set pin)
        while packed:
            rung = bucketer.batch_rung(len(packed))
            chunk, packed = packed[:rung], packed[rung:]
            if len(chunk) == 1:
                self._run_solo_job(chunk[0][0], worker=worker)
            else:
                self._run_packed(chunk, worker=worker)

    def _run_packed(self, packed: List[Tuple], worker=None) -> None:
        """One batched device call over already-packed (job, spec, slab,
        bucket) rows (a ladder rung of >= 2)."""
        from fastconsensus_tpu.analysis import CompileGuard
        from fastconsensus_tpu.consensus import run_consensus_batch
        from fastconsensus_tpu.models.registry import get_detector

        batch_id = f"b{next(self._batch_seq):05d}"
        bucket = packed[0][3]
        cfg0 = packed[0][1].config
        seeds = [spec.config.seed for _, spec, _, _ in packed]
        detect = get_detector(cfg0.algorithm, gamma=cfg0.gamma)
        tracer = get_tracer()
        device = worker.idx if worker is not None else None
        t0 = time.perf_counter()
        # thread-filtered: concurrent pool workers compile in parallel,
        # and this job-scoped count must not absorb a neighbor's builds
        guard = CompileGuard(registry=self._reg,
                             counter="serve.xla_compiles",
                             thread_ident=threading.get_ident())
        head_id = packed[0][0].job_id
        self._device_begin(worker, head_id, bucket.key(),
                           n_jobs=len(packed))
        try:
            try:
                with tracer.span("serve.batch", bucket=bucket.key(),
                                 alg=cfg0.algorithm, b=len(packed),
                                 batch_id=batch_id, device=device):
                    with guard:
                        results = run_consensus_batch(
                            [slab for _, _, slab, _ in packed], detect,
                            cfg0, n_closure=bucket.n_closure, seeds=seeds)
            finally:
                # the heartbeat closes even on a failing batch — the
                # worker is not wedged, its members retry solo
                self._device_end(worker, head_id, bucket.key())
        except Exception as e:  # noqa: BLE001 — whole-batch failure:
            # isolate by re-running every member solo; only genuinely
            # bad jobs fail, each as itself
            _logger.warning("fcserve batch %s failed (%s); retrying "
                            "members solo", batch_id, e)
            self._reg.inc("serve.batch.fallback_solo")
            for job, _, _, _ in packed:
                self._run_solo_job(job, worker=worker)
            return
        elapsed = time.perf_counter() - t0
        # batch metadata and coalescing metrics record only batches
        # that actually COMPLETED as a batch: stamping before the call
        # would leave fallback-solo jobs advertising a coalesced run
        # that never happened
        for job, _, _, _ in packed:
            job.stamp("device_done")
            job.set_batch(batch_id, len(packed))
            if worker is not None:
                job.set_device(worker.idx)
        self._reg.inc("serve.batch.coalesced")
        self._reg.inc("serve.batch.occupancy", len(packed))
        self._reg.gauge("serve.batch.last_size", len(packed))
        # whole-run latency lives on the fclat histograms (bounded
        # memory, never window-truncated — obs/latency.py), not the
        # windowed observe() series the /metricsz footgun was about
        self._lat.hist("serve.batch.seconds").record(elapsed)
        if worker is not None:
            self._reg.inc(f"serve.device.{worker.idx}.batches")
        for (job, spec, _, _), res in zip(packed, results):
            with tracer.span("serve.job", bucket=bucket.key(),
                             alg=cfg0.algorithm, batch_id=batch_id,
                             device=device):
                result = self._finish_result(
                    spec, job.key, bucket, res.partitions,
                    rounds=res.rounds, converged=res.converged,
                    compiles=guard.count, elapsed=elapsed,
                    batch_id=batch_id, batch_size=len(packed),
                    worker=worker, history=res.history)
            job.stamp("fanned_out")
            job.mark(STATE_DONE, result=result)
            self._reg.inc("serve.jobs.completed")
            if worker is not None:
                self._reg.inc(f"serve.device.{worker.idx}.jobs")
            self._lat.hist("serve.job.seconds").record(
                elapsed / len(packed))
            self._record_timeline(job, rung=len(packed), worker=worker)

    def _finish_result(self, spec: JobSpec, key: str, bucket,
                       partitions_raw, rounds: int, converged: bool,
                       compiles: int, elapsed: float,
                       batch_id: Optional[str] = None,
                       batch_size: int = 1,
                       worker=None, history=None) -> Dict[str, Any]:
        """Slice off bucket padding, recompact ids, fill the cache —
        the shared tail of the solo and batched execution paths.

        ``history`` (the run's per-round entries) yields the fcqual
        ``quality`` block.  Unlike the fclat ``timing`` block — which is
        per SUBMISSION and rides the Job — quality is derived from the
        graph content, so it rides the CACHED result payload: a cache
        hit returns the same quality block the computing job produced.
        """
        from fastconsensus_tpu.obs import quality as obs_quality

        partitions = []
        for p in partitions_raw:
            # fcheck: ok=sync-in-loop (partitions are already host numpy
            # — the engine does its one bulk readback; this loop only
            # slices off the bucket's padding nodes and recompacts ids)
            lab = np.asarray(p)[: spec.n_nodes]
            _, compact = np.unique(lab, return_inverse=True)
            partitions.append(compact.astype(np.int32))
        result = {
            "content_hash": key,
            "bucket": bucket.describe(),
            "partitions": partitions,
            "n_nodes": spec.n_nodes,
            "rounds": rounds,
            "converged": converged,
            "compiles": compiles,
            "elapsed_s": round(elapsed, 6),
            "cached": False,
            "quality": obs_quality.summarize_history(
                history or [], converged=converged),
        }
        # fcdelta: the canonical graph + run config ride the CACHED
        # payload (and the /cachez wire, so a fleet sibling's fetch
        # keeps lineage) — that is what lets a later delta submission
        # resolve this result as its parent and rebuild the child
        # graph server-side.  /result strips the graph block: clients
        # sent the edges, they don't need them echoed.
        gu, gv, gw = spec.canonical()
        result["graph"] = {
            "u": np.asarray(gu, dtype=np.int64),
            "v": np.asarray(gv, dtype=np.int64),
            "w": None if gw is None else np.asarray(gw,
                                                    dtype=np.float32),
        }
        result["config"] = dataclasses.asdict(spec.config)
        if batch_id is not None:
            result["batch_id"] = batch_id
            result["batch_size"] = batch_size
        if worker is not None:
            result["device"] = worker.idx
            result["tier"] = worker.kind
            worker.note_job(bucket.key())
        self.cache.put(key, result)
        with self._lock:
            self._buckets[bucket.key()] = \
                self._buckets.get(bucket.key(), 0) + 1
        return result

    # -- pre-warm ----------------------------------------------------

    def _prewarm_all(self, worker=None) -> None:
        """Warm every configured bucket from the calling thread — the
        embedded/single-worker path (pool workers warm their own
        assigned subset via ``_prewarm_one`` instead)."""
        for spec in self.config.prewarm:
            try:
                self._prewarm_one(spec, worker=worker)
            except Exception as e:  # noqa: BLE001 — a bad warm spec
                # must not kill the worker before it served anything
                self._reg.inc("serve.prewarm.failed")
                _logger.warning("fcserve pre-warm %r failed: %s", spec, e)
            self._prewarm_done += 1
        self._prewarm_finished = True

    def _prewarm_one(self, spec: str, worker=None) -> None:
        """Compile one bucket's executables before the first request:
        ``"n64_e96"`` warms the solo path, ``"n64_e96:4"`` also the
        batch ladder up to rung 4 — deterministic probe graphs driven
        through the REAL solo/batched paths (results discarded, cache
        untouched), compiles counted under ``serve.prewarm.compiles``.
        On a mesh (huge-tier) worker only the solo sharded path warms —
        batches never run there.
        """
        from fastconsensus_tpu.analysis import CompileGuard
        from fastconsensus_tpu.consensus import (ConsensusConfig,
                                                 run_consensus,
                                                 run_consensus_batch)
        from fastconsensus_tpu.models.registry import get_detector

        key, _, b = spec.partition(":")
        max_b = self.config.max_batch
        if b:
            if int(b) < 1:
                # a 0-rung spec would compile nothing yet count the
                # bucket as warmed — the silent no-op bucket_from_key's
                # grid check exists to prevent, one knob over
                raise ValueError(
                    f"--warm {spec!r}: rung must be >= 1")
            max_b = min(int(b), self.config.max_batch)
        bucket = bucketer.bucket_from_key(key)
        mesh = None
        if worker is not None and worker.kind == "mesh":
            mesh = worker.mesh
            max_b = 1
        # tau defaults from the RESOLVED algorithm, mirroring the
        # request path (_parse_spec's DEFAULT_TAU[alg] setdefault): tau
        # is a jit-static, so a louvain-tau probe for an infomap warm
        # spec would compile executables no request ever lands on
        cfg_kwargs = dict({"algorithm": "louvain"},
                          **(self.config.prewarm_config or {}))
        cfg_kwargs.setdefault("tau", DEFAULT_TAU[cfg_kwargs["algorithm"]])
        cfg = ConsensusConfig(**cfg_kwargs)
        detect = get_detector(cfg.algorithm, gamma=cfg.gamma)
        tracer = get_tracer()
        device = worker.idx if worker is not None else None
        t0 = time.perf_counter()
        guard = CompileGuard(registry=self._reg,
                             counter="serve.prewarm.compiles",
                             thread_ident=threading.get_ident())
        with tracer.span("serve.prewarm", bucket=bucket.key(),
                         alg=cfg.algorithm, max_b=max_b, device=device):
            with guard:
                for rung in bucketer.BATCH_LADDER:
                    if rung > max_b:
                        break
                    # distinct probe content per lane: shapes are what
                    # compile, but distinct graphs keep the probe honest
                    slabs = []
                    for v in range(rung):
                        slab, _ = bucketer.pad_to_bucket(
                            bucketer.probe_edges(bucket, variant=v),
                            bucket.n_class)
                        slabs.append(slab)
                    if rung == 1:
                        run_consensus(slabs[0], detect, cfg, mesh=mesh,
                                      n_closure=bucket.n_closure)
                    else:
                        run_consensus_batch(
                            slabs, detect, cfg,
                            n_closure=bucket.n_closure,
                            seeds=list(range(rung)))
        if worker is not None:
            worker.note_warm(bucket.key())
        self._reg.inc("serve.prewarm.buckets")
        _logger.info(
            "fcserve pre-warmed %s ladder to B=%d on device %s "
            "(%d compiles, %.1fs)", bucket.key(), max_b,
            "-" if device is None else device, guard.count,
            time.perf_counter() - t0)

    def run_spec(self, spec: JobSpec, key: Optional[str] = None,
                 worker=None, job: Optional[Job] = None) -> Dict[str, Any]:
        """Run one spec to a result payload (cache-aware, synchronous).

        This is the worker's core, callable directly (tests, embedded
        use).  Compiles during the run are counted live into the fcobs
        registry (``serve.xla_compiles``); a request landing in a warm
        bucket counts zero — the serving contract.  On a mesh worker the
        run executes edge-sharded over the reserved device group
        (``run_consensus(mesh=...)`` — the huge tier).  ``job``, when
        the call serves one, receives the fclat pack/device phase
        stamps.
        """
        from fastconsensus_tpu.analysis import CompileGuard
        from fastconsensus_tpu.consensus import run_consensus
        from fastconsensus_tpu.models.registry import get_detector

        spec = self._normalize_spec(spec)
        key = key if key is not None else spec.content_hash()
        # re-check, not first-check: the worker path already counted
        # this admission's miss in submit(); recounting it here would
        # halve the /metricsz hit rate (a hit IS a genuine serve — an
        # identical queued job finished first — and always counts)
        cached = self.cache.get(key, count_miss=False)
        if cached is not None:
            return dict(cached, cached=True)
        mesh = worker.mesh if worker is not None \
            and worker.kind == "mesh" else None
        slab, bucket = bucketer.pad_to_bucket(
            spec.edges, spec.n_nodes, spec.weights,
            max_nodes=self.config.max_nodes,
            max_edges=self.config.max_edges,
            canonical=spec.canonical())
        if job is not None:
            job.stamp("packed")
        # get_detector is memoized, so every job of one (alg, gamma)
        # shares the detector object jit keys its executables on
        detect = get_detector(spec.config.algorithm,
                              gamma=spec.config.gamma)
        tracer = get_tracer()
        device = worker.idx if worker is not None else None
        t0 = time.perf_counter()
        guard = CompileGuard(registry=self._reg,
                             counter="serve.xla_compiles",
                             thread_ident=threading.get_ident())
        run_kwargs: Dict[str, Any] = {}
        if spec.warm_labels is not None:
            # fcdelta incremental: pad the parent's labels and the
            # neighborhood mask out to the bucket — pad nodes enter as
            # frozen singletons (label = own id, active False), exactly
            # what a cold run converges them to, so bucket padding and
            # warm-start compose without a special engine path
            n_real, n_pad = spec.n_nodes, slab.n_nodes
            init = np.empty((spec.config.n_p, n_pad), dtype=np.int32)
            init[:, :n_real] = spec.warm_labels
            init[:, n_real:] = np.arange(n_real, n_pad,
                                         dtype=np.int32)[None, :]
            act = np.zeros((n_pad,), dtype=bool)
            act[:n_real] = spec.warm_active
            run_kwargs = {"init_labels": init, "active_mask": act}
        self._device_begin(worker,
                           None if job is None else job.job_id,
                           bucket.key())
        try:
            with tracer.span("serve.job", bucket=bucket.key(),
                             alg=spec.config.algorithm, device=device):
                with guard:
                    res = run_consensus(slab, detect, spec.config,
                                        mesh=mesh,
                                        n_closure=bucket.n_closure,
                                        **run_kwargs)
        finally:
            self._device_end(worker,
                             None if job is None else job.job_id,
                             bucket.key())
        if job is not None:
            job.stamp("device_done")
        elapsed = time.perf_counter() - t0
        result = self._finish_result(spec, key, bucket, res.partitions,
                                     rounds=res.rounds,
                                     converged=res.converged,
                                     compiles=guard.count,
                                     elapsed=elapsed, worker=worker,
                                     history=res.history)
        self._lat.hist("serve.job.seconds").record(elapsed)
        return result

    # -- introspection -----------------------------------------------

    # -- fcfleet cross-replica cache surface ---------------------------

    def cache_entry(self, key: str) -> Optional[Dict[str, Any]]:
        """The cached result for one content hash, or None — the
        ``GET /cachez/<hash>`` read path a fleet sibling fetches on
        miss (serve/router.py).  Counts as a cache hit: it IS a serve,
        just answered one replica over."""
        return self.cache.get(key, count_miss=False)

    def cache_seed(self, payload: Dict[str, Any]) -> str:
        """Insert one wire-shape result (``POST /cachez``) into the
        local cache, so an already-queued job for the same content
        completes via the worker's pre-run re-probe with zero device
        work — the receiving half of fleet fetch-on-miss and prewarm
        cache shipping.  Raises ValueError on a payload that is not
        the standard result shape."""
        key = payload.get("content_hash")
        parts = payload.get("partitions")
        if not isinstance(key, str) or not key or \
                not isinstance(parts, (list, tuple)) or not parts:
            raise ValueError(
                "cache seed needs content_hash + partitions")
        value = dict(payload)
        # per-SUBMISSION fields never ride cached content (the same
        # rule /result applies when attaching them)
        value.pop("timing", None)
        value["partitions"] = [np.asarray(p, dtype=np.int32)
                               for p in parts]
        if any(p.ndim != 1 for p in value["partitions"]):
            raise ValueError("partitions must be 1-D label arrays")
        graph = value.get("graph")
        if graph is not None:
            # fcdelta lineage survives the fleet wire: a seeded result
            # must still resolve delta submissions on the new replica
            value["graph"] = {
                "u": np.asarray(graph["u"], dtype=np.int64),
                "v": np.asarray(graph["v"], dtype=np.int64),
                "w": None if graph.get("w") is None
                else np.asarray(graph["w"], dtype=np.float32),
            }
        # stored uncached; a later hit serves dict(value, cached=True)
        # exactly like a locally computed result
        value["cached"] = False
        self.cache.put(key, value)
        self._reg.inc("serve.cache.seeded")
        return key

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            states: Dict[str, int] = {}
            for j in self._jobs.values():
                states[j.state] = states.get(j.state, 0) + 1
            buckets = dict(self._buckets)
            last_bundle = self._last_bundle
        if self.pool is not None:
            prewarm = self.pool.prewarm_progress()
            workers = self.pool.describe()
            affinity = self.pool.scheduler.affinity()
            cordoned = [w["device"] for w in workers if w["cordoned"]]
            suspects = self.pool.watchdog.suspects()
            watchdog_trips = self.pool.watchdog.trips()
        else:
            prewarm = {"specs": self._prewarm_total,
                       "done": self._prewarm_done,
                       "finished": self._prewarm_finished}
            workers, affinity, cordoned = [], {}, []
            suspects, watchdog_trips = [], 0
        return {
            "uptime_s": round(time.time() - self._started_at, 3),
            "draining": self.queue.draining(),
            "queue_depth": self.queue.depth(),
            "queue_max_depth": self.queue.max_depth,
            "cache_entries": len(self.cache),
            "jobs": states,
            "buckets": buckets,
            "max_batch": self.config.max_batch,
            "prewarm": prewarm,
            "workers": workers,
            "affinity": affinity,
            "cordoned_devices": cordoned,
            # fcflight: the router-facing replica self-diagnosis —
            # which devices the watchdog currently holds suspect, how
            # often it has tripped, and where the freshest crash
            # evidence lives on disk
            "suspect_devices": [t.get("device") for t in suspects],
            "watchdog_trips": watchdog_trips,
            "last_bundle": last_bundle,
        }

    def device_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-device breakdown for ``/metricsz``: jobs, batches,
        busy-fraction from the pool's own (service-scoped) bookkeeping,
        compiles/deaths from the ``serve.device.<i>.*`` fcobs counters
        (process-scoped, like every other /metricsz counter)."""
        counters = self._reg.counters()
        uptime = max(time.time() - self._started_at, 1e-9)
        out: Dict[str, Dict[str, Any]] = {}
        for w in (self.pool.describe() if self.pool is not None else []):
            i = w["device"]
            pref = f"serve.device.{i}."
            out[str(i)] = {
                "kind": w["kind"],
                "jobs": w["jobs"],
                "batches": w["batches"],
                "xla_compiles": counters.get(pref + "xla_compiles", 0),
                "deaths": counters.get(pref + "deaths", 0),
                "busy_s": w["busy_s"],
                "busy_frac": round(w["busy_s"] / uptime, 4),
                "backlog": w["backlog"],
                "cordoned": w["cordoned"],
                "warm_buckets": len(w["warm"]),
            }
        return out


# ---------------------------------------------------------------------
# HTTP front end (stdlib http.server)
# ---------------------------------------------------------------------

def _parse_spec(payload: Dict[str, Any],
                max_body_edges: int) -> JobSpec:
    """A JobSpec from a ``/submit`` JSON body (raises ValueError)."""
    from fastconsensus_tpu.consensus import ConsensusConfig

    if "edgelist" in payload:
        rows = []
        for lineno, ln in enumerate(
                str(payload["edgelist"]).splitlines(), start=1):
            ln = ln.split("#", 1)[0].strip()
            if not ln:
                continue
            parts = ln.split()
            if len(parts) < 2:
                raise ValueError(
                    f"edgelist line {lineno}: expected 'u v', got {ln!r}")
            rows.append((int(parts[0]), int(parts[1])))
        edges = np.asarray(rows, dtype=np.int64).reshape(-1, 2)
    else:
        edges = np.asarray(payload.get("edges", ()),
                           dtype=np.int64).reshape(-1, 2)
    if edges.shape[0] < 1:
        raise ValueError("no edges in request body")
    if edges.shape[0] > max_body_edges:
        raise GraphTooLarge(
            f"request carries {edges.shape[0]} edges; this server admits "
            f"at most {max_body_edges}")
    n_nodes = int(payload.get("n_nodes", int(edges.max()) + 1))
    if edges.min() < 0 or edges.max() >= n_nodes:
        raise ValueError(
            f"edge endpoints must be compact ids in [0, {n_nodes})")
    alg = str(payload.get("algorithm", "louvain"))
    if alg not in ALGORITHMS:
        raise ValueError(
            f"unknown algorithm {alg!r}; available: {', '.join(ALGORITHMS)}")
    cfg_kwargs: Dict[str, Any] = {"algorithm": alg}
    for field, cast in (("n_p", int), ("tau", float), ("delta", float),
                        ("max_rounds", int), ("seed", int),
                        ("gamma", float), ("auto_grow", bool),
                        ("warm_start", bool), ("align_frac", float),
                        ("closure_sampler", str),
                        ("closure_tau", lambda v: None if v is None
                         else float(v))):
        if field in payload:
            cfg_kwargs[field] = cast(payload[field])
    cfg_kwargs.setdefault("tau", DEFAULT_TAU[alg])
    config = ConsensusConfig(**cfg_kwargs)
    if config.closure_sampler not in ("auto", "csr", "scatter"):
        raise ValueError(
            f"closure_sampler={config.closure_sampler!r}: expected "
            f"'auto', 'csr' or 'scatter'")
    if not 0.0 <= config.tau <= 1.0:
        raise ValueError(f"tau {config.tau} out of range 0..1")
    if not 0.0 <= config.delta <= 1.0:
        raise ValueError(f"delta {config.delta} out of range 0..1")
    if config.n_p < 1 or config.max_rounds < 1:
        raise ValueError("n_p and max_rounds must be >= 1")
    return JobSpec(edges=edges, n_nodes=n_nodes, config=config,
                   priority=_parse_priority(payload),
                   slo=_parse_slo(payload),
                   slo_target_ms=_parse_slo_target(payload),
                   trace=_parse_trace(payload))


def _parse_priority(payload: Dict[str, Any]) -> int:
    """Priority from a submit body (shared by the full and delta
    paths)."""
    prio = payload.get("priority", PRIORITY_NORMAL)
    if isinstance(prio, str):
        if prio not in PRIORITY_NAMES:
            raise ValueError(
                f"unknown priority {prio!r}; one of "
                f"{', '.join(PRIORITY_NAMES)} or an int")
        return PRIORITY_NAMES[prio]
    priority = int(prio)
    if not PRIORITY_INTERACTIVE <= priority <= PRIORITY_BATCH:
        # unclamped ints would let any client jump ahead of every
        # documented class — the priority scheme is an enforced
        # contract, not a suggestion
        raise ValueError(
            f"priority {priority} out of range "
            f"{PRIORITY_INTERACTIVE}..{PRIORITY_BATCH}")
    return priority


def _parse_slo(payload: Dict[str, Any],
               default: Optional[str] = None) -> Optional[str]:
    """SLO class from a submit body; ``default`` is fcdelta's — a delta
    submission lands in the ``delta`` class unless it asks otherwise."""
    slo = payload.get("slo", default)
    if slo is not None:
        slo = str(slo)
        if slo not in SLO_CLASSES:
            raise ValueError(
                f"unknown slo class {slo!r}; one of "
                f"{', '.join(SLO_CLASSES)}")
    return slo


def _parse_slo_target(payload: Dict[str, Any]) -> Optional[float]:
    slo_target_ms = payload.get("slo_target_ms")
    if slo_target_ms is not None:
        slo_target_ms = float(slo_target_ms)
        if not slo_target_ms > 0:
            raise ValueError(
                f"slo_target_ms must be > 0, got {slo_target_ms}")
    return slo_target_ms


def _parse_trace(payload: Dict[str, Any]) -> Optional[str]:
    # fctrace id: set in the body by a direct client, or injected by
    # the handler from the X-FCTPU-Trace header the router forwards.
    # Bounded because it is stamped verbatim into flight-event aux.
    trace = payload.get("trace")
    if trace is not None:
        trace = str(trace)
        if not 0 < len(trace) <= 128:
            raise ValueError("trace id must be 1..128 characters")
    return trace


def _result_json(result: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(result)
    out["partitions"] = [np.asarray(p).tolist()
                         for p in result["partitions"]]
    graph = out.get("graph")
    if graph is not None:
        # the /cachez wire shape (fleet fetch-on-miss must preserve
        # fcdelta lineage); /result pops the block before calling here
        out["graph"] = {
            "u": np.asarray(graph["u"]).tolist(),
            "v": np.asarray(graph["v"]).tolist(),
            "w": None if graph.get("w") is None
            else np.asarray(graph["w"]).tolist(),
        }
    return out


class _Handler(BaseHTTPRequestHandler):
    """Routes: POST /submit; GET /status/<id> /result/<id> /healthz
    /metricsz."""

    server_version = "fcserve/1"
    protocol_version = "HTTP/1.1"

    @property
    def service(self) -> ConsensusService:
        return self.server.fcserve_service  # type: ignore[attr-defined]

    def log_message(self, fmt: str, *args) -> None:  # noqa: A003
        _logger.debug("fcserve http: " + fmt, *args)

    def _send(self, code: int, payload: Dict[str, Any],
              headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_fault(self, e: BaseException) -> None:
        """Last-resort 500: an exception the route arms never mapped
        still answers the promised JSON error body instead of dropping
        the connection with a raw traceback, and stamps
        ``serve.http.unhandled_errors`` so the gap is visible on
        /metricsz (fcheck-fault: unmapped-http-error)."""
        self.service._reg.inc("serve.http.unhandled_errors")
        _logger.exception("fcserve http: unhandled handler error")
        try:
            self._send(500, {"error": "internal error: "
                                      f"{type(e).__name__}: {e}"})
        except OSError:  # fcheck: ok=swallowed-error: the client socket is already gone — there is no one left to answer; the counter above carries the failure
            pass

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        try:
            self._do_post()
        except Exception as e:  # noqa: BLE001 — catch-all status mapping
            self._send_fault(e)

    def _read_json(self) -> Dict[str, Any]:
        length = int(self.headers.get("Content-Length", "0"))
        return json.loads(self.rfile.read(length) or b"{}")

    def _do_post(self) -> None:
        path = self.path.rstrip("/")
        if path == "/cachez":
            # fcfleet cache seeding: a router (fetch-on-miss) or the
            # fleet manager (prewarm shipping) plants a sibling's
            # result here
            try:
                key = self.service.cache_seed(self._read_json())
            except (ValueError, TypeError, KeyError) as e:
                self._send(400, {"error": f"bad cache seed: {e}"})
                return
            self._send(200, {"seeded": True, "content_hash": key,
                             "cache_entries": len(self.service.cache)})
            return
        if path == "/cachez/load":
            # fcfleet death inheritance: load a dead sibling's spilled
            # npz (serve/fleet.py on_death) — corrupt/missing files
            # load 0 entries, never error (the ResultCache.load
            # contract)
            try:
                spill_path = str(self._read_json()["path"])
            except (ValueError, TypeError, KeyError) as e:
                self._send(400, {"error": f"bad cache load request: {e}"})
                return
            before = set(self.service.cache.keys())
            n = self.service.cache.load(spill_path)
            # the hashes that are new here, so the router can index this
            # replica as their holder (fetch-on-miss after inheritance)
            fresh = [k for k in self.service.cache.keys()
                     if k not in before]
            self._send(200, {"loaded": n, "content_hashes": fresh})
            return
        if path != "/submit":
            self._send(404, {"error": f"no such endpoint {self.path}"})
            return
        try:
            payload = self._read_json()
            # fctrace propagation: the header the router forwards wins
            # over a body-level trace — the router's id is the one its
            # own flight events and the client's answer already carry
            header_trace = self.headers.get("X-FCTPU-Trace")
            if header_trace:
                payload["trace"] = header_trace
            if payload.get("parent") is not None:
                # fcdelta: a delta submit carries no edges of its own —
                # the child graph is rebuilt from the cached parent
                self._submit_delta(payload)
                return
            spec = _parse_spec(payload, self.service.config.max_edges)
        except GraphTooLarge as e:
            self._send(413, {"error": str(e)})
            return
        except (ValueError, TypeError, KeyError) as e:
            self._send(400, {"error": f"bad request: {e}"})
            return
        try:
            job = self.service.submit(spec)
        except GraphTooLarge as e:
            self._send(413, {"error": str(e)})
            return
        except QueueFull as e:
            self._send_backpressure(e)
            return
        except QueueClosed as e:
            self._send(503, {"error": str(e), "draining": True})
            return
        except ValueError as e:
            self._send(400, {"error": str(e)})
            return
        self._send_submit_ack(job)

    def _send_backpressure(self, e: QueueFull) -> None:
        # THE backpressure response: explicit, immediate, retryable
        # — and honest: Retry-After derives from queued depth x the
        # observed per-bucket service rate (serve/shaping.py), not
        # a literal guess.  The header is integer delta-seconds
        # (RFC 9110, rounded up so it never under-promises); the
        # body carries the unrounded float for typed clients.
        retry_s = e.retry_after_s
        if retry_s is None:
            retry_s = self.service.shaper.config.retry_after_default_s
        self._send(429, {"error": str(e), "backpressure": True,
                         "shed": isinstance(e, DeadlineShed),
                         "retry_after_s": round(retry_s, 3),
                         "queue_depth": e.depth,
                         "queue_max_depth": e.max_depth},
                   headers={"Retry-After":
                            str(max(1, math.ceil(retry_s)))})

    def _send_submit_ack(self, job: Job) -> None:
        ack = {"job_id": job.job_id, "state": job.state,
               "content_hash": job.key,
               "trace": job.spec.trace,
               "cached": job.state == STATE_DONE}
        if job.spec.delta is not None:
            # fcdelta: the client learns the warm-start verdict at
            # submit time (mode / fallback reason / delta_frac), not
            # only after polling the result
            ack["delta"] = job.spec.delta
        self._send(202 if job.state == STATE_QUEUED else 200, ack)

    def _submit_delta(self, payload: Dict[str, Any]) -> None:
        """fcdelta POST /submit with ``parent``: full status mapping —
        404 parent-not-cached, 400 malformed delta (with the offending
        ``adds[i]``/``removes[i]`` index), then the standard 413/429/
        503 admission surface."""
        try:
            job = self.service.submit_delta(payload)
        except ParentNotCached as e:
            self._send(404, {"error": str(e),
                             "parent": payload.get("parent")})
            return
        except GraphTooLarge as e:
            self._send(413, {"error": str(e)})
            return
        except QueueFull as e:
            self._send_backpressure(e)
            return
        except QueueClosed as e:
            self._send(503, {"error": str(e), "draining": True})
            return
        except (ValueError, TypeError, KeyError) as e:
            # DeltaError is a ValueError: the line-numbered parse
            # message IS the payload
            self._send(400, {"error": f"bad delta request: {e}"})
            return
        self._send_submit_ack(job)

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        try:
            self._do_get()
        except Exception as e:  # noqa: BLE001 — catch-all status mapping
            self._send_fault(e)

    def _do_get(self) -> None:
        path = self.path.rstrip("/")
        if path == "/healthz":
            stats = self.service.stats()
            self._send(200, {"ok": True, **stats})
            return
        if path == "/metricsz":
            # "scope" is fctrace self-description: this block is ONE
            # replica's view — the router's /metricsz says "router",
            # and the fleet-wide exact merge lives at the router's
            # /fleetz.  A scraper can no longer mistake one process's
            # counters for fleet totals.
            self._send(200, {"scope": "replica",
                             "fcobs": self.service._reg.snapshot(),
                             "serve": self.service.stats(),
                             "devices": self.service.device_stats(),
                             "latency": self.service.latency_stats(),
                             "shaping": self.service.shaping_stats()})
            return
        if path == "/debugz/flight":
            # fctrace: the live trace-stamped flight snapshot (with the
            # monotonic<->wall anchor), so the CI drill can assert a
            # trace id spans router and replica without killing anyone
            self._send(200, {"scope": "replica",
                             "flight":
                             obs_flight.get_flight_recorder().snapshot()})
            return
        if path == "/debugz/slowest":
            # fcflight tail exemplars: the bucket-worst serve.e2e jobs
            # joined to their flight timelines (typed in ServeClient)
            self._send(200, self.service.slowest())
            return
        if path == "/cachez":
            # fcfleet: the content-hash index a prewarm-shipping donor
            # advertises (serve/fleet.py ship_cache)
            self._send(200, {"keys": self.service.cache.keys(),
                             "entries": len(self.service.cache)})
            return
        if path.startswith("/cachez/"):
            cached = self.service.cache_entry(path[len("/cachez/"):])
            if cached is None:
                self._send(404, {"error": "no cached result for that "
                                          "content hash"})
            else:
                self._send(200, _result_json(dict(cached, cached=True)))
            return
        for prefix in ("/status/", "/result/"):
            if path.startswith(prefix):
                job = self.service.job(path[len(prefix):])
                if job is None:
                    self._send(404, {"error": "unknown job id"})
                    return
                if prefix == "/status/":
                    self._send(200, job.describe())
                elif job.state == STATE_DONE:
                    # fcdelta: the graph block is cache lineage, not a
                    # client answer — the client sent the edges (or the
                    # delta); echoing a million edges back would bloat
                    # every /result for a field only /cachez needs
                    res = dict(job.result)
                    res.pop("graph", None)
                    out = _result_json(res)
                    # the timing block is PER SUBMISSION, never cached
                    # content: two jobs sharing one cached result each
                    # report their own lifecycle, so it rides the Job,
                    # not the result payload
                    timing = job.timing()
                    if timing is not None:
                        out["timing"] = timing
                    if job.spec.delta is not None:
                        # per-submission like timing: a cache hit on a
                        # delta key still reports ITS OWN provenance
                        out["delta"] = job.spec.delta
                    self._send(200, out)
                elif job.state == STATE_FAILED:
                    self._send(500, job.describe())
                else:
                    self._send(202, job.describe())
                return
        self._send(404, {"error": f"no such endpoint {self.path}"})


def make_http_server(service: ConsensusService, host: str = "127.0.0.1",
                     port: int = 8765) -> ThreadingHTTPServer:
    """Bind the HTTP front end (``port=0`` picks a free port)."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.fcserve_service = service  # type: ignore[attr-defined]
    return httpd
