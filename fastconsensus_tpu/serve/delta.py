"""fcdelta: incremental evolving-graph consensus — the jax-free half.

Production community detection rarely sees a graph once: social and
transaction graphs arrive as the *same* graph plus a small edge delta,
over and over.  The content-addressed cache (serve/cache.py) answers
exact repeats; this module is the *approximate* reuse layer on top: a
``POST /submit`` body carrying ``parent`` (a prior job's content hash)
plus canonical edge ``adds``/``removes`` resolves the parent's cached
partitions, uses them as the warm-start ensemble, and re-runs consensus
with the move phase frozen outside the changed edges' neighborhood
(``run_consensus(init_labels=..., active_mask=...)`` — the engine keeps
shapes static under the mask, so bucketed executables are shared with
full runs and a warm-bucket delta compiles nothing).

Everything here is numpy + stdlib: delta parsing/canonicalization, the
child-graph construction, the frontier-neighborhood mask, and the
warm-start vs full-run fallback policy.  The policy reads the *parent's*
fcqual quality block (obs/quality.py) — a parent that never converged,
ended in low ensemble agreement, or was still churning labels is a bad
warm-start seed, and the honest move is a full run with
``mode="fallback"`` stamped on the response.

Incremental results are deliberately cached under a *derived* key
(:func:`delta_cache_key`), never under the child graph's own content
hash: a warm-started, frontier-restricted run is an approximation of
the from-scratch result (the bench bounds the gap), and the exact-dedup
promise of the content hash must stay exact.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class DeltaError(ValueError):
    """A malformed delta request (HTTP 400), with the offending
    ``adds[i]``/``removes[i]`` index in the message."""


class ParentNotCached(Exception):
    """The referenced parent hash is not resolvable from the result
    cache (HTTP 404): expired, evicted, never ran on this replica and
    not fetchable from a sibling, or cached before fcdelta existed (no
    graph/config block to rebuild the child graph from)."""


@dataclasses.dataclass(frozen=True)
class DeltaPolicy:
    """Operator thresholds for the warm-start vs full-run decision.

    Every rule that trips falls back to a full run on the child graph —
    fallback is a *correct* answer that costs a full run, incremental is
    a fast answer whose quality rests on the parent being a good seed.
    """

    # largest delta (changed edges / parent edges) eligible for
    # incremental re-consensus; beyond it the changed neighborhood
    # covers so much of the graph that warm-start saves nothing
    max_delta_frac: float = 0.10
    # parent quality floor (fcqual block): an ensemble that disagreed
    # with itself is noise as a warm-start seed
    min_parent_agreement: float = 0.5
    # a parent still churning labels in its final round had not
    # settled; its partitions are a mid-flight snapshot, not a
    # consensus.  The floor is deliberately high: served runs compute
    # churn on the PADDED slab, and community renumbering alone moves
    # the pad singletons' label ids every round (~0.3 on a converged
    # karate-in-n64 run), while a genuinely unsettled run churns ~0.95.
    max_parent_churn: float = 0.75

    def decide(self, n_changed: int, n_parent_edges: int,
               parent: Dict[str, Any], config,
               parent_bucket_key: str, child_bucket_key: str,
               warm_capable: bool,
               huge: bool = False) -> "DeltaDecision":
        """The warm-start vs fallback verdict for one delta submission.

        ``parent`` is the parent's cached result payload; ``config`` the
        (inherited) run config; ``warm_capable`` whether the detector
        supports warm-start at all (``supports_init`` +
        ``config.warm_start``)."""
        frac = float(n_changed) / float(max(n_parent_edges, 1))
        reason = None
        quality = parent.get("quality")
        if not warm_capable:
            reason = "detector_no_warm"
        elif huge:
            reason = "huge_tier"
        elif frac > self.max_delta_frac:
            reason = "delta_too_large"
        elif child_bucket_key != parent_bucket_key:
            # a delta that crosses a bucket boundary lands on different
            # executables AND different padding than the parent ran
            # under; full run keeps the shapes honest
            reason = "bucket_boundary"
        elif len(parent.get("partitions", ())) != config.n_p:
            reason = "ensemble_mismatch"
        elif not parent.get("converged", False):
            reason = "parent_unconverged"
        elif quality is None:
            reason = "parent_quality_missing"
        elif quality.get("final_agreement", 0.0) < \
                self.min_parent_agreement:
            reason = "low_parent_agreement"
        elif quality.get("final_churn_frac", 1.0) > \
                self.max_parent_churn:
            reason = "high_parent_churn"
        mode = "fallback" if reason is not None else "incremental"
        return DeltaDecision(mode=mode, reason=reason,
                             delta_frac=round(frac, 6))


@dataclasses.dataclass(frozen=True)
class DeltaDecision:
    mode: str                       # "incremental" | "fallback"
    reason: Optional[str]           # fallback rule name, None if warm
    delta_frac: float


def parse_edge_pairs(raw: Any, field: str,
                     n_nodes: int) -> np.ndarray:
    """Validate + canonicalize one ``adds``/``removes`` list into
    int64 ``[k, 2]`` with ``u < v``, sorted by edge key — order- and
    orientation-invariant.  Raises :class:`DeltaError` naming the
    offending entry (``adds[3]: ...``) so a client can fix its request
    without diffing the whole delta."""
    if raw is None:
        return np.empty((0, 2), dtype=np.int64)
    if not isinstance(raw, (list, tuple)):
        raise DeltaError(f"{field} must be a list of [u, v] pairs")
    rows: List[Tuple[int, int]] = []
    for i, item in enumerate(raw):
        if not isinstance(item, (list, tuple)) or len(item) != 2:
            raise DeltaError(
                f"{field}[{i}]: expected a [u, v] pair, got {item!r}")
        try:
            a, b = int(item[0]), int(item[1])
        except (TypeError, ValueError):
            raise DeltaError(
                f"{field}[{i}]: endpoints must be integers, "
                f"got {item!r}") from None
        if a == b:
            raise DeltaError(f"{field}[{i}]: self-loop ({a}, {b})")
        if not (0 <= a < n_nodes and 0 <= b < n_nodes):
            raise DeltaError(
                f"{field}[{i}]: node {max(a, b) if max(a, b) >= n_nodes else min(a, b)} "
                f"out of range for n_nodes={n_nodes}")
        rows.append((min(a, b), max(a, b)))
    if not rows:
        return np.empty((0, 2), dtype=np.int64)
    arr = np.asarray(rows, dtype=np.int64)
    key = arr[:, 0] * np.int64(n_nodes) + arr[:, 1]
    order = np.argsort(key, kind="stable")
    dup = np.flatnonzero(np.diff(key[order]) == 0)
    if dup.size:
        j = int(order[dup[0] + 1])
        u, v = int(arr[j, 0]), int(arr[j, 1])
        raise DeltaError(
            f"{field}[{j}]: duplicate edge ({u}, {v})")
    return arr[order]


def parse_delta(payload: Dict[str, Any],
                n_nodes: int) -> Tuple[np.ndarray, np.ndarray]:
    """``(adds, removes)`` canonical int64 ``[k, 2]`` arrays from a
    delta submit body.  An edge in both lists is contradictory and
    rejected; an empty delta is rejected (it is an exact resubmit —
    the content-addressed cache already answers those)."""
    adds = parse_edge_pairs(payload.get("adds"), "adds", n_nodes)
    removes = parse_edge_pairs(payload.get("removes"), "removes",
                               n_nodes)
    if adds.shape[0] == 0 and removes.shape[0] == 0:
        raise DeltaError(
            "empty delta: no adds and no removes (an unchanged graph "
            "is an exact resubmit — use /submit without a parent)")
    if adds.shape[0] and removes.shape[0]:
        akey = adds[:, 0] * np.int64(n_nodes) + adds[:, 1]
        rkey = removes[:, 0] * np.int64(n_nodes) + removes[:, 1]
        both = np.intersect1d(akey, rkey)
        if both.size:
            k = int(both[0])
            u, v = k // n_nodes, k % n_nodes
            j = int(np.flatnonzero(akey == k)[0])
            raise DeltaError(
                f"adds[{j}]: edge ({u}, {v}) appears in both adds "
                f"and removes")
    return adds, removes


def apply_delta(u: np.ndarray, v: np.ndarray, w: Optional[np.ndarray],
                n_nodes: int, adds: np.ndarray, removes: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray,
                           Optional[np.ndarray]]:
    """The child graph's canonical ``(u, v, w)`` from the parent's.

    Set semantics against the parent: every ``removes`` edge must be
    present, every ``adds`` edge must be absent (:class:`DeltaError`
    with the offending index otherwise — a delta against a graph the
    client mis-remembers must fail loudly, not silently drift).  Added
    edges carry weight 1.0 when the parent is weighted.  The result
    stays in canonical ascending edge-key order, so hashing/packing
    need no second sort.
    """
    u = np.asarray(u, dtype=np.int64)
    v = np.asarray(v, dtype=np.int64)
    key = u * np.int64(n_nodes) + v
    if removes.shape[0]:
        rkey = removes[:, 0] * np.int64(n_nodes) + removes[:, 1]
        pos = np.searchsorted(key, rkey)
        ok = (pos < key.shape[0])
        ok &= np.where(ok, key[np.minimum(pos, key.shape[0] - 1)]
                       == rkey, False)
        if not ok.all():
            j = int(np.flatnonzero(~ok)[0])
            raise DeltaError(
                f"removes[{j}]: edge ({int(removes[j, 0])}, "
                f"{int(removes[j, 1])}) not present in parent")
        keep = np.ones(key.shape[0], dtype=bool)
        keep[pos] = False
        u, v, key = u[keep], v[keep], key[keep]
        if w is not None:
            w = np.asarray(w, dtype=np.float32)[keep]
    if adds.shape[0]:
        akey = adds[:, 0] * np.int64(n_nodes) + adds[:, 1]
        pos = np.searchsorted(key, akey)
        clash = (pos < key.shape[0])
        clash &= np.where(clash, key[np.minimum(pos, key.shape[0] - 1)]
                          == akey, False)
        if clash.any():
            j = int(np.flatnonzero(clash)[0])
            raise DeltaError(
                f"adds[{j}]: edge ({int(adds[j, 0])}, "
                f"{int(adds[j, 1])}) already present in parent")
        u = np.insert(u, pos, adds[:, 0])
        v = np.insert(v, pos, adds[:, 1])
        if w is not None:
            w = np.insert(np.asarray(w, dtype=np.float32), pos,
                          np.float32(1.0))
    if u.shape[0] == 0:
        raise DeltaError("removes empty the graph: no edges remain")
    return u, v, (None if w is None else w)


def neighborhood_mask(u: np.ndarray, v: np.ndarray, n_nodes: int,
                      adds: np.ndarray,
                      removes: np.ndarray) -> np.ndarray:
    """``bool[n_nodes]`` — vertices allowed to move during incremental
    re-consensus: every endpoint of a changed edge plus its 1-hop
    neighborhood in the *child* graph (arXiv:1503.01322's pruning rule:
    only vertices whose neighborhood changed can improve).  Everything
    outside is frozen at the parent's labels by the engine's
    ``active_mask``."""
    changed = np.zeros(n_nodes, dtype=bool)
    for pairs in (adds, removes):
        if pairs.shape[0]:
            changed[pairs[:, 0]] = True
            changed[pairs[:, 1]] = True
    active = changed.copy()
    touched = changed[u] | changed[v]
    active[u[touched]] = True
    active[v[touched]] = True
    return active


def delta_cache_key(child_hash: str, parent_hash: str) -> str:
    """Cache key for an *incremental* result: namespaced by lineage so
    the approximate answer can never shadow the exact content hash of
    the child graph.  An identical delta resubmit (same parent, same
    delta, same config) still dedups exactly."""
    return f"{child_hash}:delta:{parent_hash[:16]}"


def describe_payload(parent_hash: str, decision: DeltaDecision,
                     n_adds: int, n_removes: int) -> Dict[str, Any]:
    """The JSON ``delta`` block stamped on 202/`/status`/`/result` —
    per-submission provenance, deliberately OUTSIDE any content hash
    (like the SLO and trace fields it rides beside)."""
    return {
        "parent": parent_hash,
        "mode": decision.mode,
        "reason": decision.reason,
        "delta_frac": decision.delta_frac,
        "n_adds": int(n_adds),
        "n_removes": int(n_removes),
    }
