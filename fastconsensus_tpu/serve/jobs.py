"""fcserve job model: specs, states, priorities, content addressing.

A job is one consensus request — a graph plus a :class:`ConsensusConfig`
— flowing through the service's queue (serve/queue.py) into the worker
loop (serve/server.py).  Two identity notions coexist deliberately:

* the **job id** (``Job.job_id``) names one *submission* — every submit
  gets a fresh one, it is what ``/status`` and ``/result`` key on;
* the **content hash** (:func:`content_hash`) names the *work*: a
  deterministic SHA-256 over the canonicalized graph bytes and every
  result-relevant config field.  It is the key of the result cache
  (serve/cache.py), so resubmitting the same graph+config — regardless
  of edge order, duplicate edges, or which client sent it — is answered
  without touching the device.

Canonicalization mirrors ``graph.pack_edges`` (canonical ``src < dst``
orientation, self-loops dropped, duplicates merged keeping the first
weight) and then *sorts by edge key*, so the hash is invariant to input
edge order — the property that makes it content addressing rather than
payload addressing.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from fastconsensus_tpu.consensus import ConsensusConfig

# Smaller pops first (serve/queue.py is a min-heap on priority).
PRIORITY_INTERACTIVE = 0
PRIORITY_NORMAL = 1
PRIORITY_BATCH = 2
PRIORITY_NAMES = {
    "interactive": PRIORITY_INTERACTIVE,
    "normal": PRIORITY_NORMAL,
    "batch": PRIORITY_BATCH,
}

# Job lifecycle.  There is deliberately no "rejected" state: admission
# control (queue full, graph too large, draining) refuses the submission
# before a Job exists — backpressure is an error the client sees, never
# unbounded queue growth (serve/queue.py module notes).
STATE_QUEUED = "queued"
STATE_RUNNING = "running"
STATE_DONE = "done"
STATE_FAILED = "failed"
STATES = (STATE_QUEUED, STATE_RUNNING, STATE_DONE, STATE_FAILED)

_HASH_VERSION = b"fcserve-v1"
_job_seq = itertools.count(1)


def canonical_edges(edges: np.ndarray, n_nodes: int,
                    weights: Optional[np.ndarray] = None
                    ) -> Tuple[np.ndarray, np.ndarray,
                               Optional[np.ndarray]]:
    """Canonical ``(u, v, w)`` in ascending edge-key order.

    Same dedup semantics as ``graph.pack_edges`` (src < dst, self-loops
    dropped, first weight wins on duplicates), then sorted by
    ``u * n_nodes + v`` so the result — and therefore the content hash —
    does not depend on input edge order.
    """
    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    if weights is not None:
        weights = np.asarray(weights, dtype=np.float32)
    u = np.minimum(edges[:, 0], edges[:, 1])
    v = np.maximum(edges[:, 0], edges[:, 1])
    keep = u != v
    u, v = u[keep], v[keep]
    if weights is not None:
        weights = weights[keep]
    key = u * np.int64(n_nodes) + v
    _, first = np.unique(key, return_index=True)
    first.sort()
    u, v, key = u[first], v[first], key[first]
    if weights is not None:
        weights = weights[first]
    order = np.argsort(key, kind="stable")
    return (u[order], v[order],
            None if weights is None else weights[order])


def content_hash(edges: np.ndarray, n_nodes: int,
                 config: ConsensusConfig,
                 weights: Optional[np.ndarray] = None) -> str:
    """Deterministic SHA-256 of (canonical graph bytes, config)."""
    return hash_canonical(canonical_edges(edges, n_nodes, weights),
                          n_nodes, config)


def hash_canonical(canonical: Tuple[np.ndarray, np.ndarray,
                                    Optional[np.ndarray]],
                   n_nodes: int, config: ConsensusConfig) -> str:
    """:func:`content_hash` over an already-canonicalized ``(u, v, w)``
    (JobSpec memoizes the canonicalization — at the serving limit of
    millions of edges the sort/dedupe pass is worth doing once, not
    once for the hash and again for the bucket pack)."""
    u, v, w = canonical
    h = hashlib.sha256()
    h.update(_HASH_VERSION)
    h.update(int(n_nodes).to_bytes(8, "little"))
    h.update(np.ascontiguousarray(u, dtype="<i8").tobytes())
    h.update(np.ascontiguousarray(v, dtype="<i8").tobytes())
    if w is not None and not np.all(w == 1.0):
        h.update(np.ascontiguousarray(w, dtype="<f4").tobytes())
    # every ConsensusConfig field is result-relevant (the checkpoint
    # fingerprints in consensus.py guard the same set); astuple keeps
    # this in lockstep with future config fields automatically
    h.update(repr(dataclasses.astuple(config)).encode())
    return h.hexdigest()


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One consensus request: compact 0-based edges + run config."""

    edges: np.ndarray            # int64[E, 2], compact 0-based ids
    n_nodes: int
    config: ConsensusConfig
    weights: Optional[np.ndarray] = None
    priority: int = PRIORITY_NORMAL

    def n_edges_raw(self) -> int:
        """Raw (pre-dedupe) edge count — the cheap admission bound."""
        return int(np.asarray(self.edges).reshape(-1, 2).shape[0])

    def canonical(self) -> Tuple[np.ndarray, np.ndarray,
                                 Optional[np.ndarray]]:
        """Memoized :func:`canonical_edges` of this spec — hashing (at
        submit) and bucket packing (in the worker) share ONE O(E log E)
        canonicalization pass."""
        cached = getattr(self, "_canonical", None)
        if cached is None:
            cached = canonical_edges(self.edges, self.n_nodes,
                                     self.weights)
            object.__setattr__(self, "_canonical", cached)
        return cached

    def content_hash(self) -> str:
        return hash_canonical(self.canonical(), self.n_nodes,
                              self.config)

    def bucket(self):
        """Memoized shape bucket (serve/bucketer.py) — the routing key
        of the multi-device scheduler (serve/scheduler.py): the pool
        dispatcher classifies every popped job by bucket, and a fresh
        O(E log E) canonicalization under the dispatch path would stall
        every worker behind one big graph.  ``canonical()`` is already
        memoized; this adds the grid lookup on top."""
        cached = getattr(self, "_bucket", None)
        if cached is None:
            from fastconsensus_tpu.serve import bucketer

            u, _, _ = self.canonical()
            cached = bucketer.bucket_for(self.n_nodes,
                                         max(int(u.shape[0]), 1))
            object.__setattr__(self, "_bucket", cached)
        return cached

    def batch_group(self) -> str:
        """Coalescing key for cross-request batching (serve/queue.py
        ``pop_batch``): two jobs may share one batched device call iff
        they land in the same shape bucket AND run the same config in
        every field but the seed.  The seed is excluded deliberately —
        it reaches the engine as a traced PRNG key (per-job, never
        per-batch), so distinct seeds share executables and results stay
        bit-identical to solo runs (run_consensus_batch contract).
        Memoized: pop_batch evaluates it under the queue lock.
        """
        cached = getattr(self, "_batch_group", None)
        if cached is None:
            cfg = dataclasses.replace(self.config, seed=0)
            cached = f"{self.bucket().key()}|" \
                     f"{repr(dataclasses.astuple(cfg))}"
            object.__setattr__(self, "_batch_group", cached)
        return cached


class Job:
    """One submission's mutable lifecycle record.

    Field writes are guarded by the per-job lock; the service mutates
    only through :meth:`mark` so HTTP handler threads always read a
    consistent (state, result/error) pair via :meth:`describe`.
    """

    def __init__(self, spec: JobSpec, key: Optional[str] = None) -> None:
        self.spec = spec
        self.key = key if key is not None else spec.content_hash()
        self.job_id = f"j{next(_job_seq):06d}-{self.key[:10]}"
        self.state = STATE_QUEUED
        self.submitted_at = time.time()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.error: Optional[str] = None
        self.result: Optional[Dict[str, Any]] = None
        # Cross-request batching metadata (serve/server.py): set when
        # the worker coalesces this job into a batched device call.
        # batch_size stays 1 for solo execution.
        self.batch_id: Optional[str] = None
        self.batch_size: int = 1
        # Multi-device metadata (serve/pool.py): the worker/device tag
        # that ran (or is running) the job, and the devices this job may
        # no longer be routed to — a worker that dies mid-batch requeues
        # its jobs with itself excluded, so a job that KILLS workers
        # burns through the pool at most once per device and then fails
        # as itself instead of looping forever.
        self.device: Optional[int] = None
        self.requeues: int = 0
        self._excluded: frozenset = frozenset()
        self._lock = threading.Lock()

    def set_batch(self, batch_id: str, batch_size: int) -> None:
        with self._lock:
            self.batch_id = batch_id
            self.batch_size = int(batch_size)

    def set_device(self, device: int) -> None:
        with self._lock:
            self.device = int(device)

    def exclude_device(self, device: int) -> None:
        with self._lock:
            self._excluded = self._excluded | {int(device)}
            self.requeues += 1

    def excluded(self) -> frozenset:
        with self._lock:
            return self._excluded

    def mark(self, state: str, result: Optional[Dict[str, Any]] = None,
             error: Optional[str] = None) -> None:
        assert state in STATES, state
        with self._lock:
            self.state = state
            if state == STATE_RUNNING:
                self.started_at = time.time()
            if state in (STATE_DONE, STATE_FAILED):
                self.finished_at = time.time()
            if result is not None:
                self.result = result
            if error is not None:
                self.error = error

    def describe(self) -> Dict[str, Any]:
        """JSON-ready status summary (no result payload — that is
        ``/result``'s job; keeps ``/status`` polls cheap)."""
        with self._lock:
            return {
                "job_id": self.job_id,
                "state": self.state,
                "priority": self.spec.priority,
                "content_hash": self.key,
                "n_nodes": self.spec.n_nodes,
                "algorithm": self.spec.config.algorithm,
                "submitted_at": self.submitted_at,
                "started_at": self.started_at,
                "finished_at": self.finished_at,
                "error": self.error,
                "batch_id": self.batch_id,
                "batch_size": self.batch_size,
                "device": self.device,
                "requeues": self.requeues,
                "excluded_devices": sorted(self._excluded),
            }
