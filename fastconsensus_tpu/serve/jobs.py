"""fcserve job model: specs, states, priorities, content addressing.

A job is one consensus request — a graph plus a :class:`ConsensusConfig`
— flowing through the service's queue (serve/queue.py) into the worker
loop (serve/server.py).  Two identity notions coexist deliberately:

* the **job id** (``Job.job_id``) names one *submission* — every submit
  gets a fresh one, it is what ``/status`` and ``/result`` key on;
* the **content hash** (:func:`content_hash`) names the *work*: a
  deterministic SHA-256 over the canonicalized graph bytes and every
  result-relevant config field.  It is the key of the result cache
  (serve/cache.py), so resubmitting the same graph+config — regardless
  of edge order, duplicate edges, or which client sent it — is answered
  without touching the device.

Canonicalization mirrors ``graph.pack_edges`` (canonical ``src < dst``
orientation, self-loops dropped, duplicates merged keeping the first
weight) and then *sorts by edge key*, so the hash is invariant to input
edge order — the property that makes it content addressing rather than
payload addressing.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from fastconsensus_tpu.consensus import ConsensusConfig

# Smaller pops first (serve/queue.py is a min-heap on priority).
PRIORITY_INTERACTIVE = 0
PRIORITY_NORMAL = 1
PRIORITY_BATCH = 2
PRIORITY_NAMES = {
    "interactive": PRIORITY_INTERACTIVE,
    "normal": PRIORITY_NORMAL,
    "batch": PRIORITY_BATCH,
}

# SLO classes (fclat): per-class end-to-end latency targets in
# milliseconds.  A job's class defaults from its priority name and can
# be overridden per request (``slo`` / ``slo_target_ms`` in the submit
# body).  Attainment is *observed* — counted into ``serve.slo.*`` when
# the job finishes — and since fcshape (serve/shaping.py) the target
# also SHAPES scheduling: it sets the job's absolute deadline
# (``Job.deadline_mono``), which orders the admission heap (EDF within
# a priority), bounds the hold-for-coalesce window, and drives
# deadline-aware shedding at submit.  The counters remain the ground
# truth the shaper is judged against.
SLO_CLASSES = {
    "interactive": 1_000.0,
    "normal": 10_000.0,
    "batch": 120_000.0,
    # fcdelta: incremental re-consensus of a cached parent — a short
    # frontier-restricted warm-start run, so its latency floor is a
    # fraction of a full run's and shaping/EDF/shed must treat it to a
    # tighter promise than "normal".  Delta submissions default here;
    # it is a legal explicit class for any request.
    "delta": 2_000.0,
}

# The per-job phase timeline (fclat): each phase closes at the named
# monotonic stamp, in this order, starting from the admit stamp —
# phases are CONSECUTIVE DIFFERENCES of one monotonic clock, so the
# per-job phase sum equals the end-to-end latency by construction
# (the /metricsz consistency pin in tests/test_latency.py).  A missing
# stamp (e.g. a cache hit never packs) folds its interval into the next
# present phase.  The trailing "respond" phase closes at the finished
# stamp and is computed in Job.timing().  Every pop path stamps
# "hold_start" (Job.stamp_hold) alongside "dispatched", so for a job
# the shaper never held the hold phase reads exactly 0 and queue_wait
# keeps its pre-shaping meaning; only a job that never pops at all (a
# submit-time cache hit) lacks both, folding its whole life into
# "respond" as before.
PHASE_STAMPS: Tuple[Tuple[str, str], ...] = (
    ("queue_wait", "hold_start"),    # admission heap -> hold/pop point
    ("hold", "dispatched"),          # hold-for-coalesce window -> pop
    ("dispatch", "enqueued"),        # routing -> a worker's deque
    ("deque_wait", "dequeued"),      # parked in the deque -> worker
    ("pack", "packed"),              # canonicalize + pad to the bucket
    ("device", "device_done"),       # the consensus device call(s)
    ("fanout", "fanned_out"),        # slice/recompact/cache-fill
)
PHASE_NAMES: Tuple[str, ...] = tuple(
    [p for p, _ in PHASE_STAMPS] + ["respond"])

# Job lifecycle.  There is deliberately no "rejected" state: admission
# control (queue full, graph too large, draining) refuses the submission
# before a Job exists — backpressure is an error the client sees, never
# unbounded queue growth (serve/queue.py module notes).
STATE_QUEUED = "queued"
STATE_RUNNING = "running"
STATE_DONE = "done"
STATE_FAILED = "failed"
STATES = (STATE_QUEUED, STATE_RUNNING, STATE_DONE, STATE_FAILED)

_HASH_VERSION = b"fcserve-v1"
_job_seq = itertools.count(1)


def canonical_edges(edges: np.ndarray, n_nodes: int,
                    weights: Optional[np.ndarray] = None
                    ) -> Tuple[np.ndarray, np.ndarray,
                               Optional[np.ndarray]]:
    """Canonical ``(u, v, w)`` in ascending edge-key order.

    Same dedup semantics as ``graph.pack_edges`` (src < dst, self-loops
    dropped, first weight wins on duplicates), then sorted by
    ``u * n_nodes + v`` so the result — and therefore the content hash —
    does not depend on input edge order.
    """
    edges = np.asarray(edges, dtype=np.int64).reshape(-1, 2)
    if weights is not None:
        weights = np.asarray(weights, dtype=np.float32)
    u = np.minimum(edges[:, 0], edges[:, 1])
    v = np.maximum(edges[:, 0], edges[:, 1])
    keep = u != v
    u, v = u[keep], v[keep]
    if weights is not None:
        weights = weights[keep]
    key = u * np.int64(n_nodes) + v
    _, first = np.unique(key, return_index=True)
    first.sort()
    u, v, key = u[first], v[first], key[first]
    if weights is not None:
        weights = weights[first]
    order = np.argsort(key, kind="stable")
    return (u[order], v[order],
            None if weights is None else weights[order])


def content_hash(edges: np.ndarray, n_nodes: int,
                 config: ConsensusConfig,
                 weights: Optional[np.ndarray] = None) -> str:
    """Deterministic SHA-256 of (canonical graph bytes, config)."""
    return hash_canonical(canonical_edges(edges, n_nodes, weights),
                          n_nodes, config)


def hash_canonical(canonical: Tuple[np.ndarray, np.ndarray,
                                    Optional[np.ndarray]],
                   n_nodes: int, config: ConsensusConfig) -> str:
    """:func:`content_hash` over an already-canonicalized ``(u, v, w)``
    (JobSpec memoizes the canonicalization — at the serving limit of
    millions of edges the sort/dedupe pass is worth doing once, not
    once for the hash and again for the bucket pack)."""
    u, v, w = canonical
    h = hashlib.sha256()
    h.update(_HASH_VERSION)
    h.update(int(n_nodes).to_bytes(8, "little"))
    h.update(np.ascontiguousarray(u, dtype="<i8").tobytes())
    h.update(np.ascontiguousarray(v, dtype="<i8").tobytes())
    if w is not None and not np.all(w == 1.0):
        h.update(np.ascontiguousarray(w, dtype="<f4").tobytes())
    # every ConsensusConfig field is result-relevant (the checkpoint
    # fingerprints in consensus.py guard the same set); astuple keeps
    # this in lockstep with future config fields automatically
    h.update(repr(dataclasses.astuple(config)).encode())
    return h.hexdigest()


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One consensus request: compact 0-based edges + run config."""

    edges: np.ndarray            # int64[E, 2], compact 0-based ids
    n_nodes: int
    config: ConsensusConfig
    weights: Optional[np.ndarray] = None
    priority: int = PRIORITY_NORMAL
    # SLO class (fclat): None derives the class from the priority name.
    # Deliberately OUTSIDE the content hash (hash_canonical hashes the
    # config only): the SLO changes what we *promise* about a result,
    # never the result — distinct SLOs must share one cache entry.
    slo: Optional[str] = None
    slo_target_ms: Optional[float] = None
    # fctrace id (router-minted, X-FCTPU-Trace).  Like the SLO it is
    # OUTSIDE the content hash: a trace identifies one *submission*,
    # never the result — two traced requests for the same graph must
    # share one cache entry, and a cache hit still carries the hitting
    # request's own trace through its flight events.
    trace: Optional[str] = None
    # fcdelta provenance (serve/delta.py describe_payload dict: parent
    # hash, mode, reason, delta_frac, counts) — per-SUBMISSION metadata
    # outside the content hash, stamped on the 202/`/status`/`/result`.
    delta: Optional[Dict[str, Any]] = None
    # fcdelta warm-start plumbing (incremental mode only, real-node
    # sized; the worker pads both to the bucket): the parent's
    # partitions as init labels and the changed-edge neighborhood as
    # the move mask.  Outside the hash like every per-submission field.
    warm_labels: Optional[np.ndarray] = None   # int32 [n_p, n_nodes]
    warm_active: Optional[np.ndarray] = None   # bool [n_nodes]

    def slo_class(self) -> str:
        """The job's SLO class name (``SLO_CLASSES``)."""
        if self.slo is not None:
            return self.slo
        for name, prio in PRIORITY_NAMES.items():
            if prio == self.priority:
                return name
        return "normal"

    def slo_target(self) -> float:
        """End-to-end target in milliseconds (explicit override, else
        the class default)."""
        if self.slo_target_ms is not None:
            return float(self.slo_target_ms)
        return SLO_CLASSES.get(self.slo_class(),
                               SLO_CLASSES["normal"])

    def n_edges_raw(self) -> int:
        """Raw (pre-dedupe) edge count — the cheap admission bound."""
        return int(np.asarray(self.edges).reshape(-1, 2).shape[0])

    def canonical(self) -> Tuple[np.ndarray, np.ndarray,
                                 Optional[np.ndarray]]:
        """Memoized :func:`canonical_edges` of this spec — hashing (at
        submit) and bucket packing (in the worker) share ONE O(E log E)
        canonicalization pass."""
        cached = getattr(self, "_canonical", None)
        if cached is None:
            cached = canonical_edges(self.edges, self.n_nodes,
                                     self.weights)
            object.__setattr__(self, "_canonical", cached)
        return cached

    def content_hash(self) -> str:
        return hash_canonical(self.canonical(), self.n_nodes,
                              self.config)

    def bucket(self):
        """Memoized shape bucket (serve/bucketer.py) — the routing key
        of the multi-device scheduler (serve/scheduler.py): the pool
        dispatcher classifies every popped job by bucket, and a fresh
        O(E log E) canonicalization under the dispatch path would stall
        every worker behind one big graph.  ``canonical()`` is already
        memoized; this adds the grid lookup on top."""
        cached = getattr(self, "_bucket", None)
        if cached is None:
            from fastconsensus_tpu.serve import bucketer

            u, _, _ = self.canonical()
            cached = bucketer.bucket_for(self.n_nodes,
                                         max(int(u.shape[0]), 1))
            object.__setattr__(self, "_bucket", cached)
        return cached

    def batch_group(self) -> str:
        """Coalescing key for cross-request batching (serve/queue.py
        ``pop_batch``): two jobs may share one batched device call iff
        they land in the same shape bucket AND run the same config in
        every field but the seed.  The seed is excluded deliberately —
        it reaches the engine as a traced PRNG key (per-job, never
        per-batch), so distinct seeds share executables and results stay
        bit-identical to solo runs (run_consensus_batch contract).
        Memoized: pop_batch evaluates it under the queue lock.
        """
        cached = getattr(self, "_batch_group", None)
        if cached is None:
            cfg = dataclasses.replace(self.config, seed=0)
            cached = f"{self.bucket().key()}|" \
                     f"{repr(dataclasses.astuple(cfg))}"
            if self.warm_labels is not None:
                # fcdelta incremental jobs run SOLO: the batched engine
                # path carries no per-member init-labels/active-mask,
                # and coalescing a warm-start job into a cold batch
                # would silently drop its warm start.  A unique group
                # key guarantees pop_batch never rides it along.
                cached += f"|delta-solo:{id(self)}"
            object.__setattr__(self, "_batch_group", cached)
        return cached


class Job:
    """One submission's mutable lifecycle record.

    Field writes are guarded by the per-job lock; the service mutates
    only through :meth:`mark` so HTTP handler threads always read a
    consistent (state, result/error) pair via :meth:`describe`.
    """

    def __init__(self, spec: JobSpec, key: Optional[str] = None) -> None:
        self.spec = spec
        self.key = key if key is not None else spec.content_hash()
        self.job_id = f"j{next(_job_seq):06d}-{self.key[:10]}"
        self.state = STATE_QUEUED
        # Wall stamps are DISPLAY ONLY (operators correlate them with
        # logs); every duration derives from the monotonic stamps below
        # — wall-clock differences skew (or go negative) under NTP
        # steps, which is exactly when a latency dashboard matters most.
        self.submitted_at = time.time()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        # fclat phase timeline: monotonic checkpoints, written through
        # stamp() as the job crosses each serving stage (PHASE_STAMPS).
        self._mono: Dict[str, float] = {"admit": time.monotonic()}
        # fcshape EDF deadline: the absolute monotonic instant this
        # job's SLO expires.  The admission heap orders on it within a
        # priority (serve/queue.py) and the hold-for-coalesce window is
        # bounded by the tightest one queued (serve/shaping.py).
        self.deadline_mono: float = \
            self._mono["admit"] + spec.slo_target() / 1000.0
        self.error: Optional[str] = None
        self.result: Optional[Dict[str, Any]] = None
        # Cross-request batching metadata (serve/server.py): set when
        # the worker coalesces this job into a batched device call.
        # batch_size stays 1 for solo execution.
        self.batch_id: Optional[str] = None
        self.batch_size: int = 1
        # Multi-device metadata (serve/pool.py): the worker/device tag
        # that ran (or is running) the job, and the devices this job may
        # no longer be routed to — a worker that dies mid-batch requeues
        # its jobs with itself excluded, so a job that KILLS workers
        # burns through the pool at most once per device and then fails
        # as itself instead of looping forever.
        self.device: Optional[int] = None
        self.requeues: int = 0
        self._excluded: frozenset = frozenset()
        self._lock = threading.Lock()

    def set_batch(self, batch_id: str, batch_size: int) -> None:
        with self._lock:
            self.batch_id = batch_id
            self.batch_size = int(batch_size)

    def set_device(self, device: int) -> None:
        with self._lock:
            self.device = int(device)

    def exclude_device(self, device: int) -> None:
        with self._lock:
            self._excluded = self._excluded | {int(device)}
            self.requeues += 1

    def excluded(self) -> frozenset:
        with self._lock:
            return self._excluded

    def stamp(self, name: str, at: Optional[float] = None) -> None:
        """Record one monotonic phase checkpoint (PHASE_STAMPS names).
        Re-stamping (a requeued job re-crosses the pipeline) keeps the
        LATEST time — the timeline then attributes the whole retry to
        the phases it actually re-ran.  ``at`` lets the queue stamp a
        whole coalesced pop with ONE instant (and a non-holding pop
        stamp ``hold_start``/``dispatched`` identically, so the hold
        phase reads exactly 0, not clock-read jitter)."""
        with self._lock:
            self._mono[name] = time.monotonic() if at is None \
                else float(at)

    def stamp_hold(self, t_begin: float) -> None:
        """Record where this job's hold-for-coalesce window began
        (closes the ``queue_wait`` phase; ``dispatched`` then closes
        ``hold``).  A hold episode starts once per pop but covers every
        group member, so ``t_begin`` is clamped into
        ``[admit, now]`` — a ride-along admitted mid-hold attributes
        only ITS share of the window, and a non-holding pop passes the
        pop instant so hold reads exactly 0."""
        with self._lock:
            now = time.monotonic()
            self._mono["hold_start"] = \
                min(max(float(t_begin), self._mono["admit"]), now)

    def mark(self, state: str, result: Optional[Dict[str, Any]] = None,
             error: Optional[str] = None) -> None:
        assert state in STATES, state
        with self._lock:
            self.state = state
            if state == STATE_RUNNING:
                self.started_at = time.time()
                self._mono["started"] = time.monotonic()
            if state in (STATE_DONE, STATE_FAILED):
                self.finished_at = time.time()
                self._mono["finished"] = time.monotonic()
            if result is not None:
                self.result = result
            if error is not None:
                self.error = error

    def phase_seconds(self) -> Optional[Tuple[Dict[str, float], float]]:
        """``(phases, e2e)`` in exact (unrounded) monotonic seconds for
        a finished job, or None before it finishes.  Phases are the
        consecutive differences of the recorded stamps walked in
        PHASE_STAMPS order, closed by ``respond`` (last stamp ->
        finished), so ``sum(phases.values()) == e2e`` up to float
        addition — the attribution always accounts for the whole
        lifetime, never double-counts, never leaks an interval.
        """
        with self._lock:
            mono = dict(self._mono)
        end = mono.get("finished")
        if end is None:
            return None
        admit = mono["admit"]
        phases: Dict[str, float] = {}
        prev = admit
        for phase, stamp_name in PHASE_STAMPS:
            t = mono.get(stamp_name)
            if t is None:
                continue
            phases[phase] = max(t - prev, 0.0)
            prev = min(max(t, prev), end)
        phases["respond"] = max(end - prev, 0.0)
        return phases, max(end - admit, 0.0)

    def phases_so_far(self) -> Dict[str, float]:
        """Phase attribution in seconds that works MID-FLIGHT — the
        fcflight in-flight jobs table (obs/postmortem.py bundles).

        Same fold semantics as :meth:`phase_seconds` over the stamps
        recorded so far, plus one OPEN interval from the last recorded
        stamp to now, named for the phase the job is currently *in* (the
        phase the next missing stamp would close) — so a job wedged in
        the device call shows ``device: 312.4``, a heap-parked job shows
        a growing ``queue_wait``, and a finished job matches
        :meth:`phase_seconds` exactly.
        """
        with self._lock:
            mono = dict(self._mono)
        end = mono.get("finished", time.monotonic())
        admit = mono["admit"]
        phases: Dict[str, float] = {}
        prev = admit
        last_i = -1
        for i, (phase, stamp_name) in enumerate(PHASE_STAMPS):
            t = mono.get(stamp_name)
            if t is None:
                continue
            phases[phase] = max(t - prev, 0.0)
            prev = min(max(t, prev), end)
            last_i = i
        if "finished" in mono or last_i == len(PHASE_STAMPS) - 1:
            open_name = "respond"
        else:
            open_name = PHASE_STAMPS[last_i + 1][0]
        phases[open_name] = phases.get(open_name, 0.0) \
            + max(end - prev, 0.0)
        return phases

    def timing(self) -> Optional[Dict[str, Any]]:
        """JSON-ready server-side timing block for ``/status`` and
        ``/result`` (milliseconds, monotonic-derived): the per-phase
        breakdown, the end-to-end latency, and the job's SLO verdict."""
        ph = self.phase_seconds()
        if ph is None:
            return None
        phases, e2e = ph
        e2e_ms = e2e * 1000.0
        target = self.spec.slo_target()
        return {
            "e2e_ms": round(e2e_ms, 3),
            "phases_ms": {k: round(v * 1000.0, 3)
                          for k, v in phases.items()},
            "phase_sum_ms": round(sum(phases.values()) * 1000.0, 3),
            "slo": self.spec.slo_class(),
            "slo_target_ms": target,
            "slo_met": bool(e2e_ms <= target),
        }

    def describe(self) -> Dict[str, Any]:
        """JSON-ready status summary (no result payload — that is
        ``/result``'s job; keeps ``/status`` polls cheap).  Wall stamps
        are for log correlation only; the ``timing`` block (present once
        the job finishes) carries the monotonic-derived durations."""
        timing = self.timing()   # takes the lock itself; compute first
        with self._lock:
            # fcqual: the quality block is content-derived and rides the
            # result payload (see server._finish_result) — surfacing it
            # here keeps /status self-contained once the job is done,
            # and it is small (scalars + per-round lists bounded by
            # max_rounds), unlike the partitions we deliberately omit.
            quality = (self.result or {}).get("quality") \
                if self.state == STATE_DONE else None
            return {
                "job_id": self.job_id,
                "state": self.state,
                "priority": self.spec.priority,
                "slo": self.spec.slo_class(),
                "slo_target_ms": self.spec.slo_target(),
                "content_hash": self.key,
                "trace": self.spec.trace,
                "n_nodes": self.spec.n_nodes,
                "algorithm": self.spec.config.algorithm,
                "submitted_at": self.submitted_at,
                "started_at": self.started_at,
                "finished_at": self.finished_at,
                "error": self.error,
                "batch_id": self.batch_id,
                "batch_size": self.batch_size,
                "device": self.device,
                "requeues": self.requeues,
                "excluded_devices": sorted(self._excluded),
                "timing": timing,
                "quality": quality,
                # fcdelta provenance: present only for delta
                # submissions (None otherwise keeps the wire shape
                # stable for every existing reader)
                "delta": self.spec.delta,
            }
