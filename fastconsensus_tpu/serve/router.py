"""fcfleet router: a jax-free front-end tier over N fcserve replicas.

Everything below ``make_router_server`` scales *inside* one process:
the fcpool worker pool drives one host's chips, StickyScheduler keeps
each bucket's executables on the device that compiled them, and the
admission queue bounds one replica's intake.  This module is the same
argument one level up — N whole `ConsensusService` replicas behind one
stdlib-HTTP router, with the router playing the scheduler's role
across *hosts*:

* **consistent-hash ring** (:class:`HashRing`) — the route key is the
  cross-host analogue of ``JobSpec.batch_group`` (shape bucket +
  config-minus-seed, derived jax-free from the raw submit payload), so
  same-group traffic lands on one replica and keeps that replica's
  compile cache, coalesce groups and shaping estimators hot.  The ring
  hashes ``replica#vnode`` points with sha1 (NEVER Python ``hash()`` —
  placement must be deterministic across processes and restarts), so
  adding or removing a replica re-homes only ~1/N of the groups
  instead of reshuffling everything;
* **health + cordon** (:class:`FleetRouter` poll loop) — each replica's
  ``/healthz`` is polled; a poll failure, a watchdog trip, or a
  draining replica cordons it.  Cordoned replicas stay ON the ring but
  are excluded at lookup (the PR 6 worker-cordon semantics one level
  up): their groups re-home to ring successors and come back when the
  replica does.  In-flight submissions homed on a cordoned replica are
  REPLAYED to a successor with the dead replica excluded — the fleet
  mirror of ``Job.exclude_device`` requeueing;
* **fleet-aware backpressure** — the poll loop also reads each
  replica's typed ``/metricsz`` shaping block; submit routes around
  replicas whose queues are saturated, a 429 from the home replica
  tries ring successors, and only when EVERY eligible replica sheds
  does the router answer 429 itself — carrying the DEEPEST
  ``retry_after_s`` observed, because the honest fleet-wide answer is
  "when the slowest queue you might land on has drained";
* **cross-replica cache reuse** — the router remembers which replicas
  hold which ``content_hash`` (learned from submit/result traffic);
  a submit that misses on its home replica but is known warm on a
  sibling triggers a fetch (``GET /cachez/<hash>``) + seed
  (``POST /cachez``) so the queued job completes from cache via the
  worker's pre-run re-probe, with zero device work;
* **prewarm shipping** — ``preview_owner`` lets a joining replica
  learn which replica it will inherit groups from, so the fleet
  manager (serve/fleet.py) can ship the donor's warm-spec and cache
  snapshot before the new replica takes traffic.

The module is deliberately jax-free (the thin-client posture of
serve/client.py): the grid math it needs for route keys comes from the
stdlib-only fcheck-footprint mirror (analysis/footprint.py), not from
serve/bucketer.py, whose sizing import pulls in the engine.  A router
host needs no accelerator and must never pay the engine's import cost.
"""

from __future__ import annotations

import bisect
import hashlib
import itertools
import json
import logging
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

from fastconsensus_tpu.analysis.footprint import (MIN_EDGE_CLASS,
                                                  MIN_NODE_CLASS, grid_up)
from fastconsensus_tpu.obs import counters as obs_counters
from fastconsensus_tpu.obs import flight as obs_flight
from fastconsensus_tpu.obs import latency as obs_latency
from fastconsensus_tpu.obs.fleettrace import TRACE_HEADER, aggregate_fleet

_logger = logging.getLogger("fastconsensus_tpu")

# Virtual nodes per replica on the ring.  Enough that each replica's
# arc is statistically even (placement spread ~1/sqrt(vnodes)) without
# making ring rebuilds or successor walks measurable — 128 keeps the
# measured re-home fraction on an add/remove within the advertised
# ceil(|groups|/N) across the tested group-set sizes (tests/
# test_fleet.py pins it; 64 overshoots the bound by a few percent).
DEFAULT_VNODES = 128

# Config fields that shape the route key — the payload-level mirror of
# JobSpec.batch_group's "same config in every field but the seed":
# anything that changes executable identity or result content keeps
# traffic apart; the seed deliberately does not (distinct seeds share
# executables and coalesce into one batched call on the replica).
_ROUTE_CONFIG_FIELDS = ("algorithm", "n_p", "tau", "delta", "max_rounds",
                       "gamma", "auto_grow", "warm_start", "align_frac",
                       "closure_sampler", "closure_tau")


class NoEligibleReplica(RuntimeError):
    """Every replica on the ring is cordoned or excluded."""


def route_key(payload: Dict[str, Any]) -> str:
    """The consistent-hash routing key for one ``/submit`` payload.

    Jax-free mirror of ``JobSpec.batch_group``: the ``{2^k, 3*2^k}``
    shape-bucket classes (analysis/footprint.grid_up — the same grid
    serve/bucketer.py pads onto) plus the sorted config-minus-seed
    fields.  The edge count is the RAW payload count, not the deduped
    canonical count the replica computes — affinity is a placement
    heuristic, and a near-bucket-boundary graph landing one class off
    costs one extra warm bucket on one replica, not correctness.
    """
    parent = payload.get("parent")
    if parent is not None:
        # fcdelta (serve/delta.py): a delta submission carries no graph
        # of its own — only a parent content hash plus edge changes —
        # so shape-affinity has nothing to hash.  Route on the parent
        # hash instead: every delta evolving one graph lands on one
        # replica, which (after the first parent prefetch) holds the
        # parent entry and answers the whole lineage warm.
        return f"delta|{parent}"
    if "edgelist" in payload:
        n_edges = sum(1 for ln in str(payload["edgelist"]).splitlines()
                      if ln.strip() and not ln.lstrip().startswith("#"))
    else:
        n_edges = len(payload.get("edges") or ())
    n_nodes = int(payload.get("n_nodes") or 0)
    n_class = grid_up(max(n_nodes, 1), MIN_NODE_CLASS)
    e_class = grid_up(max(n_edges, 1), MIN_EDGE_CLASS)
    cfg = "|".join(f"{f}={payload[f]!r}" for f in _ROUTE_CONFIG_FIELDS
                   if f in payload)
    return f"n{n_class}_e{e_class}|{cfg}"


class HashRing:
    """Consistent-hash ring: route key -> replica name.

    Placement is a pure function of the member set — sha1 over
    ``name#vnode`` for the points, sha1 over the key for lookups — so
    two router processes with the same members agree on every
    placement, and a member add/remove moves only the arcs adjacent to
    its vnodes (~1/N of the keyspace).  Exclusion (cordoned replicas)
    happens at LOOKUP, not by ring surgery: the excluded member keeps
    its arcs and reclaims them the moment it is eligible again,
    instead of triggering a second re-home on recovery.
    """

    def __init__(self, replicas: Tuple[str, ...] = (),
                 vnodes: int = DEFAULT_VNODES) -> None:
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = int(vnodes)
        self._points: List[Tuple[int, str]] = []   # sorted (hash, name)
        self._names: List[str] = []
        for name in replicas:
            self.add(name)

    @staticmethod
    def _hash(data: str) -> int:
        return int.from_bytes(
            hashlib.sha1(data.encode("utf-8")).digest()[:8], "big")

    def add(self, name: str) -> None:
        if name in self._names:
            raise ValueError(f"replica {name!r} already on the ring")
        self._names.append(name)
        for v in range(self.vnodes):
            bisect.insort(self._points, (self._hash(f"{name}#{v}"), name))

    def remove(self, name: str) -> None:
        if name not in self._names:
            raise ValueError(f"replica {name!r} not on the ring")
        self._names.remove(name)
        self._points = [(h, n) for h, n in self._points if n != name]

    def members(self) -> List[str]:
        return list(self._names)

    def __len__(self) -> int:
        return len(self._names)

    def route(self, key: str,
              exclude: FrozenSet[str] = frozenset()) -> str:
        """The replica owning ``key``: the first ring point clockwise
        of the key's hash whose member is not excluded.  Walking
        successors (instead of re-hashing) is what makes exclusion a
        ~1/N re-home: every key NOT on an excluded arc keeps its home.
        """
        if not self._points:
            raise NoEligibleReplica("the ring has no replicas")
        h = self._hash(key)
        idx = bisect.bisect_right(self._points, (h, "￿"))
        seen: set = set()
        for i in range(len(self._points)):
            _, name = self._points[(idx + i) % len(self._points)]
            if name in seen:
                continue
            seen.add(name)
            if name not in exclude:
                return name
            if len(seen) == len(self._names):
                break
        raise NoEligibleReplica(
            f"all {len(self._names)} replica(s) excluded for {key!r}")

    def preview_owner(self, key: str, joining: str,
                      exclude: FrozenSet[str] = frozenset()) -> Optional[str]:
        """Which CURRENT member would lose ``key`` to ``joining`` —
        the donor whose warm-spec/cache snapshot the joiner should
        inherit (serve/fleet.py prewarm shipping).  None when the key
        would not re-home."""
        trial = HashRing((*self._names, joining), vnodes=self.vnodes)
        if trial.route(key, exclude) != joining:
            return None
        return self.route(key, exclude)


class _ReplicaView:
    """The router's view of one replica: URL, cordon state, and the
    last polled health/shaping snapshot.  Mutated only by the poll
    loop and the submit path's failure handling, under the router
    lock."""

    def __init__(self, name: str, base_url: str) -> None:
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.cordoned = False
        self.cordon_reason: Optional[str] = None
        self.poll_failures = 0          # consecutive
        self.last_poll_ts: Optional[float] = None
        self.queue_depth = 0
        self.queue_max_depth = 0
        self.draining = False
        self.watchdog_trips_seen: Optional[int] = None
        self.retry_after_hint_s: Optional[float] = None
        self.last_bundle: Optional[str] = None
        # route keys this replica owned at cordon time.  _assignments is
        # last-home bookkeeping the live traffic overwrites as soon as
        # the re-homed groups land elsewhere, so successor election
        # (fleet.py on_death -> _successor_of) needs this frozen copy —
        # electing from _assignments alone races the very re-homing the
        # election is about.
        self.rehomed_keys: Tuple[str, ...] = ()

    def saturated(self) -> bool:
        return (self.queue_max_depth > 0
                and self.queue_depth >= self.queue_max_depth)

    def describe(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "url": self.base_url,
            "state": "cordoned" if self.cordoned else "up",
            "cordon_reason": self.cordon_reason,
            "poll_failures": self.poll_failures,
            "queue_depth": self.queue_depth,
            "queue_max_depth": self.queue_max_depth,
            "draining": self.draining,
            "watchdog_trips": self.watchdog_trips_seen,
            "retry_after_hint_s": self.retry_after_hint_s,
            "last_bundle": self.last_bundle,
            "rehomed_keys": list(self.rehomed_keys),
        }


class _RouterJob:
    """One forwarded submission's bookkeeping: enough to replay it."""

    def __init__(self, fleet_id: str, body: bytes, key: str,
                 trace: Optional[str] = None) -> None:
        self.fleet_id = fleet_id
        self.body = body                 # the raw /submit JSON bytes
        self.route_key = key
        self.trace = trace               # fctrace id (X-FCTPU-Trace)
        self.replica: Optional[str] = None
        self.replica_job_id: Optional[str] = None
        self.content_hash: Optional[str] = None
        # fcdelta: the parent content hash a delta submission names —
        # set at admit so every forward (first try AND replay) can
        # prefetch the parent entry into the receiving replica
        self.parent_hash: Optional[str] = None
        self.excluded: set = set()       # replicas that failed this job
        self.replays = 0
        self.done = False


def _http_json(url: str, payload_bytes: Optional[bytes] = None,
               timeout: float = 10.0,
               extra_headers: Optional[Dict[str, str]] = None
               ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
    """One JSON request; returns (status, body, headers).  HTTP error
    statuses return normally (the router maps them itself); transport
    errors raise OSError.  ``extra_headers`` is how trace context rides
    the forwarded hop (fctrace: the X-FCTPU-Trace header)."""
    headers = {"Accept": "application/json"}
    if payload_bytes is not None:
        headers["Content-Type"] = "application/json"
    if extra_headers:
        headers.update(extra_headers)
    req = urllib.request.Request(url, data=payload_bytes, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            body = json.loads(resp.read() or b"{}")
            return resp.status, body, dict(resp.headers)
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except ValueError:
            body = {"error": str(e)}
        return e.code, body, dict(e.headers)


class FleetRouter:
    """Route ``/submit`` traffic across N fcserve replicas.

    Thread model: HTTP handler threads call :meth:`submit` /
    :meth:`status` / :meth:`result`; one daemon poll thread refreshes
    replica health.  All shared state (replica views, ring membership,
    the job table, the content-hash index) is guarded by ``_lock``;
    outbound HTTP happens OUTSIDE the lock — a slow replica must never
    stall the router's other handler threads on lock convoy.
    """

    def __init__(self, replicas: Dict[str, str],
                 poll_s: float = 0.5,
                 vnodes: int = DEFAULT_VNODES,
                 timeout: float = 30.0,
                 poll_timeout: float = 2.0,
                 poll_failures_to_cordon: int = 2,
                 max_tracked_jobs: int = 4096) -> None:
        self._lock = threading.Lock()
        self._views: Dict[str, _ReplicaView] = {
            name: _ReplicaView(name, url) for name, url in replicas.items()}
        self.ring = HashRing(tuple(self._views), vnodes=vnodes)
        self.poll_s = float(poll_s)
        self.timeout = float(timeout)
        self.poll_timeout = float(poll_timeout)
        self.poll_failures_to_cordon = int(poll_failures_to_cordon)
        self.max_tracked_jobs = int(max_tracked_jobs)
        self._jobs: Dict[str, _RouterJob] = {}
        self._job_order: List[str] = []      # FIFO retention
        self._hash_holders: Dict[str, set] = {}   # content_hash -> names
        self._assignments: Dict[str, str] = {}    # route key -> last home
        self._seq = itertools.count(1)
        self._trace_seq = itertools.count(1)
        self._reg = obs_counters.get_registry()
        # fctrace: router-phase latency (router.phase.*) and the
        # router's own flight events record into the process-global
        # registries — same posture as the replica, so /metricsz and
        # post-mortem bundles of a router host need no special casing.
        self._lat = obs_latency.get_latency_registry()
        self._flight = obs_flight.get_flight_recorder()
        self._stop = threading.Event()
        self._poll_thread: Optional[threading.Thread] = None

    def _mint_trace(self) -> str:
        """A fleet-unique trace id: pid + per-router sequence — two
        routers (or a router restart) can never collide, and the id
        stays grep-friendly in logs and flight events."""
        return f"tr-{os.getpid():x}-{next(self._trace_seq):06d}"

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "FleetRouter":
        if self._poll_thread is None:
            self.poll_once()             # first routing decision is informed
            self._poll_thread = threading.Thread(
                target=self._poll_loop, name="fcfleet-poll", daemon=True)
            self._poll_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5.0)
            self._poll_thread = None

    # -- membership ---------------------------------------------------

    def add_replica(self, name: str, base_url: str) -> None:
        """Join a replica: it takes ~1/N of the groups from its ring
        predecessors (serve/fleet.py ships the donor's warm-spec +
        cache snapshot BEFORE calling this, so the re-homed groups
        land warm)."""
        with self._lock:
            if name in self._views:
                raise ValueError(f"replica {name!r} already joined")
            self._views[name] = _ReplicaView(name, base_url)
            self.ring.add(name)
            moved = [k for k, owner in self._assignments.items()
                     if self.ring.route(k, self._excluded_locked()) != owner]
        self._reg.inc("serve.fleet.joins")
        if moved:
            self._reg.inc("serve.fleet.rehomed_buckets", len(moved))

    def preview_donor(self, joining: str,
                      keys: Optional[List[str]] = None) -> Optional[str]:
        """The replica a joiner would inherit most groups from — the
        prewarm-shipping donor.  ``keys`` defaults to every route key
        the router has seen."""
        with self._lock:
            keys = list(keys if keys is not None else self._assignments)
            exclude = self._excluded_locked()
            donors: Dict[str, int] = {}
            for k in keys:
                d = self.ring.preview_owner(k, joining, exclude)
                if d is not None:
                    donors[d] = donors.get(d, 0) + 1
        if not donors:
            return None
        return max(sorted(donors), key=lambda n: donors[n])

    def cordon(self, name: str, reason: str) -> None:
        """Take a replica out of routing (ring membership kept): its
        groups re-home to ring successors and its in-flight
        submissions replay with it excluded."""
        with self._lock:
            view = self._views.get(name)
            if view is None or view.cordoned:
                return
            view.cordoned = True
            view.cordon_reason = reason
            moved = [k for k, owner in self._assignments.items()
                     if owner == name]
            view.rehomed_keys = tuple(moved)
            replay = [j for j in self._jobs.values()
                      if j.replica == name and not j.done]
        self._reg.inc("serve.fleet.cordons")
        if moved:
            self._reg.inc("serve.fleet.rehomed_buckets", len(moved))
        _logger.warning("fcfleet: cordoned replica %s (%s); re-homing "
                        "%d group(s), replaying %d in-flight job(s)",
                        name, reason, len(moved), len(replay))
        for job in replay:
            self._replay(job, exclude_also=name)

    def uncordon(self, name: str) -> None:
        with self._lock:
            view = self._views.get(name)
            if view is None or not view.cordoned:
                return
            view.cordoned = False
            view.cordon_reason = None
            view.poll_failures = 0
        self._reg.inc("serve.fleet.uncordons")

    def _excluded_locked(self) -> FrozenSet[str]:
        return frozenset(n for n, v in self._views.items() if v.cordoned)

    # -- health poll --------------------------------------------------

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            # fcheck: ok=swallowed-error (not silent: the traceback
            # goes to the log below, and per-replica failures are
            # counted inside poll_once — this backstop only keeps one
            # bad sweep from killing the health authority)
            except Exception:  # noqa: BLE001 — the poll loop is the
                # fleet's health authority; one bad snapshot must not
                # kill it (the failure is counted per replica below)
                _logger.exception("fcfleet: poll loop iteration failed")

    def poll_once(self) -> None:
        """One health sweep: refresh every replica's view; cordon on
        repeated poll failure, on a watchdog trip, or on a draining
        replica; uncordon a poll-failure cordon that answers again."""
        with self._lock:
            targets = [(v.name, v.base_url) for v in self._views.values()]
        for name, base_url in targets:
            self._reg.inc("serve.fleet.polls")
            try:
                status, body, _ = _http_json(base_url + "/healthz",
                                             timeout=self.poll_timeout)
                if status != 200:
                    raise OSError(f"/healthz answered HTTP {status}")
            # fcheck: ok=swallowed-error (not swallowed: the error is
            # handed to _note_poll_failure, which stamps
            # serve.fleet.poll_failures and drives the cordon decision)
            except (OSError, ValueError) as e:
                self._note_poll_failure(name, e)
                continue
            self._note_poll_ok(name, base_url, body)

    def _note_poll_failure(self, name: str, err: Exception) -> None:
        self._reg.inc("serve.fleet.poll_failures")
        with self._lock:
            view = self._views.get(name)
            if view is None:
                return
            view.poll_failures += 1
            should_cordon = (not view.cordoned and view.poll_failures
                             >= self.poll_failures_to_cordon)
        if should_cordon:
            self.cordon(name, f"poll failure x{self.poll_failures_to_cordon}"
                              f" ({type(err).__name__})")

    def _note_poll_ok(self, name: str, base_url: str,
                      body: Dict[str, Any]) -> None:
        shaping_hint = None
        try:
            # one extra GET per poll: the typed shaping block is where
            # retry_after_hint_s lives — the fleet-backpressure signal
            status, m, _ = _http_json(base_url + "/metricsz",
                                      timeout=self.poll_timeout)
            if status == 200:
                shaping_hint = (m.get("shaping") or {}).get(
                    "retry_after_hint_s")
        # fcheck: ok=swallowed-error (the hint is advisory — a replica
        # that answered /healthz but not /metricsz stays routable; the
        # next poll retries)
        except (OSError, ValueError):
            pass
        trips = int(body.get("watchdog_trips", 0) or 0)
        draining = bool(body.get("draining", False))
        cordon_reason = None
        uncordon = False
        with self._lock:
            view = self._views.get(name)
            if view is None:
                return
            view.poll_failures = 0
            view.last_poll_ts = time.monotonic()
            view.queue_depth = int(body.get("queue_depth", 0) or 0)
            view.queue_max_depth = int(body.get("queue_max_depth", 0) or 0)
            view.draining = draining
            view.retry_after_hint_s = shaping_hint
            view.last_bundle = body.get("last_bundle")
            if view.watchdog_trips_seen is None:
                # first successful poll sets the trip baseline: a
                # replica restarted after an incident starts clean
                view.watchdog_trips_seen = trips
            if draining and not view.cordoned:
                cordon_reason = "draining"
            elif trips > view.watchdog_trips_seen and not view.cordoned:
                view.watchdog_trips_seen = trips
                cordon_reason = f"watchdog trip ({trips} total)"
            elif (view.cordoned and not draining
                  and view.cordon_reason
                  and view.cordon_reason.startswith("poll failure")):
                # only poll-failure cordons self-heal on a good poll; a
                # trip cordon stays until an operator (or the fleet
                # manager) uncordons deliberately
                uncordon = True
        if cordon_reason is not None:
            self.cordon(name, cordon_reason)
        elif uncordon:
            self.uncordon(name)

    # -- routing ------------------------------------------------------

    def _candidates(self, route_key: str) -> List[_ReplicaView]:
        """Eligible replicas for one submit, best first: the ring home,
        then its successors — with saturated replicas (last polled
        queue at max depth) moved to the back rather than dropped,
        because a stale poll must degrade to "try later in the walk",
        never to "unroutable"."""
        with self._lock:
            exclude = self._excluded_locked()
            ordered: List[_ReplicaView] = []
            seen: set = set()
            walk_exclude = set(exclude)
            while True:
                try:
                    # fcheck: ok=key-reuse (route_key is a batch-group
                    # routing string, not a PRNG key; re-routing it with
                    # a growing exclusion set is the successor walk)
                    name = self.ring.route(route_key,
                                           frozenset(walk_exclude))
                # fcheck: ok=swallowed-error (an exhausted ring is this
                # walk's normal exit; the empty result re-raises
                # NoEligibleReplica right below, so nothing is lost)
                except NoEligibleReplica:
                    break
                if name in seen:
                    break
                seen.add(name)
                walk_exclude.add(name)
                ordered.append(self._views[name])
        if not ordered:
            raise NoEligibleReplica(
                "every replica is cordoned; nothing can take this job")
        fresh = [v for v in ordered if not v.saturated()]
        saturated = [v for v in ordered if v.saturated()]
        if saturated:
            self._reg.inc("serve.fleet.routed_around_saturation",
                          len(saturated))
        return fresh + saturated

    def submit(self, body: bytes, trace: Optional[str] = None
               ) -> Tuple[int, Dict[str, Any], Dict[str, str]]:
        """Forward one ``/submit`` body: home replica first, ring
        successors on 429/503/transport failure.  Returns the
        (status, payload, headers) the router should answer with —
        2xx payloads get the router's own ``job_id`` so /status and
        /result survive a later replay to a different replica.

        ``trace`` is the client's X-FCTPU-Trace header if it sent one;
        otherwise the router mints one here.  Either way the id rides
        the forwarded hop as the same header (the replica folds it into
        the JobSpec), is stamped on the router's own flight events, and
        is echoed back to the client in the answer."""
        self._reg.inc("serve.fleet.submits")
        t0 = time.monotonic()
        try:
            payload = json.loads(body or b"{}")
            key = route_key(payload)
        except (ValueError, TypeError) as e:
            return 400, {"error": f"bad request: {e}"}, {}
        if not trace and isinstance(payload, dict):
            trace = payload.get("trace")
        trace = str(trace) if trace else self._mint_trace()
        job = _RouterJob(f"f{next(self._seq):06d}", bytes(body), key,
                         trace=trace)
        if isinstance(payload, dict) and payload.get("parent"):
            job.parent_hash = str(payload["parent"])
        self._lat.hist("router.phase.admit").record(
            time.monotonic() - t0)
        status, out, headers = self._forward(job)
        if status in (200, 202):
            with self._lock:
                self._jobs[job.fleet_id] = job
                self._job_order.append(job.fleet_id)
                while len(self._job_order) > self.max_tracked_jobs:
                    dropped = self._job_order.pop(0)
                    self._jobs.pop(dropped, None)
            out = dict(out, job_id=job.fleet_id,
                       fleet_replica=job.replica, trace=trace)
            self._flight.record("route", job=job.fleet_id, trace=trace,
                                replica=job.replica,
                                cached=bool(out.get("cached")))
            self._maybe_fetch_on_miss(job, out)
        return status, out, headers

    def _forward(self, job: _RouterJob) -> Tuple[int, Dict[str, Any],
                                                 Dict[str, str]]:
        deepest_retry: Optional[float] = None
        shed_seen = False
        last_err: Optional[Tuple[int, Dict[str, Any], Dict[str, str]]] = None
        t0 = time.monotonic()
        try:
            candidates = self._candidates(job.route_key)
        except NoEligibleReplica as e:
            self._reg.inc("serve.fleet.unroutable")
            return 503, {"error": str(e), "fleet": True,
                         "draining": False}, {}
        self._lat.hist("router.phase.ring_lookup").record(
            time.monotonic() - t0)
        fwd_headers = {TRACE_HEADER: job.trace} if job.trace else None
        for view in candidates:
            if view.name in job.excluded:
                continue
            if job.parent_hash is not None:
                # fcdelta: make the parent local BEFORE the delta
                # arrives — a replica can only resolve a delta against
                # a parent entry it holds; running this per-candidate
                # (not once per submit) keeps replays and successor
                # hops resolvable too
                self._prefetch_parent(job.parent_hash, view.name)
            try:
                status, out, headers = _http_json(
                    view.base_url + "/submit", job.body,
                    timeout=self.timeout, extra_headers=fwd_headers)
            except (OSError, ValueError) as e:
                # transport failure IS a health signal, not just a
                # routing miss — count it toward the cordon threshold
                self._note_poll_failure(view.name, e)
                self._reg.inc("serve.fleet.forward_errors")
                continue
            if status in (200, 202):
                with self._lock:
                    job.replica = view.name
                    job.replica_job_id = str(out.get("job_id"))
                    job.content_hash = out.get("content_hash")
                    self._assignments[job.route_key] = view.name
                    if job.content_hash:
                        self._hash_holders.setdefault(
                            job.content_hash, set()).add(view.name)
                    if out.get("cached"):
                        job.done = True
                self._reg.inc("serve.fleet.forwards")
                return status, out, headers
            if status == 429:
                self._reg.inc("serve.fleet.backpressure_hops")
                r = out.get("retry_after_s")
                if r is not None:
                    deepest_retry = max(deepest_retry or 0.0, float(r))
                shed_seen = shed_seen or bool(out.get("shed"))
                last_err = (status, out, headers)
                continue
            if status == 503:
                # the replica is draining; the poll loop will cordon it
                # on its next sweep — this submit just walks on
                self._reg.inc("serve.fleet.draining_hops")
                last_err = (status, out, headers)
                continue
            # 4xx (bad request / too large) is the CLIENT's problem on
            # every replica equally — answer it verbatim, no walking
            return status, out, headers
        if deepest_retry is not None or (last_err and last_err[0] == 429):
            self._reg.inc("serve.fleet.shed")
            retry_s = deepest_retry if deepest_retry is not None else 1.0
            return (429,
                    {"error": "every eligible replica is shedding",
                     "backpressure": True, "fleet": True,
                     "shed": shed_seen,
                     "retry_after_s": round(retry_s, 3)},
                    {"Retry-After": str(max(1, int(retry_s + 0.999)))})
        if last_err is not None:
            return last_err
        self._reg.inc("serve.fleet.unroutable")
        return 503, {"error": "no replica accepted the job",
                     "fleet": True, "draining": False}, {}

    def _replay(self, job: _RouterJob,
                exclude_also: Optional[str] = None) -> bool:
        """Resubmit a job's stored body, excluding replicas that
        already failed it (the fleet mirror of Job.exclude_device).  A
        job that burns every replica fails as itself — the caller sees
        the terminal error, never a silent retry loop."""
        if exclude_also is not None:
            job.excluded.add(exclude_also)
        job.replays += 1
        self._reg.inc("serve.fleet.replays")
        t0 = time.monotonic()
        status, out, _ = self._forward(job)
        self._lat.hist("router.phase.replay").record(
            time.monotonic() - t0)
        self._flight.record("rehome_replay", job=job.fleet_id,
                            trace=job.trace, replica=job.replica,
                            replays=job.replays,
                            excluded=",".join(sorted(job.excluded)),
                            ok=status in (200, 202))
        if status in (200, 202):
            self._maybe_fetch_on_miss(job, out)
            return True
        _logger.warning("fcfleet: replay of %s failed everywhere "
                        "(HTTP %s)", job.fleet_id, status)
        self._reg.inc("serve.fleet.replay_failures")
        return False

    # -- cross-replica cache ------------------------------------------

    def note_holder(self, content_hash: str, name: str) -> None:
        """Register ``name`` as holding a cached result.  fcfleet death
        inheritance calls this (serve/fleet.py ``on_death`` loads a
        dead sibling's spill into the successor): without it the hash
        index still points at the corpse and fetch-on-miss can never
        source from the inheritor."""
        with self._lock:
            if name in self._views:
                self._hash_holders.setdefault(
                    content_hash, set()).add(name)

    def _maybe_fetch_on_miss(self, job: _RouterJob,
                             out: Dict[str, Any]) -> None:
        """A submit that MISSED on its home replica but whose content
        hash is known warm on a live sibling: fetch the sibling's
        cached result and seed it into the home replica, so the queued
        job completes via the worker's pre-run cache re-probe with no
        device work."""
        if out.get("cached") or not job.content_hash or job.replica is None:
            return
        with self._lock:
            holders = [n for n in self._hash_holders.get(
                           job.content_hash, ())
                       if n != job.replica and n in self._views
                       and not self._views[n].cordoned]
            home_url = self._views[job.replica].base_url
            holder_urls = [(n, self._views[n].base_url) for n in holders]
        for name, url in holder_urls:
            try:
                status, res, _ = _http_json(
                    url + f"/cachez/{job.content_hash}",
                    timeout=self.timeout)
            # fcheck: ok=swallowed-error (a holder that cannot answer
            # is a miss for that holder only; the walk tries the next
            # one and cache_fetch_misses / cache_no_holder carry the
            # aggregate outcome)
            except (OSError, ValueError):
                continue
            if status != 200:
                self._reg.inc("serve.fleet.cache_fetch_misses")
                continue
            self._reg.inc("serve.fleet.cache_fetch_hits")
            try:
                seed_status, _, _ = _http_json(
                    home_url + "/cachez",
                    json.dumps(res).encode("utf-8"),
                    timeout=self.timeout)
            except (OSError, ValueError):
                return
            if seed_status == 200:
                self._reg.inc("serve.fleet.cache_seeded")
                with self._lock:
                    self._hash_holders.setdefault(
                        job.content_hash, set()).add(job.replica)
            return
        if holder_urls:
            return
        self._reg.inc("serve.fleet.cache_no_holder")

    def _prefetch_parent(self, parent_hash: str, target: str) -> None:
        """The forward-looking twin of :meth:`_maybe_fetch_on_miss`,
        for fcdelta (serve/delta.py): a delta submission is about to
        be forwarded to ``target``, and it can only resolve there if
        the PARENT's cached result is local to that replica.  When the
        hash index says a live sibling holds the parent and ``target``
        does not, copy it over (``GET /cachez/<hash>`` on the holder,
        ``POST /cachez`` on the target — the wire shape carries the
        graph + config lineage blocks) before forwarding, so the
        replica answers incrementally instead of 404ing a parent the
        fleet actually has.  No holder anywhere means the 404 the
        replica will return is the honest fleet-wide answer."""
        with self._lock:
            holders = self._hash_holders.get(parent_hash, set())
            if target in holders or target not in self._views:
                return
            sources = [(n, self._views[n].base_url) for n in holders
                       if n != target and n in self._views
                       and not self._views[n].cordoned]
            target_url = self._views[target].base_url
        if not sources:
            return
        for _name, url in sources:
            try:
                status, res, _ = _http_json(
                    url + f"/cachez/{parent_hash}", timeout=self.timeout)
            # fcheck: ok=swallowed-error (an unreachable holder is a
            # miss for that holder only; the next source is tried, and
            # the replica's own parent-miss 404 stays the honest
            # terminal answer when every source fails)
            except (OSError, ValueError):
                continue
            if status != 200:
                self._reg.inc("serve.fleet.cache_fetch_misses")
                continue
            try:
                seed_status, _, _ = _http_json(
                    target_url + "/cachez",
                    json.dumps(res).encode("utf-8"),
                    timeout=self.timeout)
            # fcheck: ok=swallowed-error (a target that cannot accept
            # the seed will also fail the forward right after — THAT
            # path owns the error accounting)
            except (OSError, ValueError):
                return
            if seed_status == 200:
                self._reg.inc("serve.fleet.delta_parent_prefetch")
                with self._lock:
                    self._hash_holders.setdefault(
                        parent_hash, set()).add(target)
            return

    # -- status / result proxy ----------------------------------------

    def _proxy(self, kind: str, fleet_id: str
               ) -> Tuple[int, Dict[str, Any]]:
        with self._lock:
            job = self._jobs.get(fleet_id)
        if job is None:
            return 404, {"error": "unknown job id"}
        for _ in range(len(self.ring) + 1):
            with self._lock:
                replica = job.replica
                view = self._views.get(replica) if replica else None
            if view is None:
                return 500, {"error": f"job {fleet_id} lost its replica"}
            t0 = time.monotonic()
            try:
                status, out, _ = _http_json(
                    f"{view.base_url}/{kind}/{job.replica_job_id}",
                    timeout=self.timeout)
                # per-replica proxy-overhead attribution (fctrace):
                # the router-side cost of one proxied hop to THIS
                # replica — network + replica handler time, the slice
                # of fleet latency no replica-side histogram can see
                self._lat.hist("router.phase.proxy",
                               replica=replica).record(
                    time.monotonic() - t0)
            except (OSError, ValueError) as e:
                # the replica died under this job: replay elsewhere and
                # answer "still pending" — the client's poll loop keeps
                # working through the failover
                self._note_poll_failure(replica, e)
                if not self._replay(job, exclude_also=replica):
                    return 503, {"error": f"replica {replica} is gone "
                                          f"and no replica can replay "
                                          f"job {fleet_id}"}
                continue
            if status == 500 and kind == "result":
                # the job FAILED server-side (e.g. an injected device-
                # path fault): burn that replica for this job and
                # replay — the fleet answer is "someone else runs it",
                # not the replica's stack trace
                if self._replay(job, exclude_also=replica):
                    continue
                return status, dict(out, fleet_replica=replica,
                                    fleet_replays=job.replays)
            if status == 404:
                # the replica restarted and forgot the job: same
                # failover as a dead replica
                if self._replay(job, exclude_also=replica):
                    continue
                return 404, {"error": f"job {fleet_id} lost by "
                                      f"{replica} and unreplayable"}
            if status == 200 and kind == "result":
                with self._lock:
                    job.done = True
                    if job.content_hash:
                        self._hash_holders.setdefault(
                            job.content_hash, set()).add(replica)
                # one flight event per COMPLETED proxy, not per poll:
                # a 2 ms client poll loop would otherwise flood the
                # bounded rings with thousands of identical events
                self._flight.record("proxy", job=job.fleet_id,
                                    trace=job.trace, replica=replica,
                                    replays=job.replays)
            return status, dict(out, fleet_replica=replica,
                                fleet_replays=job.replays)
        return 503, {"error": f"job {fleet_id} could not be served "
                              f"by any replica"}

    def status(self, fleet_id: str) -> Tuple[int, Dict[str, Any]]:
        return self._proxy("status", fleet_id)

    def result(self, fleet_id: str) -> Tuple[int, Dict[str, Any]]:
        return self._proxy("result", fleet_id)

    # -- introspection ------------------------------------------------

    def fleet_stats(self) -> Dict[str, Any]:
        counters = self._reg.counters()
        with self._lock:
            replicas = [v.describe() for v in self._views.values()]
            assignments = dict(self._assignments)
            tracked = len(self._jobs)
            in_flight = sum(1 for j in self._jobs.values() if not j.done)
            hash_index = len(self._hash_holders)
        return {
            "replicas": replicas,
            "ring": {"members": self.ring.members(),
                     "vnodes": self.ring.vnodes},
            "assignments": assignments,
            "jobs_tracked": tracked,
            "jobs_in_flight": in_flight,
            "content_hash_index": hash_index,
            "counters": {k: n for k, n in sorted(counters.items())
                         if k.startswith("serve.fleet.")},
        }

    def fleetz(self) -> Dict[str, Any]:
        """The ``GET /fleetz`` payload: every replica's ``/metricsz``
        scraped live and folded into one fleet view (fctrace
        ``aggregate_fleet``) — latency histograms exact-merged across
        replicas, SLO met/missed summed per class, plus the router's
        own ``router.phase.*`` family and per-replica proxy-overhead
        attribution.  A replica that cannot be scraped is reported
        ``ok: false``, never silently dropped."""
        self._reg.inc("serve.fleet.fleetz")
        with self._lock:
            targets = [(v.name, v.base_url)
                       for v in self._views.values()]
        per_replica: Dict[str, Optional[Dict[str, Any]]] = {}
        for name, base_url in targets:
            try:
                status, m, _ = _http_json(base_url + "/metricsz",
                                          timeout=self.poll_timeout)
                per_replica[name] = m if status == 200 else None
            # fcheck: ok=swallowed-error (the unscrapable replica is
            # REPORTED — aggregate_fleet marks it ok:false; nothing to
            # re-raise in an aggregation that must answer regardless)
            except (OSError, ValueError):
                per_replica[name] = None
        return aggregate_fleet(per_replica,
                               router_latency=self._lat.snapshot(),
                               router_fleet=self.fleet_stats())


# ---------------------------------------------------------------------
# Router HTTP front end (stdlib http.server, the replica handler's twin)
# ---------------------------------------------------------------------

class _RouterHandler(BaseHTTPRequestHandler):
    """Routes: POST /submit; GET /status/<id> /result/<id> /healthz
    /metricsz — the same surface as one replica, so every existing
    client (serve/client.py, cli.py --server) talks to the fleet
    unchanged — plus the router-only fctrace surface: GET /fleetz
    (exact-merged fleet metrics) and GET /debugz/flight (the router's
    own trace-stamped flight snapshot)."""

    server_version = "fcfleet/1"
    protocol_version = "HTTP/1.1"

    @property
    def router(self) -> FleetRouter:
        return self.server.fcfleet_router  # type: ignore[attr-defined]

    def log_message(self, fmt: str, *args) -> None:  # noqa: A003
        _logger.debug("fcfleet http: " + fmt, *args)

    def _send(self, code: int, payload: Dict[str, Any],
              headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(payload).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _send_fault(self, e: BaseException) -> None:
        obs_counters.get_registry().inc("serve.fleet.http_unhandled_errors")
        _logger.exception("fcfleet http: unhandled handler error")
        try:
            self._send(500, {"error": "internal error: "
                                      f"{type(e).__name__}: {e}"})
        except OSError:  # fcheck: ok=swallowed-error: the client socket is already gone — there is no one left to answer; the counter above carries the failure
            pass

    def do_POST(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        try:
            if self.path.rstrip("/") != "/submit":
                self._send(404, {"error": f"no such endpoint {self.path}"})
                return
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            status, out, headers = self.router.submit(
                body, trace=self.headers.get(TRACE_HEADER))
            hop = {k: v for k, v in headers.items()
                   if k.lower() == "retry-after"}
            if out.get("trace"):
                hop[TRACE_HEADER] = str(out["trace"])
            self._send(status, out, headers=hop or None)
        except Exception as e:  # noqa: BLE001 — catch-all status mapping
            self._send_fault(e)

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        try:
            self._do_get()
        except Exception as e:  # noqa: BLE001 — catch-all status mapping
            self._send_fault(e)

    def _do_get(self) -> None:
        path = self.path.rstrip("/")
        if path == "/healthz":
            fleet = self.router.fleet_stats()
            up = sum(1 for r in fleet["replicas"] if r["state"] == "up")
            self._send(200, {"ok": up > 0, "fleet": fleet})
            return
        if path == "/metricsz":
            # scope self-description (fctrace): these counters and
            # histograms are ROUTER-local — a scraper must never read
            # them as fleet totals.  The fleet view lives at /fleetz.
            self._send(200, {
                "scope": "router",
                "fcobs": obs_counters.get_registry().snapshot(),
                "latency": obs_latency.get_latency_registry().snapshot(),
                "fleet": self.router.fleet_stats()})
            return
        if path == "/fleetz":
            self._send(200, self.router.fleetz())
            return
        if path == "/debugz/flight":
            self._send(200, {
                "scope": "router",
                "flight": obs_flight.get_flight_recorder().snapshot()})
            return
        for prefix, fn in (("/status/", self.router.status),
                           ("/result/", self.router.result)):
            if path.startswith(prefix):
                status, out = fn(path[len(prefix):])
                self._send(status, out)
                return
        self._send(404, {"error": f"no such endpoint {self.path}"})


def make_router_server(router: FleetRouter, host: str = "127.0.0.1",
                       port: int = 0) -> ThreadingHTTPServer:
    """Bind the router's HTTP front end (``port=0`` picks a free port)."""
    httpd = ThreadingHTTPServer((host, port), _RouterHandler)
    httpd.fcfleet_router = router  # type: ignore[attr-defined]
    return httpd
