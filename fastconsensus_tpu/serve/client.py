"""fcserve client: a stdlib (urllib) wrapper over the HTTP endpoints.

Deliberately jax-free and numpy-optional at import time, so a thin
front-end process (``cli.py --server``) can submit work without paying
the engine's import cost — the whole point of keeping one warm serving
process is that *clients* stay cheap.

Backpressure is surfaced as a typed exception (:class:`Backpressure`,
HTTP 429) rather than a generic error: callers are expected to catch it
and retry-with-delay / shed load — that contract is what keeps an
overloaded server answering instead of queueing itself to death
(serve/queue.py module notes).
"""

from __future__ import annotations

import dataclasses
import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class WorkerState:
    """One pool worker's ``/healthz`` entry, typed (serve/pool.py).

    ``device`` is the worker's device ordinal (the fcobs ``device=i``
    tag); ``kind`` is ``"chip"`` or ``"mesh"`` (the huge tier);
    ``cordoned`` workers died and take no more work; ``buckets`` is the
    bucket residency (bucket key -> jobs served there) the sticky
    scheduler routes on.
    """

    device: int
    kind: str
    alive: bool
    cordoned: bool
    backlog: int
    jobs: int
    batches: int
    busy_s: float
    buckets: Dict[str, int]
    warm: Tuple[str, ...]
    prewarm_pending: int
    error: Optional[str] = None
    mesh_devices: Tuple[int, ...] = ()

    @classmethod
    def from_payload(cls, w: Dict[str, Any]) -> "WorkerState":
        return cls(device=int(w["device"]), kind=str(w["kind"]),
                   alive=bool(w["alive"]), cordoned=bool(w["cordoned"]),
                   backlog=int(w["backlog"]), jobs=int(w["jobs"]),
                   batches=int(w["batches"]),
                   busy_s=float(w["busy_s"]),
                   buckets=dict(w.get("buckets") or {}),
                   warm=tuple(w.get("warm") or ()),
                   prewarm_pending=int(w.get("prewarm_pending", 0)),
                   error=w.get("error"),
                   mesh_devices=tuple(w.get("mesh_devices") or ()))


@dataclasses.dataclass(frozen=True)
class JobTiming:
    """A job's server-side fclat timing block (``/result`` /
    ``/status`` ``timing``), typed: monotonic-derived milliseconds per
    phase, the end-to-end latency, and the observed SLO verdict.  The
    phase names tile the lifetime (queue_wait, hold, dispatch,
    deque_wait, pack, device, fanout, respond — ``hold`` is the
    fcshape hold-for-coalesce window, 0 for un-held jobs), so
    ``phase_sum_ms ~= e2e_ms`` — the attribution-consistency contract
    tests pin server-side."""

    e2e_ms: float
    phases_ms: Dict[str, float]
    phase_sum_ms: float
    slo: str
    slo_target_ms: float
    slo_met: bool

    @classmethod
    def from_payload(cls, t: Dict[str, Any]) -> "JobTiming":
        return cls(e2e_ms=float(t["e2e_ms"]),
                   phases_ms={str(k): float(v)
                              for k, v in t["phases_ms"].items()},
                   phase_sum_ms=float(t["phase_sum_ms"]),
                   slo=str(t["slo"]),
                   slo_target_ms=float(t["slo_target_ms"]),
                   slo_met=bool(t["slo_met"]))


@dataclasses.dataclass(frozen=True)
class JobQuality:
    """A finished job's fcqual convergence-quality block (``/result`` /
    ``/status`` ``quality``), typed: how the consensus run converged —
    final ensemble agreement / mean modularity, the active-frontier
    trajectory (fraction of vertices still incident to a mid-weight
    consensus edge, per round and averaged over the late half), total
    label churn, and rounds-to-converge (None when the run hit
    max_rounds unconverged).  Content-derived, so two jobs sharing one
    cached result report the same block (contrast :class:`JobTiming`,
    which is per submission)."""

    rounds: int
    final_agreement: Optional[float]
    final_modularity_mean: Optional[float]
    final_frontier_frac: Optional[float]
    final_churn_frac: Optional[float]
    late_frontier_frac: Optional[float]
    frontier_frac_by_round: Tuple[float, ...]
    agreement_by_round: Tuple[float, ...]
    labels_changed_total: int
    agg_overflow_total: int
    rounds_to_converge: Optional[int]

    @classmethod
    def from_payload(cls, q: Dict[str, Any]) -> "JobQuality":
        def _opt(key: str) -> Optional[float]:
            v = q.get(key)
            return None if v is None else float(v)

        rtc = q.get("rounds_to_converge")
        return cls(rounds=int(q.get("rounds", 0)),
                   final_agreement=_opt("final_agreement"),
                   final_modularity_mean=_opt("final_modularity_mean"),
                   final_frontier_frac=_opt("final_frontier_frac"),
                   final_churn_frac=_opt("final_churn_frac"),
                   late_frontier_frac=_opt("late_frontier_frac"),
                   frontier_frac_by_round=tuple(
                       float(v) for v in
                       q.get("frontier_frac_by_round") or ()),
                   agreement_by_round=tuple(
                       float(v) for v in
                       q.get("agreement_by_round") or ()),
                   labels_changed_total=int(
                       q.get("labels_changed_total", 0)),
                   agg_overflow_total=int(
                       q.get("agg_overflow_total", 0)),
                   rounds_to_converge=None if rtc is None else int(rtc))


@dataclasses.dataclass(frozen=True)
class DeltaInfo:
    """A delta submission's fcdelta provenance block (``/submit`` ack,
    ``/status``, ``/result`` ``delta``), typed: which cached parent the
    submission evolved (``parent`` is the parent's content hash), the
    mode the serve-side policy picked (``"incremental"`` — warm-start
    from the parent's ensemble with moves frontier-restricted to the
    changed edges' neighborhood — or ``"fallback"`` — a plain
    from-scratch run), the stable policy-rule name that forced a
    fallback (None for incremental), and the delta's size: edge-change
    fraction relative to the parent plus raw add/remove counts.  Lives
    OUTSIDE the content hash — two submissions producing the same child
    graph dedup to one cache entry regardless of how they got there."""

    parent: str
    mode: str
    reason: Optional[str]
    delta_frac: float
    n_adds: int
    n_removes: int

    @property
    def incremental(self) -> bool:
        return self.mode == "incremental"

    @classmethod
    def from_payload(cls, d: Dict[str, Any]) -> "DeltaInfo":
        reason = d.get("reason")
        return cls(parent=str(d["parent"]), mode=str(d["mode"]),
                   reason=None if reason is None else str(reason),
                   delta_frac=float(d.get("delta_frac", 0.0)),
                   n_adds=int(d.get("n_adds", 0)),
                   n_removes=int(d.get("n_removes", 0)))


@dataclasses.dataclass(frozen=True)
class PhaseLatency:
    """One fclat histogram from ``/metricsz``'s ``latency`` block: a
    log2-bucketed latency distribution (seconds) for one (name, tags)
    pair — e.g. ``serve.phase.device`` at bucket n64_e96 / rung 2.
    ``exemplars`` is the fcflight tail sidecar: per bucket key, the
    retained worst (job_id, seconds) pairs, empty for histograms whose
    observations carried no exemplar id."""

    name: str
    tags: Dict[str, str]
    count: int
    sum_s: float
    min_s: Optional[float]
    max_s: Optional[float]
    p50_s: Optional[float]
    p95_s: Optional[float]
    p99_s: Optional[float]
    buckets: Dict[str, int]
    exemplars: Dict[str, Tuple[Tuple[str, float], ...]] = \
        dataclasses.field(default_factory=dict)

    @classmethod
    def from_payload(cls, h: Dict[str, Any]) -> "PhaseLatency":
        return cls(name=str(h["name"]),
                   tags={str(k): str(v)
                         for k, v in (h.get("tags") or {}).items()},
                   count=int(h["count"]), sum_s=float(h["sum_s"]),
                   min_s=h.get("min_s"), max_s=h.get("max_s"),
                   p50_s=h.get("p50_s"), p95_s=h.get("p95_s"),
                   p99_s=h.get("p99_s"),
                   buckets={str(k): int(v)
                            for k, v in (h.get("buckets") or {}).items()},
                   exemplars={str(k): tuple((str(e), float(v))
                                            for e, v in rows)
                              for k, rows in
                              (h.get("exemplars") or {}).items()})


@dataclasses.dataclass(frozen=True)
class SloStats:
    """Per-class SLO attainment from ``/metricsz`` (observed, never
    enforced: ``serve.slo.<class>.met/missed`` folded server-side)."""

    slo_class: str
    met: int
    missed: int
    attainment: float
    target_default_ms: float

    @classmethod
    def from_payload(cls, name: str, s: Dict[str, Any]) -> "SloStats":
        return cls(slo_class=str(name), met=int(s["met"]),
                   missed=int(s["missed"]),
                   attainment=float(s["attainment"]),
                   target_default_ms=float(s["target_default_ms"]))


@dataclasses.dataclass(frozen=True)
class ShapingStats:
    """The ``/metricsz`` ``shaping`` block (serve/shaping.py), typed:
    which control-loop arms are live, the ``serve.shape.*`` counters
    (holds / bypasses / EDF promotions / deadline sheds / buckets
    seeded from the static cost prior), the per-bucket measured
    service-time estimates the loop decides on, and the Retry-After a
    429 issued right now would carry."""

    edf: bool
    hold: bool
    shed: bool
    max_hold_s: float
    holds: int
    bypass: int
    edf_promotions: int
    deadline_sheds: int
    prior_seeded: int
    estimates: Dict[str, Dict[str, float]]
    retry_after_hint_s: Optional[float]

    @classmethod
    def from_payload(cls, p: Dict[str, Any]) -> "ShapingStats":
        cfg = p.get("config") or {}
        c = p.get("counters") or {}
        return cls(edf=bool(cfg.get("edf", False)),
                   hold=bool(cfg.get("hold", False)),
                   shed=bool(cfg.get("shed", False)),
                   max_hold_s=float(cfg.get("max_hold_s", 0.0)),
                   holds=int(c.get("holds", 0)),
                   bypass=int(c.get("bypass", 0)),
                   edf_promotions=int(c.get("edf_promotions", 0)),
                   deadline_sheds=int(c.get("deadline_sheds", 0)),
                   prior_seeded=int(c.get("prior_seeded", 0)),
                   estimates={str(k): dict(v) for k, v in
                              (p.get("estimates") or {}).items()},
                   retry_after_hint_s=p.get("retry_after_hint_s"))


@dataclasses.dataclass(frozen=True)
class SlowJobExemplar:
    """One ``/debugz/slowest`` row (obs/flight.py fcflight), typed: a
    tail-latency exemplar — a worst-observed ``serve.e2e`` job id with
    its latency and histogram tags — joined to its retained
    flight-recorder timeline (``events``: ts/kind/aux dicts, oldest
    first) and, while the server still tracks the job, its per-phase
    timing block.  The answer to "why was THIS request the p99", one
    HTTP GET away."""

    job_id: str
    e2e_s: float
    bucket: Optional[str]
    rung: Optional[str]
    priority: Optional[str]
    device: Optional[str]
    events: Tuple[Dict[str, Any], ...]
    timing: Optional[JobTiming] = None

    @classmethod
    def from_payload(cls, r: Dict[str, Any]) -> "SlowJobExemplar":
        t = r.get("timing")
        return cls(job_id=str(r["job_id"]), e2e_s=float(r["e2e_s"]),
                   bucket=r.get("bucket"), rung=r.get("rung"),
                   priority=r.get("priority"), device=r.get("device"),
                   events=tuple(dict(e) for e in r.get("events") or ()),
                   timing=None if t is None
                   else JobTiming.from_payload(t))


@dataclasses.dataclass(frozen=True)
class ReplicaState:
    """One replica's row in the fcfleet router's ``/healthz`` /
    ``/metricsz`` fleet block (serve/router.py), typed: where the
    replica lives, whether the router routes to it (``state`` is
    ``"up"`` or ``"cordoned"``, with the cordon reason when set), the
    last polled queue depth pair the saturation routing reads, and the
    replica's own health self-reports (draining flag, watchdog trip
    count, freshest flight-bundle path) as the router last saw them."""

    name: str
    url: str
    state: str
    cordon_reason: Optional[str]
    poll_failures: int
    queue_depth: int
    queue_max_depth: int
    draining: bool
    watchdog_trips: Optional[int]
    retry_after_hint_s: Optional[float]
    last_bundle: Optional[str]
    # route keys this replica owned when it was cordoned (empty while
    # up): the frozen snapshot successor election reads, surfaced so a
    # post-mortem can see exactly which groups a dead replica donated
    rehomed_keys: Tuple[str, ...] = ()

    @property
    def cordoned(self) -> bool:
        return self.state == "cordoned"

    @classmethod
    def from_payload(cls, r: Dict[str, Any]) -> "ReplicaState":
        trips = r.get("watchdog_trips")
        hint = r.get("retry_after_hint_s")
        return cls(name=str(r["name"]), url=str(r["url"]),
                   state=str(r["state"]),
                   cordon_reason=r.get("cordon_reason"),
                   poll_failures=int(r.get("poll_failures", 0)),
                   queue_depth=int(r.get("queue_depth", 0)),
                   queue_max_depth=int(r.get("queue_max_depth", 0)),
                   draining=bool(r.get("draining", False)),
                   watchdog_trips=None if trips is None else int(trips),
                   retry_after_hint_s=None if hint is None
                   else float(hint),
                   last_bundle=r.get("last_bundle"),
                   rehomed_keys=tuple(
                       str(k) for k in r.get("rehomed_keys") or ()))


@dataclasses.dataclass(frozen=True)
class FleetStats:
    """The fcfleet router's fleet block, typed: per-replica states,
    the consistent-hash ring membership, the route-key -> replica
    assignment table the re-home accounting runs on, in-flight router
    bookkeeping, and the ``serve.fleet.*`` counters (cordons /
    re-homed groups / replays / cross-replica cache traffic)."""

    replicas: Tuple[ReplicaState, ...]
    ring_members: Tuple[str, ...]
    vnodes: int
    assignments: Dict[str, str]
    jobs_tracked: int
    jobs_in_flight: int
    content_hash_index: int
    counters: Dict[str, int]

    @property
    def up(self) -> Tuple[ReplicaState, ...]:
        return tuple(r for r in self.replicas if r.state == "up")

    @classmethod
    def from_payload(cls, f: Dict[str, Any]) -> "FleetStats":
        ring = f.get("ring") or {}
        return cls(replicas=tuple(ReplicaState.from_payload(r)
                                  for r in f.get("replicas") or ()),
                   ring_members=tuple(str(m) for m in
                                      ring.get("members") or ()),
                   vnodes=int(ring.get("vnodes", 0)),
                   assignments={str(k): str(v) for k, v in
                                (f.get("assignments") or {}).items()},
                   jobs_tracked=int(f.get("jobs_tracked", 0)),
                   jobs_in_flight=int(f.get("jobs_in_flight", 0)),
                   content_hash_index=int(
                       f.get("content_hash_index", 0)),
                   counters={str(k): int(v) for k, v in
                             (f.get("counters") or {}).items()})


@dataclasses.dataclass(frozen=True)
class FleetLatency:
    """The fcfleet router's ``GET /fleetz`` aggregate
    (obs/fleettrace.py fctrace), typed: the exact-merged fleet-wide
    fclat histograms (``histograms`` — merged bucket-by-bucket with
    the PR 9 fixed-bucket semantics, so quantiles are bit-identical
    to a single registry having observed every sample), per-class SLO
    attainment summed across replicas, summed fcobs counters, the
    router's own ``router.phase.*`` histograms, and the per-replica
    proxy-overhead attribution.  ``replicas_ok`` records which
    replicas answered the scrape — an unreachable replica appears as
    False, never silently vanishes from the aggregate."""

    scope: str
    replicas_ok: Dict[str, bool]
    histograms: Tuple[PhaseLatency, ...]
    slo: Tuple[SloStats, ...]
    counters: Dict[str, int]
    router_histograms: Tuple[PhaseLatency, ...]
    proxy_overhead: Dict[str, Dict[str, float]]

    @property
    def replicas_down(self) -> Tuple[str, ...]:
        return tuple(sorted(n for n, ok in self.replicas_ok.items()
                            if not ok))

    def histogram(self, name: str, **tags: str) -> Optional[PhaseLatency]:
        """The merged fleet histogram for one (name, tags) pair."""
        want = {str(k): str(v) for k, v in tags.items()}
        for h in self.histograms:
            if h.name == name and h.tags == want:
                return h
        return None

    @classmethod
    def from_payload(cls, p: Dict[str, Any]) -> "FleetLatency":
        lat = p.get("latency") or {}
        router = p.get("router") or {}
        rlat = router.get("latency") or {}
        return cls(
            scope=str(p.get("scope", "fleet")),
            replicas_ok={str(k): bool((v or {}).get("ok", False))
                         for k, v in (p.get("replicas") or {}).items()},
            histograms=tuple(PhaseLatency.from_payload(h)
                             for h in lat.get("histograms") or ()),
            slo=tuple(SloStats.from_payload(name, s)
                      for name, s in sorted((p.get("slo") or {}).items())),
            counters={str(k): int(v) for k, v in
                      (p.get("counters") or {}).items()},
            router_histograms=tuple(PhaseLatency.from_payload(h)
                                    for h in rlat.get("histograms") or ()),
            proxy_overhead={
                str(k): {str(a): float(b) for a, b in (v or {}).items()
                         if b is not None}
                for k, v in (router.get("proxy_overhead") or {}).items()})


@dataclasses.dataclass(frozen=True)
class TraceTimeline:
    """A fleettrace merged incident timeline (the ``fctrace-timeline``
    JSON emitted by ``python -m fastconsensus_tpu.obs.fleettrace render
    --json``), typed: the clock-aligned, replica-tagged event stream
    merged from every collected bundle — each event carries its source
    ``replica`` and a wall-clock ``t_wall`` (events are sorted on it),
    plus its original flight fields (ts/kind/thread/job/trace/aux).
    ``trace`` echoes the trace-id filter the render ran with (None for
    an unfiltered fleet timeline)."""

    trace: Optional[str]
    replicas: Tuple[str, ...]
    n_events: int
    events_per_replica: Dict[str, int]
    skipped_bundles: Tuple[str, ...]
    events: Tuple[Dict[str, Any], ...]
    schema: int = 1
    tool: str = "fctrace-timeline"

    def for_replica(self, name: str) -> Tuple[Dict[str, Any], ...]:
        return tuple(e for e in self.events if e.get("replica") == name)

    @classmethod
    def from_payload(cls, p: Dict[str, Any]) -> "TraceTimeline":
        t = p.get("trace")
        return cls(schema=int(p.get("schema", 1)),
                   tool=str(p.get("tool", "fctrace-timeline")),
                   trace=None if t is None else str(t),
                   replicas=tuple(str(r) for r in p.get("replicas") or ()),
                   n_events=int(p.get("n_events", 0)),
                   events_per_replica={
                       str(k): int(v) for k, v in
                       (p.get("events_per_replica") or {}).items()},
                   skipped_bundles=tuple(
                       str(b) for b in p.get("skipped_bundles") or ()),
                   events=tuple(dict(e) for e in p.get("events") or ()))


# What Backpressure.retry_after_s reports when the server sent no (or a
# malformed) Retry-After — the pre-fcshape constant, kept as the
# honest "we know nothing" floor.
DEFAULT_RETRY_AFTER_S = 1.0


def _retry_after_s(header: Optional[str],
                   payload: Dict[str, Any]) -> float:
    """The retry delay a 429 carried, in seconds: the JSON body's
    unrounded ``retry_after_s`` float when present (the header is
    integer delta-seconds, rounded UP server-side), else the parsed
    header, else :data:`DEFAULT_RETRY_AFTER_S`.  Malformed or negative
    values fall back to the default — a client must never interpret a
    broken header as "hammer immediately" (or "wait forever")."""
    from fastconsensus_tpu.obs import counters as obs_counters

    reg = obs_counters.get_registry()
    for candidate in (payload.get("retry_after_s"), header):
        if candidate is None:
            continue
        try:
            v = float(candidate)
        except (TypeError, ValueError):
            # a 429 whose hint cannot be parsed is a wire bug worth
            # counting, not just skipping — the shaping stack promised
            # an honest Retry-After
            reg.inc("serve.client.retry_after_malformed")
            v = None
        if v is not None and v > 0.0:
            return v
    return DEFAULT_RETRY_AFTER_S


class ServeError(RuntimeError):
    """Non-2xx response; carries the HTTP status and decoded payload."""

    def __init__(self, status: int, payload: Dict[str, Any]) -> None:
        super().__init__(
            f"fcserve HTTP {status}: {payload.get('error', payload)}")
        self.status = status
        self.payload = payload


class Backpressure(ServeError):
    """HTTP 429: admission refused (queue full, or the job's deadline
    is provably unmeetable at the current depth — ``shed``).  Retry
    after ``retry_after_s`` seconds: the server derives it from queued
    depth x its observed service rate, so honoring it converges on the
    server's actual drain time instead of a fixed-backoff guess."""

    def __init__(self, status: int, payload: Dict[str, Any],
                 retry_after_s: float = DEFAULT_RETRY_AFTER_S) -> None:
        super().__init__(status, payload)
        self.retry_after_s = float(retry_after_s)
        self.shed = bool(payload.get("shed", False))


class JobFailed(ServeError):
    """The job ran and failed server-side (HTTP 500 on /result)."""


class ServeClient:
    """Talk to one fcserve instance at ``base_url``."""

    def __init__(self, base_url: str, timeout: float = 30.0) -> None:
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    # -- transport ---------------------------------------------------

    def _request(self, path: str,
                 payload: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
        url = self.base_url + path
        data = None
        headers = {"Accept": "application/json"}
        if payload is not None:
            data = json.dumps(payload).encode("utf-8")
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(url, data=data, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except ValueError:
                body = {"error": str(e)}
            if e.code == 429:
                raise Backpressure(
                    e.code, body,
                    retry_after_s=_retry_after_s(
                        e.headers.get("Retry-After"), body)) from None
            if e.code == 500 and path.startswith("/result/"):
                raise JobFailed(e.code, body) from None
            raise ServeError(e.code, body) from None

    # -- endpoints ---------------------------------------------------

    def submit(self, edges=None, n_nodes: Optional[int] = None,
               edgelist: Optional[str] = None,
               priority=None, **config) -> Dict[str, Any]:
        """POST /submit.  ``edges`` is a list of ``[u, v]`` pairs (or a
        numpy array — ``.tolist()`` is applied); ``config`` fields are
        the ConsensusConfig subset the server accepts (algorithm, n_p,
        tau, delta, max_rounds, seed, gamma, ...)."""
        payload: Dict[str, Any] = dict(config)
        if edgelist is not None:
            payload["edgelist"] = edgelist
        if edges is not None:
            payload["edges"] = edges.tolist() if hasattr(edges, "tolist") \
                else list(edges)
        if n_nodes is not None:
            payload["n_nodes"] = int(n_nodes)
        if priority is not None:
            payload["priority"] = priority
        return self._request("/submit", payload)

    def submit_delta(self, parent: str, adds=None, removes=None,
                     priority=None, slo: Optional[str] = None,
                     slo_target_ms: Optional[float] = None,
                     trace: Optional[str] = None) -> Dict[str, Any]:
        """POST /submit with a ``parent`` content hash + edge delta
        (fcdelta, serve/delta.py).  ``adds``/``removes`` are lists of
        ``[u, v]`` pairs (numpy arrays accepted) against the parent's
        node ids; at least one must be non-empty.  The server resolves
        the parent's cached result, applies the delta to its canonical
        edge list, and either warm-starts from the parent's ensemble
        (``mode="incremental"``) or falls back to a from-scratch run —
        the ack/status/result ``delta`` block says which and why.
        Delta submissions default to the ``"delta"`` SLO class; pass
        ``slo`` to override.  Raises :class:`ServeError` with status
        404 when the parent is not cached (re-submit the full graph)
        and 400 on a malformed delta (self-loops, out-of-range nodes,
        removes of absent edges, ... — the error message names the
        offending list index)."""
        def _pairs(rows) -> List[List[int]]:
            rows = rows.tolist() if hasattr(rows, "tolist") else rows
            return [list(r) for r in rows]

        payload: Dict[str, Any] = {"parent": str(parent)}
        if adds is not None:
            payload["adds"] = _pairs(adds)
        if removes is not None:
            payload["removes"] = _pairs(removes)
        if priority is not None:
            payload["priority"] = priority
        if slo is not None:
            payload["slo"] = slo
        if slo_target_ms is not None:
            payload["slo_target_ms"] = float(slo_target_ms)
        if trace is not None:
            payload["trace"] = trace
        return self._request("/submit", payload)

    def status(self, job_id: str) -> Dict[str, Any]:
        return self._request(f"/status/{job_id}")

    def result(self, job_id: str) -> Dict[str, Any]:
        """GET /result/<id>; the payload includes ``state`` while the
        job is still pending (HTTP 202)."""
        return self._request(f"/result/{job_id}")

    def healthz(self) -> Dict[str, Any]:
        return self._request("/healthz")

    def metricsz(self) -> Dict[str, Any]:
        return self._request("/metricsz")

    def workers(self) -> List[WorkerState]:
        """The pool's per-worker state (``/healthz``), typed: device id,
        tier kind, bucket residency, queue backlog, cordoned flag."""
        return [WorkerState.from_payload(w)
                for w in self.healthz().get("workers", ())]

    def device_metrics(self) -> Dict[str, Dict[str, Any]]:
        """Per-device breakdown from ``/metricsz`` (jobs, batches,
        compiles, busy-fraction, cordon state), keyed by device id."""
        return self.metricsz().get("devices", {})

    def scope(self) -> str:
        """What ``base_url`` points at, as ``/metricsz`` self-describes
        it: ``"router"`` or ``"replica"``.  Pre-fctrace servers sent no
        scope field; they can only have been replicas."""
        return str(self.metricsz().get("scope", "replica"))

    def latency(self) -> Dict[str, Any]:
        """The fclat request-latency view from ``/metricsz``, typed:
        ``histograms`` ([:class:`PhaseLatency`] — per-phase and
        end-to-end distributions tagged by bucket/rung/priority/
        device), ``slo`` ([:class:`SloStats`] per class), and the raw
        per-bucket ``arrivals`` / ``dispatches`` rate maps.  Works
        against both scopes: a router's block holds its
        ``router.phase.*`` histograms and (having no SLO accounting of
        its own) empty slo/arrivals/dispatches maps."""
        block = self.metricsz().get("latency", {})
        return {
            "histograms": [PhaseLatency.from_payload(h)
                           for h in block.get("histograms", ())],
            "slo": [SloStats.from_payload(name, s)
                    for name, s in sorted(
                        (block.get("slo") or {}).items())],
            "arrivals": dict(block.get("arrivals") or {}),
            "dispatches": dict(block.get("dispatches") or {}),
        }

    def shaping(self) -> ShapingStats:
        """The traffic-shaping view from ``/metricsz``, typed: live
        config arms, ``serve.shape.*`` counters, per-bucket service
        estimates, and the current Retry-After hint."""
        return ShapingStats.from_payload(
            self.metricsz().get("shaping", {}))

    def slowest(self) -> List[SlowJobExemplar]:
        """The server's worst observed end-to-end jobs
        (``/debugz/slowest``), typed — tail exemplars with their flight
        timelines, sorted slowest-first server-side."""
        return [SlowJobExemplar.from_payload(r)
                for r in self._request("/debugz/slowest")
                .get("slowest", ())]

    def timing(self, job_id: str) -> Optional[JobTiming]:
        """A finished job's typed server-side timing block (None while
        the job is still pending, or for pre-fclat servers)."""
        t = self.status(job_id).get("timing")
        return None if t is None else JobTiming.from_payload(t)

    def quality(self, job_id: str) -> Optional[JobQuality]:
        """A finished job's typed fcqual quality block (None while the
        job is still pending, for pre-fcqual servers, and for results
        computed from pre-fcqual checkpoint histories)."""
        q = self.status(job_id).get("quality")
        return None if q is None else JobQuality.from_payload(q)

    def delta_info(self, job_id: str) -> Optional[DeltaInfo]:
        """A delta submission's typed fcdelta provenance block (None
        for plain full-graph submissions and pre-fcdelta servers)."""
        d = self.status(job_id).get("delta")
        return None if d is None else DeltaInfo.from_payload(d)

    def coalescing(self) -> Dict[str, Any]:
        """Operator view of cross-request batching, extracted from
        ``/metricsz``: how many batched device calls ran
        (``serve.batch.coalesced``), how many jobs rode in them
        (``serve.batch.occupancy``), the mean occupancy, and the solo
        fallback count.  ``/status/<id>`` of any coalesced job also
        carries its ``batch_id``/``batch_size``."""
        counters = self.metricsz().get("fcobs", {}).get("counters", {})
        batches = counters.get("serve.batch.coalesced", 0)
        jobs = counters.get("serve.batch.occupancy", 0)
        return {
            "batches": batches,
            "jobs_coalesced": jobs,
            "mean_occupancy": round(jobs / batches, 3) if batches else 0.0,
            "solo_fallbacks": counters.get("serve.batch.fallback_solo", 0),
            "queue_coalesced_pops": counters.get(
                "serve.queue.coalesced_pops", 0),
        }

    def fleet(self) -> Optional[FleetStats]:
        """The fcfleet block, typed, when ``base_url`` points at a
        router (serve/router.py) — None against a plain replica, so a
        caller can probe what it is talking to."""
        f = self.healthz().get("fleet")
        return None if f is None else FleetStats.from_payload(f)

    def fleetz(self) -> FleetLatency:
        """The router's fleet-wide latency aggregate (``GET /fleetz``),
        typed — exact-merged histograms, summed SLO/counters, router
        phase histograms, per-replica proxy overhead.  Raises
        :class:`ServeError` (404) against a plain replica."""
        return FleetLatency.from_payload(self._request("/fleetz"))

    def flight(self) -> Dict[str, Any]:
        """The server's raw fcflight ring snapshot
        (``GET /debugz/flight``) with its ``scope`` tag — the
        per-process half of a fleettrace timeline, one HTTP GET away
        (both tiers serve it)."""
        return self._request("/debugz/flight")

    def retry(self, call, attempts: int = 6, backoff: float = 1.5,
              jitter_frac: float = 0.1, max_sleep_s: float = 30.0,
              sleep=time.sleep, rng=None) -> Any:
        """Run ``call()`` (any zero-arg client operation, e.g.
        ``lambda: c.submit(...)``) with backpressure retries: each
        :class:`Backpressure` sleeps the server's TYPED
        ``retry_after_s`` — the shaping stack derived it from queued
        depth x observed service rate, so honoring it converges on the
        actual drain time — scaled by ``backoff ** attempt`` (a still-
        shedding server earns growing patience) plus up to
        ``jitter_frac`` random jitter (synchronized clients all
        retrying at exactly the hinted instant would arrive as one
        thundering herd and shed each other again).  The final
        Backpressure re-raises; non-429 errors propagate immediately.
        ``sleep``/``rng`` are injectable for deterministic tests."""
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        if backoff < 1.0:
            raise ValueError(f"backoff must be >= 1.0, got {backoff}")
        if rng is None:
            import random

            rng = random.Random()
        for attempt in range(attempts):
            try:
                return call()
            except Backpressure as e:
                if attempt == attempts - 1:
                    raise
                delay = min(max_sleep_s,
                            e.retry_after_s * (backoff ** attempt))
                delay += rng.uniform(0.0, jitter_frac * delay)
                sleep(delay)
        raise AssertionError("unreachable")  # the loop returns or raises

    def wait(self, job_id: str, timeout: float = 300.0,
             poll_s: float = 0.2) -> Dict[str, Any]:
        """Poll until the job finishes; returns the result payload.
        Raises :class:`JobFailed` on server-side failure and
        TimeoutError when ``timeout`` elapses first."""
        deadline = time.monotonic() + timeout
        while True:
            res = self.result(job_id)
            if "partitions" in res:
                return res
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"job {job_id} still {res.get('state')!r} after "
                    f"{timeout:.0f}s")
            time.sleep(poll_s)

    def run(self, edges, n_nodes: Optional[int] = None,
            timeout: float = 300.0, **config) -> Dict[str, Any]:
        """submit + wait in one call."""
        sub = self.submit(edges=edges, n_nodes=n_nodes, **config)
        return self.wait(sub["job_id"], timeout=timeout)
