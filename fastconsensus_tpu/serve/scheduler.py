"""fcpool scheduler: sticky bucket->device affinity routing.

The pool's whole throughput story rests on one fact about jit: compiled
executables live per *device* — a bucket's round/batch executables
compiled on chip 3 serve chip 3 only, and running the same bucket on
chip 5 compiles the entire set again (minutes on a real TPU).  Routing
therefore cannot be round-robin: it must send same-bucket work back to
the device that already holds the bucket's executables.  That is the
**sticky home**: the first time a bucket is routed, the least-loaded
eligible worker becomes its home, and every later batch of that bucket
lands there — zero warm compiles, the serve/bucketer.py contract
extended across devices.

Stickiness is not absolute, because a hot bucket would otherwise turn
the pool back into a single chip.  When the home's backlog exceeds
``spill_backlog`` queued jobs, the batch **spills** to the least-loaded
eligible worker — preferring workers that already ran this bucket (they
hold warm executables; spilling there costs nothing) and falling back to
a cold worker only when no warm one exists (paying one compile set to
mint a second home, which the warm-preference then reuses forever).

Cordoning: a worker that died (serve/pool.py failure isolation) is never
routed to again, and a job that *killed* a worker carries that device in
its exclusion set (``Job.excluded_devices``) so the requeue cannot
bounce it back.  A bucket whose home is cordoned is re-homed on its next
batch.  When no eligible worker remains, :class:`NoEligibleWorker`
propagates and the caller fails the jobs explicitly — a poisoned job
that cordons every device must end as ITS failure, not an infinite
requeue loop.

Workers are duck-typed (tests drive the scheduler with plain stubs):
``idx`` (int device tag), ``eligible(exclude)`` (alive, not cordoned,
not excluded), ``load()`` (queued jobs + unfinished pre-warm specs) and
``is_warm(bucket)`` (whether this worker has executed the bucket — a
locked accessor, because routing threads probe it while the worker
thread updates its residency set).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, FrozenSet, Optional, Sequence

from fastconsensus_tpu.obs import counters as obs_counters
from fastconsensus_tpu.obs import flight as obs_flight
from fastconsensus_tpu.obs import latency as obs_latency


class NoEligibleWorker(RuntimeError):
    """Every worker for the tier is cordoned, dead, or excluded."""


class StickyScheduler:
    """Route buckets to workers; see the module docstring."""

    def __init__(self, spill_backlog: int = 8,
                 cost_weight: Optional[
                     Callable[[str], float]] = None) -> None:
        if spill_backlog < 0:
            raise ValueError(
                f"spill_backlog must be >= 0, got {spill_backlog}")
        self.spill_backlog = int(spill_backlog)
        # Per-bucket backlog weight (>= 1.0) from the static cost
        # model: ``spill_backlog`` counts JOBS, but a queued job of a
        # minutes-long bucket is not the backlog of a 10 ms one —
        # weighting the home's load by the bucket's modeled device
        # seconds (analysis/cost.py spill_weight) makes expensive
        # buckets spill off a busy home instead of serializing behind
        # it, while sub-second buckets keep weight 1.0 and route
        # exactly as before.  None = unweighted (weight 1.0).
        self._cost_weight = cost_weight
        self._affinity: Dict[str, int] = {}   # bucket key -> worker idx
        self._lock = threading.Lock()
        self._reg = obs_counters.get_registry()
        self._lat = obs_latency.get_latency_registry()

    def _weight(self, bucket: str) -> float:
        if self._cost_weight is None:
            return 1.0
        try:
            return max(float(self._cost_weight(bucket)), 1.0)
        except Exception:  # noqa: BLE001 — a broken cost model must
            return 1.0     # not take down routing

    def affinity(self) -> Dict[str, int]:
        """Snapshot of the bucket -> home-device map (``/healthz``)."""
        with self._lock:
            return dict(self._affinity)

    def route(self, bucket: str, workers: Sequence,
              exclude: FrozenSet[int] = frozenset(),
              n_jobs: int = 1):
        """The worker that should run the next batch of ``bucket``.

        ``workers`` is the tier's worker list (chip workers for normal
        buckets, mesh workers for huge ones — serve/pool.py picks the
        tier before calling); ``n_jobs`` is how many jobs the routed
        batch carries.  Raises :class:`NoEligibleWorker` when nothing
        can take the work.
        """
        candidates = [w for w in workers if w.eligible(exclude)]
        if not candidates:
            raise NoEligibleWorker(
                f"no eligible worker for bucket {bucket!r} "
                f"(excluded: {sorted(exclude)})")
        # fclat dispatch-rate tracking: together with the per-bucket
        # ARRIVAL rate marked at admission (serve/server.py submit),
        # this is the signal pair the fcshape control loop reads —
        # arrivals/s predicts the time-to-fill of a batch rung
        # (hold-for-coalesce) and dispatches/s is the honest drain rate
        # the deadline-shed math trusts.  Marked once PER JOB, not per
        # batch: a rung-8 batch drains eight jobs, and a batch-counted
        # rate would understate the drain by the mean occupancy —
        # shedding work an 8-wide pool was about to serve.
        for _ in range(max(int(n_jobs), 1)):
            self._lat.dispatches.mark(bucket)
        with self._lock:
            home_idx = self._affinity.get(bucket)
            home = next((w for w in candidates if w.idx == home_idx),
                        None)
            if home is not None and \
                    home.load() * self._weight(bucket) \
                    <= self.spill_backlog:
                self._reg.inc("serve.sched.sticky_hits")
                obs_flight.record("route", bucket=bucket,
                                  device=home.idx, via="sticky",
                                  n_jobs=n_jobs)
                return home
            # spill (home overloaded) or first/renewed assignment (no
            # home, or the home is cordoned/excluded): least-loaded,
            # warm-capable first
            warm = [w for w in candidates if w.is_warm(bucket)
                    and w is not home]
            pool = warm or [w for w in candidates if w is not home] \
                or candidates
            pick = min(pool, key=lambda w: (w.load(), w.idx))
            if home_idx is None:
                # sticky home minted where the bucket will compile
                self._affinity[bucket] = pick.idx
                self._reg.inc("serve.sched.assigns")
                via = "assign"
            elif home is None:
                # the recorded home is cordoned/excluded: re-home the
                # bucket where its work lands now
                self._affinity[bucket] = pick.idx
                self._reg.inc("serve.sched.rehomes")
                via = "rehome"
            else:
                self._reg.inc("serve.sched.spills")
                if not pick.is_warm(bucket):
                    self._reg.inc("serve.sched.spill_cold")
                via = "spill"
            obs_flight.record("route", bucket=bucket, device=pick.idx,
                              via=via, n_jobs=n_jobs)
            return pick
