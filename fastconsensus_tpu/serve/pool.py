"""fcpool: the multi-device worker pool behind ``ConsensusService``.

Until now the service drove ONE device from one worker thread while the
environment reported 8 green chips (MULTICHIP_r05.json) — 7/8 of the
machine idle by construction.  The pool puts every chip to work without
giving up the serving contracts:

* **one device-pinned worker thread per chip** (:class:`DeviceWorker`):
  each worker enters ``jax.default_device(dev)`` for its whole life, so
  everything it runs — prewarm probes, solo jobs, coalesced batches —
  compiles and executes on ITS chip.  jax's config contexts are
  thread-local, so N workers pin N devices concurrently in one process;
* **sticky bucket->device routing** (serve/scheduler.py): a dispatcher
  thread pops coalesced batches off the admission queue and routes each
  to the bucket's home device, because executables live per device and
  round-robin would recompile every bucket on every chip.  Overflow
  spills to the least-loaded warm-capable worker;
* **a mesh-sharded "huge" tier** (:class:`MeshWorker`): buckets past the
  single-chip ceiling (``ServeConfig.chip_max_edges``) route to a
  reserved device group and run under a ``jax.sharding.Mesh`` whose
  edge axis shards the slab across the group's HBM
  (parallel/sharding.py + the explicit shard_map tail in
  ops/sharded_tail.py) — the service accepts graphs past one chip's
  memory instead of 413-ing them, bit-identical to the unsharded path
  (tests/test_parallel.py parity);
* **failure isolation**: an exception that escapes a worker's batch
  machinery (the per-job try/excepts in serve/server.py already absorb
  job-level errors, so an escape means the worker itself is broken)
  cordons the worker, requeues its unfinished jobs with that device
  excluded (``Job.excluded_devices``), and lets the survivors carry the
  traffic.  ``/healthz`` surfaces the cordon; a job that cordons every
  device fails as itself.

Observability: every worker tags its spans with ``device=i`` and owns a
thread-filtered :class:`analysis.CompileGuard` feeding
``serve.device.<i>.xla_compiles``, so ``/metricsz`` breaks compiles,
jobs and busy-time down per device and the drain-time Perfetto trace
renders one track per device (obs/export.py thread naming).
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time
from collections import deque
from typing import Dict, FrozenSet, List, Optional, Sequence

from fastconsensus_tpu.obs import counters as obs_counters
from fastconsensus_tpu.obs import flight as obs_flight
from fastconsensus_tpu.obs import latency as obs_latency
from fastconsensus_tpu.serve.jobs import (STATE_FAILED, STATE_QUEUED,
                                          STATE_RUNNING, Job)
from fastconsensus_tpu.serve.scheduler import (NoEligibleWorker,
                                               StickyScheduler)
from fastconsensus_tpu.serve.watchdog import (DISABLED_WATCHDOG,
                                              HangWatchdog)

_logger = logging.getLogger("fastconsensus_tpu")


def _cost_spill_weight():
    """The scheduler's per-bucket backlog weight from the fcheck-cost
    jax-free mirror (analysis/cost.py spill_weight), or None when the
    analyzer cannot load — routing then stays unweighted, never
    broken."""
    try:
        from fastconsensus_tpu.analysis import cost as _cost
        return _cost.spill_weight
    except Exception:  # noqa: BLE001 — optional model, mandatory pool
        return None


class _Worker:
    """One device-driving worker thread (base: queueing + lifecycle).

    The worker owns a deque of batches fed by the dispatcher, a
    long-lived thread-filtered CompileGuard (per-device compile
    attribution), and the residency/warmth bookkeeping the scheduler
    routes on.  Subclasses provide the device scope (one chip vs a mesh
    group) and how a batch executes.
    """

    kind = "chip"

    def __init__(self, idx: int, service, pool) -> None:
        self.idx = idx
        self.service = service
        self.pool = pool
        self.cordoned = False
        self.error: Optional[str] = None
        self.jobs_done = 0
        self.batches_done = 0
        self.busy_s = 0.0
        self.warm_buckets: set = set()
        self.buckets: Dict[str, int] = {}   # residency: bucket -> jobs
        self.prewarm_specs: List[str] = []
        self.prewarm_left = 0
        self.tid: Optional[int] = None      # thread ident once running
        self._running = False               # mid-batch right now
        self._batches: "deque[List[Job]]" = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._started = False
        self._thread = threading.Thread(
            target=self._loop, name=f"fcpool-{self.kind}-{idx}",
            daemon=True)
        self._reg = obs_counters.get_registry()

    # -- scheduler interface ----------------------------------------

    def alive(self) -> bool:
        """Not yet started (pre-warm assignment runs before the threads
        do) or the thread is still running."""
        return not self._started or self._thread.is_alive()

    def eligible(self, exclude: FrozenSet[int] = frozenset()) -> bool:
        if self.idx in exclude or not self.alive():
            return False
        with self._cond:
            if self.cordoned:
                return False
            # a closed worker still drains its backlog, but routing new
            # work at one about to exit would strand the jobs
            return not (self._closed and not self._batches)

    def load(self) -> int:
        """Queued jobs + unfinished pre-warm specs (routing weight)."""
        with self._cond:
            return sum(len(b) for b in self._batches) + self.prewarm_left

    def queued_jobs(self) -> int:
        """Admitted jobs parked in this worker's deque (the admission
        bound's view — excludes pre-warm, which consumed no queue
        slot)."""
        with self._cond:
            return sum(len(b) for b in self._batches)

    def is_busy(self) -> bool:
        """Mid-batch or holding backlog — the fcshape hold-economics
        probe (serve/shaping.py set_busy_probe): while every worker is
        busy a held job would only have waited in a deque, so holding
        for coalescing costs nothing."""
        with self._cond:
            return self._running or bool(self._batches)

    # -- dispatcher interface ---------------------------------------

    def start(self) -> None:
        self._started = True
        self._thread.start()

    def enqueue(self, batch: List[Job]) -> None:
        for job in batch:
            # fclat: the dispatch phase closes when the job lands in a
            # worker's deque (stamped outside _cond — Job.stamp takes
            # the job's own lock, and keeping it out of the critical
            # section keeps _cond covering only the deque)
            job.stamp("enqueued")
        with self._cond:
            self._batches.append(batch)
            self._cond.notify()

    def close(self) -> None:
        """Finish the backlog, then exit (drain)."""
        with self._cond:
            self._closed = True
            self._cond.notify()

    def join(self, timeout: Optional[float]) -> bool:
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def note_job(self, bucket: str) -> None:
        """Residency bookkeeping (``bucket`` is the bucket key string),
        called by the service per finished job.  Guarded: ``describe``
        snapshots these maps from /healthz handler threads, and a dict
        iterated while this thread inserts raises RuntimeError."""
        with self._cond:
            self.jobs_done += 1
            self.warm_buckets.add(bucket)
            self.buckets[bucket] = self.buckets.get(bucket, 0) + 1

    def note_warm(self, bucket: str) -> None:
        """Mark a bucket's executables resident (pre-warm path)."""
        with self._cond:
            self.warm_buckets.add(bucket)

    def is_warm(self, bucket: str) -> bool:
        """Whether this worker already holds the bucket's executables
        (the scheduler's warm-preference probe — a locked accessor, so
        routing threads never read the set mid-mutation)."""
        with self._cond:
            return bucket in self.warm_buckets

    def add_prewarm(self, spec: str) -> None:
        """Queue one pre-warm spec (called before ``start()``, but
        locked anyway: the field is read by the worker thread)."""
        with self._cond:
            self.prewarm_specs.append(spec)
            self.prewarm_left += 1

    # -- the worker loop --------------------------------------------

    def _device_scope(self):
        raise NotImplementedError

    def _next(self) -> Optional[List[Job]]:
        with self._cond:
            while True:
                if self._batches:
                    batch = self._batches.popleft()
                    self._coalesce(batch)
                    # busy flips on ATOMICALLY with the deque pop: a
                    # gap between "deque emptied" and "running set"
                    # would read as idle to the fcshape busy probe and
                    # spuriously abort a free hold mid-handoff
                    self._running = True
                    return batch
                if self._closed:
                    return None
                self._cond.wait()

    def _coalesce(self, batch: List[Job]) -> None:
        """Merge immediately-following same-group deque batches into
        ``batch`` up to ``max_batch`` (caller holds ``_cond``).

        The dispatcher pops eagerly — while this worker is busy, a
        same-bucket burst lands in the deque as single-job batches, and
        without this re-merge the cross-request coalescing win
        (serve/queue.py ``pop_batch``) would only survive when the
        admission heap itself ran deep.  Order is preserved: merging
        stops at the first batch of a different group, so nothing jumps
        the deque."""
        max_b = self.service.config.max_batch
        if max_b <= 1 or not batch or self.kind == "mesh":
            return
        group = batch[0].spec.batch_group()
        if any(j.spec.batch_group() != group for j in batch[1:]):
            return  # a mixed batch never merges (and never packs)
        merged = 0
        # fcheck: ok=guarded-field (the caller — _next — holds
        # self._cond across this whole merge; the lock is a documented
        # precondition of _coalesce, not re-taken to stay re-entrant)
        while self._batches and len(batch) < max_b:
            # fcheck: ok=guarded-field (same caller-held _cond contract)
            nxt = self._batches[0]
            if len(batch) + len(nxt) > max_b or \
                    any(j.spec.batch_group() != group for j in nxt):
                break
            batch.extend(self._batches.popleft())
            merged += 1
        if merged:
            self._reg.inc("serve.pool.deque_coalesced", merged)

    def _loop(self) -> None:
        from fastconsensus_tpu.analysis import CompileGuard

        tid = threading.get_ident()
        with self._cond:
            # published for thread_names() (drain-time track naming),
            # which reads from the main thread
            self.tid = tid
        batch: Optional[List[Job]] = None
        guard = CompileGuard(
            registry=self._reg,
            counter=f"serve.device.{self.idx}.xla_compiles",
            thread_ident=tid)
        try:
            with self._device_scope(), guard:
                self._prewarm()
                while True:
                    batch = self._next()
                    if batch is None:
                        return
                    self._run(batch)
                    batch = None
                    self.service._flush_trace()
        except Exception as e:  # noqa: BLE001 — the worker is broken
            # (per-job failures never escape _run); isolate the device,
            # keep the pool serving
            self._die(e, batch)
        finally:
            with self._cond:
                busy = self.busy_s
            self._reg.gauge(f"serve.device.{self.idx}.busy_s",
                            round(busy, 6))

    def _prewarm(self) -> None:
        with self._cond:
            specs = list(self.prewarm_specs)
        for spec in specs:
            try:
                self.service._prewarm_one(spec, worker=self)
            except Exception as e:  # noqa: BLE001 — a bad warm spec
                # must not cordon a worker before it served anything
                self._reg.inc("serve.prewarm.failed")
                _logger.warning("fcserve pre-warm %r failed on device "
                                "%d: %s", spec, self.idx, e)
            with self._cond:
                self.prewarm_left -= 1
            self.pool.note_prewarm_done()

    def _run(self, batch: List[Job]) -> None:
        for job in batch:
            # fclat: deque_wait closes when the worker thread takes the
            # batch (after any _coalesce re-merge — ride-alongs merged
            # from later deque entries stamp here too)
            job.stamp("dequeued")
        obs_flight.record("dequeue", device=self.idx, n_jobs=len(batch))
        self.pool.watchdog.beat(self.idx, "dequeue",
                                n_jobs=len(batch))
        t0 = time.perf_counter()
        with self._cond:
            self._running = True
        try:
            self.service._drain_group(deque(batch), worker=self)
        finally:
            self.pool.watchdog.beat(self.idx, "idle")
            with self._cond:
                self._running = False
                self.busy_s += time.perf_counter() - t0
                self.batches_done += 1
                busy = self.busy_s
            self._reg.gauge(f"serve.device.{self.idx}.busy_s",
                            round(busy, 6))

    def _die(self, exc: Exception, batch: Optional[List[Job]]) -> None:
        with self._cond:
            self.cordoned = True
            self.error = f"{type(exc).__name__}: {exc}"
        self._reg.inc("serve.pool.worker_deaths")
        self._reg.inc(f"serve.device.{self.idx}.deaths")
        obs_flight.record("cordon", device=self.idx, reason="death",
                          error=f"{type(exc).__name__}: {exc}")
        _logger.exception(
            "fcpool worker %d (%s) died; cordoning the device and "
            "requeueing its jobs", self.idx, self.kind)
        pending: List[Job] = list(batch or ())
        with self._cond:
            while self._batches:
                pending.extend(self._batches.popleft())
        self._requeue_pending(pending)
        self.service._on_worker_death(self, exc)

    def cordon(self, reason: str) -> None:
        """Externally cordon this worker — the hang watchdog's
        cordon-on-stall path.  A hung worker cannot run its own
        ``_die`` (its thread is wedged inside a device call), so the
        WATCHDOG thread flips the cordon flag and requeues the deque
        backlog onto surviving devices with this one excluded.  The
        in-flight batch stays with the stuck thread: it either finishes
        late (the worker completes it but — cordoned — takes no new
        work) or never, and its jobs stay visible in the in-flight
        table either way."""
        with self._cond:
            if self.cordoned:
                return
            self.cordoned = True
            self.error = reason
            pending: List[Job] = []
            while self._batches:
                pending.extend(self._batches.popleft())
        self._reg.inc("serve.pool.worker_cordons")
        self._reg.inc(f"serve.device.{self.idx}.cordons")
        obs_flight.record("cordon", device=self.idx, reason="watchdog",
                          error=reason)
        _logger.warning(
            "fcpool worker %d (%s) cordoned: %s (requeueing %d backlog "
            "job(s))", self.idx, self.kind, reason, len(pending))
        self._requeue_pending(pending)

    def _requeue_pending(self, pending: List[Job]) -> None:
        """The shared cordon tail (worker death and watchdog cordon):
        re-admit this worker's unfinished backlog with the device
        excluded, so the survivors carry the traffic."""
        requeue = [j for j in pending
                   if j.state in (STATE_QUEUED, STATE_RUNNING)]
        for job in requeue:
            job.exclude_device(self.idx)
            job.mark(STATE_QUEUED)
        if requeue:
            self._reg.inc("serve.pool.requeued_jobs", len(requeue))
            obs_flight.record("requeue", device=self.idx,
                              n_jobs=len(requeue))
            self.pool.requeue(requeue)

    def describe(self) -> dict:
        # one atomic snapshot: /healthz handler threads call this while
        # the worker mutates the residency maps — iterating them
        # unlocked is the "dictionary changed size" crash class the
        # concurrency lint exists to catch
        alive = self.alive()
        with self._cond:
            return {
                "device": self.idx,
                "kind": self.kind,
                "alive": alive,
                "cordoned": self.cordoned,
                "error": self.error,
                "backlog": sum(len(b) for b in self._batches),
                "jobs": self.jobs_done,
                "batches": self.batches_done,
                "busy_s": round(self.busy_s, 3),
                "buckets": dict(self.buckets),
                "warm": sorted(self.warm_buckets),
                "prewarm_pending": self.prewarm_left,
            }


class DeviceWorker(_Worker):
    """A worker pinned to one accelerator chip."""

    kind = "chip"

    def __init__(self, idx: int, device, service, pool) -> None:
        super().__init__(idx, service, pool)
        self.device = device

    def _device_scope(self):
        import jax

        return jax.default_device(self.device)


class MeshWorker(_Worker):
    """The huge-tier worker: drives a reserved multi-chip mesh group.

    Jobs here run SOLO through ``run_consensus(mesh=...)`` — the batch
    coalescing path is single-chip-only (run_consensus_batch), and huge
    graphs are throughput-bound by the device anyway.  The mesh's edge
    axis spans the whole group so the slab (the HBM-resident state)
    shards across every reserved chip; the ensemble axis stays 1 so any
    ``n_p`` is admissible (run_consensus requires n_p divisible by the
    ensemble axis).
    """

    kind = "mesh"

    def __init__(self, idx: int, devices: Sequence, service, pool) -> None:
        super().__init__(idx, service, pool)
        self.devices = list(devices)
        self.mesh = None   # built on the worker thread, first use

    def _device_scope(self):
        from fastconsensus_tpu import parallel

        self.mesh = parallel.make_mesh(ensemble=1, edge=len(self.devices),
                                       devices=self.devices)
        return contextlib.nullcontext()

    def describe(self) -> dict:
        out = super().describe()
        out["mesh_devices"] = [getattr(d, "id", i)
                               for i, d in enumerate(self.devices)]
        return out


class WorkerPool:
    """Dispatcher + workers + scheduler for one ``ConsensusService``.

    Built (and its device list resolved) inside ``start()`` so the
    jax-free paths — thin clients, ``-h`` — never import jax through
    the pool.
    """

    def __init__(self, service) -> None:
        import jax

        self.service = service
        cfg = service.config
        devices = list(jax.local_devices())
        n = cfg.devices if cfg.devices is not None else len(devices)
        if not 1 <= n <= len(devices):
            raise ValueError(
                f"devices={cfg.devices} out of range 1..{len(devices)}")
        huge = int(cfg.huge_devices)
        if huge < 0 or (huge > 0 and huge >= n):
            raise ValueError(
                f"huge_devices={huge} must leave at least one serving "
                f"chip (devices={n})")
        if cfg.chip_max_edges is not None and huge < 1:
            raise ValueError(
                "chip_max_edges needs a huge tier: set huge_devices >= 1")
        if huge >= 1 and cfg.chip_max_edges is None:
            # the mirror check: without a ceiling no bucket ever routes
            # huge, so the reserved mesh group would sit idle forever —
            # the exact waste the pool exists to remove
            raise ValueError(
                "huge_devices reserves a mesh group nothing can reach: "
                "set chip_max_edges (the single-chip bucket ceiling)")
        self._reg = obs_counters.get_registry()
        self.scheduler = StickyScheduler(
            spill_backlog=cfg.spill_backlog,
            cost_weight=_cost_spill_weight())
        # the LAST huge_devices devices form the reserved mesh group;
        # chip workers take the rest (device ordinal == worker idx ==
        # the fcobs `device=` tag)
        self.chip_workers: List[DeviceWorker] = [
            DeviceWorker(i, devices[i], service, self)
            for i in range(n - huge)]
        self.mesh_workers: List[MeshWorker] = []
        if huge:
            self.mesh_workers.append(
                MeshWorker(n - huge, devices[n - huge: n], service, self))
        self.workers: List[_Worker] = \
            list(self.chip_workers) + list(self.mesh_workers)
        self._prewarm_total = 0
        self._prewarm_done = 0
        self._prewarm_lock = threading.Lock()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="fcpool-dispatch",
            daemon=True)
        # fcflight: the hang watchdog thread (serve/watchdog.py) — the
        # disabled singleton keeps every beat/describe call site
        # unconditional, like the disabled tracer
        wd_cfg = cfg.watchdog
        if wd_cfg is not None and wd_cfg.enabled:
            self.watchdog = HangWatchdog(
                obs_latency.get_latency_registry(), wd_cfg,
                on_trip=service._on_watchdog_trip)
        else:
            self.watchdog = DISABLED_WATCHDOG

    # -- lifecycle ---------------------------------------------------

    def start(self) -> None:
        # admitted work the dispatcher already moved into worker deques
        # still counts against the queue's depth bound — eager dispatch
        # must not hollow out the 429 backpressure contract
        self.service.queue.set_extra_depth(self.backlog)
        self._assign_prewarm()
        for w in self.workers:
            w.start()
        self._dispatcher.start()
        self.watchdog.start()
        self._reg.gauge("serve.pool.workers", len(self.workers))

    def backlog(self) -> int:
        """Admitted-but-undispatched jobs across every worker deque
        (the queue's ``extra_depth`` hook; running jobs don't count —
        they hold no admission slot, exactly as before the pool)."""
        return sum(w.queued_jobs() for w in self.workers)

    def chips_all_busy(self) -> bool:
        """True when no eligible chip worker sits idle — the fcshape
        busy probe: a hold-for-coalesce window is free exactly while
        the head job could not have started anywhere anyway.  An EMPTY
        eligible set (every chip cordoned) reports False: nothing can
        serve a held job, so holding buys latency on a path already
        headed for NoEligibleWorker.  Called under the admission
        queue's condition; worker locks are always taken after it (the
        documented extra_depth ordering)."""
        eligible = [w for w in self.chip_workers if w.eligible()]
        return bool(eligible) and all(w.is_busy() for w in eligible)

    def drain(self, timeout: Optional[float]) -> bool:
        """Join the dispatcher and every worker (the queue must already
        be closed — ConsensusService.begin_drain).  True = all exited."""
        # no trips during shutdown: a drain that exceeds its deadline is
        # the DRAIN-TIMEOUT incident (its own bundle), not a hang
        self.watchdog.stop()
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        remaining = lambda: (None if deadline is None else  # noqa: E731
                             max(0.0, deadline - time.monotonic()))
        self._dispatcher.join(remaining())
        ok = not self._dispatcher.is_alive()
        for w in self.workers:
            ok = w.join(remaining()) and ok
        return ok

    # -- pre-warm distribution ---------------------------------------

    def _assign_prewarm(self) -> None:
        """Distribute ``--warm`` specs across workers through the
        scheduler, so each bucket's executables compile on the device
        the routing will later send its traffic to (the sticky home IS
        minted here, before the first request)."""
        from fastconsensus_tpu.serve import bucketer

        for spec in self.service.config.prewarm:
            self._prewarm_total += 1
            key = spec.partition(":")[0]
            try:
                bucket = bucketer.bucket_from_key(key)
                worker = self.route_bucket(bucket.key(),
                                           huge=self._is_huge(bucket))
            except (ValueError, NoEligibleWorker):
                # unparseable/ineligible specs still consume a slot so
                # /healthz progress adds up; the worker's warm-time
                # error path owns the counting and the log line
                worker = self.workers[0]
            worker.add_prewarm(spec)

    def note_prewarm_done(self) -> None:
        with self._prewarm_lock:
            self._prewarm_done += 1

    def prewarm_progress(self) -> dict:
        with self._prewarm_lock:
            done = self._prewarm_done
        return {"specs": self._prewarm_total, "done": done,
                "finished": done >= self._prewarm_total}

    # -- routing ------------------------------------------------------

    def _is_huge(self, bucket) -> bool:
        ceiling = self.service.config.chip_max_edges
        return bool(self.mesh_workers) and ceiling is not None \
            and bucket.e_class > ceiling

    def _classify(self, job: Job):
        """(bucket key, huge?) for routing; specs the bucketer rejects
        route anywhere (they will fail as their own job at pack time)."""
        try:
            bucket = job.spec.bucket()
            return bucket.key(), self._is_huge(bucket)
        except Exception:  # noqa: BLE001 — routing must never reject
            return f"solo:{job.job_id}", False

    def route_bucket(self, bucket_key: str, huge: bool,
                     exclude: FrozenSet[int] = frozenset(),
                     n_jobs: int = 1) -> _Worker:
        tier = self.mesh_workers if huge else self.chip_workers
        return self.scheduler.route(bucket_key, tier, exclude=exclude,
                                    n_jobs=n_jobs)

    def dispatch(self, batch: List[Job]) -> None:
        """Route one coalesced pop.  Jobs requeued after a worker death
        carry per-job exclusion sets and may mix batch groups (several
        deque batches die together), so the batch splits by (bucket,
        exclusions, batch group) — uniform for normal traffic, and a
        requeued mix can never pack different configs into one batched
        device call."""
        groups: Dict[tuple, List[Job]] = {}
        for job in batch:
            bucket_key, huge = self._classify(job)
            try:
                group = job.spec.batch_group()
            except Exception:  # noqa: BLE001 — routing must never
                group = f"solo:{job.job_id}"   # reject (packs solo)
            sig = (bucket_key, huge, job.excluded(), group)
            groups.setdefault(sig, []).append(job)
        for (bucket_key, huge, exclude, _group), jobs in groups.items():
            try:
                worker = self.route_bucket(bucket_key, huge,
                                           exclude=exclude,
                                           n_jobs=len(jobs))
            except NoEligibleWorker as e:
                for job in jobs:
                    job.mark(STATE_FAILED, error=str(e))
                    self._reg.inc("serve.jobs.failed")
                    # an SLO miss, not a gap: during a full cordon the
                    # attainment counters must crater with the traffic
                    self.service._record_timeline(job, failed=True)
                _logger.warning(
                    "fcpool: failed %d job(s) of bucket %s: %s",
                    len(jobs), bucket_key, e)
                continue
            worker.enqueue(jobs)

    def requeue(self, jobs: List[Job]) -> None:
        """Re-dispatch a dead worker's unfinished jobs directly (the
        admission queue may already be closed and drained mid-shutdown,
        so requeues never pass through it)."""
        now = time.monotonic()
        for job in jobs:
            # requeues bypass the admission queue's pop, so the fclat
            # dispatch checkpoint is re-stamped here: the retry's
            # timeline re-opens at routing, not at a stale first pop
            # (and not at a stale first hold — hold re-stamps to 0)
            job.stamp_hold(now)
            job.stamp("dispatched", at=now)
        self.dispatch(list(jobs))

    # -- the dispatcher ----------------------------------------------

    def _dispatch_loop(self) -> None:
        service = self.service
        while True:
            batch = None
            try:
                batch = service.queue.pop_batch(
                    service.config.max_batch,
                    group_key=service._group_key)
                if batch is None:
                    break  # queue closed and drained
                self.dispatch(batch)
            except Exception as e:  # noqa: BLE001 — the dispatcher is
                # the pool's only feed: one poisoned pop must fail its
                # own batch, not silently kill the thread and starve
                # every worker behind a healthy-looking queue
                self._reg.inc("serve.pool.dispatch_errors")
                for job in (batch or []):
                    job.mark(STATE_FAILED,
                             error=f"dispatch: {type(e).__name__}: {e}")
                    self._reg.inc("serve.jobs.failed")
                    self.service._record_timeline(job, failed=True)
                _logger.exception(
                    "fcpool: dispatch error, failed %d job(s)",
                    len(batch or []))
        for w in self.workers:
            w.close()

    # -- introspection ------------------------------------------------

    def worker_for(self, idx: int) -> Optional[_Worker]:
        """Worker by device ordinal (the watchdog trip dict's
        ``device`` field) — the cordon-on-stall lookup."""
        for w in self.workers:
            if w.idx == idx:
                return w
        return None

    def describe(self) -> List[dict]:
        return [w.describe() for w in self.workers]

    def thread_names(self) -> Dict[int, str]:
        """Raw thread ident -> display name, for the drain-time Perfetto
        export (one named track per device)."""
        names = {}
        for w in self.workers:
            if w.tid is not None:
                tag = f"device-{w.idx}" if w.kind == "chip" \
                    else f"mesh-{w.idx}"
                names[w.tid] = tag
        return names
