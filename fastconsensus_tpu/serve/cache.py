"""fcserve result cache: content-addressed, LRU + TTL bounded.

Keyed by :func:`serve.jobs.content_hash` — the canonical-graph + config
digest — so a resubmission of the same work (any edge order, any client)
is answered from memory: no queue slot, no device time, no detect spans.
Consensus is deterministic per (graph, config, seed), which is what
makes caching *results* (not just executables) sound.

Two bounds, both mandatory (a serving cache that only ever grows is a
slow OOM):

* **LRU capacity** — at most ``max_entries`` results resident; inserts
  beyond it evict the least-recently-hit entry;
* **TTL** — entries older than ``ttl_seconds`` answer nothing and are
  dropped on touch (long-lived servers should not serve arbitrarily
  stale artifacts once operators rotate configs/data around them).

fcdelta lineage pins: a delta submission (serve/delta.py) resolves its
parent's cached partitions *by reference* during admission — between
the moment the handler reads the parent hash and the moment the
warm-start labels are copied out, an LRU eviction or TTL expiry would
turn an admissible delta into a spurious 404.  :meth:`pin` marks an
entry unevictable (refcounted — concurrent deltas may share a parent)
for exactly that resolve window; :meth:`unpin` releases it.  Pinned
entries are skipped by the LRU eviction loop and survive TTL on touch;
the cache may transiently exceed ``max_entries`` by the number of live
pins, which is bounded by in-flight delta admissions.  Counted as
``serve.cache.parent_pins``.

Every outcome counts itself in the fcobs registry
(``serve.cache.{hit,miss,insert,evict_lru,expired}`` + the
``serve.cache.entries`` gauge), so ``/metricsz`` exposes hit rate
directly.  The clock is injectable for deterministic TTL tests.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Optional, Tuple

from fastconsensus_tpu.obs import counters as obs_counters


class ResultCache:
    """Thread-safe LRU+TTL map of content hash -> result payload."""

    def __init__(self, max_entries: int = 256,
                 ttl_seconds: float = 3600.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.max_entries = int(max_entries)
        self.ttl_seconds = float(ttl_seconds)
        self._clock = clock
        self._lock = threading.Lock()
        # key -> (stored_at, value); OrderedDict end = most recent
        self._entries: "OrderedDict[str, Tuple[float, Any]]" = OrderedDict()
        # fcdelta lineage pins: key -> refcount of in-flight delta
        # admissions currently resolving this entry as their parent
        self._pins: dict = {}
        self._reg = obs_counters.get_registry()
        # Spill coordination (fcfleet): the periodic background spill
        # and the drain-time spill may race; one coarse lock serializes
        # the npz write, and the dirty flag lets the loser skip instead
        # of rewriting identical bytes (spill_if_dirty).
        self._spill_lock = threading.Lock()
        self._dirty = False

    def get(self, key: str, count_miss: bool = True) -> Optional[Any]:
        """The cached result, or None (counts hit/miss/expired).

        ``count_miss=False`` is for RE-checks of one admission (the
        worker re-probes right before running in case an identical
        queued job completed meanwhile — serve/server.py): a hit there
        is a genuine serve and always counts, but recounting the miss
        would double it per computed job and halve the hit rate an
        operator reads off ``/metricsz``.
        """
        now = self._clock()
        ttl = self.ttl_seconds   # immutable after init; read unlocked
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                if count_miss:
                    self._reg.inc("serve.cache.miss")
                return None
            stored_at, value = entry
            if now - stored_at > ttl and key not in self._pins:
                del self._entries[key]
                self._reg.inc("serve.cache.expired")
                if count_miss:
                    self._reg.inc("serve.cache.miss")
                self._reg.gauge("serve.cache.entries", len(self._entries))
                return None
            # fcheck: ok=key-reuse (this `key` is the content-hash
            # cache-key STRING, not a PRNG key — the name-based
            # heuristic misfires; strings have no consumption semantics)
            self._entries.move_to_end(key)
            self._reg.inc("serve.cache.hit")
            return value

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            self._dirty = True
            self._entries[key] = (self._clock(), value)
            self._entries.move_to_end(key)
            self._reg.inc("serve.cache.insert")
            # evict least-recently-hit UNPINNED entries; a pinned parent
            # (fcdelta resolve in flight) is skipped even at capacity —
            # the transient overshoot is bounded by live pins
            excess = len(self._entries) - self.max_entries
            if excess > 0:
                victims = [k for k in self._entries
                           if k not in self._pins][:excess]
                for k in victims:
                    del self._entries[k]
                    self._reg.inc("serve.cache.evict_lru")
            self._reg.gauge("serve.cache.entries", len(self._entries))

    # -- fcdelta lineage pins ------------------------------------------

    def pin(self, key: str) -> bool:
        """Hold ``key`` against LRU eviction and TTL expiry for a delta
        admission's parent-resolve window.  Returns False (and pins
        nothing) when the entry is absent or already past its TTL —
        the caller's "parent not cached" signal.  Refcounted: every
        successful pin needs exactly one :meth:`unpin`."""
        now = self._clock()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return False
            if now - entry[0] > self.ttl_seconds and \
                    key not in self._pins:
                # already dead, just not collected yet — pinning it
                # would resurrect an expired artifact
                del self._entries[key]
                self._reg.inc("serve.cache.expired")
                self._reg.gauge("serve.cache.entries",
                                len(self._entries))
                return False
            # fcheck: ok=key-reuse (this `key` is the content-hash
            # cache-key STRING, not a PRNG key — the name-based
            # heuristic misfires; strings have no consumption semantics)
            self._pins[key] = self._pins.get(key, 0) + 1
            self._reg.inc("serve.cache.parent_pins")
            return True

    def unpin(self, key: str) -> None:
        """Release one :meth:`pin`.  Unknown/unpinned keys are a no-op
        (the pin may have returned False).  An entry that outlived its
        TTL only because it was pinned drops on the next touch."""
        with self._lock:
            # fcheck: ok=key-reuse (cache-key string, not a PRNG key —
            # same name-based misfire as get() above)
            n = self._pins.get(key, 0)
            if n <= 1:
                self._pins.pop(key, None)  # fcheck: ok=key-reuse
            else:
                self._pins[key] = n - 1  # fcheck: ok=key-reuse

    def pinned(self) -> dict:
        """Snapshot of live pin refcounts (introspection/tests)."""
        with self._lock:
            return dict(self._pins)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def keys(self) -> list:
        with self._lock:
            return list(self._entries)

    # -- persistence across restarts (npz spill / reload) -------------
    #
    # A restarted server otherwise starts cold: every request recomputes
    # until the cache refills.  Result payloads are plain numpy (the
    # partitions) plus JSON scalars, so the utils/checkpoint-style npz
    # spill captures them losslessly.  TTL survives the restart as
    # REMAINING lifetime: the monotonic stored_at clock is meaningless
    # across processes, so each entry persists its *age* at spill time
    # and re-enters the new process's clock with that age pre-spent.

    def spill(self, path: str) -> int:
        """Write every live (unexpired) entry to ``path`` (npz,
        atomic); returns the number spilled.  Entries whose payload is
        not the standard result shape (a dict with a ``partitions``
        array list and JSON scalars) are skipped with a counter — the
        spill must never fail the drain that triggers it.  Serialized
        against concurrent spills (blocking): the drain-time spill and
        the fcfleet periodic spill share one atomic-write path."""
        with self._spill_lock:
            return self._spill_locked(path)

    def spill_if_dirty(self, path: str) -> int:
        """The fcfleet periodic-spill entry (serve/server.py
        ``--cache-spill-s`` loop): spill only when entries changed
        since the last spill, and never while another spill is already
        writing — returns -1 when skipped because a concurrent spill
        holds the lock (counted), 0 when clean, else the number
        spilled.  This is what keeps a SIGKILLed replica's cache
        recoverable (serve/fleet.py ``on_death`` feeds the file to the
        ring successor) without the drain-time spill ever racing it
        into a double write."""
        if not self._spill_lock.acquire(blocking=False):
            self._reg.inc("serve.cache.persist_concurrent_skip")
            return -1
        try:
            with self._lock:
                if not self._dirty:
                    return 0
            return self._spill_locked(path)
        finally:
            self._spill_lock.release()

    def _spill_locked(self, path: str) -> int:
        import json

        import numpy as np

        now = self._clock()
        with self._lock:
            self._dirty = False
            items = [(k, t, v) for k, (t, v) in self._entries.items()]
        meta, arrays = [], {}
        for key, stored_at, value in items:
            age = now - stored_at
            if age > self.ttl_seconds:
                continue
            try:
                payload = dict(value)
                parts = payload.pop("partitions")
                # fcheck: ok=sync-in-loop (cached partitions are host
                # numpy already — this is pure serialization, no device)
                arr = np.stack([np.asarray(p, dtype=np.int32)
                                for p in parts])
                # fcdelta: the canonical graph block rides cached
                # results so a spilled/inherited parent can still
                # resolve delta submissions; arrays spill beside the
                # partitions, never through json
                graph = payload.pop("graph", None)
                garr = None
                if graph is not None:
                    # host numpy/list blocks — pure spill serialization,
                    # no device round-trip (hence the pragmas below)
                    garr = {
                        "u": np.asarray(  # fcheck: ok=sync-in-loop
                            graph["u"], dtype=np.int64),
                        "v": np.asarray(  # fcheck: ok=sync-in-loop
                            graph["v"], dtype=np.int64),
                    }
                    if graph.get("w") is not None:
                        # fcheck: ok=sync-in-loop (same: host-side spill)
                        garr["w"] = np.asarray(graph["w"],
                                               dtype=np.float32)
                json.dumps(payload)  # everything else must be JSON
            except (TypeError, ValueError, KeyError):
                self._reg.inc("serve.cache.persist_skipped")
                continue
            idx = len(meta)
            arrays[f"p{idx}"] = arr
            if garr is not None:
                for name, a in garr.items():
                    arrays[f"g{idx}{name}"] = a
            meta.append({"key": key, "age": age, "payload": payload,
                         "graph": sorted(garr) if garr else None})
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            np.savez(fh, meta=np.frombuffer(
                json.dumps(meta).encode("utf-8"), dtype=np.uint8),
                **arrays)
        import os

        os.replace(tmp, path)
        self._reg.inc("serve.cache.persist_saved", len(meta))
        return len(meta)

    def load(self, path: str) -> int:
        """Reload a :meth:`spill` artifact into this cache (LRU order
        preserved; entries past their remaining TTL are dropped).
        Returns the number loaded; missing/corrupt files load nothing
        (a cold start, not a crash — counted)."""
        import json

        import numpy as np

        try:
            with np.load(path) as z:
                meta = json.loads(bytes(z["meta"]).decode("utf-8"))
                loaded = 0
                now = self._clock()
                for idx, ent in enumerate(meta):
                    if ent["age"] > self.ttl_seconds:
                        self._reg.inc("serve.cache.persist_expired")
                        continue
                    arr = z[f"p{idx}"]
                    value = dict(ent["payload"])
                    value["partitions"] = [arr[i].copy()
                                           for i in range(arr.shape[0])]
                    if ent.get("graph"):
                        value["graph"] = {
                            name: z[f"g{idx}{name}"].copy()
                            for name in ent["graph"]}
                        value["graph"].setdefault("w", None)
                    with self._lock:
                        self._entries[ent["key"]] = (now - ent["age"],
                                                     value)
                        self._entries.move_to_end(ent["key"])
                        while len(self._entries) > self.max_entries:
                            self._entries.popitem(last=False)
                            self._reg.inc("serve.cache.evict_lru")
                    loaded += 1
        except Exception as e:  # noqa: BLE001 — the persistence
            # contract is "corrupt or missing file means a cold start,
            # never a crash": np.load surfaces OSError/ValueError for
            # most damage but zipfile.BadZipFile/EOFError for truncated
            # archives, and server startup must survive ALL of them
            self._reg.inc("serve.cache.persist_load_failed")
            import logging

            logging.getLogger("fastconsensus_tpu").warning(
                "result-cache reload from %s failed (%s); starting cold",
                path, e)
            return 0
        with self._lock:
            if loaded:
                # loaded entries count as un-spilled content: a replica
                # that inherits a dead sibling's cache must re-spill it
                # on its own schedule or lose it at its own crash
                self._dirty = True
            self._reg.gauge("serve.cache.entries", len(self._entries))
        self._reg.inc("serve.cache.persist_loaded", loaded)
        return loaded
