"""fcserve result cache: content-addressed, LRU + TTL bounded.

Keyed by :func:`serve.jobs.content_hash` — the canonical-graph + config
digest — so a resubmission of the same work (any edge order, any client)
is answered from memory: no queue slot, no device time, no detect spans.
Consensus is deterministic per (graph, config, seed), which is what
makes caching *results* (not just executables) sound.

Two bounds, both mandatory (a serving cache that only ever grows is a
slow OOM):

* **LRU capacity** — at most ``max_entries`` results resident; inserts
  beyond it evict the least-recently-hit entry;
* **TTL** — entries older than ``ttl_seconds`` answer nothing and are
  dropped on touch (long-lived servers should not serve arbitrarily
  stale artifacts once operators rotate configs/data around them).

Every outcome counts itself in the fcobs registry
(``serve.cache.{hit,miss,insert,evict_lru,expired}`` + the
``serve.cache.entries`` gauge), so ``/metricsz`` exposes hit rate
directly.  The clock is injectable for deterministic TTL tests.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Optional, Tuple

from fastconsensus_tpu.obs import counters as obs_counters


class ResultCache:
    """Thread-safe LRU+TTL map of content hash -> result payload."""

    def __init__(self, max_entries: int = 256,
                 ttl_seconds: float = 3600.0,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.max_entries = int(max_entries)
        self.ttl_seconds = float(ttl_seconds)
        self._clock = clock
        self._lock = threading.Lock()
        # key -> (stored_at, value); OrderedDict end = most recent
        self._entries: "OrderedDict[str, Tuple[float, Any]]" = OrderedDict()
        self._reg = obs_counters.get_registry()

    def get(self, key: str, count_miss: bool = True) -> Optional[Any]:
        """The cached result, or None (counts hit/miss/expired).

        ``count_miss=False`` is for RE-checks of one admission (the
        worker re-probes right before running in case an identical
        queued job completed meanwhile — serve/server.py): a hit there
        is a genuine serve and always counts, but recounting the miss
        would double it per computed job and halve the hit rate an
        operator reads off ``/metricsz``.
        """
        now = self._clock()
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                if count_miss:
                    self._reg.inc("serve.cache.miss")
                return None
            stored_at, value = entry
            if now - stored_at > self.ttl_seconds:
                del self._entries[key]
                self._reg.inc("serve.cache.expired")
                if count_miss:
                    self._reg.inc("serve.cache.miss")
                self._reg.gauge("serve.cache.entries", len(self._entries))
                return None
            # fcheck: ok=key-reuse (this `key` is the content-hash
            # cache-key STRING, not a PRNG key — the name-based
            # heuristic misfires; strings have no consumption semantics)
            self._entries.move_to_end(key)
            self._reg.inc("serve.cache.hit")
            return value

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            self._entries[key] = (self._clock(), value)
            self._entries.move_to_end(key)
            self._reg.inc("serve.cache.insert")
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self._reg.inc("serve.cache.evict_lru")
            self._reg.gauge("serve.cache.entries", len(self._entries))

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def keys(self) -> list:
        with self._lock:
            return list(self._entries)
