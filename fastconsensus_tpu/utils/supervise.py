"""Failure detection + restart supervision for long accelerator runs.

The reference has no failure story at all: a crash loses everything
(SURVEY.md §5 "failure detection / elastic recovery: none").  Here the
layers compose:

* per-round state -> utils/checkpoint.py (``--checkpoint`` / ``--resume``),
* per-detection-chunk labels -> consensus.py ``detect_cache_dir``
  (``--detect-cache``),
* and this module: run a command under a *stall watchdog* — if its
  progress file stops advancing (the TPU tunnel wedges multi-hundred-call
  RPC sequences with no error, simply hanging the client), kill the
  process, wait out the transport recovery, and rerun.  With the two
  persistence layers above, each rerun resumes within the round it died
  in, so total lost work per failure is bounded by one detection chunk.

Telemetry continuity (fcobs): each restart of a ``--trace`` child would
overwrite the previous attempt's event log; ``--rotate PATH`` chains the
artifacts instead (``PATH.1``, ``PATH.2``, ... per dead attempt —
:func:`rotate_for_retry`), ``obs/export.read_jsonl_chain`` reads the
chain back as one cumulative stream, and checkpointed counter snapshots
(utils/checkpoint.py) make each attempt's counters resume where the dead
one stopped.

CLI: ``python -m fastconsensus_tpu.utils.supervise --progress rounds.jsonl
--rotate trace.json --rotate trace.json.jsonl
-- python -m fastconsensus_tpu.cli -f g.txt --checkpoint ck.npz --resume
--detect-cache cache --trace trace.json --trace-jsonl rounds.jsonl ...``
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import List, Optional, Sequence


def rotate_for_retry(paths: Sequence[str], log=print) -> None:
    """Rotate per-attempt artifacts before relaunching a failed child.

    Each existing ``path`` moves to ``{path}.{k}`` with ``k`` one past
    the highest existing numeric suffix (obs/export.next_chain_suffix —
    the chain reader and this rotation share one naming scheme), so a
    run that died N times leaves the segments ``path.1 .. path.N`` plus
    the final attempt's live file at ``path`` — the chain
    ``obs/export.read_jsonl_chain`` reads back as one cumulative stream.
    Without rotation each restart of a ``--trace`` run *overwrites* the
    event log, reducing a 13-attempt run's telemetry to its last
    fragment.

    The chain is append-only and per-path: like the detect cache, use
    fresh paths per logical run — re-supervising the SAME run (e.g. the
    supervisor host rebooted mid ``--resume`` sequence) legitimately
    extends the chain, but pointing a new, unrelated run at old paths
    would splice two runs into one stream.
    """
    from fastconsensus_tpu.obs.export import next_chain_suffix

    for path in paths:
        if not os.path.exists(path):
            continue
        dest = f"{path}.{next_chain_suffix(path)}"
        os.replace(path, dest)
        log(f"[supervise] rotated {path} -> {dest}")


def run_supervised(argv: Sequence[str],
                   progress_path: str,
                   stall_seconds: float = 300.0,
                   recover_seconds: float = 90.0,
                   max_attempts: int = 10,
                   poll_seconds: float = 5.0,
                   rotate: Sequence[str] = (),
                   flight_dir: Optional[str] = None,
                   quit_grace_seconds: float = 10.0,
                   log=print) -> int:
    """Run ``argv`` until it exits 0, restarting on stall or failure.

    A *stall* is ``stall_seconds`` without the progress file's mtime (or
    size) advancing; the child is then killed and, after
    ``recover_seconds`` for the transport to recover, rerun.  Returns
    the final exit code (0 on success, the last child's code otherwise).

    The stall kill is a two-step **SIGQUIT-then-SIGKILL** (fcflight): a
    child running with ``--dump-on-signal`` (cli.py) or the fcserve
    SIGQUIT handler gets ``quit_grace_seconds`` to write a post-mortem
    bundle naming the wedged phase before the unignorable SIGKILL lands
    — the one artifact that distinguishes "tunnel wedged" from "our
    collective hung" after the fact.  A child without a handler dies on
    the SIGQUIT itself (default disposition), which is the same outcome
    one grace period sooner.  Bundles land in ``flight_dir`` (exported
    to the child as ``FCTPU_FLIGHT_DIR``; default: ``fcflight/`` next
    to the progress file) and each dead attempt's new bundles are
    recorded into the first ``.jsonl`` rotate artifact as ``{"kind":
    "flight_bundle"}`` lines, so ``obs/export.read_jsonl_chain`` reads
    them back attempt-tagged alongside the attempt's spans.

    ``rotate``: files to chain-rotate (:func:`rotate_for_retry`) before
    every relaunch — point it at the child's fcobs artifacts (the
    ``--trace`` JSONL sidecar, the Perfetto JSON) so each attempt's
    telemetry survives instead of being overwritten by the next.
    """
    import signal

    from fastconsensus_tpu.obs import postmortem as obs_postmortem

    if flight_dir is None:
        flight_dir = os.path.join(
            os.path.dirname(os.path.abspath(progress_path)), "fcflight")
    child_env = dict(os.environ)
    child_env[obs_postmortem.ENV_DIR] = flight_dir

    def progress_sig() -> Optional[tuple]:
        try:
            st = os.stat(progress_path)
            return (st.st_mtime, st.st_size)
        except OSError:
            return None

    def kill_tree(child) -> None:
        # the command may be a wrapper (bash, python -m ...); killing only
        # the direct child would orphan the real worker, which then keeps
        # the device transport and output files busy across retries.
        # SIGQUIT first: give a dump-on-signal child one grace period to
        # write its flight bundle — the wedge is host-side, so the
        # handler usually CAN run even when progress has stopped.
        try:
            os.killpg(child.pid, signal.SIGQUIT)
        except (ProcessLookupError, PermissionError):
            pass
        deadline = time.monotonic() + max(quit_grace_seconds, 0.0)
        while child.poll() is None and time.monotonic() < deadline:
            time.sleep(0.2)
        if child.poll() is None:
            try:
                os.killpg(child.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                child.kill()
        child.wait()

    def collect_bundles(known: set) -> List[str]:
        """New completed bundles since ``known``, recorded into the
        first .jsonl rotate artifact (pre-rotation, so they chain with
        THIS attempt's segment)."""
        fresh = [b for b in obs_postmortem.list_bundles(flight_dir)
                 if b not in known]
        if not fresh:
            return []
        sink = next((p for p in rotate if p.endswith(".jsonl")), None)
        for b in fresh:
            log(f"[supervise] flight bundle: {b}")
            if sink is not None:
                import json

                with open(sink, "a", encoding="utf-8") as fh:
                    fh.write(json.dumps(
                        {"kind": "flight_bundle", "bundle": b}) + "\n")
        return fresh

    # Fence before attempt 1: a live artifact left behind by a PREVIOUS
    # supervision of this run (supervisor killed/rebooted mid-sequence)
    # becomes a chain segment instead of being overwritten by the first
    # relaunch — the chain stays one coherent stream across supervisor
    # restarts of the same resumable run.
    rotate_for_retry(rotate, log=log)
    rc = 1
    for attempt in range(1, max_attempts + 1):
        log(f"[supervise] attempt {attempt}/{max_attempts}: "
            f"{' '.join(argv)}")
        known_bundles = set(obs_postmortem.list_bundles(flight_dir))
        start = time.monotonic()
        child = subprocess.Popen(list(argv), start_new_session=True,
                                 env=child_env)
        last_sig = progress_sig()
        # any observed change (including the file disappearing) refreshes
        # the stall clock; before the first change the clock runs from
        # launch (first-round compiles are slow; callers set stall_seconds
        # above their compile budget)
        last_change = start
        seen_change = False
        killed = False
        while True:
            rc = child.poll()
            if rc is not None:
                break
            time.sleep(poll_seconds)
            sig = progress_sig()
            now = time.monotonic()
            if sig != last_sig:
                last_sig, last_change = sig, now
                seen_change = True
            ref = last_change if seen_change else start
            if now - ref > stall_seconds:
                log(f"[supervise] stalled {now - ref:.0f}s "
                    f"(no progress on {progress_path}); killing")
                kill_tree(child)
                killed = True
                rc = -9
                break
        if rc == 0:
            log(f"[supervise] success on attempt {attempt}")
            return 0
        log(f"[supervise] attempt {attempt} ended rc={rc}"
            f"{' (stall-killed)' if killed else ''}")
        # harvest the dead attempt's post-mortem evidence BEFORE the
        # rotation, so the bundle records chain inside this attempt's
        # telemetry segment
        collect_bundles(known_bundles)
        if attempt < max_attempts:
            rotate_for_retry(rotate, log=log)
            log(f"[supervise] waiting {recover_seconds:.0f}s before retry")
            time.sleep(recover_seconds)
    return rc


def main(args: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m fastconsensus_tpu.utils.supervise",
        description="Stall-watchdog supervisor for long runs (see module "
                    "docstring).  Everything after `--` is the command.")
    p.add_argument("--progress", required=True,
                   help="file whose mtime/size advancing counts as progress "
                        "(e.g. the run's --trace-jsonl)")
    p.add_argument("--stall-seconds", type=float, default=300.0)
    p.add_argument("--recover-seconds", type=float, default=90.0)
    p.add_argument("--max-attempts", type=int, default=10)
    p.add_argument("--poll-seconds", type=float, default=5.0)
    p.add_argument("--rotate", action="append", default=[],
                   metavar="PATH",
                   help="rotate PATH to PATH.<n> before each retry "
                        "(repeatable; point at the child's fcobs "
                        "--trace artifacts so every attempt's telemetry "
                        "chains instead of being overwritten)")
    p.add_argument("--flight-dir", type=str, default=None, metavar="DIR",
                   help="where the child's fcflight post-mortem bundles "
                        "land (exported as FCTPU_FLIGHT_DIR; default: "
                        "fcflight/ next to --progress)")
    p.add_argument("--quit-grace-seconds", type=float, default=10.0,
                   metavar="S",
                   help="on stall, send SIGQUIT and wait S seconds for "
                        "the child to dump a flight bundle before the "
                        "SIGKILL (default 10)")
    ns, rest = p.parse_known_args(args)
    if rest and rest[0] == "--":
        rest = rest[1:]
    if not rest:
        p.error("no command given (put it after `--`)")
    return run_supervised(rest, ns.progress,
                          stall_seconds=ns.stall_seconds,
                          recover_seconds=ns.recover_seconds,
                          max_attempts=ns.max_attempts,
                          poll_seconds=ns.poll_seconds,
                          rotate=ns.rotate,
                          flight_dir=ns.flight_dir,
                          quit_grace_seconds=ns.quit_grace_seconds)


if __name__ == "__main__":
    sys.exit(main())
