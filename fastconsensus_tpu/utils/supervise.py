"""Failure detection + restart supervision for long accelerator runs.

The reference has no failure story at all: a crash loses everything
(SURVEY.md §5 "failure detection / elastic recovery: none").  Here the
layers compose:

* per-round state -> utils/checkpoint.py (``--checkpoint`` / ``--resume``),
* per-detection-chunk labels -> consensus.py ``detect_cache_dir``
  (``--detect-cache``),
* and this module: run a command under a *stall watchdog* — if its
  progress file stops advancing (the TPU tunnel wedges multi-hundred-call
  RPC sequences with no error, simply hanging the client), kill the
  process, wait out the transport recovery, and rerun.  With the two
  persistence layers above, each rerun resumes within the round it died
  in, so total lost work per failure is bounded by one detection chunk.

CLI: ``python -m fastconsensus_tpu.utils.supervise --progress rounds.jsonl
-- python -m fastconsensus_tpu.cli -f g.txt --checkpoint ck.npz --resume
--detect-cache cache --trace-jsonl rounds.jsonl ...``
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time
from typing import List, Optional, Sequence


def run_supervised(argv: Sequence[str],
                   progress_path: str,
                   stall_seconds: float = 300.0,
                   recover_seconds: float = 90.0,
                   max_attempts: int = 10,
                   poll_seconds: float = 5.0,
                   log=print) -> int:
    """Run ``argv`` until it exits 0, restarting on stall or failure.

    A *stall* is ``stall_seconds`` without the progress file's mtime (or
    size) advancing; the child is then killed (SIGKILL — a wedged RPC
    ignores SIGTERM) and, after ``recover_seconds`` for the transport to
    recover, rerun.  Returns the final exit code (0 on success, the last
    child's code otherwise).
    """
    import signal

    def progress_sig() -> Optional[tuple]:
        try:
            st = os.stat(progress_path)
            return (st.st_mtime, st.st_size)
        except OSError:
            return None

    def kill_tree(child) -> None:
        # the command may be a wrapper (bash, python -m ...); killing only
        # the direct child would orphan the real worker, which then keeps
        # the device transport and output files busy across retries
        try:
            os.killpg(child.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            child.kill()
        child.wait()

    rc = 1
    for attempt in range(1, max_attempts + 1):
        log(f"[supervise] attempt {attempt}/{max_attempts}: "
            f"{' '.join(argv)}")
        start = time.monotonic()
        child = subprocess.Popen(list(argv), start_new_session=True)
        last_sig = progress_sig()
        # any observed change (including the file disappearing) refreshes
        # the stall clock; before the first change the clock runs from
        # launch (first-round compiles are slow; callers set stall_seconds
        # above their compile budget)
        last_change = start
        seen_change = False
        killed = False
        while True:
            rc = child.poll()
            if rc is not None:
                break
            time.sleep(poll_seconds)
            sig = progress_sig()
            now = time.monotonic()
            if sig != last_sig:
                last_sig, last_change = sig, now
                seen_change = True
            ref = last_change if seen_change else start
            if now - ref > stall_seconds:
                log(f"[supervise] stalled {now - ref:.0f}s "
                    f"(no progress on {progress_path}); killing")
                kill_tree(child)
                killed = True
                rc = -9
                break
        if rc == 0:
            log(f"[supervise] success on attempt {attempt}")
            return 0
        log(f"[supervise] attempt {attempt} ended rc={rc}"
            f"{' (stall-killed)' if killed else ''}")
        if attempt < max_attempts:
            log(f"[supervise] waiting {recover_seconds:.0f}s before retry")
            time.sleep(recover_seconds)
    return rc


def main(args: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m fastconsensus_tpu.utils.supervise",
        description="Stall-watchdog supervisor for long runs (see module "
                    "docstring).  Everything after `--` is the command.")
    p.add_argument("--progress", required=True,
                   help="file whose mtime/size advancing counts as progress "
                        "(e.g. the run's --trace-jsonl)")
    p.add_argument("--stall-seconds", type=float, default=300.0)
    p.add_argument("--recover-seconds", type=float, default=90.0)
    p.add_argument("--max-attempts", type=int, default=10)
    ns, rest = p.parse_known_args(args)
    if rest and rest[0] == "--":
        rest = rest[1:]
    if not rest:
        p.error("no command given (put it after `--`)")
    return run_supervised(rest, ns.progress,
                          stall_seconds=ns.stall_seconds,
                          recover_seconds=ns.recover_seconds,
                          max_attempts=ns.max_attempts)


if __name__ == "__main__":
    sys.exit(main())
