"""Host-side I/O boundary: edgelist parsing and partition writers.

Mirrors the reference's file formats while fixing its ingest bugs:

* the reference crashes on the 3-column weighted format its own README
  documents (``nx.read_edgelist(..., nodetype=int)`` literal-evals column 3,
  reference ``fast_consensus.py:434``) — here both 2- and 3-column files
  parse; input weights are accepted but, like the reference, overwritten with
  1.0 at the start of the consensus loop (``fast_consensus.py:135-136``);
* the reference requires 0-indexed contiguous ids (relabeling commented out at
  ``fast_consensus.py:435-436``) — here arbitrary integer ids are compacted
  and original ids restored on output.

Output formats (reference ``fast_consensus.py:440-466``):

* ``out_partitions_t{t}_d{d}_np{np}/{i}`` — one community per line,
  space-separated original node ids;
* ``memberships_t{t}_d{d}_np{np}/{i}`` — ``node\tcommunity`` lines in
  1-indexed *compact* ids (the reference requires 0-indexed input and writes
  ``id + 1``, fc:450-455; with compact ids this reproduces it exactly on
  every input the reference accepts, and stays well-defined for arbitrary
  ids).  The reference only writes memberships for louvain; we write them for
  every algorithm, as merged_consensus.py:319-328 does.
"""

from __future__ import annotations

import os
from typing import List, Optional, Sequence, Tuple

import numpy as np


def read_edgelist(path: str) -> Tuple[np.ndarray, Optional[np.ndarray], np.ndarray]:
    """Parse an edgelist file.

    Accepts lines ``u v`` or ``u v w``; ``#`` comments and blank lines are
    skipped.  Node ids may be arbitrary (possibly sparse) integers.

    Returns ``(edges, weights, original_ids)`` where ``edges`` is int64[E, 2]
    in compact 0-based ids, ``weights`` is float32[E] or None if the file had
    no weight column, and ``original_ids[i]`` is the input id of compact
    node ``i`` (sorted ascending).
    """
    try:  # native single-pass parser (the framework's data loader)
        from fastconsensus_tpu import native

        if native.available():
            u64, v64, w64 = native.parse_edgelist(path)
            if u64.shape[0] > 0:
                return _compact(u64, v64,
                                None if w64 is None
                                else w64.astype(np.float32))
    # fcheck: ok=swallowed-error (the fallthrough IS the
    # handling: the pure-Python parser below re-reads the
    # file and ITS errors name the offending line)
    except (ImportError, ValueError):
        # No toolchain, or a line the fast parser rejects: fall through to
        # the pure-Python parse, whose errors name the offending line.
        pass

    us: List[int] = []
    vs: List[int] = []
    ws: List[float] = []
    saw_weight = False
    with open(path) as fh:
        for ln, line in enumerate(fh, 1):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) < 2:
                raise ValueError(f"{path}:{ln}: expected 'u v [w]', got {line!r}")
            us.append(int(parts[0]))
            vs.append(int(parts[1]))
            if len(parts) >= 3:
                saw_weight = True
                ws.append(float(parts[2]))
            else:
                ws.append(1.0)
    if not us:
        raise ValueError(f"{path}: empty edgelist")
    return _compact(np.asarray(us, dtype=np.int64),
                    np.asarray(vs, dtype=np.int64),
                    np.asarray(ws, dtype=np.float32) if saw_weight else None)


def _compact(u: np.ndarray, v: np.ndarray, weights: Optional[np.ndarray]
             ) -> Tuple[np.ndarray, Optional[np.ndarray], np.ndarray]:
    """Compact arbitrary integer ids to 0..N-1 (sorted ascending)."""
    original_ids, inverse = np.unique(np.concatenate([u, v]),
                                      return_inverse=True)
    edges = np.stack([inverse[:u.shape[0]], inverse[u.shape[0]:]], axis=1)
    return edges.astype(np.int64), weights, original_ids


def labels_to_communities(labels: np.ndarray) -> List[List[int]]:
    """Group a membership vector into a list of communities.

    Communities are ordered by their smallest member; members ascending.
    (Reference ``group_to_partition``, fast_consensus.py:55-71, keyed by
    first-seen order — ordering is cosmetic, contents identical.)
    """
    labels = np.asarray(labels)
    order = np.argsort(labels, kind="stable")
    sorted_labels = labels[order]
    boundaries = np.flatnonzero(np.diff(sorted_labels)) + 1
    groups = np.split(order, boundaries)
    groups.sort(key=lambda g: int(g.min()))
    return [sorted(int(x) for x in g) for g in groups]


def write_partition_dirs(out_dir: str,
                         memberships_dir: str,
                         partitions: Sequence[np.ndarray],
                         original_ids: np.ndarray,
                         one_indexed_memberships: bool = True) -> None:
    """Write the reference's two output trees for a list of label vectors."""
    os.makedirs(out_dir, exist_ok=True)
    os.makedirs(memberships_dir, exist_ok=True)
    original_ids = np.asarray(original_ids)
    for i, labels in enumerate(partitions, start=1):
        # fcheck: ok=sync-in-loop (each partition is a distinct array; the
        # per-file host write IS the loop body)
        labels = np.asarray(labels)
        with open(os.path.join(out_dir, str(i)), "w") as fh:
            for comm in labels_to_communities(labels):
                fh.write(" ".join(str(int(original_ids[n])) for n in comm))
                fh.write("\n")
        off = 1 if one_indexed_memberships else 0
        # memberships use compact node ids (+1) — see module docstring
        _, compact = np.unique(labels, return_inverse=True)
        with open(os.path.join(memberships_dir, str(i - 1)), "w") as fh:
            for n in range(labels.shape[0]):
                fh.write(f"{n + off}\t{int(compact[n]) + off}\n")


def read_partition_file(path: str) -> List[List[int]]:
    """Read one out_partitions file back (one community per line)."""
    comms: List[List[int]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line:
                comms.append([int(x) for x in line.split()])
    return comms
