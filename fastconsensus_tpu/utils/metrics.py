"""Partition-quality metrics (host-side, numpy).

The reference computes no metrics at all (SURVEY.md §5); its validation
protocol is the paper's: NMI against planted partitions on LFR graphs.  These
are the metrics the test-suite and benchmark harness use for that protocol.
"""

from __future__ import annotations

import numpy as np


def nmi(labels_a, labels_b) -> float:
    """Normalized mutual information (arithmetic normalization), in [0, 1].

    Matches sklearn's ``normalized_mutual_info_score(average_method=
    'arithmetic')``; implemented directly so the framework has no sklearn
    dependency on the hot path.
    """
    a = np.asarray(labels_a).ravel()
    b = np.asarray(labels_b).ravel()
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
    n = a.shape[0]
    _, ai = np.unique(a, return_inverse=True)
    _, bi = np.unique(b, return_inverse=True)
    ka, kb = ai.max() + 1, bi.max() + 1
    cont = np.zeros((ka, kb), dtype=np.float64)
    np.add.at(cont, (ai, bi), 1.0)
    pij = cont / n
    pa = pij.sum(axis=1)
    pb = pij.sum(axis=0)
    outer = pa[:, None] * pb[None, :]
    nz = pij > 0
    mi = float((pij[nz] * np.log(pij[nz] / outer[nz])).sum())
    ha = float(-(pa[pa > 0] * np.log(pa[pa > 0])).sum())
    hb = float(-(pb[pb > 0] * np.log(pb[pb > 0])).sum())
    if ha == 0.0 and hb == 0.0:
        return 1.0
    denom = 0.5 * (ha + hb)
    if denom == 0.0:
        return 0.0
    return max(0.0, min(1.0, mi / denom))


def modularity(src, dst, weight, labels) -> float:
    """Newman modularity of a partition of an undirected weighted graph.

    Edges are given once (canonical orientation); self-loops count once.
    """
    src = np.asarray(src)
    dst = np.asarray(dst)
    w = np.asarray(weight, dtype=np.float64)
    labels = np.asarray(labels)
    m2 = 2.0 * w.sum()          # 2m
    if m2 == 0.0:
        return 0.0
    n_comm = int(labels.max()) + 1
    strength = np.zeros(labels.shape[0], dtype=np.float64)
    np.add.at(strength, src, w)
    np.add.at(strength, dst, w)
    sigma_tot = np.zeros(n_comm, dtype=np.float64)
    np.add.at(sigma_tot, labels, strength)
    intra = w[labels[src] == labels[dst]].sum()
    return float(2.0 * intra / m2 - np.square(sigma_tot / m2).sum())
