"""Partition-quality metrics (host-side, numpy).

The reference computes no metrics at all (SURVEY.md §5); its validation
protocol is the paper's: NMI against planted partitions on LFR graphs.  These
are the metrics the test-suite and benchmark harness use for that protocol.
"""

from __future__ import annotations

import numpy as np


def nmi(labels_a, labels_b) -> float:
    """Normalized mutual information (arithmetic normalization), in [0, 1].

    Matches sklearn's ``normalized_mutual_info_score(average_method=
    'arithmetic')``; implemented directly so the framework has no sklearn
    dependency on the hot path.
    """
    a = np.asarray(labels_a).ravel()
    b = np.asarray(labels_b).ravel()
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch {a.shape} vs {b.shape}")
    n = a.shape[0]
    _, ai = np.unique(a, return_inverse=True)
    _, bi = np.unique(b, return_inverse=True)
    ka, kb = ai.max() + 1, bi.max() + 1
    cont = np.zeros((ka, kb), dtype=np.float64)
    np.add.at(cont, (ai, bi), 1.0)
    pij = cont / n
    pa = pij.sum(axis=1)
    pb = pij.sum(axis=0)
    outer = pa[:, None] * pb[None, :]
    nz = pij > 0
    mi = float((pij[nz] * np.log(pij[nz] / outer[nz])).sum())
    ha = float(-(pa[pa > 0] * np.log(pa[pa > 0])).sum())
    hb = float(-(pb[pb > 0] * np.log(pb[pb > 0])).sum())
    if ha == 0.0 and hb == 0.0:
        return 1.0
    denom = 0.5 * (ha + hb)
    if denom == 0.0:
        return 0.0
    return max(0.0, min(1.0, mi / denom))


def map_equation(src, dst, weight, labels) -> float:
    """Two-level map equation L(M) in bits (Rosvall–Bergstrom 2008).

    For an undirected weighted graph with visit rates ``p_i =
    strength_i / 2m`` and module exit rates ``q_m = w_cross(m) / 2m``:

        L(M) = plogp(sum_m q_m) - 2 sum_m plogp(q_m)
             + sum_m plogp(q_m + sum_{i in m} p_i) - sum_i plogp(p_i)

    This is the quantity ``native/src/infomap.cpp`` minimizes (which drops
    the partition-independent last term); implemented independently here so
    tests can verify the native optimizer against hand-computed values
    (VERDICT round 1 #8).  Self-loops must be passed once; they contribute
    to strengths but never to exit rates.
    """
    src = np.asarray(src)
    dst = np.asarray(dst)
    w = np.asarray(weight, dtype=np.float64)
    labels = np.asarray(labels)
    m2 = 2.0 * w.sum()
    if m2 == 0.0:
        return 0.0

    def plogp(x):
        x = np.asarray(x, dtype=np.float64)
        out = np.zeros_like(x)
        nz = x > 0
        out[nz] = x[nz] * np.log2(x[nz])
        return out

    n_comm = int(labels.max()) + 1
    strength = np.zeros(labels.shape[0], dtype=np.float64)
    np.add.at(strength, src, w)
    np.add.at(strength, dst, w)
    p_mod = np.zeros(n_comm, dtype=np.float64)
    np.add.at(p_mod, labels, strength / m2)
    q = np.zeros(n_comm, dtype=np.float64)
    cross = labels[src] != labels[dst]
    np.add.at(q, labels[src[cross]], w[cross] / m2)
    np.add.at(q, labels[dst[cross]], w[cross] / m2)
    return float(plogp(q.sum()).sum() - 2.0 * plogp(q).sum()
                 + plogp(q + p_mod).sum() - plogp(strength / m2).sum())


def modularity(src, dst, weight, labels) -> float:
    """Newman modularity of a partition of an undirected weighted graph.

    Edges are given once (canonical orientation); self-loops count once.
    """
    src = np.asarray(src)
    dst = np.asarray(dst)
    w = np.asarray(weight, dtype=np.float64)
    labels = np.asarray(labels)
    m2 = 2.0 * w.sum()          # 2m
    if m2 == 0.0:
        return 0.0
    n_comm = int(labels.max()) + 1
    strength = np.zeros(labels.shape[0], dtype=np.float64)
    np.add.at(strength, src, w)
    np.add.at(strength, dst, w)
    sigma_tot = np.zeros(n_comm, dtype=np.float64)
    np.add.at(sigma_tot, labels, strength)
    intra = w[labels[src] == labels[dst]].sum()
    return float(2.0 * intra / m2 - np.square(sigma_tot / m2).sum())
