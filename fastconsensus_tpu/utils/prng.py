"""Single keyed PRNG tree.

The reference mixes four uncorrelated randomness sources (stdlib ``random``,
``np.random`` global, python-louvain's internal RNG, leiden seeds
``range(n_p)`` — reference fast_consensus.py:125-127,148,177,181) and is
reproducible only on the leiden path.  Here every random draw descends from
one ``jax.random`` key via ``fold_in``, making the whole framework replayable
from a single ``--seed``.
"""

from __future__ import annotations

import jax


# Stable stream tags: fold_in(key, TAG) partitions the key tree by purpose.
STREAM_ROUND = 0x01       # one sub-key per consensus round (detection and
                          # closure split from it inside the round)
STREAM_FINAL = 0x03       # final re-detection runs
STREAM_DATA = 0x04        # synthetic benchmark graph generation


def stream(key: jax.Array, tag: int, *indices: int) -> jax.Array:
    """Derive a sub-key for a named stream and optional indices (round, p)."""
    k = jax.random.fold_in(key, tag)
    for ix in indices:
        k = jax.random.fold_in(k, ix)
    return k


def partition_keys(key: jax.Array, n_p: int) -> jax.Array:
    """n_p independent keys, one per ensemble partition (the vmap axis)."""
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(
        jax.numpy.arange(n_p, dtype=jax.numpy.uint32))
