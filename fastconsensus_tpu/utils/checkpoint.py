"""Checkpoint / resume for the consensus loop.

The reference has no persistence: results are written once at the very end
(reference ``fast_consensus.py:440-466``) and an interrupted run loses
everything (SURVEY.md §5).  Here each consensus round is a natural
checkpoint: the entire mutable state is one GraphSlab (four arrays), the
round counter, and the root PRNG key — a few hundred KB even at the
100k-node stress config.

Format: a single ``.npz`` with the slab arrays + a JSON metadata blob,
written atomically (tmp + rename) so a crash mid-write never corrupts the
latest checkpoint.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from fastconsensus_tpu.graph import GraphSlab

# v2 adds d_hyb/hub_cap (hybrid move-path sizing) to the metadata: a v1
# checkpoint restored them as 0, silently flipping select_move_path from
# "hybrid" to "hash" on resume (different lowering => different labels,
# round-2 VERDICT Weak #2).  v1 checkpoints are still loadable; the loader
# marks them ``extra["_legacy_v1"]`` and the consensus driver re-derives the
# sizing from the caller's freshly packed slab (deterministic in the graph).
_FORMAT_VERSION = 2


def save_checkpoint(path: str,
                    slab: GraphSlab,
                    rounds_done: int,
                    key_data: np.ndarray,
                    history: List[dict],
                    extra: Optional[Dict[str, Any]] = None,
                    labels: Optional[np.ndarray] = None,
                    telemetry: Optional[Dict[str, int]] = None) -> None:
    """Atomically persist the consensus state after a round.

    ``labels`` ([n_p, N] int32, optional) is the round's detection output —
    persisted so a warm-started run (consensus.ConsensusConfig.warm_start)
    resumes bit-identically; surfaced by load_checkpoint as
    ``extra["_labels"]``.

    ``telemetry`` (optional) is the fcobs counter snapshot at checkpoint
    time (``ObsRegistry.counters()``) — telemetry continuity: a resumed
    process delta-restores these totals (obs/counters.restore_counters)
    so its ``--trace`` summary reports the RUN's cumulative counts, not
    just the surviving process's.  Surfaced as ``extra["_telemetry"]``;
    counters only (series percentiles cannot be merged across processes
    and are deliberately not persisted).
    """
    meta = {
        "version": _FORMAT_VERSION,
        "n_nodes": int(slab.n_nodes),
        "d_cap": int(slab.d_cap),
        "cap_hint": int(slab.cap_hint),
        "d_hyb": int(slab.d_hyb),
        "hub_cap": int(slab.hub_cap),
        "agg_cap": int(slab.agg_cap),
        "rounds_done": int(rounds_done),
        "history": history,
        "extra": extra or {},
    }
    if telemetry:
        meta["telemetry"] = {k: int(v) for k, v in telemetry.items()}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(os.path.abspath(path)),
                               suffix=".tmp")
    arrays = dict(src=np.asarray(slab.src),
                  dst=np.asarray(slab.dst),
                  weight=np.asarray(slab.weight),
                  alive=np.asarray(slab.alive),
                  key_data=np.asarray(key_data),
                  meta=np.frombuffer(
                      json.dumps(meta).encode(), dtype=np.uint8))
    if labels is not None:
        arrays["labels"] = np.asarray(labels)
    try:
        with os.fdopen(fd, "wb") as fh:
            np.savez(fh, **arrays)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def load_checkpoint(path: str
                    ) -> Tuple[GraphSlab, int, np.ndarray, List[dict],
                               Dict[str, Any]]:
    """Load ``(slab, rounds_done, key_data, history, extra)``."""
    import jax.numpy as jnp

    with np.load(path) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        if meta.get("version") not in (1, _FORMAT_VERSION):
            raise ValueError(
                f"{path}: unsupported checkpoint version {meta.get('version')}")
        slab = GraphSlab(src=jnp.asarray(z["src"]),
                         dst=jnp.asarray(z["dst"]),
                         weight=jnp.asarray(z["weight"]),
                         alive=jnp.asarray(z["alive"]),
                         n_nodes=int(meta["n_nodes"]),
                         d_cap=int(meta.get("d_cap", 0)),
                         cap_hint=int(meta.get("cap_hint", 0)),
                         d_hyb=int(meta.get("d_hyb", 0)),
                         hub_cap=int(meta.get("hub_cap", 0)),
                         # absent in pre-r5 checkpoints: 0 keeps the
                         # aggregate move uncompacted, i.e. the exact
                         # lowering the run was started with
                         agg_cap=int(meta.get("agg_cap", 0)))
        extra = dict(meta["extra"])
        if meta.get("version") == 1:
            extra["_legacy_v1"] = True
        if meta.get("telemetry"):
            extra["_telemetry"] = dict(meta["telemetry"])
        if "labels" in z.files:
            extra["_labels"] = z["labels"].copy()
        return (slab, int(meta["rounds_done"]), z["key_data"].copy(),
                list(meta["history"]), extra)
