"""Deprecation shims: this surface moved into fcobs (``obs/``).

The pre-fcobs tracing helpers lived here; their real implementations are
now part of the observability subsystem so one artifact carries every
host signal:

* ``RoundTracer``  -> :class:`fastconsensus_tpu.obs.roundlog.RoundLog`
* ``phase_timer``  -> :func:`fastconsensus_tpu.obs.roundlog.phase_span`
* ``profiler_trace`` -> :class:`fastconsensus_tpu.obs.device.
  ProfilerSession` (which also anchors the clock for host+device
  timeline merging)

The names below keep existing callers and ``runs/`` scripts working;
each emits a ``DeprecationWarning`` pointing at its fcobs home.  New
code should import from ``fastconsensus_tpu.obs``.
"""

from __future__ import annotations

import contextlib
import logging
import warnings
from typing import Dict, Optional

from fastconsensus_tpu.obs.device import ProfilerSession
from fastconsensus_tpu.obs.roundlog import RoundLog, logger, phase_span

__all__ = ["RoundTracer", "phase_timer", "profiler_trace", "logger"]


def _warn(old: str, new: str) -> None:
    warnings.warn(f"fastconsensus_tpu.utils.trace.{old} moved to "
                  f"fastconsensus_tpu.obs ({new}); this shim will go "
                  f"away", DeprecationWarning, stacklevel=3)


class RoundTracer(RoundLog):
    """Deprecated alias of :class:`fastconsensus_tpu.obs.roundlog.
    RoundLog` (identical behavior, including ``.records`` and the
    ``jsonl_path`` sidecar)."""

    def __init__(self, log_level: int = logging.INFO,
                 jsonl_path: Optional[str] = None):
        _warn("RoundTracer", "roundlog.RoundLog")
        super().__init__(log_level=log_level, jsonl_path=jsonl_path)


@contextlib.contextmanager
def phase_timer(name: str, sink: Optional[Dict[str, float]] = None,
                level: int = logging.DEBUG):
    """Deprecated alias of :func:`fastconsensus_tpu.obs.roundlog.
    phase_span`."""
    _warn("phase_timer", "roundlog.phase_span")
    with phase_span(name, sink=sink, level=level):
        yield


@contextlib.contextmanager
def profiler_trace(log_dir: Optional[str]):
    """Deprecated alias of :class:`fastconsensus_tpu.obs.device.
    ProfilerSession` (no-op when ``log_dir`` is None)."""
    _warn("profiler_trace", "device.ProfilerSession")
    with ProfilerSession(log_dir):
        yield
