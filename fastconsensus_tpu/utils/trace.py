"""Structured tracing / observability.

The reference's only observability is print statements in its debug variant
(``new_consensus.py:140-283``: iteration counter + per-phase edge counts;
SURVEY.md §5).  Here the same signals are structured:

* :class:`RoundTracer` — an ``on_round`` hook for ``run_consensus`` that
  logs each round's stats (edges alive, unconverged fraction, closure /
  repair counts — the exact quantities nc prints) through :mod:`logging`
  and keeps machine-readable records;
* :func:`profiler_trace` — optional ``jax.profiler`` context producing a
  TensorBoard-loadable device trace for kernel-level timing;
* :func:`phase_timer` — wall-clock phase timing for host-side stages
  (pack, rounds, final detection, write-out).
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from typing import Dict, List, Optional

logger = logging.getLogger("fastconsensus_tpu")


class RoundTracer:
    """Collects per-round stats; pass ``tracer.on_round`` to run_consensus."""

    def __init__(self, log_level: int = logging.INFO,
                 jsonl_path: Optional[str] = None):
        self.records: List[dict] = []
        self._level = log_level
        self._jsonl_path = jsonl_path
        self._t0 = time.perf_counter()
        self._last = self._t0

    def on_round(self, entry: Dict) -> None:
        now = time.perf_counter()
        rec = dict(entry)
        rec["round_seconds"] = round(now - self._last, 4)
        rec["elapsed_seconds"] = round(now - self._t0, 4)
        self._last = now
        self.records.append(rec)
        frac = (rec["n_unconverged"] / rec["n_alive"]
                if rec["n_alive"] else 0.0)
        logger.log(self._level,
                   "round %d: %d edges alive, %d unconverged (%.1f%%), "
                   "+%d closure, +%d repaired, %d dropped [%.2fs]",
                   rec["round"], rec["n_alive"], rec["n_unconverged"],
                   100.0 * frac, rec["n_closure_added"], rec["n_repaired"],
                   rec["n_dropped"], rec["round_seconds"])
        if self._jsonl_path:
            with open(self._jsonl_path, "a") as fh:
                fh.write(json.dumps(rec) + "\n")


@contextlib.contextmanager
def profiler_trace(log_dir: Optional[str]):
    """Wrap a region in a jax.profiler trace (no-op when log_dir is None)."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def phase_timer(name: str, sink: Optional[Dict[str, float]] = None,
                level: int = logging.DEBUG):
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        logger.log(level, "phase %s: %.3fs", name, dt)
        if sink is not None:
            sink[name] = sink.get(name, 0.0) + dt
