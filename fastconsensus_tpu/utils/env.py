"""Typed FCTPU_* environment-knob parsing with named errors.

Bare ``int(os.environ[...])`` raises an anonymous ValueError deep inside the
consensus driver when a knob is malformed; these helpers name the variable
and the offending value so a typo reads as a configuration error, not a
crash (ADVICE round 1).
"""

from __future__ import annotations

import os
from typing import Optional


def env_int(name: str, default: Optional[int] = None) -> Optional[int]:
    """Integer env knob; unset/empty returns ``default``."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"environment variable {name}={raw!r} is not an integer") from None


def setup_compile_cache() -> None:
    """Persistent XLA compilation cache (call before the first jax import).

    The TPU tunnel's remote-compile service costs ~20-60 s per executable;
    supervised long runs restart the process on stalls and would otherwise
    re-pay every compile.  Keyed by a host-CPU fingerprint: an XLA:CPU AOT
    executable loaded on a host with different CPU features aborts the
    process (see tests/conftest.py).  Shared by bench.py and the CLI.
    """
    import hashlib
    import os
    import sys

    try:
        with open("/proc/cpuinfo") as fh:
            flags = next(line for line in fh if line.startswith("flags"))
        tag = hashlib.sha1(flags.encode()).hexdigest()[:8]
    except (OSError, StopIteration):
        tag = "generic"
    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.expanduser(f"~/.cache/fctpu_xla_{tag}"))
    raw_secs = os.environ.get(
        "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
    try:
        min_secs = float(raw_secs)
    except ValueError:
        raise ValueError(
            "environment variable JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_"
            f"SECS={raw_secs!r} is not a number") from None
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir
    os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = raw_secs
    if "jax" in sys.modules:
        # jax reads these env vars at import time; importing anything from
        # this package pulls jax in first, so set the live config too
        # (ADVICE round 4: os.environ alone is a silent no-op here).
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_secs)
