"""Typed FCTPU_* environment-knob parsing with named errors.

Bare ``int(os.environ[...])`` raises an anonymous ValueError deep inside the
consensus driver when a knob is malformed; these helpers name the variable
and the offending value so a typo reads as a configuration error, not a
crash (ADVICE round 1).
"""

from __future__ import annotations

import os
from typing import Optional


def env_int(name: str, default: Optional[int] = None) -> Optional[int]:
    """Integer env knob; unset/empty returns ``default``."""
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"environment variable {name}={raw!r} is not an integer") from None
