"""Persisted per-backend on-device rate calibration.

Call sizing (consensus._members_per_call) and fused-block sizing need a
per-member detection-time estimate *before* anything has been measured in
the current process.  Round 2 derived it from a hardcoded
``_NS_PER_TEMP_BYTE`` table calibrated to one v5e dev tunnel — on different
hardware the first fused block or detection call could still exceed the
~60 s single-execute ceiling and wedge the worker (round-2 VERDICT Weak #5).

This module persists rates **measured by real runs** per
``(backend, move path, algorithm)`` in a small JSON file next to the XLA
compilation cache, so every later process on the same backend sizes its
first call from hardware truth; the table remains only as the
never-measured prior.  (The reference sizes nothing — its per-process pool,
``fast_consensus.py:210-211``, has no single-call ceiling to respect.)

Rates are tagged ``cold`` (measured on a from-singletons detection round)
or ``warm`` (capped-sweep warm-started rounds, ~3x faster).  First-call
sizing needs the cold rate — a fresh run's round 0 always cold-starts —
so lookups prefer ``cold`` and conservatively scale a ``warm``-only entry
by the measured cold/warm factor.

``FCTPU_CALIBRATE=0`` disables both reads and writes (the test suite sets
it: persisted rates would couple test outcomes across runs).
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
from typing import Optional

_logger = logging.getLogger("fastconsensus_tpu")

# Measured on the v5e dev tunnel: warm (capped-sweep) rounds run ~3x faster
# than the cold from-singletons round.  Used only when a backend has a warm
# measurement but no cold one yet.
COLD_OVER_WARM = 3.0

# In-process cache of the rates file (one read per process).
_cache: Optional[dict] = None
_cache_path: Optional[str] = None


def enabled() -> bool:
    return os.environ.get("FCTPU_CALIBRATE", "1") != "0"


def atomic_write_json(path: str, obj) -> bool:
    """tmp + rename JSON write; False (with a debug log) on OSError.

    Shared by every small-JSON persistence site (rates here, the detect
    chunk-sizing file in consensus.py): these files are optimizations, so
    a read-only or full filesystem must never abort the run.
    """
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   suffix=".tmp")
        with os.fdopen(fd, "w") as fh:
            json.dump(obj, fh, indent=2, sort_keys=True)
        os.replace(tmp, path)
        return True
    except OSError as e:
        _logger.debug("not persisted (%s): %s", path, e)
        return False


def _rates_path(backend: str) -> str:
    d = os.environ.get("FCTPU_CALIBRATE_DIR") or \
        os.environ.get("JAX_COMPILATION_CACHE_DIR") or \
        os.path.expanduser("~/.cache/fctpu_xla")
    return os.path.join(d, f"fctpu_rates_{backend}.json")


def _load(backend: str) -> dict:
    global _cache, _cache_path
    path = _rates_path(backend)
    if _cache is not None and _cache_path == path:
        return _cache
    try:
        with open(path) as fh:
            rates = json.load(fh)
    except (OSError, ValueError):
        # absent or corrupt calibration is the cold-start default, not
        # an error: every get_rate() answers None and callers fall back
        # to their static cost model
        rates = {}
    _cache, _cache_path = rates, path
    return rates


def get_rate(backend: str, move_path: str, alg: str) -> Optional[float]:
    """Measured ns-per-sweep-temp-byte for this backend/path/algorithm, or
    None if nothing applicable was ever measured.  The value includes the
    algorithm's full per-member cost (multi-phase detectors need no extra
    cost multiplier on top)."""
    if not enabled():
        return None
    rates = _load(backend)
    entry = rates.get(f"{move_path}/{alg}")
    if not entry:
        return None
    if entry.get("cold"):
        return float(entry["cold"])
    if entry.get("warm"):
        return float(entry["warm"]) * COLD_OVER_WARM
    return None


def update_rate(backend: str, move_path: str, alg: str, ns_per_byte: float,
                kind: str) -> None:
    """Blend a newly measured rate into the persisted file (atomic write).

    ``kind`` is "cold" or "warm" (see module docstring).  New measurements
    are averaged 50/50 with the stored value: one noisy call (a degraded
    remote-compile service, a host hiccup) must not swing first-call sizing
    by more than 2x.
    """
    if not enabled() or not ns_per_byte > 0:
        return
    global _cache
    path = _rates_path(backend)
    rates = _load(backend)
    entry = dict(rates.get(f"{move_path}/{alg}") or {})
    old = entry.get(kind)
    entry[kind] = 0.5 * (old + ns_per_byte) if old else ns_per_byte
    rates[f"{move_path}/{alg}"] = entry
    atomic_write_json(path, rates)  # failure: keep the in-process value
    _cache = rates
