"""Force the CPU backend in environments with the axon TPU plugin.

The tunnel plugin registers itself from sitecustomize at interpreter
start and hijacks backend selection even under ``JAX_PLATFORMS=cpu``
(setting env vars inside Python is too late).  This is the one canonical
copy of the workaround — tests/conftest.py and __graft_entry__ carry
historical inline variants with extra context-specific guards; new
host-side scripts should call this.

Call before the first jax backend initialization; raises loudly if a
backend is already up on something other than CPU (a silent TPU fallback
is how the round-5 policy A/B initially contended with the 100k
flagship run).
"""

from __future__ import annotations

import os


def force_cpu_backend() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    import jax

    try:
        # private API: the plugin factory table is the only place the
        # axon registration can be unhooked once sitecustomize ran
        import jax._src.xla_bridge as _xb

        if not _xb.backends_are_initialized():
            _xb._backend_factories.pop("axon", None)
            jax.config.update("jax_platforms", "cpu")
    except (ImportError, AttributeError) as exc:
        # a jax upgrade moved/renamed the private bridge module: the
        # unhook silently not happening is exactly the silent-TPU-
        # fallback failure mode this module exists to prevent, so fail
        # loudly with the fix location instead of limping on
        raise RuntimeError(
            "hostcpu.force_cpu_backend could not reach "
            "jax._src.xla_bridge to unhook the axon plugin factory — "
            f"a jax upgrade likely moved the private API ({exc}); "
            "update fastconsensus_tpu/utils/hostcpu.py for the new "
            "layout") from exc
    backend = jax.default_backend()
    if backend != "cpu":
        # not an assert: this must hold under `python -O` too — a
        # silently optimized-away check here re-opens the round-5
        # silent-TPU-contention incident
        raise RuntimeError(
            f"force_cpu_backend ran, but the jax backend is {backend!r} "
            "(a backend was already initialized before the call, or the "
            "plugin re-registered) — call force_cpu_backend before "
            "anything touches jax devices")
