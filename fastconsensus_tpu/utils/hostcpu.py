"""Force the CPU backend in environments with the axon TPU plugin.

The tunnel plugin registers itself from sitecustomize at interpreter
start and hijacks backend selection even under ``JAX_PLATFORMS=cpu``
(setting env vars inside Python is too late).  This is the one canonical
copy of the workaround — tests/conftest.py and __graft_entry__ carry
historical inline variants with extra context-specific guards; new
host-side scripts should call this.

Call before the first jax backend initialization; asserts loudly if a
backend is already up on something other than CPU (a silent TPU fallback
is how the round-5 policy A/B initially contended with the 100k
flagship run).
"""

from __future__ import annotations

import os


def force_cpu_backend() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    import jax
    import jax._src.xla_bridge as _xb

    if not _xb.backends_are_initialized():
        _xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    assert jax.default_backend() == "cpu", jax.default_backend()
