"""Synthetic benchmark graphs (host-side generators).

The reference ships one 34-node example and no generators; its validation
protocol (and BASELINE.json's eval configs) is NMI against *planted*
partitions on LFR benchmark graphs (reference ``README.md:78``, SURVEY.md §4).
These generators provide that protocol:

* :func:`planted_partition` — sparse stochastic-block-model sampler, O(E),
  usable up to the 100k-node stress config (BASELINE.json config 5);
* :func:`lfr_graph` — LFR benchmark via networkx (power-law degrees and
  community sizes, mixing parameter mu), the exact family the paper uses.

Both return ``(edges, labels)`` with compact 0-based node ids, ready for
``fastconsensus_tpu.graph.pack_edges``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def planted_partition(n: int,
                      n_comm: int,
                      p_in: float,
                      p_out: float,
                      seed: int = 0,
                      sizes: Optional[np.ndarray] = None
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Sparse planted-partition (SBM) sample.

    Nodes are split into ``n_comm`` contiguous near-equal blocks (or the
    given ``sizes``, which must sum to ``n`` — used to mimic real datasets
    with heterogeneous community sizes, e.g. the email-Eu-core stand-in);
    each intra-block pair is an edge with probability ``p_in``, inter-block
    with ``p_out``.  Sampling is done per block pair by drawing the edge
    *count* from the exact binomial and then drawing that many pairs
    uniformly (duplicates dropped), so the cost is O(E), not O(N^2) —
    required for the 100k-node configs.  The tiny downward bias from
    dropped duplicates is irrelevant for benchmarking and testing.

    Returns ``(edges int64[E, 2] with u < v, labels int64[n])``.
    """
    if not 0 <= p_out <= p_in <= 1:
        raise ValueError(f"need 0 <= p_out <= p_in <= 1, got {p_in}, {p_out}")
    rng = np.random.default_rng(seed)
    if sizes is not None:
        sizes = np.asarray(sizes, dtype=np.int64)
        if sizes.shape != (n_comm,) or sizes.sum() != n or (sizes < 1).any():
            raise ValueError(
                f"sizes must be {n_comm} positive ints summing to {n}")
        bounds = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
    else:
        bounds = np.linspace(0, n, n_comm + 1).astype(np.int64)
    labels = np.zeros(n, dtype=np.int64)
    for c in range(n_comm):
        labels[bounds[c]:bounds[c + 1]] = c

    chunks = []

    def sample_block(lo_a, hi_a, lo_b, hi_b, p, same):
        sa, sb = hi_a - lo_a, hi_b - lo_b
        n_pairs = sa * (sa - 1) // 2 if same else sa * sb
        if n_pairs <= 0 or p <= 0:
            return
        count = rng.binomial(n_pairs, p)
        if count == 0:
            return
        # rejection-free for cross blocks; rejection (u<v) for diagonal
        draw = int(count * (2.2 if same else 1.1)) + 8
        u = rng.integers(lo_a, hi_a, draw)
        v = rng.integers(lo_b, hi_b, draw)
        if same:
            keep = u < v
            u, v = u[keep], v[keep]
        pair = np.stack([np.minimum(u, v), np.maximum(u, v)], 1)
        pair = np.unique(pair, axis=0)[:count]
        chunks.append(pair)

    for a in range(n_comm):
        sample_block(bounds[a], bounds[a + 1], bounds[a], bounds[a + 1],
                     p_in, same=True)
        for b in range(a + 1, n_comm):
            sample_block(bounds[a], bounds[a + 1], bounds[b], bounds[b + 1],
                         p_out, same=False)
    if not chunks:
        raise ValueError("generated an empty graph; raise p_in/p_out")
    edges = np.unique(np.concatenate(chunks, axis=0), axis=0)
    return edges, labels


def lfr_graph(n: int,
              mu: float,
              average_degree: float = 10.0,
              min_community: int = 20,
              tau1: float = 3.0,
              tau2: float = 1.5,
              seed: int = 0,
              max_tries: int = 5
              ) -> Tuple[np.ndarray, np.ndarray]:
    """LFR benchmark graph with planted community labels.

    Wraps ``networkx.LFR_benchmark_graph`` (the generator from the LFR paper
    the reference's README cites).  The generator occasionally fails to
    converge for a given seed; we retry with successive seeds.

    Returns ``(edges int64[E, 2], labels int64[n])``.
    """
    import networkx as nx

    last_err: Optional[Exception] = None
    for t in range(max_tries):
        try:
            g = nx.LFR_benchmark_graph(
                n, tau1, tau2, mu, average_degree=average_degree,
                min_community=min_community, seed=seed + t)
            break
        except Exception as e:  # nx raises ExceededMaxIterations and others
            last_err = e
    else:
        raise RuntimeError(
            f"LFR generation failed after {max_tries} seeds: {last_err}")

    labels = np.zeros(n, dtype=np.int64)
    seen = {}
    for node in g.nodes():
        comm = frozenset(g.nodes[node]["community"])
        labels[node] = seen.setdefault(comm, len(seen))
    edges = np.array([(min(u, v), max(u, v)) for u, v in g.edges()
                      if u != v], dtype=np.int64)
    edges = np.unique(edges, axis=0)
    return edges, labels
