"""Command-line interface, flag-compatible with the reference.

    python -m fastconsensus_tpu.cli -f edgelist.txt --alg louvain -np 50 -t 0.2 -d 0.02

Flags ``-f -np -t -d --alg`` and the per-algorithm default-tau table mirror
``fast_consensus.py:414-432``; leiden is added to the tau table explicitly
(the reference silently defaults it to 0.2 via ``.get``, fc:426-428).
Extensions: ``--seed`` (single keyed PRNG tree — the reference is
reproducible only on its leiden path), ``--max-rounds`` safety cap, and
``--out-dir`` to root the output trees somewhere other than $PWD.

Outputs match the reference layout (fc:440-466): ``out_partitions_t{t}_d{d}_
np{np}/{1..n_p}`` with one community per line, and ``memberships_.../{0..}``
with 1-indexed ``node\tcommunity`` lines — written for every algorithm (the
reference writes memberships only for louvain; merged_consensus.py:319-328
writes them for all).
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time
from typing import List, Optional

DEFAULT_TAU = {"louvain": 0.2, "cnm": 0.7, "infomap": 0.6, "lpm": 0.8,
               "leiden": 0.2}
ALGORITHMS = ("louvain", "lpm", "cnm", "infomap", "leiden")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="fastconsensus-tpu",
        description="TPU-native fast consensus clustering "
                    "(Tandon et al. 2019, arXiv:1902.04014).")
    p.add_argument("-f", metavar="filename", type=str, required=True,
                   help="edgelist file: 'u v' or 'u v w' per line")
    p.add_argument("-np", dest="n_p", metavar="n_p", type=int, default=20,
                   help="number of input partitions (default: 20)")
    p.add_argument("-t", dest="tau", metavar="tau", type=float, default=None,
                   help="threshold for filtering weak edges "
                        "(default: per-algorithm table)")
    p.add_argument("-d", dest="delta", metavar="delta", type=float,
                   default=0.02,
                   help="convergence parameter (default: 0.02)")
    p.add_argument("--alg", metavar="alg", type=str, default="louvain",
                   choices=ALGORITHMS,
                   help=f"one of {', '.join(ALGORITHMS)}")
    p.add_argument("-g", dest="gamma", metavar="gamma", type=float,
                   default=1.0,
                   help="resolution parameter for modularity detectors "
                        "(the reference parses -g but ignores it, "
                        "merged_consensus.py:284-285; here it works)")
    p.add_argument("--seed", type=int, default=0,
                   help="PRNG seed for the whole run (default: 0)")
    p.add_argument("--max-rounds", type=int, default=64,
                   help="safety cap on consensus rounds (default: 64)")
    p.add_argument("--capacity", type=int, default=None, metavar="E_CAP",
                   help="initial edge-slab capacity (default: 2*E+16). The "
                        "slab self-sizes: a saturated round grows it and "
                        "replays (one recompile); pre-sizing here skips "
                        "those recompiles on dense consensus graphs. On "
                        "--resume the checkpoint's (possibly auto-grown) "
                        "capacity wins unless this asks for more")
    p.add_argument("--no-grow", action="store_true",
                   help="disable slab self-sizing; saturated rounds drop "
                        "closure candidates with a reported count (the "
                        "round-1 behavior)")
    p.add_argument("--align-frac", type=float, default=None,
                   metavar="FRAC",
                   help="unconverged-edge fraction below which detection "
                        "rounds share one PRNG key across ensemble members "
                        "(endgame tie-break alignment; 0 disables, 1 aligns "
                        "every warm round; default: engine default)")
    p.add_argument("--closure-sampler", type=str, default="auto",
                   choices=("auto", "csr", "scatter"),
                   help="triadic-closure wedge sampler: csr (single-chip "
                        "fast path), scatter (sort-free engine, required "
                        "under an edge-sharded mesh), or auto (default: "
                        "csr unsharded, scatter under a mesh)")
    p.add_argument("--closure-tau", type=float, default=None,
                   metavar="FRAC",
                   help="minimum co-membership fraction for a triadic-"
                        "closure insert (threshold-at-insert; densification "
                        "control). Default: no bar, matching the reference; "
                        "try the -t threshold value when a theta-randomized "
                        "run densifies without converging")
    p.add_argument("--cold-detect", action="store_true",
                   help="disable warm-started detection (every round "
                        "re-derives partitions from singletons, like the "
                        "reference); warm start is the default and is "
                        "usually several times faster at equal quality")
    p.add_argument("--server", type=str, default=None, metavar="URL",
                   help="submit the run to a running fcserve instance "
                        "(python -m fastconsensus_tpu.serve) instead of "
                        "executing locally: the warm server reuses "
                        "compiled executables across requests and answers "
                        "repeats from its result cache. Outputs are "
                        "written locally as usual; engine-local flags "
                        "(--checkpoint/--resume/--detect-cache/--trace/"
                        "--trace-jsonl/--profile-dir/--capacity) are "
                        "ignored")
    p.add_argument("--out-dir", type=str, default=".",
                   help="directory to create output trees in (default: .)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress per-round progress lines")
    p.add_argument("--checkpoint", type=str, default=None, metavar="PATH",
                   help="persist consensus state to PATH after every round")
    p.add_argument("--resume", action="store_true",
                   help="resume from --checkpoint if it exists")
    p.add_argument("--detect-cache", type=str, default=None, metavar="DIR",
                   help="persist completed detection chunks under DIR so a "
                        "killed run resumes mid-round (pair with "
                        "--checkpoint/--resume; use a fresh DIR per "
                        "configuration)")
    p.add_argument("--trace", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="record a runtime observability trace (fcobs): "
                        "spans for every round / detect chunk / "
                        "executable build plus host-sync and compile "
                        "counters. Writes Chrome/Perfetto trace_event "
                        "JSON to PATH (open it in ui.perfetto.dev) and a "
                        "JSONL event log to PATH.jsonl; bare --trace "
                        "defaults to fcobs_trace.json under --out-dir. "
                        "Combine with --profile-dir for one merged "
                        "host+device timeline")
    p.add_argument("--trace-jsonl", type=str, default=None, metavar="PATH",
                   help="append per-round stats records to a JSONL file")
    p.add_argument("--profile-dir", type=str, default=None, metavar="DIR",
                   help="write a jax.profiler device trace to DIR; with "
                        "--trace, fcobs spans are mirrored into it as "
                        "profiler annotations (per-round steps) and the "
                        "profiler timeline is merged into the --trace "
                        "Perfetto artifact (host-only on CPU)")
    p.add_argument("--dump-on-signal", action="store_true",
                   help="install a SIGQUIT handler that dumps an "
                        "fcflight post-mortem bundle (thread stacks, "
                        "counters, flight events, the latest consensus "
                        "round) and KEEPS RUNNING — `kill -QUIT <pid>` "
                        "answers 'what is this run doing' without "
                        "killing it; bundles land under FCTPU_FLIGHT_DIR "
                        "(supervise exports it) else ./fcflight")
    return p


def check_arguments(args) -> Optional[str]:
    """Range validation (reference check_arguments, fc:73-88)."""
    if not 0.0 <= args.delta <= 1.0:
        return f"delta {args.delta} out of range; allowed values are 0..1"
    if not 0.0 <= args.tau <= 1.0:
        return f"tau {args.tau} out of range; allowed values are 0..1"
    if args.n_p < 1:
        return f"np {args.n_p} out of range; need at least 1 partition"
    if args.max_rounds < 1:
        return "max-rounds must be >= 1"
    if args.closure_tau is not None and not 0.0 <= args.closure_tau <= 1.0:
        return (f"closure-tau {args.closure_tau} out of range; allowed "
                f"values are 0..1")
    if args.align_frac is not None and not 0.0 <= args.align_frac <= 1.0:
        # a negative value silently disables alignment and > 1 behaves as
        # 1 — surface the range like every other config error (ADVICE r3)
        return (f"align-frac {args.align_frac} out of range; allowed "
                f"values are 0..1")
    return None


def _run_remote(args) -> int:
    """``--server``: submit to a running fcserve instance and write the
    reference-layout outputs locally (jax-free client path)."""
    import numpy as np

    from fastconsensus_tpu.serve.client import (Backpressure, JobFailed,
                                                ServeClient, ServeError)
    from fastconsensus_tpu.utils.io import read_edgelist, write_partition_dirs

    try:
        edges, _, original_ids = read_edgelist(args.f)
    except (OSError, ValueError) as e:
        print(f"error reading {args.f}: {e}", file=sys.stderr)
        return 2
    client = ServeClient(args.server)
    t0 = time.perf_counter()
    try:
        sub = client.submit(edges=edges, n_nodes=len(original_ids),
                            algorithm=args.alg, n_p=args.n_p, tau=args.tau,
                            delta=args.delta, max_rounds=args.max_rounds,
                            seed=args.seed, gamma=args.gamma,
                            auto_grow=not args.no_grow,
                            warm_start=not args.cold_detect,
                            closure_sampler=args.closure_sampler,
                            **({"align_frac": args.align_frac}
                               if args.align_frac is not None else {}),
                            **({"closure_tau": args.closure_tau}
                               if args.closure_tau is not None else {}))
        result = client.wait(sub["job_id"])
    except Backpressure as e:
        print(f"error: server overloaded ({e.payload.get('error')}); "
              f"retry later", file=sys.stderr)
        return 3
    except (JobFailed, ServeError, OSError, TimeoutError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    elapsed = time.perf_counter() - t0
    partitions = [np.asarray(p, dtype=np.int32)
                  for p in result["partitions"]]
    if not args.quiet:
        state = "converged" if result.get("converged") else \
            f"max_rounds={args.max_rounds} reached"
        src = "cache" if result.get("cached") else \
            f"bucket {result.get('bucket', {}).get('key')}"
        if result.get("batch_size", 1) > 1 and not result.get("cached"):
            # the server coalesced this run with same-bucket requests
            # into one batched device call (fcserve cross-request
            # batching); surface it so the shared elapsed_s reads
            # right.  Cache hits skip it: their payload carries the
            # ORIGINAL computation's batch metadata as provenance, not
            # a batch that ran for this request.
            src += (f", coalesced x{result['batch_size']} as "
                    f"{result.get('batch_id')}")
        print(f"{state} after {result.get('rounds')} round(s) in "
              f"{elapsed:.2f}s (served from {src})", file=sys.stderr)
    suffix = f"t{args.tau}_d{args.delta}_np{args.n_p}"
    out_dir = os.path.join(args.out_dir, f"out_partitions_{suffix}")
    mem_dir = os.path.join(args.out_dir, f"memberships_{suffix}")
    write_partition_dirs(out_dir, mem_dir, partitions, original_ids)
    if not args.quiet:
        print(f"wrote {len(partitions)} partitions to {out_dir} "
              f"and {mem_dir}", file=sys.stderr)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.tau is None:
        args.tau = DEFAULT_TAU[args.alg]
    err = check_arguments(args)
    if err:
        print(err, file=sys.stderr)
        return 2

    if args.server is not None:
        # Thin-client path: no jax import at all — the resident server
        # owns the engine (serve/); this process only reads the file,
        # submits, polls, and writes the reference-layout outputs.
        return _run_remote(args)

    from fastconsensus_tpu.utils.env import setup_compile_cache

    setup_compile_cache()
    # Imports deferred so `-h` and argument errors never pay jax startup.
    from fastconsensus_tpu.consensus import ConsensusConfig, run_consensus
    from fastconsensus_tpu.graph import pack_edges
    from fastconsensus_tpu.models.registry import get_detector
    from fastconsensus_tpu.utils.io import read_edgelist, write_partition_dirs

    try:
        edges, _, original_ids = read_edgelist(args.f)
    except (OSError, ValueError) as e:
        print(f"error reading {args.f}: {e}", file=sys.stderr)
        return 2

    from fastconsensus_tpu.models.registry import supports_param

    try:
        if args.gamma != 1.0 and not supports_param(args.alg, "gamma"):
            print(f"warning: -g {args.gamma} ignored for --alg {args.alg} "
                  f"(resolution applies to modularity detectors)",
                  file=sys.stderr)
            # an ignored gamma must not poison checkpoint/detect-cache
            # fingerprints either — results are provably identical
            args.gamma = 1.0
        detector = get_detector(args.alg, gamma=args.gamma)
    except (ValueError, NotImplementedError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    try:
        slab = pack_edges(edges, n_nodes=len(original_ids),
                          capacity=args.capacity)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    extra_cfg = {}
    if args.align_frac is not None:
        extra_cfg["align_frac"] = args.align_frac
    cfg = ConsensusConfig(algorithm=args.alg, n_p=args.n_p, tau=args.tau,
                          delta=args.delta, max_rounds=args.max_rounds,
                          seed=args.seed, gamma=args.gamma,
                          auto_grow=not args.no_grow,
                          warm_start=not args.cold_detect,
                          closure_sampler=args.closure_sampler,
                          closure_tau=args.closure_tau, **extra_cfg)
    from fastconsensus_tpu.obs.device import ProfilerSession
    from fastconsensus_tpu.obs.roundlog import RoundLog

    round_log = RoundLog(jsonl_path=args.trace_jsonl)
    last_round: dict = {}
    if args.dump_on_signal:
        # fcflight for long non-serving runs: SIGQUIT dumps a bundle
        # (stacks + counters + flight ring + the run's live state) and
        # returns — the supervise stall kill sends exactly this signal
        # before its SIGKILL, so a wedged supervised run leaves evidence
        # naming the round it died in
        from fastconsensus_tpu.obs import postmortem as obs_postmortem

        def _collect() -> dict:
            return {
                "run": {
                    "file": args.f,
                    "config": dataclasses.asdict(cfg),
                    "checkpoint": args.checkpoint,
                    "resume": args.resume,
                    "detect_cache": args.detect_cache,
                },
                "rounds": {"last": dict(last_round)},
            }

        obs_postmortem.install_signal_handler(
            _collect, reason="sigquit",
            on_written=lambda path: print(
                f"fcflight bundle written to {path}", file=sys.stderr))

    def on_round(entry):
        last_round.clear()
        last_round.update(entry)
        round_log.on_round(entry)
    obs_tracer = None
    streamer = None
    trace_path = None
    if args.trace is not None:
        # fcobs span tracing (obs/): installed for the run, exported as
        # Perfetto + JSONL artifacts below.  Dormant (the no-op ambient
        # tracer) unless asked for.  With --profile-dir the tracer also
        # ANNOTATES: every span mirrors into the jax.profiler timeline
        # (TraceAnnotation / per-round StepTraceAnnotation), and the
        # profiler's trace merges into the Perfetto artifact below —
        # one timeline with aligned host and device tracks.
        from fastconsensus_tpu.obs import Tracer, get_registry, set_tracer

        # bare --trace (const ""): default filename under --out-dir; an
        # explicit PATH — even one named fcobs_trace.json — is honored
        # verbatim
        trace_path = args.trace or os.path.join(args.out_dir,
                                                "fcobs_trace.json")
        get_registry().reset()
        obs_tracer = Tracer(annotate=args.profile_dir is not None)
        set_tracer(obs_tracer)
        # the .jsonl sidecar STREAMS (one flush per round): a
        # stall-killed (SIGKILL) process leaves everything but its
        # in-flight round on disk, so supervised restarts still chain a
        # killed attempt's telemetry (supervise --rotate)
        from fastconsensus_tpu.obs.export import JsonlStreamer

        streamer = JsonlStreamer(trace_path + ".jsonl", obs_tracer)
        base_on_round = on_round

        def on_round(entry):
            base_on_round(entry)
            streamer.flush()
    t0 = time.perf_counter()
    run_ok = False
    prof = ProfilerSession(args.profile_dir)
    try:
        with prof:
            result = run_consensus(slab, detector, cfg,
                                   checkpoint_path=args.checkpoint,
                                   resume=args.resume,
                                   on_round=on_round,
                                   detect_cache_dir=args.detect_cache)
        run_ok = True
    except ValueError as e:
        # checkpoint/config mismatch (incl. a changed --capacity) or a
        # stale detect cache — an operator error, not a crash
        print(f"error: {e}", file=sys.stderr)
        return 2
    finally:
        # Export in the finally so a FAILED run still yields its (partial)
        # trace — the spans recorded up to the failure are exactly what
        # the operator debugging that run needs.
        if obs_tracer is not None:
            from fastconsensus_tpu.obs import export as obs_export
            from fastconsensus_tpu.obs import get_registry, set_tracer

            set_tracer(None)
            snapshot = get_registry().snapshot()
            events = obs_tracer.events()
            blob = obs_export.to_perfetto(events, snapshot)
            merged_note = ""
            if args.profile_dir:
                # one merge-or-stamp policy shared with bench.py: graft
                # the profiler's trace (stopped above) onto the fcobs
                # timeline, or record WHY there was nothing to graft —
                # the artifact always carries device_attribution
                from fastconsensus_tpu.obs.device import finalize_merge

                blob, info = finalize_merge(blob, prof, obs_tracer.t0)
                if info.get("merged"):
                    merged_note = (" [merged host+device]"
                                   if info.get("device_track")
                                   else " [merged, host-only profile]")
            obs_export.write_perfetto_blob(trace_path, blob)
            streamer.close(snapshot)
            if not args.quiet and run_ok:
                print(obs_export.summary_table(events, snapshot),
                      file=sys.stderr)
            partial = "" if run_ok else " (partial: the run failed)"
            print(f"fcobs trace written to {trace_path}{partial}"
                  f"{merged_note} (open in ui.perfetto.dev); event log "
                  f"at {trace_path}.jsonl", file=sys.stderr)
    elapsed = time.perf_counter() - t0

    if not args.quiet:
        for h in result.history:
            dropped = (f", {h['n_dropped']} dropped (capacity; rerun "
                       f"without --no-grow)" if h["n_dropped"] else "")
            print(f"round {h['round']}: {h['n_alive']} edges, "
                  f"{h['n_unconverged']} unconverged, "
                  f"+{h['n_closure_added']} closure, "
                  f"+{h['n_repaired']} repaired{dropped}", file=sys.stderr)
        state = "converged" if result.converged else \
            f"max_rounds={cfg.max_rounds} reached"
        print(f"{state} after {result.rounds} round(s) in {elapsed:.2f}s",
              file=sys.stderr)

    suffix = f"t{args.tau}_d{args.delta}_np{args.n_p}"
    out_dir = os.path.join(args.out_dir, f"out_partitions_{suffix}")
    mem_dir = os.path.join(args.out_dir, f"memberships_{suffix}")
    write_partition_dirs(out_dir, mem_dir, result.partitions, original_ids)
    if not args.quiet:
        print(f"wrote {len(result.partitions)} partitions to {out_dir} "
              f"and {mem_dir}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
