"""Host/CPU oracle implementations used for cross-checking and baselining."""
