"""CPU reference-equivalent consensus: the measurement + correctness oracle.

The reference cannot run in this environment (its pinned native deps —
igraph, leidenalg, python-louvain — are not installed), so this module
re-implements its louvain consensus path (reference ``fast_consensus.py:
129-201``, with the corrected semantics catalogued in SURVEY.md §2.22) on
plain networkx, using ``networkx.community.louvain_communities`` in place of
python-louvain.  Both are pure-Python Louvain over dict-of-dicts graphs, which
is where ~100% of the reference's wall time goes (SURVEY.md §3.1) — so this
is a faithful *performance* baseline and a usable *quality* oracle:

* ``bench.py`` times it to produce the measured ``vs_baseline`` ratio
  (BASELINE.md: CPU numbers "to be measured ... as step 0");
* tests compare the TPU engine's NMI against it (SURVEY.md §4
  "oracle cross-check").

Known deviation: ``louvain_communities`` returns the dendrogram's *top*
level while the reference uses level 0 (fc:148); for timing this is the
cheaper choice (we are being generous to the baseline), and for NMI oracles
the planted partition is the ground truth anyway.
"""

from __future__ import annotations

import random
import time
from typing import Dict, List, Tuple

import numpy as np


def _detect_labels(g, algorithm: str, seed: int) -> Dict[int, int]:
    """One base-detection run via the closest networkx equivalent.

    louvain/leiden -> louvain_communities (leidenalg is absent; both are
    modularity maximizers), lpm -> asyn_lpa_communities (igraph's async LPA
    counterpart), cnm -> greedy_modularity_communities (same CNM greedy
    agglomeration as igraph's community_fastgreedy), infomap -> louvain
    (networkx has no map-equation implementation; documented deviation).
    """
    import networkx as nx

    if algorithm == "lpm":
        comms = list(nx.community.asyn_lpa_communities(
            g, weight="weight", seed=seed))
    elif algorithm == "cnm":
        # greedy_modularity_communities is deterministic; the reference
        # injects ensemble randomness by randomly relabeling the graph per
        # run (fast_consensus.py:319-335) — mirror that here, else all n_p
        # ensemble members are identical and the consensus is degenerate.
        rng = random.Random(seed)
        perm = list(g.nodes())
        rng.shuffle(perm)
        fwd = {node: i for i, node in enumerate(perm)}
        relabeled = nx.relabel_nodes(g, fwd, copy=True)
        comms = [{perm[i] for i in comm}
                 for comm in nx.community.greedy_modularity_communities(
                     relabeled, weight="weight")]
    else:  # louvain / leiden / infomap
        comms = nx.community.louvain_communities(g, weight="weight",
                                                 seed=seed)
    labels: Dict[int, int] = {}
    for i, comm in enumerate(comms):
        for node in comm:
            labels[node] = i
    return labels


def cpu_consensus(edges: np.ndarray,
                  n_nodes: int,
                  n_p: int = 20,
                  tau: float = 0.2,
                  delta: float = 0.02,
                  seed: int = 0,
                  max_rounds: int = 64,
                  algorithm: str = "louvain"
                  ) -> Tuple[List[np.ndarray], int]:
    """Reference-equivalent fast consensus on networkx.

    Mirrors fast_consensus.py:129-201 (louvain path) with SURVEY.md §2.22's
    corrected semantics: proper co-membership accumulation (no
    else-misattachment, §2.22.1), working triadic-closure membership test
    (§2.22.4), singleton repair to the strongest neighbor (§2.22.11).

    Returns (n_p final label vectors as int64[n_nodes], rounds_used).
    """
    import networkx as nx

    rng = random.Random(seed)
    graph = nx.Graph()
    graph.add_nodes_from(range(n_nodes))
    graph.add_edges_from((int(u), int(v)) for u, v in edges)
    L = graph.number_of_edges()
    nx.set_edge_attributes(graph, 1.0, "weight")

    rounds = 0
    for _ in range(max_rounds):
        rounds += 1
        parts = [_detect_labels(graph, algorithm, rng.randrange(2**31))
                 for _ in range(n_p)]
        nextgraph = graph.copy()
        # co-membership counts restricted to existing edges (fc:150-159)
        for u, v in graph.edges():
            w = graph[u][v]["weight"]
            if w != n_p:  # skip already-converged edges (nc:157-163)
                w = sum(1.0 for p in parts if p[u] == p[v])
            nextgraph[u][v]["weight"] = w
        # tau-threshold (fc:163-168)
        nextgraph.remove_edges_from(
            [(u, v) for u, v, w in nextgraph.edges(data="weight")
             if w < tau * n_p])
        mid = sum(1 for _, _, w in nextgraph.edges(data="weight")
                  if 0 < w < n_p)
        if mid <= delta * max(nextgraph.number_of_edges(), 1):
            graph = nextgraph
            break
        # triadic closure: L wedge samples (fc:175-191)
        nodes = list(nextgraph.nodes())
        for _ in range(L):
            anchor = rng.choice(nodes)
            nbrs = list(nextgraph[anchor])
            if len(nbrs) < 2:
                continue
            a, b = rng.sample(nbrs, 2)
            if not nextgraph.has_edge(a, b):
                w = sum(1.0 for p in parts if p[a] == p[b])
                nextgraph.add_edge(a, b, weight=w)
        # singleton repair to the strongest previous neighbor (§2.22.11)
        for node in list(nx.isolates(nextgraph)):
            if graph.degree(node) == 0:
                continue
            best = max(graph[node].items(),
                       key=lambda kv: kv[1].get("weight", 1.0))
            nextgraph.add_edge(node, best[0], weight=best[1].get("weight", 1.0))
        graph = nextgraph

    final = []
    for _ in range(n_p):
        labels = _detect_labels(graph, algorithm, rng.randrange(2**31))
        # fcheck: ok=sync-in-loop (pure-host numpy oracle; no device arrays)
        final.append(np.array([labels.get(i, 0) for i in range(n_nodes)],
                              dtype=np.int64))
    return final, rounds


def time_cpu_consensus(edges: np.ndarray, n_nodes: int, **kw
                       ) -> Tuple[float, List[np.ndarray], int]:
    """Wall-clock one full CPU consensus run.  Returns (seconds, partitions,
    rounds)."""
    t0 = time.perf_counter()
    parts, rounds = cpu_consensus(edges, n_nodes, **kw)
    return time.perf_counter() - t0, parts, rounds
