"""The consensus engine: the reference's ``while True`` loop, TPU-style.

One consensus round (reference ``fast_consensus.py:138-201``) becomes a
single jitted function over the static-shape GraphSlab:

    detect (vmapped over n_p keys)          fc:148 / :211 / :268-270 / :324-335
    -> co-membership counts per edge        fc:150-159
    -> tau-threshold                        fc:163-168
    -> convergence check                    fc:172 (-> fc:17-37)
    -> triadic closure (skipped if converged)  fc:175-191
    -> singleton repair                     fc:193-195
    -> convergence check                    fc:201

The outer loop runs on the host — a handful of rounds, one compiled step, one
scalar readback per round (the `converged` flag + round stats).  On
convergence the base algorithm runs n_p final times on the consensus graph
(fc:383-411); that list of partitions is the product.

Deliberate deviations from the reference, all catalogued in SURVEY.md §2.22:
corrected co-membership accumulation (no else-misattachment), singleton
repair to the *strongest* previous neighbor, one keyed PRNG tree, and a
``max_rounds`` safety cap (the reference can loop forever).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fastconsensus_tpu import policy, sizing
from fastconsensus_tpu.graph import GraphSlab, pack_edges
from fastconsensus_tpu.models.base import Detector
from fastconsensus_tpu.obs import counters as obs_counters
from fastconsensus_tpu.obs.tracer import get_tracer
from fastconsensus_tpu.utils import prng
from fastconsensus_tpu.utils.env import env_int

_logger = logging.getLogger("fastconsensus_tpu")


@dataclasses.dataclass(frozen=True)
class ConsensusConfig:
    """Run parameters; mirrors the reference CLI surface (fc:416-428)."""

    algorithm: str = "louvain"
    n_p: int = 20
    tau: float = 0.2          # threshold: drop edges with weight < tau * n_p
    delta: float = 0.02       # convergence: frac of edges allowed mid-weight
    max_rounds: int = 64      # safety cap (reference loops unboundedly)
    seed: int = 0
    # Detector hyper-parameters that change results (currently the
    # resolution parameter -g).  Part of the config so checkpoint/
    # detect-cache fingerprints reject mixing runs across values.  Used for
    # fingerprinting ONLY — it must equal the gamma the detector passed to
    # run_consensus was built with (the CLI and fast_consensus keep the two
    # in lockstep; hand-built pairs are the caller's responsibility).
    gamma: float = 1.0
    # Self-sizing slab: when a round drops closure/repair survivors for
    # capacity, grow the slab and deterministically replay that round so
    # nothing is lost (one recompile per growth).  Off = report-and-continue
    # (the round 1 behavior; candidates are dropped with a counter).
    auto_grow: bool = True
    # Warm-start detection: seed each round's (and the final) detection from
    # the previous round's labels.  The consensus graph changes little
    # between rounds, so warm members converge in a few sweeps AND keep
    # their tie-degenerate choices stable across rounds, cutting both
    # per-round sweep count and the number of consensus rounds (the
    # reference re-runs every detection from scratch, fc:148 — its
    # libraries offer no warm path).  Ignored by detectors that do not
    # support initialization (native CNM/Infomap).
    warm_start: bool = True
    # Endgame member alignment: once a round ends with an unconverged-edge
    # fraction below this, subsequent detection rounds share ONE PRNG key
    # across all ensemble members (instead of n_p independent keys).
    # Tie-break jitter is content-keyed on (node, community-representative)
    # — member-invariant, models/louvain._community_reps — so members
    # facing the same modularity-degenerate choice break it identically,
    # collapsing exactly the residual disagreements that otherwise grind
    # for rounds (round-1: 5 rounds on planted-100k vs 1 for the
    # near-deterministic CPU reference).  Only active with warm_start
    # (aligned COLD members would be identical clones — a single run in
    # disguise); the independent singleton-start round provides the
    # ensemble's diversity, and members keep their label-structure
    # differences through aligned rounds.  The final re-detection is never
    # aligned, and the singleton-start round never aligns.  Fused round
    # blocks re-derive the flag per round from their own stats, so fused
    # and per-round execution stay bit-identical.  Detectors without
    # content-keyed tie-breaks (supports_align unset: lpm, native
    # cnm/infomap) ignore it.  0 disables.  Default 1.0 — align EVERY
    # warm round: measured head-to-head on lfr10k/leiden (BASELINE.md
    # round 3), full alignment held consensus quality at the cold
    # engine's level (NMI 0.524 vs 0.525) while threshold-0.4 alignment,
    # which lets members accumulate uncorrelated densification noise for
    # the first rounds, ended at 0.482.
    align_frac: float = 1.0
    # Triadic-closure wedge sampler.  "csr": per-round CSR build (one
    # argsort of the directed edges) + uniform anchor draws — the fastest
    # single-chip lowering (the round-3 sort-free engine cost a measured
    # 1.6x on emailEu/CPU, BASELINE.md r3).  "scatter": the sort-free
    # batched partner-draw engine (ops/consensus_ops.py
    # sample_wedges_scatter) — required under an edge-sharded mesh, where
    # the CSR argsort would re-gather the whole slab every round.  "auto"
    # (default): csr when unsharded, scatter under a mesh.  The two
    # samplers draw different (equally valid) wedges, so sharded and
    # unsharded runs are bitwise-comparable only when this is pinned to
    # "scatter" (tests/test_parallel.py parity tests do exactly that).
    closure_sampler: str = "auto"
    # Threshold-at-insert for triadic closure: a closure candidate is
    # inserted only if its co-membership weight is >= closure_tau * n_p
    # (None disables — the reference's semantics, fc:175-191, which
    # inserts any-weight closure edges and lets the NEXT round's tau
    # threshold kill the weak ones after they influenced one detection
    # round).  Densification control (VERDICT r3 Missing #1): on
    # theta-randomized leiden, closure inserts ~30k candidates/round of
    # which ~20k earn partial agreement and stick, densifying the
    # consensus graph faster than members can agree — delta-convergence
    # became unreachable on lfr10k/mu0.5.  Setting closure_tau = tau
    # drops the sub-threshold inserts one round early (cheaper, nearly
    # equivalent: a warm ensemble's counts barely change between rounds).
    closure_tau: Optional[float] = None


# The jitted engine itself (one round, fused blocks, chunked detection)
# lives in engine.py; these names are re-exported here because they are the
# public API surface this module historically carried (tests, graft entry,
# sharded_tail all import them from fastconsensus_tpu.consensus).
from fastconsensus_tpu.engine import (  # noqa: E402,F401
    RoundStats, _detect_chunked, _jitted_detect, _jitted_detect_batch,
    _jitted_round, _jitted_rounds_batch, _jitted_rounds_block,
    _jitted_tail, consensus_batch_block, consensus_round,
    consensus_rounds_block, consensus_tail)


def _resume_from_checkpoint(checkpoint_path: str, slab: GraphSlab,
                            config: ConsensusConfig, warm: bool,
                            sampler: str, key: jax.Array):
    """Load and validate a checkpoint for ``run_consensus``.

    Returns ``(slab, start_round, key, prior_history, cur_labels,
    measured_member_s, resumed_converged, sampler, saved_counters)``
    where ``saved_counters`` is the checkpoint's fcobs counter snapshot
    ({} when absent) — already delta-restored into the live registry for
    display, and handed back so later checkpoints can persist
    ``saved + this-process increments`` (run-scoped, immune to counts an
    unrelated earlier run left in the process registry).  Rejects checkpoints
    from a different run configuration: resuming a tau/n_p/algorithm/graph
    mismatch would silently mix semantics (weights are co-membership
    counts out of the *saved* n_p).
    """
    from fastconsensus_tpu.utils import checkpoint as ckpt

    in_nodes, in_cap = slab.n_nodes, slab.capacity
    in_hyb, in_hub = slab.d_hyb, slab.hub_cap
    slab, start_round, key_data, prior_history, extra = \
        ckpt.load_checkpoint(checkpoint_path)
    if extra.pop("_legacy_v1", False) and (in_hyb or in_hub):
        # v1 checkpoints predate hybrid sizing in the metadata; loading
        # them with d_hyb=0 would flip select_move_path hybrid -> hash
        # on resume (different lowering => different labels).  The
        # sizing is a deterministic function of the input degree
        # histogram, so the caller's freshly packed slab carries the
        # original run's exact values — inherit them.
        _logger.info(
            "migrating v1 checkpoint: restoring hybrid sizing "
            "d_hyb=%d hub_cap=%d from the input pack", in_hyb, in_hub)
        slab = dataclasses.replace(slab, d_hyb=in_hyb, hub_cap=in_hub)
    if extra.get("closure_sampler") is None:
        # pre-r4 checkpoints predate the sampler knob; every such run
        # used the scatter engine.  Continuing under "auto" must keep
        # drawing the wedges the run was started with (an explicit
        # --closure-sampler csr still fails the mismatch check below).
        extra["closure_sampler"] = "scatter"
        if config.closure_sampler == "auto":
            _logger.info(
                "checkpoint predates closure_sampler; continuing with "
                "the scatter engine it was written with")
            sampler = "scatter"
    saved_counters = extra.pop("_telemetry", None) or {}
    cur_labels = None
    if warm and extra.get("_labels") is not None:
        cur_labels = jnp.asarray(extra["_labels"])
    measured_member_s = extra.get("member_seconds") or None
    key = jax.random.wrap_key_data(jnp.asarray(key_data))
    saved = {k: extra.get(k) for k in
             ("algorithm", "n_p", "tau", "delta", "gamma",
              "warm_start", "align_frac", "closure_sampler")}
    # Pre-r4 checkpoints predate the closure_tau knob, but the historical
    # value is known: every such run inserted with no bar, so backfill
    # None (mirrors the closure_sampler migration above) and reject a
    # resumed bar — mixing unbarred and barred insert semantics in one
    # run is exactly what this check exists to prevent (ADVICE round 4).
    ctau_migrated = "closure_tau" not in extra
    extra.setdefault("closure_tau", None)
    if extra["closure_tau"] != config.closure_tau:
        if ctau_migrated:
            # be precise about provenance: the None is a checkpoint-
            # format migration default, not a value read from the file
            raise ValueError(
                f"checkpoint {checkpoint_path} predates the closure_tau "
                f"knob (checkpoint-format migration backfills "
                f"closure_tau=None: such runs inserted with no bar); "
                f"resuming with {config.closure_tau} would mix insert "
                f"semantics")
        raise ValueError(
            f"checkpoint {checkpoint_path} was written with closure_tau="
            f"{extra['closure_tau']}; resuming with "
            f"{config.closure_tau} would mix insert semantics")
    want = {"algorithm": config.algorithm, "n_p": config.n_p,
            "tau": config.tau, "delta": config.delta,
            "gamma": config.gamma, "warm_start": config.warm_start,
            "align_frac": config.align_frac,
            "closure_sampler": sampler}
    mismatch = {k: (saved[k], want[k]) for k in want
                if saved[k] is not None and saved[k] != want[k]}
    if slab.n_nodes != in_nodes:
        mismatch["graph"] = (slab.n_nodes, in_nodes)
    elif slab.capacity < in_cap:
        # The caller asked for more room than the checkpoint has
        # (e.g. --capacity raised after watching growth recompiles):
        # honor it — growth is result-preserving (graph.grow_slab).
        from fastconsensus_tpu.graph import grow_slab

        _logger.info("growing resumed slab capacity %d -> %d to honor "
                     "the requested pack size", slab.capacity, in_cap)
        slab = grow_slab(slab, in_cap)
    elif slab.capacity > in_cap:
        # Legitimate trace of mid-run auto-growth; keep it.
        _logger.info("resuming with auto-grown slab capacity %d "
                     "(freshly packed: %d)", slab.capacity, in_cap)
    if mismatch:
        raise ValueError(
            f"checkpoint {checkpoint_path} was written by a different "
            f"run configuration: {mismatch} (saved, requested)")
    if saved_counters:
        # Telemetry continuity: raise the process-global counters to at
        # least the dead process's checkpointed totals (delta restore —
        # an in-process re-resume that already holds the counts adds
        # nothing), so summaries/artifacts of the resumed run report
        # cumulative counts across the whole run, not this process.
        # Restored only AFTER every validation above: a REJECTED resume
        # must not leak the dead run's counts into the live registry.
        applied = obs_counters.get_registry().restore_counters(
            saved_counters)
        if applied:
            _logger.info(
                "restored %d fcobs counter(s) from checkpoint telemetry "
                "(cumulative across restarts; rounds.total now %d)",
                len(applied),
                obs_counters.get_registry().counters().get(
                    "rounds.total", 0))
    resumed_converged = bool(extra.get("converged", False))
    return (slab, start_round, key, prior_history, cur_labels,
            measured_member_s, resumed_converged, sampler, saved_counters)


def _validate_config(config: ConsensusConfig) -> None:
    """Shared range/enum validation for the solo and batch drivers —
    ONE implementation so the two paths can never drift into accepting
    different configs (the batch path's parity contract presumes the
    same config means the same behavior)."""
    if config.closure_sampler not in ("auto", "csr", "scatter"):
        raise ValueError(
            f"closure_sampler={config.closure_sampler!r}: expected "
            f"'auto', 'csr' or 'scatter'")
    if config.closure_tau is not None and \
            not 0.0 <= config.closure_tau <= 1.0:
        raise ValueError(
            f"closure_tau={config.closure_tau} out of range; allowed "
            f"values are 0..1 (or None to disable)")
    if not 0.0 <= config.align_frac <= 1.0:
        # out-of-range values would silently disable (or saturate)
        # alignment (ADVICE r3)
        raise ValueError(
            f"align_frac={config.align_frac} out of range; allowed "
            f"values are 0..1")


class ConsensusResult(NamedTuple):
    partitions: List[np.ndarray]   # n_p final label vectors, compact ids
    graph: GraphSlab               # converged consensus graph
    rounds: int
    converged: bool
    history: List[dict]            # per-round stats (observability, §5)


def run_consensus(slab: GraphSlab,
                  detect: Detector,
                  config: ConsensusConfig,
                  key: Optional[jax.Array] = None,
                  mesh=None,
                  checkpoint_path: Optional[str] = None,
                  checkpoint_every: int = 1,
                  resume: bool = False,
                  on_round=None,
                  detect_cache_dir: Optional[str] = None,
                  n_closure: Optional[int] = None,
                  init_labels=None,
                  active_mask=None) -> ConsensusResult:
    """Host-side driver: iterate jitted rounds to delta-convergence.

    With ``mesh`` (a ``jax.sharding.Mesh`` from parallel/sharding.py) the
    ensemble axis shards over the mesh's ``"p"`` axis and the edge slab over
    its ``"e"`` axis; XLA's SPMD partitioner inserts the collectives.  The
    reference's scale-out story is a fork+pickle process pool on one path
    only (fc:210-211); here every algorithm shards identically.

    ``checkpoint_path`` persists the consensus state every
    ``checkpoint_every`` rounds (utils/checkpoint.py); with ``resume=True``
    an existing checkpoint restarts the loop where it left off (the reference
    loses everything on interruption, SURVEY.md §5).  ``on_round`` is an
    observability hook called with each round's stats dict (utils/trace.py).

    ``detect_cache_dir``: finer-grained elastic recovery for split-phase
    runs — each completed detection chunk persists under this directory
    (tagged with a config+seed fingerprint and the round), so a killed and
    restarted process (same config/seed, ``resume=True`` + checkpoint for
    the round state) re-detects only unfinished chunks.  Pair with
    ``checkpoint_path``; clean the directory between unrelated runs.

    ``n_closure``: override for the per-round wedge-sample count L
    (default: the slab's alive edge count, the reference's ``L = |E0|``,
    fc:175).  L is a *static* shape of every jitted round executable, so
    the serving layer (serve/bucketer.py) passes the bucket-canonical
    edge class here — distinct graphs padded into one size bucket then
    share executables instead of each compiling its own round over its
    own exact edge count.

    ``init_labels`` / ``active_mask`` (fcdelta, serve/delta.py): seed the
    whole run from a PRIOR run's final partitions (``init_labels``
    [n_p, n_nodes] int32) instead of the singleton cold start — round 0
    runs the capped-sweep warm detector, exactly like a checkpoint-resumed
    round — and optionally restrict re-consensus to the vertices inside
    ``active_mask`` (bool[n_nodes]): vertices outside it keep their
    init labels through every round AND through the final re-detection
    (host-side clamp — no extra executables).  Both are traced inputs of
    the same fused-block executable full runs compile, so an incremental
    re-run after a full run on the same bucket compiles nothing.
    Requires a warm-capable detector (``warm_start`` on +
    ``supports_init``); incompatible with ``mesh`` and with
    checkpoint/resume.
    """
    if key is None:
        key = jax.random.key(config.seed)
    # fcobs: the ambient tracer (a no-op singleton unless the caller set
    # one — cli.py --trace) and the always-on counter registry.  Both are
    # host-side dict/list work; with tracing disabled the per-round cost
    # is a handful of attribute checks (the <2% bench contract, ISSUE 2).
    tracer = get_tracer()
    obs_reg = obs_counters.get_registry()
    if n_closure is None:
        n_closure = int(slab.num_alive())  # L := |E0|, static across rounds
    n_closure = int(n_closure)
    _validate_config(config)
    # Resolved wedge-sampling lowering (ConsensusConfig.closure_sampler):
    # an edge-sharded mesh requires the sort-free engine; single-chip runs
    # default to the CSR fast path.
    if config.closure_sampler == "csr" and mesh is not None:
        raise ValueError(
            "closure_sampler='csr' is incompatible with a mesh: the CSR "
            "argsort re-gathers the edge-sharded slab every round; use "
            "'auto' or 'scatter'")
    sampler = "scatter" if mesh is not None else (
        "csr" if config.closure_sampler == "auto" else
        config.closure_sampler)
    warm = config.warm_start and getattr(detect, "supports_init", False)
    # fcdelta masked warm-start entry: validate before any device work
    if active_mask is not None and init_labels is None:
        raise ValueError("active_mask requires init_labels (the frozen "
                         "vertices' labels come from the parent run)")
    if init_labels is not None:
        if not warm:
            raise ValueError(
                "init_labels requires warm_start=True and a detector "
                "with supports_init (the warm ensemble IS the reuse)")
        if mesh is not None:
            raise ValueError("init_labels is not supported with a mesh")
        if checkpoint_path is not None or resume:
            raise ValueError("init_labels is incompatible with "
                             "checkpoint/resume (two competing notions "
                             "of 'where the run starts')")
        init_labels = np.asarray(init_labels, np.int32)
        if init_labels.shape != (config.n_p, slab.n_nodes):
            raise ValueError(
                f"init_labels shape {init_labels.shape} != "
                f"{(config.n_p, slab.n_nodes)} (n_p, n_nodes)")
    active_np: Optional[np.ndarray] = None
    if active_mask is not None:
        active_np = np.asarray(active_mask, bool)
        if active_np.shape != (slab.n_nodes,):
            raise ValueError(f"active_mask shape {active_np.shape} != "
                             f"({slab.n_nodes},)")
    # Endgame alignment only for detectors whose tie-breaks are
    # content-keyed (louvain._community_reps): without that, sharing keys
    # merely strips the ensemble's key diversity with no collapse mechanism
    # (label-id-keyed jitter differs per member regardless of the key).
    align_ok = getattr(detect, "supports_align", False)
    # Capped-sweep variant for warm rounds (louvain.warm_sweep_budget):
    # under the ensemble vmap the sweep loop runs to the slowest member, so
    # warm rounds must *bound* sweeps to realize the warm-start savings.
    detect_warm = (getattr(detect, "warm_variant", None) or detect) \
        if warm else detect
    # Stagnation refreshes use a LOW-VARIANCE full-sweep variant when the
    # detector provides one (leiden: theta=0 — theta-resampling on every
    # refresh would re-inject the cross-member variance the refresh exists
    # to burn down; see models/leiden.py).
    detect_refresh = getattr(detect, "refresh_variant", None) or detect
    # Last successful round's labels [n_p, N] (device-resident); None until
    # the first round completes.  Seeds warm detection and the final
    # re-detection; persisted in checkpoints so resume stays bit-identical.
    cur_labels: Optional[jax.Array] = None

    # On-device call-rate measurement: None until the first chunked
    # detection round reports timings; persisted in checkpoints so a
    # resumed process derives the same chunking (and thus hits the same
    # detect-cache files) as the run it resumes.  measured_in_process
    # distinguishes a rate THIS process measured from one restored out of a
    # checkpoint: re-sizing may only act on the former — a checkpointed
    # rate can be older than the in-flight round's persisted chunks
    # (checkpoint_every > 1), and re-sizing from it would override the
    # sizing.json adoption and orphan them (round-3 review).
    measured_member_s: Optional[float] = None
    measured_in_process = False

    if resume and checkpoint_path is not None and \
            os.path.exists(checkpoint_path):
        (slab, start_round, key, prior_history, cur_labels,
         measured_member_s, resumed_converged, sampler, saved_counters) = \
            _resume_from_checkpoint(checkpoint_path, slab, config, warm,
                                    sampler, key)
    else:
        start_round = 0
        prior_history = []
        resumed_converged = False
        saved_counters = {}
        # weights <- 1.0 at loop start (fc:135-136); input weights are
        # ignored, matching the reference (documented in utils/io.py).
        slab = slab.with_weights(jnp.where(slab.alive, 1.0, 0.0))
    if init_labels is not None:
        # fcdelta warm-start: the run begins where the parent run ended —
        # the same posture as a labels-bearing checkpoint resume, so
        # cold_start_round below becomes -1 and round 0 runs the
        # capped-sweep warm variant instead of the singleton cold start
        cur_labels = jnp.asarray(init_labels, jnp.int32)
    # Run-scoped telemetry base (taken AFTER any resume restore): a
    # checkpoint persists saved_counters + the increments since here, so
    # counts an unrelated earlier run left in the process-global registry
    # never leak into this run's checkpoint metadata.
    obs_base = obs_reg.counters()

    def run_telemetry() -> dict:
        out = dict(saved_counters)
        for k, v in obs_reg.counters_since(obs_base).items():
            out[k] = out.get(k, 0) + v
        return out

    ensemble_sharding = None
    if mesh is not None:
        from fastconsensus_tpu.parallel import sharding as shard

        if config.n_p % mesh.shape[shard.ENSEMBLE_AXIS]:
            # Uneven ensemble axes are not silently tolerable: device_put
            # rejects them and GSPMD re-shards behind your back (verified),
            # and round 1's warn-and-run-unsharded left long multi-chip
            # runs quietly single-chip (VERDICT #4).  Fail with the fixes.
            raise ValueError(
                f"n_p={config.n_p} is not divisible by the mesh ensemble "
                f"axis ({mesh.shape[shard.ENSEMBLE_AXIS]}); choose an "
                f"ensemble axis that divides n_p, or round n_p up with "
                f"parallel.pad_n_p")
        slab = shard.shard_slab(slab, mesh)
        ensemble_sharding = shard.keys_sharding(mesh)

    members = 0
    cache_fp = ""
    split_phase = False
    fused_block = 1
    block_fn = round_fn = None

    def mesh_rounded(m: int) -> int:
        """Round a per-call member count up to tile the mesh ensemble axis:
        chunked detection under a mesh must device_put whole-axis chunks
        (round 1 disabled split-phase — and with it mid-round elastic
        recovery — on exactly the long multi-chip runs that need it most,
        VERDICT #4/#6)."""
        if ensemble_sharding is None or m >= config.n_p:
            return m
        from fastconsensus_tpu.parallel import sharding as shard

        p_axis = mesh.shape[shard.ENSEMBLE_AXIS]
        return min(config.n_p, -(-m // p_axis) * p_axis)

    def derive_sizing(force_members: Optional[int] = None
                      ) -> Tuple[int, bool, int]:
        """(members, split_phase, fused_block) from the current slab and the
        best per-member rate known (this run's measurement, a persisted
        backend calibration, or the static prior — in that order).
        ``force_members`` pins the member count (chunk-cache adoption)."""
        m = force_members if force_members is not None else mesh_rounded(
            sizing.members_per_call(
                slab, config.n_p, detect, measured_s=measured_member_s,
                alg=config.algorithm))
        sp = m < config.n_p
        # Fused-rounds mode: when a whole round is cheap (small graphs, no
        # sharded mesh, no per-round checkpointing), run blocks of rounds
        # in a single device call — the per-round dispatch + stats-readback
        # latency through the TPU tunnel otherwise dominates the driver
        # loop.  Block size targets ~15 s per call; 1 disables fusion.
        fb = 1
        if not sp and checkpoint_path is None and mesh is None:
            fb_env = env_int("FCTPU_ROUNDS_BLOCK")
            if fb_env is not None:
                # pinned block size: the block count is part of the
                # compiled executable's shape, and rate-adaptive fusion
                # re-sizes (recompiles) when measurements drift — fine
                # for one long run, a compile hazard for a resident
                # server cycling heterogeneous requests through shared
                # bucket executables (serve/server.py pins this)
                fb = max(1, min(8, fb_env))
            else:
                round_s = (measured_member_s * config.n_p
                           if measured_member_s else
                           sizing.est_member_seconds(slab, detect,
                                                     config.algorithm)
                           * config.n_p)
                fb = max(1, min(8, int(15.0 / max(round_s, 1e-9))))
        return m, sp, fb

    def setup_executables() -> None:
        """(Re-)derive call sizing and jitted step functions from the
        current slab.  Rerun after auto-growth — capacity is part of the
        compiled shapes, so growth costs one recompile here.  Span- and
        counter-wrapped (fcobs): every recompile-bearing rebuild is
        visible in the trace instead of reading as a mystery stall."""
        with tracer.span("setup_executables"):
            obs_reg.inc("engine.setup_executables")
            _setup_executables()

    def _setup_executables() -> None:
        nonlocal members, cache_fp, split_phase, fused_block
        nonlocal block_fn, seen_execs, first_setup
        # Sized AFTER checkpoint resume: the loaded slab's d_cap can differ
        # from the caller's repack (the resume check matches
        # n_nodes/capacity only), and d_cap drives the move-path/time
        # estimate.  shard_slab only pads capacity by < mesh_edge_axis
        # entries, so the estimate carries over to the sharded slab.
        fp_base = ""
        if detect_cache_dir:
            import hashlib

            os.makedirs(detect_cache_dir, exist_ok=True)
            # The fingerprint guards mixing runs: max_rounds guards the
            # `_final` tag (a capped run's final detection is of a
            # different consensus graph); gamma (detector hyper-parameter)
            # guards rerunning with a different -g against the same dir —
            # shape checks cannot catch that.  Live capacity is
            # deliberately absent: labels are capacity-independent
            # (louvain._cap_hint), so auto-growth must not retire a
            # round's already-detected chunks; cap_hint covers the
            # pack-time sizing instead.  The mesh shape IS included: an
            # adopted member count must tile the current ensemble axis.
            fp_base = hashlib.sha1(repr(
                (config.algorithm, config.n_p, config.tau, config.delta,
                 config.seed, config.max_rounds, slab.n_nodes,
                 slab.cap_hint or slab.capacity, slab.agg_cap,
                 # the candidate budgets select the move lowering, and
                 # labels depend on the lowering.  In-run they are a pure
                 # function of (history, graph) so a killed-and-restarted
                 # process re-derives them identically — but a CODE change
                 # to the derivation between attempts (the live-tree
                 # import hazard, BASELINE.md) must orphan the chunks,
                 # not silently mix lowerings within one round.
                 slab.d_cap, slab.d_hyb, slab.hub_cap,
                 config.gamma, warm,
                 config.align_frac, sampler, config.closure_tau,
                 tuple(mesh.shape.items()) if mesh is not None else None)
            ).encode()).hexdigest()[:10]
        forced = None
        if fp_base and first_setup and \
                env_int("FCTPU_DETECT_CALL_MEMBERS") is None:
            # A restarted process must reuse the killed run's chunking even
            # though first-call sizing consults the mutable calibration
            # file (utils/calibrate.py — possibly written by the killed
            # run itself) or a checkpointed rate older than the in-flight
            # round's chunks (checkpoint_every > 1): a different member
            # count changes cache_fp and would orphan every
            # already-persisted chunk of the round.  The sizing actually
            # used is persisted next to the chunks and adopted on the
            # process's FIRST setup only — later setups exist to change
            # sizing (growth, measured re-sizes) and overwrite the file.
            prev = sizing.read_sizing(detect_cache_dir)
            if prev is not None and prev.get("fp") == fp_base:
                forced = int(prev["members"])
        members, split_phase, fused_block = derive_sizing(forced)
        seen_execs = set()
        cache_fp = ""
        if fp_base:
            # members is part of the chunk fingerprint: a retry with a
            # different chunking (the natural response to tunnel trouble)
            # must not load mis-sized chunks.
            cache_fp = hashlib.sha1(repr(
                (fp_base, members)).encode()).hexdigest()[:10]
            sizing.write_sizing(detect_cache_dir, fp_base, members)
        first_setup = False
        block_fn = None
        if fused_block > 1:
            block_fn = _jitted_rounds_block(
                detect, detect_warm, detect_refresh, config.n_p,
                config.tau, config.delta, n_closure, fused_block, warm,
                config.align_frac if (warm and align_ok) else 0.0,
                sampler, config.closure_tau)

    # Executable identities that already ran at least once since the last
    # setup: their next call is compile-free, so its wall time is an honest
    # rate measurement.  Keyed by detector object (the warm variant is a
    # DIFFERENT executable whose first call pays its own compile — round-3
    # review) or the "block" sentinel.
    seen_execs: set = set()
    first_setup = True
    setup_executables()

    def record_rate(member_s: float, cold: bool, call_s: float) -> None:
        """Persist the measured per-member rate for this backend so later
        processes size their *first* call from hardware truth
        (utils/calibrate.py; round-2 VERDICT Weak #5).

        ``call_s`` is the wall time of the device call the rate came from:
        short calls are dominated by host-device dispatch/readback latency
        (through the TPU tunnel a near-empty round still costs ~0.5 s) and
        would poison the per-byte rate for every other config on the
        backend, so they are not persisted.  In-run sizing still uses them
        (measured_member_s) — there the latency is part of the real cost
        of the call being sized.
        """
        if call_s < sizing.MIN_PERSIST_CALL_S:
            return
        from fastconsensus_tpu.models import louvain
        from fastconsensus_tpu.utils import calibrate

        calibrate.update_rate(
            jax.default_backend(), louvain.select_move_path(slab),
            config.algorithm,
            member_s / sizing.member_temp_bytes(slab) * 1e9,
            "cold" if cold else "warm")

    def maybe_resize() -> None:
        """Between-round re-sizing from measured rates.  Only ever called at
        the top of a loop iteration — a mid-round setup_executables() nulls
        the executables the round in flight still needs (round-2 ADVICE
        high).  Hysteresis on the fused-block size: a recompile through the
        TPU tunnel costs ~35-55 s, so only act when the current sizing is
        unsafe (estimated call > 30 s — the tunnel kills ~60 s executes) or
        leaves a >= 2x fusion win on the table.  Acts only on rates this
        process measured itself (see measured_in_process above)."""
        if not measured_in_process:
            return
        m, sp, fb = derive_sizing()
        unsafe = fused_block > 1 and \
            measured_member_s * config.n_p * fused_block > 30.0
        if (sp != split_phase) or (sp and m != members) or unsafe or \
                fb >= 2 * fused_block or 2 * fb <= fused_block:
            _logger.info(
                "re-sizing executables from measured %.3fs/member: "
                "members %d -> %d, fused block %d -> %d",
                measured_member_s, members, m, fused_block, fb)
            setup_executables()

    def round_mode(r0: int) -> str:
        """"cold" (round-0 / cold-run full-sweep base detector),
        "refresh" (warm-stagnation full-sweep low-variance refresh), or
        "warm" (capped-sweep warm variant).

        The stall/stale/align rules live ONCE in ``policy`` (division-free
        f32, evaluated here with numpy and inside the fused block with
        jnp — fused and per-round execution must take identical
        decisions).  Alignment earns a gentler one-step threshold but does
        NOT suppress the stall rule, and the stale (limit-cycle) rule
        fires regardless of alignment — the measurements behind both are
        on the policy module."""
        if not warm or r0 == cold_start_round:
            return "cold"
        if bool(policy.stale(np, config.delta, pstate)):
            _logger.warning(
                "warm limit cycle (no new unconverged-fraction minimum "
                "in %d rounds): round %d re-detects cold",
                policy.STALE_ROUNDS, r0)
            return "refresh"
        if bool(policy.stalled(np, config.delta, pstate, align_now(r0))):
            _logger.warning(
                "warm stagnation (unconverged %d -> %d): round %d "
                "re-detects cold", int(pstate.u2), int(pstate.u1), r0)
            return "refresh"
        return "warm"

    def align_now(r0: int) -> bool:
        """Share one detection key across members in round ``r0``?  Engages
        once the consensus is nearly there (ConsensusConfig.align_frac),
        only under warm start, and never on the singleton-start round —
        aligned members with identical (cold or singleton-fallback) inits
        would be clones, degrading the consensus to a single run."""
        if not (warm and align_ok and config.align_frac > 0 and history):
            return False
        if r0 == cold_start_round:
            return False
        return bool(policy.align_now(np, config.align_frac, pstate))

    def maybe_regrow_budgets() -> None:
        """Re-derive the dense/hybrid move-candidate budgets from the LIVE
        degree histogram when the last round's overflow breached
        policy.budgets_stale (closure densifies the graph past the
        pack-time sizing; measured on lfr100k the hub overflow grew 34k ->
        3.26M over 8 rounds while convergence regressed — VERDICT r3
        Weak #4).  Only ever called at the top of a loop iteration (a
        mid-round re-setup nulls in-flight executables, same contract as
        maybe_resize).  The sizing is a pure function of slab content
        (graph.derive_*_sizing), so a killed-and-resumed run re-derives
        the identical budgets at the identical round."""
        nonlocal slab, budget_noop
        if not config.auto_grow or not history:
            return
        h = history[-1]
        if budget_noop is not None and \
                h["n_overflow"] <= budget_noop[0] and \
                h["n_hub_overflow"] <= budget_noop[1] and \
                h["n_alive"] <= budget_noop[2]:
            return
        if not bool(policy.budgets_stale(
                np, h["n_overflow"], h["n_hub_overflow"], slab.d_cap,
                slab.hub_cap, slab.n_nodes, h["n_alive"], slab.agg_cap)):
            return
        from fastconsensus_tpu.graph import (derive_agg_sizing,
                                             derive_dense_sizing,
                                             derive_hybrid_sizing)

        deg = np.asarray(jax.device_get(slab.degrees())).astype(np.int64)
        n_alive = int(np.asarray(jax.device_get(slab.num_alive())))
        obs_counters.host_sync("budget_histogram", 2)
        new_d_cap = derive_dense_sizing(deg, slab.n_nodes)
        new_hyb, new_hub = derive_hybrid_sizing(deg, slab.n_nodes, n_alive)
        # agg_cap == 0 means compaction is off for this run (a resumed
        # pre-r5 checkpoint): never turn it on mid-run — that would change
        # the aggregate-move lowering the run was started with.
        new_agg = derive_agg_sizing(n_alive) if slab.agg_cap > 0 \
            else 0
        if (new_d_cap, new_hyb, new_hub, new_agg) == \
                (slab.d_cap, slab.d_hyb, slab.hub_cap, slab.agg_cap):
            # re-derivation cannot help at these overflow levels; suppress
            # until starvation worsens (and let fused blocks run full).
            # The alive entry is the level at which the AGG STALE TERM
            # would newly fire (policy.budgets_stale: 25% past agg_cap)
            # — NOT the observed alive count: closure grows n_alive a
            # little every round, and piercing on raw growth would
            # re-break fused blocks and re-read the degree histogram
            # every round while dense/hub staleness persists unchanged
            # (round-5 review).
            budget_noop = (h["n_overflow"], h["n_hub_overflow"],
                           (5 * slab.agg_cap) // 4 if slab.agg_cap > 0
                           else 2 ** 31 - 1)
            return
        budget_noop = None
        _logger.warning(
            "move-candidate budgets starved (overflow %d dense / %d hub, "
            "%d alive): re-deriving from the live degree histogram: "
            "d_cap %d -> %d, d_hyb %d -> %d, hub_cap %d -> %d, "
            "agg_cap %d -> %d (one recompile)",
            h["n_overflow"], h["n_hub_overflow"], h["n_alive"],
            slab.d_cap, new_d_cap, slab.d_hyb, new_hyb, slab.hub_cap,
            new_hub, slab.agg_cap, new_agg)
        slab = dataclasses.replace(slab, d_cap=new_d_cap, d_hyb=new_hyb,
                                   hub_cap=new_hub, agg_cap=new_agg)
        obs_reg.inc("budgets.rederive_events")
        setup_executables()

    def grow_and_replay(pre_slab: GraphSlab, dropped: int) -> None:
        """Self-sizing slab: grow from the *pre-round* state and let the
        caller replay the round.  Replay is deterministic (same round key,
        growth preserves slot-fill order — graph.grow_slab), so the replayed
        round reproduces itself exactly except the previously dropped
        survivors now land in the new tail slots."""
        nonlocal slab
        from fastconsensus_tpu.graph import grow_slab

        with tracer.span("grow_and_replay", dropped=dropped):
            obs_reg.inc("slab.regrow_events")
            new_cap = pre_slab.capacity + max(2 * dropped,
                                              pre_slab.capacity // 2)
            _logger.warning(
                "edge slab saturated (%d survivors dropped); growing "
                "capacity %d -> %d and replaying the round", dropped,
                pre_slab.capacity, new_cap)
            slab = grow_slab(pre_slab, new_cap)
            if mesh is not None:
                from fastconsensus_tpu.parallel import sharding as shard

                slab = shard.shard_slab(slab, mesh)
            setup_executables()

    def record(stats) -> bool:
        """Append one round's (host-side) stats; returns converged.  Also
        folds the round into the running policy state — the same
        policy.observe the fused block applies in its carry."""
        nonlocal rounds, converged, pstate
        rounds += 1
        lc = [int(v) for v in np.asarray(stats.labels_changed).ravel()]
        mod = [float(v) for v in
               np.asarray(stats.member_modularity).ravel()]
        n_nodes = max(slab.n_nodes, 1)
        entry = {
            "round": rounds,
            "n_alive": int(stats.n_alive),
            "n_unconverged": int(stats.n_unconverged),
            "n_closure_added": int(stats.n_closure_added),
            "n_repaired": int(stats.n_repaired),
            "n_dropped": int(stats.n_dropped),
            "n_overflow": int(stats.n_overflow),
            "n_hub_overflow": int(stats.n_hub_overflow),
            "n_agg_overflow": int(stats.n_agg_overflow),
            "cold": bool(stats.cold),
            "capacity": slab.capacity,
            # fcqual per-round quality series (obs/quality.py docstring
            # defines each metric; all computed device-side, riding the
            # same bulk stats readback)
            "n_w_zero": int(stats.n_w_zero),
            "n_w_full": int(stats.n_w_full),
            "n_frontier": int(stats.n_frontier),
            "frontier_frac": round(int(stats.n_frontier) / n_nodes, 6),
            "labels_changed": int(sum(lc)),
            "labels_changed_by_member": lc,
            "churn_frac": round(sum(lc) / (max(len(lc), 1) * n_nodes), 6),
            "agreement": round(float(stats.agreement), 6),
            "modularity_mean": round(sum(mod) / max(len(mod), 1), 6),
            "modularity_by_member": [round(m, 6) for m in mod],
        }
        history.append(entry)
        obs_counters.fold_round(entry)
        pstate = policy.observe(np, pstate, np.bool_(entry["cold"]),
                                np.int32(entry["n_unconverged"]),
                                np.int32(entry["n_alive"]))
        if on_round is not None:
            on_round(entry)
        converged = bool(stats.converged)
        return converged

    history: List[dict] = list(prior_history)
    # Stagnation/alignment state (policy.PolicyState), reconstructed from
    # the (possibly resumed) history and maintained incrementally by
    # record(); the single source both round_mode and the fused block's
    # carry seed read.
    pstate = policy.state_from_history(history)
    # Budget-regrowth suppression: overflow levels at the last re-derivation
    # that produced UNCHANGED sizing (None = none).  Until the overflow
    # worsens past these levels, re-checking cannot help and would only
    # stop fused blocks + re-read the degree histogram every round.
    budget_noop: Optional[Tuple[int, int, int]] = None
    converged = resumed_converged
    rounds = start_round
    end_round = start_round if resumed_converged else config.max_rounds
    # Rounds starting from real previous-round labels take the capped-sweep
    # warm variant; the one round that starts from singletons (round 0, or
    # the first resumed round of a labels-less legacy checkpoint) runs the
    # full-sweep base detector.
    cold_start_round = start_round if cur_labels is None else -1
    # Round-0 warm init = singletons, which is exactly what every kernel's
    # cold start uses — so warm mode needs only one trace and round 0 is
    # bit-identical to a cold run.  Stagnation-refresh rounds
    # (round_mode "refresh") reuse the same singleton init, and therefore the
    # same compiled executable as round 0.
    sing_labels = jnp.broadcast_to(
        jnp.arange(slab.n_nodes, dtype=jnp.int32),
        (config.n_p, slab.n_nodes))
    if warm and cur_labels is None:
        cur_labels = sing_labels
    # fcqual churn baseline for the warm_start=False paths, where
    # cur_labels is not maintained: the labels that entered the current
    # round (previous round's output; singletons before round 0 — the
    # same baseline the fused block carries via labels0).  Consumed only
    # by the quality metrics; never fed back into detection.
    prev_round_labels = sing_labels
    # fcdelta traced block inputs — ALWAYS passed, so full runs and
    # incremental re-runs share ONE fused-block executable per bucket
    # (all-True mask + warm0=False selects the identity/cold-start
    # program bit-for-bit; see engine.consensus_rounds_block).
    block_active = (jnp.asarray(active_np) if active_np is not None
                    else jnp.ones((slab.n_nodes,), bool))
    block_warm0 = jnp.bool_(init_labels is not None)
    r = start_round
    while r < end_round:
        t_iter = time.perf_counter()
        maybe_resize()
        maybe_regrow_budgets()
        pre_slab = slab
        if fused_block > 1:
            # non-warm blocks carry the singleton baseline as labels0:
            # detection ignores it (init_labels=None in the block body),
            # but the carry is the fcqual churn baseline for round 0
            labels0 = cur_labels if warm else prev_round_labels
            t0 = time.perf_counter()
            noop = budget_noop if budget_noop is not None \
                else (-1, -1, -1)
            # step_span: under --profile-dir the block is one profiler
            # step (StepTraceAnnotation) keyed by its first round
            with tracer.step_span("rounds_block", r, block=fused_block):
                # fcheck: ok=key-reuse (run key + traced round index;
                # per-round keys derive in-block exactly as the unfused
                # path derives them)
                slab, done, buf, new_labels = block_fn(
                    slab, key, labels0, jnp.int32(r),
                    jnp.int32(end_round - r), jnp.bool_(align_now(r)),
                    policy.PolicyState(*(jnp.int32(v) for v in pstate)),
                    jnp.bool_(config.auto_grow),
                    jnp.asarray(noop, jnp.int32),
                    block_active, block_warm0)
                # fcheck: ok=sync-in-loop (ONE bulk readback per block —
                # round count + stats in a single transfer; the readback
                # the block fusion exists to amortize)
                done, buf = jax.device_get((done, buf))
                done = int(done)
            obs_counters.host_sync("block_stats")
            dt = time.perf_counter() - t0
            first_call = "block" not in seen_execs
            seen_execs.add("block")
            dropped = int(max((buf.n_dropped[i] for i in range(done)),
                              default=0))
            if config.auto_grow and dropped > 0:
                # the block replays from its start; rounds before the
                # saturating one recompute identically (same keys)
                grow_and_replay(pre_slab, dropped)
                continue
            if not first_call and done > 0:
                # the first call of a fresh executable pays the compile;
                # later blocks measure the true on-device round rate.
                # A block mixing stagnation-cold and warm rounds yields a
                # blended rate: fine for in-run sizing (conservative), but
                # not persisted — it would pollute the warm calibration.
                measured_member_s = dt / (done * config.n_p)
                measured_in_process = True
                any_cold = any(bool(buf.cold[i]) for i in range(done))
                if not (warm and any_cold):
                    record_rate(measured_member_s, cold=not warm,
                                call_s=dt)
            if warm:
                cur_labels = new_labels
            prev_round_labels = new_labels
            for i in range(done):
                if record(jax.tree.map(lambda b: b[i], buf)):
                    break
            r += done
            if done:
                # per-round samples are the block average (one device
                # call covers all `done` rounds); the unsmeared block
                # wall goes to its own series so a single slow block —
                # e.g. a mid-run recompile — still surfaces as an
                # outlier in rounds_block.seconds p95/max
                obs_reg.observe("rounds_block.seconds", dt)
                per_round = (time.perf_counter() - t_iter) / done
                for _ in range(done):
                    obs_reg.observe("round.seconds", per_round)
            if converged:
                break
        else:
            k = prng.stream(key, prng.STREAM_ROUND, r)
            if split_phase:
                # same key derivation as consensus_round, so split and
                # one-call execution produce identical results
                mode = round_mode(r)
                is_cold = mode != "warm"
                det_r = {"cold": detect, "refresh": detect_refresh,
                         "warm": detect_warm}[mode]
                k_detect, k_closure = jax.random.split(k)
                keys = prng.partition_keys(k_detect, config.n_p)
                if align_now(r) and not is_cold:
                    # endgame alignment: every member draws member 0's key
                    # (tie-break jitter is community-content-keyed, so
                    # members still differ through their warm labels)
                    keys = keys[jnp.zeros((config.n_p,), jnp.int32)]
                timings: List[float] = []
                # step_span: the whole split round (detect chunks + tail
                # + any capacity replay) is one profiler step, so device
                # ops group per consensus round under --profile-dir
                with tracer.step_span("round", r, mode=mode, split=True):
                    with tracer.span("detect", r=r, mode=mode):
                        labels = _detect_chunked(
                            det_r, slab, keys, members,
                            cache_dir=detect_cache_dir,
                            cache_tag=f"{cache_fp}_r{r}",
                            init_labels=(sing_labels if is_cold
                                         else cur_labels)
                            if warm else None,
                            ensemble_sharding=ensemble_sharding,
                            timings=timings)
                    if timings:
                        # feed the measured on-device rate back into call
                        # sizing (replaces the static estimate after
                        # round 0; persisted in checkpoints below and
                        # per-backend via record_rate).  Applied by
                        # maybe_resize at the TOP of the next iteration,
                        # never here: a mid-round re-size may turn
                        # split-phase off entirely and null the
                        # executables this round still needs (ADVICE
                        # round 2).
                        measured_member_s = float(np.median(timings))
                        measured_in_process = True
                        record_rate(measured_member_s,
                                    cold=not warm or is_cold,
                                    call_s=measured_member_s * members)
                    # fcqual churn baseline: the labels that entered this
                    # round — the warm path's cur_labels (even on refresh
                    # rounds, matching the fused block's carry), the
                    # non-warm path's tracked previous-round labels
                    prev_lab = cur_labels if warm else prev_round_labels
                    if active_np is not None:
                        # fcdelta frontier restriction on the split-phase
                        # path: eager clamp between detect and tail (the
                        # fused path folds the same where into its block)
                        labels = jnp.where(block_active[None, :], labels,
                                           prev_lab)
                    with tracer.span("tail", r=r):
                        slab, stats = _jitted_tail(
                            config.n_p, config.tau, config.delta,
                            n_closure, mesh, sampler, config.closure_tau)(
                            slab, labels, k_closure, prev_labels=prev_lab)
                        # fcheck: ok=sync-in-loop (one bulk stats tuple
                        # per round)
                        stats = jax.device_get(stats)
                    obs_counters.host_sync("round_stats")
                    while config.auto_grow and int(stats.n_dropped) > 0:
                        # capacity only matters after detection: replay
                        # just the tail with the in-hand labels (labels
                        # are capacity-independent; redetecting here
                        # would double the round's dominant cost at
                        # exactly the scale split-phase exists for)
                        grow_and_replay(pre_slab, int(stats.n_dropped))
                        # fcheck: ok=key-reuse (deliberate: the grown
                        # replay must reuse the round key bit-for-bit —
                        # grow_and_replay determinism contract)
                        slab, stats = _jitted_tail(
                            config.n_p, config.tau, config.delta,
                            n_closure, mesh, sampler, config.closure_tau)(
                            slab, labels, k_closure, prev_labels=prev_lab)
                        # fcheck: ok=sync-in-loop (bulk stats of the
                        # replay)
                        stats = jax.device_get(stats)
                        obs_counters.host_sync("round_stats")
                if warm:
                    cur_labels = labels
                prev_round_labels = labels
            else:
                mode = round_mode(r)
                is_cold = mode != "warm"
                round_detect = {"cold": detect, "refresh": detect_refresh,
                                "warm": detect_warm}[mode]
                round_fn = _jitted_round(  # lru-cached: cheap per round
                    round_detect, config.n_p, config.tau,
                    config.delta, n_closure, ensemble_sharding, sampler,
                    config.closure_tau)
                t0 = time.perf_counter()
                # step_span: one profiler step per consensus round
                with tracer.step_span("round", r, mode=mode):
                    if warm:
                        # align passed traced: flipping it mid-run reuses
                        # the same executable (no endgame recompile); cold
                        # refresh rounds take singleton init — round 0's
                        # executable.  prev_labels (fcqual churn baseline)
                        # is always the round's entering labels.  The
                        # fcdelta active mask is passed only when present:
                        # full unfused runs keep their exact legacy trace.
                        slab_new, new_labels, stats = round_fn(
                            slab, k,
                            init_labels=sing_labels if is_cold
                            else cur_labels,
                            align=jnp.bool_(align_now(r) and not is_cold),
                            prev_labels=cur_labels,
                            **({"active": block_active}
                               if active_np is not None else {}))
                    else:
                        slab_new, new_labels, stats = round_fn(
                            slab, k, prev_labels=prev_round_labels)
                    slab = slab_new
                    # One bulk device->host transfer for the whole stats
                    # tuple: per-field scalar readbacks each pay the full
                    # device round-trip latency, which through the TPU
                    # tunnel dwarfs the round's compute (measured).
                    # fcheck: ok=sync-in-loop (that one bulk transfer)
                    stats = jax.device_get(stats)
                obs_counters.host_sync("round_stats")
                dt = time.perf_counter() - t0
                # The round-0 cold detector and the warm variant are
                # DIFFERENT executables: each one's first call pays its own
                # compile and must not be recorded as a rate.
                first_call = round_detect not in seen_execs
                seen_execs.add(round_detect)
                if config.auto_grow and int(stats.n_dropped) > 0:
                    grow_and_replay(pre_slab, int(stats.n_dropped))
                    continue
                if not first_call:
                    # compile-free round: the whole-round wall time over
                    # n_p approximates the per-member rate (tail included
                    # — detection dominates at every measured config)
                    measured_member_s = dt / config.n_p
                    measured_in_process = True
                    record_rate(measured_member_s, cold=not warm or is_cold,
                                call_s=dt)
                if warm:
                    cur_labels = new_labels
                prev_round_labels = new_labels
            r += 1
            stats = stats._replace(cold=np.bool_(is_cold))
            record(stats)
            obs_reg.observe("round.seconds", time.perf_counter() - t_iter)
            if checkpoint_path is not None and \
                    (rounds % checkpoint_every == 0 or converged):
                from fastconsensus_tpu.utils import checkpoint as ckpt

                with tracer.span("checkpoint", round=rounds):
                    # two readbacks when warm (key data + labels), one
                    # when cold — same per-readback convention as
                    # budget_histogram
                    obs_counters.host_sync("checkpoint", 2 if warm else 1)
                    ckpt.save_checkpoint(
                        checkpoint_path, slab, rounds,
                        # fcheck: ok=sync-in-loop (once-per-checkpoint
                        # persistence; the readback IS the feature)
                        np.asarray(jax.random.key_data(key)), history,
                        extra={"algorithm": config.algorithm,
                               "n_p": config.n_p,
                               "tau": config.tau, "delta": config.delta,
                               "gamma": config.gamma,
                               "warm_start": config.warm_start,
                               "align_frac": config.align_frac,
                               "closure_sampler": sampler,
                               "closure_tau": config.closure_tau,
                               "member_seconds": measured_member_s,
                               "converged": converged},
                        # fcheck: ok=sync-in-loop (labels persisted with
                        # the checkpoint)
                        labels=(np.asarray(cur_labels)
                                if warm else None),
                        # run-scoped fcobs counter totals ride along so
                        # a resumed process reports cumulative telemetry
                        # (delta-restored in _resume_from_checkpoint)
                        telemetry=run_telemetry())
            if converged:
                break

    # the final re-detection deserves complete candidate rows too (and the
    # re-derivation is content-pure, so a killed-and-restarted process
    # reaches the same sizing and the same _final chunk fingerprints)
    maybe_regrow_budgets()
    final_keys = prng.partition_keys(
        prng.stream(key, prng.STREAM_FINAL), config.n_p)
    # Warm-start the final re-detection too: on a converged consensus graph
    # the structure is stark, so warm members exit after a sweep or two
    # (measured round 1: even on a fully converged graph, cold detection
    # still cost 73% of fresh-graph time — the churn floor, BASELINE.md).
    # Chunking + the detect cache apply under a mesh exactly as off it
    # (chunks are device_put onto the ensemble axis).
    # warm variant only when the seed labels come from real detection (not
    # the singleton fallback of a labels-less legacy checkpoint)
    final_detect = detect_warm if (
        warm and (cold_start_round == -1 or rounds > start_round)) \
        else detect
    with tracer.span("final_detect"):
        final_labels = _detect_chunked(
            final_detect, slab, final_keys, members,
            cache_dir=detect_cache_dir,
            cache_tag=f"{cache_fp}_final",
            init_labels=cur_labels if warm else None,
            ensemble_sharding=ensemble_sharding)
        # Single bulk readback of the [n_p, N] label matrix (per-row
        # transfers each pay the device round-trip; see the stats
        # readback note above).
        all_labels = jax.device_get(final_labels)
    obs_counters.host_sync("final_labels")
    partitions = [all_labels[i] for i in range(config.n_p)]
    if active_np is not None:
        # fcdelta: frozen vertices keep the parent ensemble's labels
        # through the final re-detection too.  Host-side numpy clamp —
        # zero extra executables, and the serving layer's per-member
        # recompaction (np.unique) runs downstream of this anyway.
        frozen = ~active_np
        partitions = [np.where(frozen, init_labels[i], p)
                      for i, p in enumerate(partitions)]
    return ConsensusResult(partitions=partitions, graph=slab, rounds=rounds,
                           converged=converged, history=history)


def run_consensus_batch(slabs,
                        detect: Detector,
                        config: ConsensusConfig,
                        n_closure: int,
                        seeds=None,
                        keys=None) -> List[ConsensusResult]:
    """Run B independent same-bucket consensus jobs as ONE device-call
    stream: the batch analog of :func:`run_consensus`.

    The paper's core structure — n_p independent detector runs vmapped
    into one ensemble — extends one axis up: B independent *graphs*
    stacked along a leading batch axis drive a batch-vmapped variant of
    the fused round block (engine.consensus_batch_block), so a burst of
    small same-bucket requests costs ~one graph's dispatch/readback
    latency instead of B of them (the fcserve coalescing path).

    **Bit-parity contract**: every job's partitions are identical to
    running it alone through :func:`run_consensus` at the same seed.
    The PRNG tree keys per job (``seeds[b]`` / ``keys[b]`` is job b's
    run key — exactly ``jax.random.key(seed)``), per-round keys derive
    in-batch exactly as the solo driver derives them, and the policy
    rules fold per-element with the same functions.  Warm-stagnation
    cold refreshes stay batched (a masked singleton-init round through
    the cold-mode block — the solo driver's round_mode() fires from the
    identical policy state).  Whenever a job's trajectory would deviate
    in a way that changes STATIC shapes — slab auto-growth
    (``n_dropped > 0``) or a budget re-derivation
    (``policy.budgets_stale``) — that job is **split off to a solo
    tail**: its batched progress is discarded and it re-runs start to
    finish through ``run_consensus`` with its own key, which IS the
    parity definition.  Converged jobs mask to no-ops (their while-loop
    carry freezes) until the whole batch finishes.

    Restrictions vs the solo driver (all serving-path irrelevancies):
    no mesh, no checkpoint/resume, no detect-chunk cache, whole-ensemble
    detection only (the fcserve posture, ``FCTPU_DETECT_CALL_MEMBERS=0``).
    ``n_closure`` is REQUIRED: it is a static shape shared by the whole
    batch, so the caller must pass the bucket-canonical L
    (serve/bucketer.Bucket.n_closure) rather than letting each graph
    default to its own alive count.

    ``seeds`` gives job b the run key ``jax.random.key(seeds[b])``
    (default: ``config.seed`` for every job — only useful with distinct
    graphs); ``keys`` passes pre-built run keys instead.  Returns one
    :class:`ConsensusResult` per input slab, in order.
    """
    from fastconsensus_tpu.graph import stack_slabs

    B = len(slabs)
    if B < 1:
        raise ValueError("run_consensus_batch needs at least one slab")
    if keys is not None and seeds is not None:
        raise ValueError("pass seeds or keys, not both")
    if keys is None:
        seeds = list(seeds) if seeds is not None else [config.seed] * B
        if len(seeds) != B:
            raise ValueError(f"{len(seeds)} seeds for {B} slabs")
        keys = [jax.random.key(int(s)) for s in seeds]
    keys = list(keys)
    if len(keys) != B:
        raise ValueError(f"{len(keys)} keys for {B} slabs")
    _validate_config(config)
    # same resolution as the solo driver (batching is single-chip only)
    sampler = "csr" if config.closure_sampler == "auto" \
        else config.closure_sampler
    n_closure = int(n_closure)
    tracer = get_tracer()
    obs_reg = obs_counters.get_registry()

    warm = config.warm_start and getattr(detect, "supports_init", False)
    align_ok = getattr(detect, "supports_align", False)
    detect_warm = (getattr(detect, "warm_variant", None) or detect) \
        if warm else detect
    detect_refresh = getattr(detect, "refresh_variant", None) or detect
    align_frac = config.align_frac if (warm and align_ok) else 0.0
    fb_env = env_int("FCTPU_ROUNDS_BLOCK")
    block = max(1, min(8, fb_env)) if fb_env else 8

    base = slabs[0]
    n_nodes, n_p = base.n_nodes, config.n_p
    # weights <- 1.0 at loop start, per slab (run_consensus parity)
    slabs = [s.with_weights(jnp.where(s.alive, 1.0, 0.0)) for s in slabs]
    stacked = stack_slabs(slabs)
    keys_b = jax.random.wrap_key_data(jnp.stack(
        [jax.random.key_data(k) for k in keys]))

    sing = jnp.broadcast_to(jnp.arange(n_nodes, dtype=jnp.int32),
                            (B, n_p, n_nodes))
    # non-warm carries the singleton baseline too: detection ignores it
    # (scratch mode passes init=None), but the labels carry is the fcqual
    # churn baseline for round 0 — solo-driver parity (prev_round_labels)
    labels = sing

    histories: List[List[dict]] = [[] for _ in range(B)]
    pstates = [policy.state_from_history([]) for _ in range(B)]
    conv = np.zeros(B, bool)
    rounds = np.zeros(B, np.int64)
    solo = np.zeros(B, bool)       # split off to the solo tail
    watch = np.full(B, bool(config.auto_grow))
    noop = np.full((B, 3), -1, np.int32)

    def align_next(i: int) -> bool:
        """The solo driver's align_now(r) for job i's next round (every
        batched round has r >= 1 and a non-empty history)."""
        if not (warm and align_ok and config.align_frac > 0
                and histories[i]):
            return False
        return bool(policy.align_now(np, config.align_frac, pstates[i]))

    def refresh_due(i: int) -> bool:
        """Would the solo driver's round_mode() run job i's next round
        cold (stagnation refresh)?  Split it off if so."""
        if not warm or not histories[i]:
            return False
        return bool(policy.stale(np, config.delta, pstates[i])) or \
            bool(policy.stalled(np, config.delta, pstates[i],
                                align_next(i)))

    def budgets_fire(entry: dict) -> bool:
        """Would the solo driver's maybe_regrow_budgets() act on this
        round's stats?  (First firing only — the batch splits off before
        any no-op suppression state can accrue.)"""
        if not config.auto_grow:
            return False
        return bool(policy.budgets_stale(
            np, entry["n_overflow"], entry["n_hub_overflow"], base.d_cap,
            base.hub_cap, base.n_nodes, entry["n_alive"], base.agg_cap))

    def split_off(i: int, why: str) -> None:
        solo[i] = True
        obs_reg.inc("batch.solo_splits")
        _logger.info("batch job %d split off to solo tail (%s)", i, why)

    def record_block(done, buf) -> None:
        """Fold one batched block's readback into the per-job state —
        the batch form of the solo driver's record()."""
        for i in range(B):
            if solo[i] or conv[i]:
                continue
            for j in range(int(done[i])):
                st = jax.tree.map(lambda b: b[i][j], buf)
                if config.auto_grow and int(st.n_dropped) > 0:
                    # the solo driver would grow-and-replay this round
                    split_off(i, f"slab saturated at round {rounds[i]}")
                    break
                # fcheck: ok=sync-in-loop (pure host-side numpy — buf was
                # bulk-device_get'd once above; these just reshape rows)
                lc = [int(v) for v in np.asarray(st.labels_changed).ravel()]
                mod = [float(v) for v in
                       # fcheck: ok=sync-in-loop (same host-side buf)
                       np.asarray(st.member_modularity).ravel()]
                entry = {
                    "round": int(rounds[i]) + 1,
                    "n_alive": int(st.n_alive),
                    "n_unconverged": int(st.n_unconverged),
                    "n_closure_added": int(st.n_closure_added),
                    "n_repaired": int(st.n_repaired),
                    "n_dropped": int(st.n_dropped),
                    "n_overflow": int(st.n_overflow),
                    "n_hub_overflow": int(st.n_hub_overflow),
                    "n_agg_overflow": int(st.n_agg_overflow),
                    "cold": bool(st.cold),
                    "capacity": base.capacity,
                    # fcqual series — key-for-key with the solo record()
                    "n_w_zero": int(st.n_w_zero),
                    "n_w_full": int(st.n_w_full),
                    "n_frontier": int(st.n_frontier),
                    "frontier_frac": round(
                        int(st.n_frontier) / max(n_nodes, 1), 6),
                    "labels_changed": int(sum(lc)),
                    "labels_changed_by_member": lc,
                    "churn_frac": round(
                        sum(lc) / (max(len(lc), 1) * max(n_nodes, 1)), 6),
                    "agreement": round(float(st.agreement), 6),
                    "modularity_mean": round(
                        sum(mod) / max(len(mod), 1), 6),
                    "modularity_by_member": [round(m, 6) for m in mod],
                }
                histories[i].append(entry)
                pstates[i] = policy.observe(
                    np, pstates[i], np.bool_(entry["cold"]),
                    np.int32(entry["n_unconverged"]),
                    np.int32(entry["n_alive"]))
                rounds[i] += 1
                conv[i] = bool(st.converged)
                if budgets_fire(entry):
                    # the solo driver would re-derive budgets (a static-
                    # shape change) at the next loop top / before the
                    # final detection
                    split_off(i, f"budget re-derivation at round "
                                 f"{rounds[i]}")
                    break
                if conv[i]:
                    break

    def pst_b():
        return policy.PolicyState(*(jnp.asarray(
            np.stack([np.int32(getattr(pstates[i], f))
                      for i in range(B)]))
            for f in policy.PolicyState._fields))

    def active():
        return ~conv & ~solo & (rounds < config.max_rounds)

    def run_block(mode: str, det, block_n: int, only=None) -> None:
        nonlocal stacked, labels
        mask = active() if only is None else (active() & only)
        iters = np.where(mask, config.max_rounds - rounds, 0)
        if block_n == 1:
            iters = np.minimum(iters, 1)
        fn = _jitted_rounds_batch(det, n_p, config.tau, config.delta,
                                  n_closure, block_n, mode, align_frac,
                                  sampler, config.closure_tau)
        align0 = np.array([align_next(i) and mode == "warm"
                           for i in range(B)])
        with tracer.step_span("batch_block", int(rounds.min()),
                              b=B, mode=mode):
            # fcheck: ok=key-reuse (per-job run keys + traced round
            # index; per-round keys derive in-block exactly as the solo
            # driver derives them)
            stacked, done, buf, new_labels = fn(
                stacked, keys_b, labels,
                jnp.asarray(rounds, jnp.int32),
                jnp.asarray(iters, jnp.int32),
                jnp.asarray(align0),
                pst_b(),
                jnp.asarray(watch),
                jnp.asarray(noop))
            # fcheck: ok=sync-in-loop (ONE bulk readback per batched
            # block — B jobs' round counts + stats in a single transfer,
            # the readback coalescing exists to amortize)
            done, buf = jax.device_get((done, buf))
        obs_counters.host_sync("batch_block_stats")
        obs_reg.inc("batch.blocks")
        labels = new_labels
        record_block(done, buf)

    if warm:
        # absolute round 0: uniformly cold (singleton-init full sweeps)
        run_block("cold", detect, 1)
        while active().any():
            # fcheck: ok=sync-in-loop (pure host-side policy numpy —
            # refresh_due reads the recorded history, no device arrays)
            refresh = np.array([bool(active()[i]) and refresh_due(i)
                                for i in range(B)])
            if refresh.any():
                # Stagnation refreshes run BATCHED too: a refresh round
                # is a singleton-init full-sweep round — the cold-mode
                # body with the low-variance refresh variant — masked to
                # exactly the elements whose policy fired (the others
                # freeze at 0 iterations).  The solo driver's
                # round_mode() takes the identical decision from the
                # identical policy state, so parity holds.
                obs_reg.inc("batch.refresh_rounds", int(refresh.sum()))
                run_block("cold", detect_refresh, 1, only=refresh)
                continue
            run_block("warm", detect_warm, block)
    else:
        while active().any():
            run_block("scratch", detect, block)

    results: List[Optional[ConsensusResult]] = [None] * B
    batched = [i for i in range(B) if not solo[i]]
    if batched:
        # batched final re-detection: per-job final keys derive exactly
        # as the solo driver's (STREAM_FINAL off each job's run key)
        final_keys = jax.vmap(
            lambda k: prng.partition_keys(
                prng.stream(k, prng.STREAM_FINAL), n_p))(keys_b)
        final_detect = detect_warm if warm else detect
        with tracer.span("batch_final_detect", b=B):
            fd = _jitted_detect_batch(final_detect, warm)
            out = fd(stacked, final_keys, labels) if warm \
                else fd(stacked, final_keys)
            # fcheck: ok=sync-in-loop (single bulk readback of the whole
            # batch's [B, n_p, N] label block)
            all_labels = jax.device_get(out)
        obs_counters.host_sync("batch_final_labels")
        for i in batched:
            # counter folding happens HERE, not in record_block: a job
            # split off to the solo tail discards its batched rounds,
            # and run_consensus re-folds the rerun's rounds itself —
            # folding eagerly would double-count every split job's
            # prefix in rounds.total / closure totals
            for entry in histories[i]:
                obs_counters.fold_round(entry)
            results[i] = ConsensusResult(
                partitions=[all_labels[i][p] for p in range(n_p)],
                graph=jax.tree.map(lambda x: x[i], stacked),
                rounds=int(rounds[i]), converged=bool(conv[i]),
                history=histories[i])
    for i in range(B):
        if solo[i]:
            # the solo tail: discard the batched progress and re-run
            # this job alone with its own key — solo execution is the
            # parity reference, so the answer is identical by definition
            results[i] = run_consensus(slabs[i], detect, config,
                                       key=keys[i], n_closure=n_closure)
    return results  # type: ignore[return-value]


def fast_consensus(edges: np.ndarray,
                   n_nodes: int,
                   algorithm: str = "louvain",
                   n_p: int = 20,
                   tau: float = 0.2,
                   delta: float = 0.02,
                   seed: int = 0,
                   max_rounds: int = 64,
                   gamma: float = 1.0) -> ConsensusResult:
    """Convenience API mirroring the reference's ``fast_consensus()``
    signature (fc:129) with edges in, partitions out.  ``gamma`` reaches
    both the detector and the config fingerprints, so it cannot drift the
    way a hand-built (detector, config) pair can (see ConsensusConfig)."""
    from fastconsensus_tpu.models.registry import get_detector, supports_param

    slab = pack_edges(edges, n_nodes)
    if gamma != 1.0 and not supports_param(algorithm, "gamma"):
        import warnings

        warnings.warn(
            f"gamma={gamma} ignored for algorithm={algorithm!r} (resolution "
            f"applies to modularity detectors)", stacklevel=2)
        gamma = 1.0
    cfg = ConsensusConfig(algorithm=algorithm, n_p=n_p, tau=tau, delta=delta,
                          seed=seed, max_rounds=max_rounds, gamma=gamma)
    return run_consensus(slab, get_detector(algorithm, gamma=gamma), cfg)
