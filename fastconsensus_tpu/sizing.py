"""Detection call sizing: how much work one device call should carry.

A single XLA execution through the TPU tunnel must stay well under the
~60 s single-call ceiling (longer executes kill the worker), and splitting
detection into several calls keeps the driver responsive for checkpoint /
trace hooks.  This module owns the per-member time model those decisions
run on:

* a **never-measured prior** (:data:`NS_PER_TEMP_BYTE`) for the very first
  call on fresh hardware,
* a **persisted per-backend calibration** (utils/calibrate.py) measured by
  earlier runs, and
* the **live in-run measurement** the driver feeds back after every round
  (``measured_s``), which wins over both.

Extracted from consensus.py (round-4 refactor, VERDICT r3 Weak #6); the
driver-side re-sizing policy (when to act on a measurement) stays with the
loop in ``consensus.run_consensus``.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from fastconsensus_tpu.graph import GraphSlab
from fastconsensus_tpu.models.base import Detector
from fastconsensus_tpu.utils.env import env_int

# Never-measured prior: effective cost per byte of per-sweep temporaries,
# by move path (TPU v5e via the dev tunnel): the matmul path streams
# (MXU/HBM-bound), dense pays the row sort / pallas compare, hash and runs
# are scatter/sort-bound; hybrid sits between dense and hash (narrow rows +
# small scatters).  Calibrated against lfr1k (matmul), planted-100k
# (dense) and lfr10k (hash/hybrid) detections.  Once a run has measured a
# real rate on a backend it is persisted and preferred
# (utils/calibrate.py), so this table is load-bearing only for the very
# first run on fresh hardware.
NS_PER_TEMP_BYTE = {"matmul": 0.02, "dense": 0.2, "hybrid": 0.3,
                    "hash": 0.8, "runs": 1.5}

# Shortest device call whose wall time is persisted as a calibration rate
# (run_consensus.record_rate): below this, host-device dispatch/readback
# latency dominates and the derived ns/byte would be garbage.
MIN_PERSIST_CALL_S = 2.0


def member_temp_bytes(slab: GraphSlab) -> int:
    """The denominator of the ns-per-byte rate unit — shared by the
    estimator and the recorder (record_rate), and baked into persisted
    calibration files: both sides MUST use this one definition or every
    stored rate silently mis-scales."""
    from fastconsensus_tpu.models import louvain

    return 96 * louvain.sweep_temp_bytes(slab)


def est_member_seconds(slab: GraphSlab,
                       detect: Optional[Detector] = None,
                       alg: Optional[str] = None) -> float:
    """Per-ensemble-member detection time estimate for call sizing.

    Prefers a rate measured on this backend by an earlier run (persisted —
    utils/calibrate.py; it embodies the detector's full per-member cost).
    Falls back to the :data:`NS_PER_TEMP_BYTE` prior scaled by the
    detector's ``cost_mult`` hint (multi-phase detectors like leiden).
    """
    from fastconsensus_tpu.models import louvain
    from fastconsensus_tpu.utils import calibrate

    path = louvain.select_move_path(slab)
    temp_bytes = member_temp_bytes(slab)
    if alg is not None:
        rate = calibrate.get_rate(jax.default_backend(), path, alg)
        if rate is not None:
            return temp_bytes * rate * 1e-9
    mult = getattr(detect, "cost_mult", 1.0) if detect is not None else 1.0
    return temp_bytes * NS_PER_TEMP_BYTE[path] * 1e-9 * mult


def members_per_call(slab: GraphSlab, n_p: int,
                     detect: Optional[Detector] = None,
                     measured_s: Optional[float] = None,
                     alg: Optional[str] = None) -> int:
    """How many ensemble members one detection device-call should carry.

    Targets ~15 s per call (a 4x safety margin under the tunnel's ~60 s
    execute ceiling).  A ~30 s measured-rate target was tried in round 5
    to amortize per-call fixed costs (the hybrid build's full-slab sort)
    and cut dispatch count — and MEASURED NET-NEGATIVE on the 100k
    config: doubling the batch 4 -> 8 members doubled the per-member
    cost (3.4 -> 6.9 s; the vmapped sweep while-loop runs to the
    slowest member, so wider batches accumulate stragglers) and the
    resulting 55-63 s calls brushed the tunnel's execute-kill ceiling,
    triggering the very wedges fewer dispatches were meant to avoid.
    Per-member time: ``measured_s`` — the actual on-device rate from
    this run's own detection calls — or, before anything has been
    measured in this process, the :func:`est_member_seconds` prior.
    FCTPU_DETECT_CALL_MEMBERS overrides everything (<= 0 disables
    splitting).

    The raw count is snapped DOWN to a coarse grid ({2^k, 3*2^k}: 1, 2,
    3, 4, 6, 8, 12, 16, 24, ...): the member count is part of the
    compiled executable's shape, and the un-quantized rate estimate
    produced a slightly different count on every run (15/16/17/20/41
    observed across one round-5 afternoon) — each one a fresh
    multi-minute remote compile that the persistent XLA cache could have
    served at a grid value.  Snapping down keeps the 4x call-ceiling
    margin conservative.
    """
    c = env_int("FCTPU_DETECT_CALL_MEMBERS")
    if c is not None:
        return n_p if c <= 0 else min(c, n_p)
    per = measured_s if measured_s else est_member_seconds(slab, detect, alg)
    raw = max(1, min(n_p, int(15.0 / max(per, 1e-9))))
    if raw >= n_p:
        return n_p  # whole-ensemble calls are themselves a stable shape
    g = 1
    while 2 * g <= raw or 3 * g <= raw:
        if 3 * g <= raw < 4 * g:
            return 3 * g
        g *= 2
    return g


def grid_up(n: int, minimum: int = 1) -> int:
    """Smallest value >= n on the coarse {2^k, 3*2^k} grid (1, 2, 3, 4,
    6, 8, 12, 16, 24, 32, 48, 64, ...).

    The same grid :func:`members_per_call` snaps DOWN onto, exposed for
    callers that must snap UP: the serving layer's shape buckets
    (serve/bucketer.py) pad every incoming graph's (n_nodes, n_edges) to
    a grid class so distinct graphs share compiled executables — the
    serving analog of the member-count quantization above (an
    un-quantized shape per request would be a fresh multi-minute compile
    per request).  Successive classes are at most 4/3 apart, so padding
    waste is bounded at ~33% while the number of distinct classes (and
    thus resident executables) stays logarithmic in graph size.
    """
    n = max(int(n), int(minimum), 1)
    p = 1
    while p < n:
        p *= 2
    q = (3 * p) // 4
    return q if p >= 4 and q >= n else p


def read_sizing(cache_dir: str) -> Optional[dict]:
    """The detect-call sizing a previous process used with this chunk-cache
    dir (run_consensus.setup_executables: a restart must reuse the killed
    run's chunking or every persisted chunk of the round is orphaned)."""
    import json

    try:
        with open(os.path.join(cache_dir, "sizing.json")) as fh:
            return json.load(fh)
    except (OSError, ValueError):
        return None


def write_sizing(cache_dir: str, fp: str, members: int) -> None:
    from fastconsensus_tpu.utils.calibrate import atomic_write_json

    atomic_write_json(os.path.join(cache_dir, "sizing.json"),
                      {"fp": fp, "members": members})
