"""Pallas TPU kernels for the hot per-sweep aggregation.

The detection sweeps' inner op is: per padded neighbor row, the weighted
total of each row slot's label over the whole row, plus a first-occurrence
mask (ops/dense_adj.py:row_label_totals — there expressed as a minor-axis
sort + segmented scans).  Row widths are small (``d_cap`` <= 2048, typically
~100-200), so the whole aggregation fits VMEM as an O(D^2) broadcast-compare:

    total[i]   = sum_j w[j] * (lab[j] == lab[i])
    is_head[i] = no j < i with lab[j] == lab[i]

One VMEM-resident [BN, D, D] compare per node block replaces the sort's
log^2 passes; the weighted reduction over j vectorizes on the VPU.  No
inter-block communication, no HBM intermediates — a pure map over node
blocks, which is exactly the shape Pallas is for.

The public entry :func:`row_totals` handles padding to lane/TPU-friendly
shapes and falls back to interpret mode off-TPU (used by the CPU test suite
for bit-equivalence against the sort-based path).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Sentinel marking invalid row slots; must sort above any real label and
# equal the one used by ops/dense_adj.py.  A Python int, not a jnp constant:
# the kernel body must not close over traced arrays.
SENTINEL = 2**31 - 1


def fits_vmem(d: int, budget: int = 12 * 1024 * 1024) -> bool:
    """Whether the O(D^2) kernel can run at width ``d`` without faulting.

    Mosaic forces the node-block to >= 8 rows, so the [8, D', D'] compare
    temps (~6 bytes/element at padded D') are the floor cost; past the VMEM
    budget the kernel faults the TPU worker.  Callers (dense_adj auto-select)
    should use the sort-based path instead for wide rows.
    """
    dp = d + (-d) % 128
    return 8 * 6 * dp * dp <= budget


def _row_totals_kernel(lab_ref, w_ref, total_ref, head_ref):
    lab = lab_ref[...]                       # int32[BN, D]
    w = w_ref[...]                           # float32[BN, D]
    eq = lab[:, :, None] == lab[:, None, :]  # bool[BN, D, D]; [b, i, j]
    total_ref[...] = jnp.sum(
        jnp.where(eq, w[:, None, :], 0.0), axis=2)
    i_idx = jax.lax.broadcasted_iota(jnp.int32, eq.shape, 1)
    j_idx = jax.lax.broadcasted_iota(jnp.int32, eq.shape, 2)
    dup_earlier = jnp.any(eq & (j_idx < i_idx), axis=2)
    real = lab != SENTINEL
    head_ref[...] = (~dup_earlier) & real


def _hash_jitter(row0: jax.Array, d: int, salt: jax.Array) -> jax.Array:
    """Deterministic per-(row, slot) uniform in [0, 1): multiply-xorshift of
    (global row id, slot, salt).  Cheaper than materializing a jax.random
    draw in HBM for every candidate; used only to break ties."""
    bn = row0.shape[0] if hasattr(row0, "shape") else 1
    # f32 -> i32 -> u32: Mosaic has no direct f32->u32 cast; values are
    # <= 2^24 so the detour is exact.  row0 IS the global row id (scal[:,5]
    # carries arange(n)); adding a block-local iota on top would make rows
    # in adjacent blocks collide to identical jitter vectors.
    i = jnp.broadcast_to(
        row0.astype(jnp.int32).astype(jnp.uint32)[:, None], (bn, d))
    j = jax.lax.broadcasted_iota(jnp.uint32, (bn, d), 1)
    m = i * jnp.uint32(0x9E3779B1) + j * jnp.uint32(0x85EBCA77) + salt
    m = m ^ (m >> 15)
    m = m * jnp.uint32(0x2C1B3C6D)
    m = m ^ (m >> 13)
    # top 23 bits -> i32 -> f32 (no direct u32->f32 cast in Mosaic)
    return (m >> 9).astype(jnp.int32).astype(jnp.float32) * \
        jnp.float32(2.0 ** -23)


def _fused_move_kernel(lab_ref, w_ref, sig_ref, scal_ref,
                       best_ref, want_ref, *, d_self: int):
    """One whole move-step sweep for a block of dense rows.

    Row layout: slots 0..d_self-1 are neighbors, slot d_self is the node's
    own zero-weight candidate, the rest is SENTINEL padding.  ``scal`` rows
    pack per-row scalars: [k_i, coef (= gamma*k_i/2m), jitter scale,
    margin, salt, global row id, 0...].  Emits best label + want per row;
    totals/heads/gains never leave VMEM (the unfused pipeline wrote and
    re-read several [N, D] arrays per sweep).
    """
    lab = lab_ref[...]                       # int32[BN, D]
    w = w_ref[...]                           # float32[BN, D]
    sig = sig_ref[...]                       # float32[BN, D]
    scal = scal_ref[...]                     # float32[BN, 8]
    bn, d = lab.shape

    eq = lab[:, :, None] == lab[:, None, :]  # [BN, i, j]
    total = jnp.sum(jnp.where(eq, w[:, None, :], 0.0), axis=2)
    i_idx = jax.lax.broadcasted_iota(jnp.int32, eq.shape, 1)
    j_idx = jax.lax.broadcasted_iota(jnp.int32, eq.shape, 2)
    head = ~jnp.any(eq & (j_idx < i_idx), axis=2)
    real = lab != SENTINEL

    k_i = scal[:, 0:1]
    coef = scal[:, 1:2]
    jscale = scal[:, 2:3]
    margin = scal[:, 3:4]
    salt = scal[0, 4].astype(jnp.int32).astype(jnp.uint32)
    row0 = scal[:, 5]

    own_lab = lab[:, d_self][:, None]        # int32[BN, 1]
    own = lab == own_lab
    gain = total - coef * (sig - jnp.where(own, k_i, 0.0))
    jit = _hash_jitter(row0, d, salt) * jscale
    neg = jnp.float32(-jnp.inf)
    score = jnp.where(head & real, gain + jit, neg)

    best_score = jnp.max(score, axis=1)
    # no per-row gather in Mosaic: recover the argmax label by masked max
    # (ties toward the larger label, like the sorted/scatter paths)
    is_best = score == best_score[:, None]
    best_lab = jnp.max(jnp.where(is_best & head & real, lab, -1), axis=1)
    stay = jnp.max(jnp.where(own & head & real, gain, neg), axis=1)
    has = best_score > neg
    want = has & (best_lab != own_lab[:, 0]) & \
        (best_score > stay + margin[:, 0])
    best_ref[...] = jnp.where(has, best_lab, own_lab[:, 0])[:, None]
    want_ref[...] = want[:, None]


@functools.partial(jax.jit,
                   static_argnames=("d_self", "block_n", "interpret"))
def fused_move_rows(lab: jax.Array, w: jax.Array, sig: jax.Array,
                    scal: jax.Array, d_self: int,
                    block_n: int = None, interpret: bool = None
                    ) -> Tuple[jax.Array, jax.Array]:
    """Fused dense move sweep: (best int32[N], want bool[N]).

    Inputs are pre-padded to lane width by the caller (models/louvain.py's
    dense step builds them once per sweep); ``scal`` is float32[N, 8] as
    documented on the kernel.  Same VMEM sizing rule as :func:`row_totals`.
    """
    n, d = lab.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_n is None:
        budget = 4 * 1024 * 1024
        block_n = max(1, min(32, budget // (6 * d * d)))
        if not interpret:
            block_n = max(8, block_n - block_n % 8)
    n_pad = (-n) % block_n
    if n_pad:
        lab = jnp.pad(lab, ((0, n_pad), (0, 0)), constant_values=SENTINEL)
        w = jnp.pad(w, ((0, n_pad), (0, 0)))
        sig = jnp.pad(sig, ((0, n_pad), (0, 0)))
        scal = jnp.pad(scal, ((0, n_pad), (0, 0)))
    np_ = lab.shape[0]

    grid = (np_ // block_n,)
    spec = pl.BlockSpec((block_n, d), lambda i: (i, 0))
    sspec = pl.BlockSpec((block_n, scal.shape[1]), lambda i: (i, 0))
    ospec = pl.BlockSpec((block_n, 1), lambda i: (i, 0))
    best, want = pl.pallas_call(
        functools.partial(_fused_move_kernel, d_self=d_self),
        grid=grid,
        in_specs=[spec, spec, spec, sspec],
        out_specs=[ospec, ospec],
        out_shape=[jax.ShapeDtypeStruct((np_, 1), jnp.int32),
                   jax.ShapeDtypeStruct((np_, 1), jnp.bool_)],
        interpret=interpret,
    )(lab, w, sig, scal)
    return best[:n, 0], want[:n, 0]


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def row_totals(lab: jax.Array, w: jax.Array,
               block_n: int = None, interpret: bool = None
               ) -> Tuple[jax.Array, jax.Array]:
    """Per-slot label totals + first-occurrence mask for padded rows.

    ``lab`` int32[N, D] (SENTINEL = invalid slot, weight must be 0 there),
    ``w`` float32[N, D].  Returns ``(total float32[N, D], head bool[N, D])``
    with the same slot order as the input (no sorting).

    ``block_n`` defaults to a VMEM-budgeted size: the kernel's [BN, D, D]
    intermediates cost ~6 bytes/element, so BN shrinks as D grows (a fixed
    BN would blow the ~16MB VMEM budget past D ~ 350).  ``interpret``
    defaults to True off-TPU, where pallas has no native lowering.
    """
    n, d = lab.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_n is None:
        dp_est = d + (-d) % 128
        budget = 4 * 1024 * 1024  # target VMEM for the O(BN*D^2) temps
        block_n = max(1, min(32, budget // (6 * dp_est * dp_est)))
        if not interpret:
            # Mosaic requires the second-to-last block dim to be a multiple
            # of 8 (jax pallas TPU lowering constraint).
            block_n = max(8, block_n - block_n % 8)
    n_pad = (-n) % block_n
    d_pad = (-d) % 128
    if n_pad or d_pad:
        lab = jnp.pad(lab, ((0, n_pad), (0, d_pad)),
                      constant_values=SENTINEL)
        w = jnp.pad(w, ((0, n_pad), (0, d_pad)))
    np_, dp = lab.shape

    grid = (np_ // block_n,)
    spec = pl.BlockSpec((block_n, dp), lambda i: (i, 0))
    total, head = pl.pallas_call(
        _row_totals_kernel,
        grid=grid,
        in_specs=[spec, spec],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct((np_, dp), jnp.float32),
                   jax.ShapeDtypeStruct((np_, dp), jnp.bool_)],
        interpret=interpret,
    )(lab, w)
    return total[:n, :d], head[:n, :d]
