"""Pallas TPU kernels for the hot per-sweep aggregation.

The detection sweeps' inner op is: per padded neighbor row, the weighted
total of each row slot's label over the whole row, plus a first-occurrence
mask (ops/dense_adj.py:row_label_totals — there expressed as a minor-axis
sort + segmented scans).  Row widths are small (``d_cap`` <= 2048, typically
~100-200), so the whole aggregation fits VMEM as an O(D^2) broadcast-compare:

    total[i]   = sum_j w[j] * (lab[j] == lab[i])
    is_head[i] = no j < i with lab[j] == lab[i]

One VMEM-resident [BN, D, D] compare per node block replaces the sort's
log^2 passes; the weighted reduction over j vectorizes on the VPU.  No
inter-block communication, no HBM intermediates — a pure map over node
blocks, which is exactly the shape Pallas is for.

The public entry :func:`row_totals` handles padding to lane/TPU-friendly
shapes and falls back to interpret mode off-TPU (used by the CPU test suite
for bit-equivalence against the sort-based path).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Sentinel marking invalid row slots; must sort above any real label and
# equal the one used by ops/dense_adj.py.  A Python int, not a jnp constant:
# the kernel body must not close over traced arrays.
SENTINEL = 2**31 - 1


def fits_vmem(d: int, budget: int = 12 * 1024 * 1024) -> bool:
    """Whether the O(D^2) kernel can run at width ``d`` without faulting.

    Mosaic forces the node-block to >= 8 rows, so the [8, D', D'] compare
    temps (~6 bytes/element at padded D') are the floor cost; past the VMEM
    budget the kernel faults the TPU worker.  Callers (dense_adj auto-select)
    should use the sort-based path instead for wide rows.
    """
    dp = d + (-d) % 128
    return 8 * 6 * dp * dp <= budget


def _row_totals_kernel(lab_ref, w_ref, total_ref, head_ref):
    lab = lab_ref[...]                       # int32[BN, D]
    w = w_ref[...]                           # float32[BN, D]
    eq = lab[:, :, None] == lab[:, None, :]  # bool[BN, D, D]; [b, i, j]
    total_ref[...] = jnp.sum(
        jnp.where(eq, w[:, None, :], 0.0), axis=2)
    i_idx = jax.lax.broadcasted_iota(jnp.int32, eq.shape, 1)
    j_idx = jax.lax.broadcasted_iota(jnp.int32, eq.shape, 2)
    dup_earlier = jnp.any(eq & (j_idx < i_idx), axis=2)
    real = lab != SENTINEL
    head_ref[...] = (~dup_earlier) & real


@functools.partial(jax.jit, static_argnames=("block_n", "interpret"))
def row_totals(lab: jax.Array, w: jax.Array,
               block_n: int = None, interpret: bool = None
               ) -> Tuple[jax.Array, jax.Array]:
    """Per-slot label totals + first-occurrence mask for padded rows.

    ``lab`` int32[N, D] (SENTINEL = invalid slot, weight must be 0 there),
    ``w`` float32[N, D].  Returns ``(total float32[N, D], head bool[N, D])``
    with the same slot order as the input (no sorting).

    ``block_n`` defaults to a VMEM-budgeted size: the kernel's [BN, D, D]
    intermediates cost ~6 bytes/element, so BN shrinks as D grows (a fixed
    BN would blow the ~16MB VMEM budget past D ~ 350).  ``interpret``
    defaults to True off-TPU, where pallas has no native lowering.
    """
    n, d = lab.shape
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if block_n is None:
        dp_est = d + (-d) % 128
        budget = 4 * 1024 * 1024  # target VMEM for the O(BN*D^2) temps
        block_n = max(1, min(32, budget // (6 * dp_est * dp_est)))
        if not interpret:
            # Mosaic requires the second-to-last block dim to be a multiple
            # of 8 (jax pallas TPU lowering constraint).
            block_n = max(8, block_n - block_n % 8)
    n_pad = (-n) % block_n
    d_pad = (-d) % 128
    if n_pad or d_pad:
        lab = jnp.pad(lab, ((0, n_pad), (0, d_pad)),
                      constant_values=SENTINEL)
        w = jnp.pad(w, ((0, n_pad), (0, d_pad)))
    np_, dp = lab.shape

    grid = (np_ // block_n,)
    spec = pl.BlockSpec((block_n, dp), lambda i: (i, 0))
    total, head = pl.pallas_call(
        _row_totals_kernel,
        grid=grid,
        in_specs=[spec, spec],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct((np_, dp), jnp.float32),
                   jax.ShapeDtypeStruct((np_, dp), jnp.bool_)],
        interpret=interpret,
    )(lab, w)
    return total[:n, :d], head[:n, :d]
