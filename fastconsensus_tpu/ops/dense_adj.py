"""Padded dense-row adjacency + per-row neighbor-label aggregation.

The sorted-run machinery (ops/segment.py) pays one *global* lexsort of all
2·capacity directed edges **per sweep** of every detection kernel.  On TPU
that sort dominates the whole consensus round (measured: ~99% of round time
on the LFR-1k config).  This module re-expresses the same per-(node, label)
aggregation over a **fixed-width padded adjacency** ``[N, D]``:

* :func:`build_dense_adjacency` — one global sort per *detection call*
  (not per sweep) scatters the alive directed edges into per-node rows of
  static width ``slab.d_cap``;
* :func:`row_label_totals` — per sweep, a cheap *minor-axis* sort of each
  row by neighbor label + segmented scans gives every (node, label)
  weighted total.  Minor-axis sorts of width ~100 vectorize across the
  node and ensemble axes, unlike one giant cross-lane sort.

Rows wider than ``d_cap`` lose their overflow edges from *candidate
generation only* (the slab itself — co-membership counts, thresholds,
convergence — is untouched); ``build_dense_adjacency`` reports the dropped
count so callers can surface it.  ``pack_edges`` sizes ``d_cap`` at 1.25x
the input max degree (the per-sweep cost is quadratic in the padded width,
see graph.py), so overflow appears once triadic closure grows a hub's
degree past that slack; consensus_round surfaces it per round
(RoundStats.n_overflow).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from fastconsensus_tpu.graph import GraphSlab


class DenseAdj(NamedTuple):
    """Padded neighbor rows; invalid slots have ``valid=False``."""

    nbr: jax.Array        # int32[N, D] neighbor node id (0 where invalid)
    w: jax.Array          # float32[N, D] edge weight (0 where invalid)
    valid: jax.Array      # bool[N, D]
    n_overflow: jax.Array # int32[] directed edges dropped for row width


def build_dense_adjacency(slab: GraphSlab,
                          width: int = 0,
                          edge_mask: jax.Array = None) -> DenseAdj:
    """Scatter alive directed edges into [N, width] rows (one global sort).

    ``width`` defaults to ``slab.d_cap``.  ``edge_mask`` (bool[2*capacity],
    aligned with ``slab.directed()``) restricts which directed edges enter
    the rows — the hybrid path passes the non-hub-source mask so hub rows
    stay empty (their candidates go through hashed aggregation instead).
    """
    d = width or slab.d_cap
    if d <= 0:
        raise ValueError("row width is 0; pack with pack_edges or set d_cap")
    n = slab.n_nodes
    srcd, dstd, wd, ad = slab.directed()
    ad = ad & (srcd != dstd)  # self-loops never vote
    if edge_mask is not None:
        ad = ad & edge_mask
    key = jnp.where(ad, srcd, n)
    order = jnp.argsort(key)
    ssrc = key[order]
    sdst = dstd[order]
    sw = wd[order]
    offsets = jnp.searchsorted(ssrc, jnp.arange(n + 1, dtype=jnp.int32)
                               ).astype(jnp.int32)
    pos = jnp.arange(ssrc.shape[0], dtype=jnp.int32) - \
        offsets[jnp.clip(ssrc, 0, n - 1)]
    ok = (ssrc < n) & (pos < d)
    flat = jnp.where(ok, ssrc * d + pos, n * d)

    nbr = jnp.zeros((n * d + 1,), jnp.int32).at[flat].set(
        sdst, mode="drop")[:-1].reshape(n, d)
    w = jnp.zeros((n * d + 1,), jnp.float32).at[flat].set(
        sw, mode="drop")[:-1].reshape(n, d)
    valid = jnp.zeros((n * d + 1,), bool).at[flat].set(
        True, mode="drop")[:-1].reshape(n, d)
    n_overflow = jnp.sum(((ssrc < n) & ~ok).astype(jnp.int32))
    return DenseAdj(nbr=nbr, w=w, valid=valid, n_overflow=n_overflow)


class HybridAdj(NamedTuple):
    """Degree-partitioned adjacency: complete dense rows for nodes with
    degree <= d_hyb, plus a compacted directed-edge prefix for the hubs.

    The hash move path's per-sweep cost is O(capacity) scatter work
    regardless of how few nodes are actually hub-like; this layout confines
    the scatters to the hub edges (a small static budget, slab.hub_cap) and
    serves the ~95% low-degree nodes from narrow Pallas-friendly rows.
    Non-hub rows are complete by construction (degree <= row width), so the
    dense side is exact; the hub side inherits the hash tables' documented
    collision approximation (ops/segment.py:HashTables).
    """

    adj: DenseAdj         # [N, d_hyb] rows; empty for hub nodes
    is_hub: jax.Array     # bool[N] degree > d_hyb at build time
    hsrc: jax.Array       # int32[hub_cap] compacted hub-source directed edges
    hdst: jax.Array       # int32[hub_cap]
    hw: jax.Array         # float32[hub_cap]
    hvalid: jax.Array     # bool[hub_cap]
    n_hub_overflow: jax.Array  # int32[] hub edges dropped for hub_cap


def build_hybrid(slab: GraphSlab) -> HybridAdj:
    """Partition directed edges by source degree (one global sort, built
    once per detection call like build_dense_adjacency)."""
    if slab.d_hyb <= 0 or slab.hub_cap <= 0:
        raise ValueError("slab carries no hybrid sizing (d_hyb/hub_cap); "
                         "pack with pack_edges")
    n = slab.n_nodes
    degrees = slab.degrees()
    is_hub = degrees > slab.d_hyb

    srcd, dstd, wd, ad = slab.directed()
    ad = ad & (srcd != dstd)
    hub_src = is_hub[jnp.clip(srcd, 0, n - 1)]
    adj = build_dense_adjacency(slab, width=slab.d_hyb,
                                edge_mask=~hub_src)

    # Compact hub edges into the static prefix.  Stable sort keeps slot
    # order, but nothing downstream depends on position (tie-breaks are
    # pair-keyed, sums are exact integers), so growth stays
    # result-preserving except *which* overflow edges drop when hub_cap
    # saturates (counted below, surfaced like RoundStats.n_overflow).
    hub_e = ad & hub_src
    order = jnp.argsort(jnp.where(hub_e, 0, 1), stable=True)
    take = order[:slab.hub_cap]
    hvalid = hub_e[take]
    hsrc = jnp.where(hvalid, srcd[take], n)
    hdst = jnp.where(hvalid, dstd[take], n)
    hw = jnp.where(hvalid, wd[take], 0.0)
    n_hub = jnp.sum(hub_e.astype(jnp.int32))
    n_hub_overflow = jnp.maximum(n_hub - slab.hub_cap, 0)
    return HybridAdj(adj=adj, is_hub=is_hub, hsrc=hsrc, hdst=hdst, hw=hw,
                     hvalid=hvalid, n_hub_overflow=n_hub_overflow)


class RowTotals(NamedTuple):
    """Per-row candidate labels with aggregated neighbor weight.

    ``label[n, i]`` is a candidate community for node n with total incident
    weight ``total[n, i]``; only slots with ``is_head`` are distinct
    candidates (duplicates of a label within a row are masked off).  The
    node's own current label is always present as a candidate (appended with
    weight 0 before aggregation, so "stay" is always scored).
    """

    label: jax.Array    # int32[N, D+1]
    total: jax.Array    # float32[N, D+1]
    is_head: jax.Array  # bool[N, D+1]


def row_label_totals(adj: DenseAdj, labels: jax.Array,
                     use_pallas: bool = None) -> RowTotals:
    """Aggregate neighbor weight per (row, neighbor-label): the dense analog
    of ops/segment.py:node_label_runs.

    Two equivalent lowerings: a Pallas O(D^2) in-VMEM broadcast-compare
    (ops/pallas_kernels.py — default on TPU) and a minor-axis sort +
    segmented scans (default elsewhere).  Candidate slot *order* differs
    between the two; consumers must treat RowTotals as an unordered
    candidate set (best_candidate does).
    """
    n, d = adj.nbr.shape
    sentinel = jnp.int32(2**31 - 1)

    lab_n = jnp.where(adj.valid, labels[jnp.clip(adj.nbr, 0, n - 1)],
                      sentinel)
    w = jnp.where(adj.valid, adj.w, 0.0)
    # append the own-label candidate with zero weight
    lab_ext = jnp.concatenate([lab_n, labels[:, None]], axis=1)
    w_ext = jnp.concatenate([w, jnp.zeros((n, 1), jnp.float32)], axis=1)

    if use_pallas is None:
        import os

        env = os.environ.get("FCTPU_PALLAS", "")
        from fastconsensus_tpu.ops import pallas_kernels as pk

        if env in ("0", "1"):
            use_pallas = env == "1"
        else:
            # Wide rows blow the kernel's VMEM budget (the [8, D, D] compare
            # temps fault the TPU worker past ~D=500); the sort path also
            # scales better than O(D^2) there.
            use_pallas = (jax.default_backend() == "tpu"
                          and pk.fits_vmem(d + 1))
    if use_pallas:
        from fastconsensus_tpu.ops import pallas_kernels as pk

        total, head = pk.row_totals(lab_ext, w_ext)
        real = lab_ext != sentinel
        return RowTotals(label=jnp.where(real, lab_ext, 0),
                         total=jnp.where(real, total, 0.0),
                         is_head=head)

    slab_sorted, w_sorted = jax.lax.sort((lab_ext, w_ext), dimension=1,
                                         num_keys=1)
    head = jnp.concatenate([
        jnp.ones((n, 1), bool),
        slab_sorted[:, 1:] != slab_sorted[:, :-1]], axis=1)
    csum = jnp.cumsum(w_sorted, axis=1)
    iota = jnp.broadcast_to(jnp.arange(d + 1, dtype=jnp.int32), (n, d + 1))
    start = jax.lax.cummax(jnp.where(head, iota, 0), axis=1)
    tail = jnp.concatenate([head[:, 1:], jnp.ones((n, 1), bool)], axis=1)
    end = jax.lax.cummin(jnp.where(tail, iota, d), axis=1, reverse=True)
    csum_end = jnp.take_along_axis(csum, end, axis=1)
    csum_start = jnp.take_along_axis(csum, start, axis=1)
    w_start = jnp.take_along_axis(w_sorted, start, axis=1)
    total = csum_end - csum_start + w_start
    real = slab_sorted != sentinel
    return RowTotals(label=jnp.where(real, slab_sorted, 0),
                     total=jnp.where(real, total, 0.0),
                     is_head=head & real)


def best_candidate(tot: RowTotals, score: jax.Array, labels: jax.Array
                   ) -> Tuple[jax.Array, jax.Array]:
    """Argmax candidate label per row.

    ``score[N, D+1]`` is the caller's scored candidates (gain + jitter);
    non-head slots must already be masked to -inf.  Returns
    ``(best_label, want_move)`` where ``want_move`` is False for rows whose
    best is their current label or with no finite score.
    """
    idx = jnp.argmax(score, axis=1)
    best = jnp.take_along_axis(tot.label, idx[:, None], axis=1)[:, 0]
    best_score = jnp.take_along_axis(score, idx[:, None], axis=1)[:, 0]
    has = jnp.isfinite(best_score)
    best = jnp.where(has, best, labels)
    return best, has & (best != labels)
