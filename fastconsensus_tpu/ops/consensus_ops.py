"""Jitted building blocks of the consensus round.

Each op re-expresses one phase of the reference's consensus loop
(``fast_consensus.py:129-411``) as a static-shape array program over the
GraphSlab:

* co-membership accumulation  (reference fc:150-159)  -> comembership_counts
* tau-thresholding            (fc:163-168)            -> threshold_weights
* delta-convergence           (fc:17-37)              -> convergence_stats
* triadic closure             (fc:175-191)            -> sample_wedges + insert_edges
* singleton repair            (fc:193-195)            -> singleton_candidates

The consensus matrix never materializes: co-membership counts are a gather +
compare + sum along the partition axis of a labels[n_p, N] array, restricted
to the edge slab (the paper's sparse-consensus trick, arXiv:1902.04014).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from fastconsensus_tpu.graph import GraphSlab


def comembership_counts(labels: jax.Array, src: jax.Array, dst: jax.Array
                        ) -> jax.Array:
    """Per-edge count of partitions whose endpoints share a community.

    ``labels`` is int32[n_p, N]; returns float32[E] in [0, n_p].  This is the
    one-op replacement for the reference's O(E * n_p) Python dict loops
    (fc:150-159 louvain, fc:213-221 leiden, fc:273-280 infomap/lpm).
    """
    agree = labels[:, src] == labels[:, dst]            # bool[n_p, E]
    return jnp.sum(agree, axis=0, dtype=jnp.float32)


def update_weights(slab: GraphSlab, counts: jax.Array, n_p: int) -> GraphSlab:
    """New round weights: co-membership counts, skipping converged edges.

    Edges whose weight already equals n_p (all partitions agreed last round)
    keep it — the intended "skip already-converged edges" semantics that
    fast_consensus.py's louvain branch garbles (else-misattachment, fc:156-159)
    and new/merged_consensus implement (nc:157-163, mc:185-192).
    """
    frozen = slab.weight >= jnp.float32(n_p)
    new_w = jnp.where(frozen, slab.weight, counts)
    return slab.with_weights(jnp.where(slab.alive, new_w, 0.0))


def threshold_weights(slab: GraphSlab, tau: float, n_p: int) -> GraphSlab:
    """Kill edges with weight < tau * n_p (strict, matching fc:163-168)."""
    keep = slab.alive & (slab.weight >= jnp.float32(tau) * jnp.float32(n_p))
    return slab.with_weights(jnp.where(keep, slab.weight, 0.0), alive=keep)


class ConvergenceStats(NamedTuple):
    converged: jax.Array      # bool[]
    n_unconverged: jax.Array  # int32[]  alive edges with 0 < w < n_p
    n_alive: jax.Array        # int32[]


def convergence_stats(slab: GraphSlab, n_p: int, delta: float
                      ) -> ConvergenceStats:
    """Converged iff #(alive edges with weight not in {0, n_p}) <= delta*|E|.

    Matches check_consensus_graph (fc:17-37): weight-0 edges (closure edges no
    partition agreed on) count in the denominator but not the numerator.
    """
    mid = slab.alive & (slab.weight > 0) & (slab.weight < jnp.float32(n_p))
    n_mid = jnp.sum(mid.astype(jnp.int32))
    n_alive = jnp.sum(slab.alive.astype(jnp.int32))
    converged = n_mid.astype(jnp.float32) <= jnp.float32(delta) * \
        n_alive.astype(jnp.float32)
    return ConvergenceStats(converged, n_mid, n_alive)


class CSR(NamedTuple):
    """Sorted-by-source view of the alive directed edges.

    ``neighbors[offsets[n]:offsets[n+1]]`` are node n's alive neighbors.
    Static shape 2*capacity; dead entries sort to the tail.
    """

    offsets: jax.Array    # int32[n_nodes + 1]
    neighbors: jax.Array  # int32[2 * capacity]


def build_csr(slab: GraphSlab) -> CSR:
    srcd, dstd, _, ad = slab.directed()
    key = jnp.where(ad, srcd, slab.n_nodes)
    order = jnp.argsort(key)
    sorted_key = key[order]
    offsets = jnp.searchsorted(
        sorted_key, jnp.arange(slab.n_nodes + 1, dtype=jnp.int32)
    ).astype(jnp.int32)
    return CSR(offsets=offsets, neighbors=dstd[order])


def sample_wedges(key: jax.Array, csr: CSR, n_nodes: int, n_samples: int
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Triadic-closure wedge sampling (reference fc:175-191).

    L times: pick a uniform random node; if it has >= 2 alive neighbors, pick
    two distinct ones uniformly.  Returns canonical candidate endpoints
    (u, v) with a validity mask; candidates may duplicate existing edges —
    insert_edges dedups (the reference's ``has_edge`` test, fc:183).
    """
    k_node, k_i, k_j = jax.random.split(key, 3)
    anchors = jax.random.randint(k_node, (n_samples,), 0, n_nodes,
                                 dtype=jnp.int32)
    left = csr.offsets[anchors]
    deg = csr.offsets[anchors + 1] - left
    valid = deg >= 2
    degf = jnp.maximum(deg, 2).astype(jnp.float32)
    i = jnp.floor(jax.random.uniform(k_i, (n_samples,)) * degf).astype(jnp.int32)
    j = jnp.floor(jax.random.uniform(k_j, (n_samples,)) * (degf - 1.0)
                  ).astype(jnp.int32)
    i = jnp.minimum(i, deg - 1)
    j = jnp.minimum(j, deg - 2)
    j = j + (j >= i)  # distinct pair, uniform over ordered pairs
    a = csr.neighbors[jnp.clip(left + i, 0, csr.neighbors.shape[0] - 1)]
    b = csr.neighbors[jnp.clip(left + j, 0, csr.neighbors.shape[0] - 1)]
    u = jnp.minimum(a, b)
    v = jnp.maximum(a, b)
    valid = valid & (u != v)
    return u, v, valid


def sample_wedges_scatter(key: jax.Array, slab: GraphSlab, n_samples: int
                          ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Sort-free triadic-closure sampling (reference fc:175-191 semantics).

    The CSR-based :func:`sample_wedges` needs a global argsort of the
    directed edges every round; under an edge-sharded mesh that sort
    re-gathers the whole slab onto every device (parallel/sharding.py
    module notes).  This variant draws, per round, ``ceil(L / N)`` rounds
    of *random-partner pairs*: for every node u, two independent uniform
    random alive neighbors p1(u), p2(u), each realized as a scatter-argmax
    over per-directed-edge priorities — O(E) scatter work that XLA keeps
    edge-local.  A draw's candidate for anchor u is (p1(u), p2(u)),
    rejected when equal (matches the reference's distinct-pair rule; a
    degree-<2 node always rejects).  Conditioned on acceptance the pair is
    exactly uniform over ordered distinct neighbor pairs — the reference's
    distribution.  Documented deviation: anchors are swept once per draw
    (every node appears ceil(L/N) times) instead of L independent uniform
    node draws; a key-rotated ``n_samples``-wide window of the draw grid
    is kept (rotation prevents the remainder draws from always favoring
    the lowest node ids — see partner_draw_batches).

    Priorities are content-keyed (hash of (u, v, salt), as
    segment.pair_jitter) so auto-growth replay reproduces the identical
    wedges (graph.grow_slab's result-preservation contract).
    """
    from fastconsensus_tpu.ops import segment as seg

    n = slab.n_nodes
    srcd, dstd, _, ad = slab.directed()
    valid_e = ad & (srcd != dstd)
    u, v, ok = partner_draw_batches(
        key, srcd, dstd, valid_e, n, slab.capacity, n_samples,
        lambda score, segs, lab, m, num: seg.scatter_argmax_label(
            segs, score, lab, m, num))
    return jnp.where(ok, u, 0), jnp.where(ok, v, 0), ok


def partner_draw_batches(key, srcd, dstd, valid_e, n: int, capacity: int,
                         n_samples: int, argmax
                         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """The wedge sampler's draw engine, shared verbatim by the unsharded
    and shard_map tails (their winners must be bit-identical — the mesh
    parity tests depend on it; only the argmax callee differs).

    Batches G draws into ONE flat scatter-argmax over (draw, node)
    segments — per-draw passes cost 1.6x a whole emailEu consensus on CPU
    (measured round 3) — and runs ``lax.scan`` over fixed-size batch
    groups so program size stays O(1) in the draw count (an unrolled loop
    blew up tunnel compiles on dense graphs).  The group size is bounded
    by BOTH the [G, 2*capacity] priority temporaries and the [G*(n+1)]
    argmax buffers (the latter scale with the GLOBAL node count even on a
    capacity-sharded mesh).

    ``argmax(score, segs, label, valid, num) -> (best, score, has)``.
    """
    from fastconsensus_tpu.ops import segment as seg

    draws = -(-n_samples // max(n, 1))
    if draws == 0:
        z = jnp.zeros((0,), jnp.int32)
        return z, z, jnp.zeros((0,), bool)
    group = min(draws, max(1, 32_000_000 // max(2 * capacity, n + 1)))
    n_groups = -(-draws // group)
    ks = jax.vmap(
        lambda d: jax.random.split(jax.random.fold_in(key, d)))(
        jnp.arange(n_groups * group, dtype=jnp.int32))  # padded [D', 2]

    def partners(keys):
        g = keys.shape[0]
        pri = jax.vmap(lambda k: seg.pair_jitter(k, srcd, dstd, 1.0))(keys)
        seg_ids = (jnp.arange(g, dtype=jnp.int32)[:, None] * (n + 1) +
                   jnp.where(valid_e, srcd, n)[None, :]).reshape(-1)
        lab = jnp.broadcast_to(dstd, (g,) + dstd.shape).reshape(-1)
        ok = jnp.broadcast_to(valid_e, (g,) + valid_e.shape).reshape(-1)
        best, _, has = argmax(pri.reshape(-1), seg_ids, lab, ok,
                              g * (n + 1))
        return (best.reshape(g, n + 1)[:, :n],
                has.reshape(g, n + 1)[:, :n])

    def body(_, kchunk):
        p1, h1 = partners(kchunk[:, 0])
        p2, h2 = partners(kchunk[:, 1])
        ok = h1 & h2 & (p1 != p2)
        return None, (jnp.minimum(p1, p2), jnp.maximum(p1, p2), ok)

    _, (us, vs, oks) = jax.lax.scan(
        body, None, ks.reshape(n_groups, group, 2))
    # Keep a key-rotated window of the (draw, node) grid: keeping the first
    # n_samples would hand every remainder draw (n_samples % n != 0) to the
    # lowest node ids — a systematic per-round anchor bias (ADVICE r3).
    # Offset and modulus derive from the UNPADDED grid (draws * n): the
    # padded count n_groups*group depends on capacity through the group
    # cap, and capacity differs between the unsharded tail (global) and
    # the shard_map tail (local chunk) and changes under grow_and_replay —
    # a capacity-dependent window would break both the mesh bit-parity
    # contract and replay determinism.  fold_in(key, draws) may coincide
    # with a PADDING draw's key (indices >= draws); those draws' outputs
    # are never inside the unpadded window, so the collision is inert.
    total = draws * n
    if total >= 2 ** 31:
        # jax.random.randint(high=total) and the int32 window arithmetic
        # below both break past 2^31 entries; fail loudly instead of
        # wrapping to negative indices (ADVICE round 4).
        raise ValueError(
            f"wedge grid draws*n = {total} exceeds int32 indexing; "
            "shard the closure-candidate axis before scaling here")
    off = jax.random.randint(
        jax.random.fold_in(key, draws), (), 0, total, dtype=jnp.int32)
    # where-based wrap instead of (arange + off) % total: the raw sum can
    # reach 2*total and would wrap int32 before the modulus once
    # total > 2^30; each selected lane below stays < total.
    ar = jnp.arange(n_samples, dtype=jnp.int32)
    rem = jnp.int32(total) - off
    idx = jnp.where(ar < rem, ar + off, ar - rem)
    return us.reshape(-1)[idx], vs.reshape(-1)[idx], oks.reshape(-1)[idx]


def insert_edges_hash(slab: GraphSlab,
                      cand_u: jax.Array,
                      cand_v: jax.Array,
                      cand_w: jax.Array,
                      cand_valid: jax.Array,
                      unique_new: bool = False
                      ) -> Tuple[GraphSlab, jax.Array]:
    """Sort-free :func:`insert_edges`: hash-table dedup + prefix-sum slots.

    Replaces the global lexsort over (capacity + k) entries — which under
    an edge-sharded mesh re-gathers the slab — with O(E + k) scatters:

    * existing-edge membership: the two-table scheme of
      segment.HashTables over the alive canonical (u, v) pairs.  A
      candidate whose pair is present reads > 0 in both tables
      (no false negatives, so a duplicate edge can never be inserted); an
      absent pair collides in both tables with probability ~(E/B)^2 and is
      then dropped — closure candidates are random samples, so a rare
      false drop is sampling noise, not an error.
    * candidate-vs-candidate dedup: scatter-min of the candidate index
      into two tag tables; a candidate survives if it holds the minimum in
      either bucket.  Duplicate candidates share both buckets, so exactly
      the first occurrence survives (the lexsort rule); two *distinct*
      candidates drop one only on a double collision (~(k/B)^2).
    * free slots: prefix-sum rank over dead slots + one scatter — the same
      slot order as argsort(alive, stable), preserving grow_slab's
      result-preservation contract.

    Table sizes derive from the growth-stable cap hint, so auto-growth
    replays identically.

    ``unique_new=True`` declares the candidates already pairwise-distinct
    and absent from the slab (singleton repair guarantees both —
    singleton_candidates); membership and dedup are skipped entirely, so
    such candidates are EXACT — a repair edge must never be lost to a
    hash collision.
    """
    from fastconsensus_tpu.models.louvain import _cap_hint
    from fastconsensus_tpu.ops import segment as seg

    cap = slab.capacity
    k = cand_u.shape[0]
    n = slab.n_nodes
    cu = cand_u.astype(jnp.int32)
    cv = cand_v.astype(jnp.int32)

    if unique_new:
        surv = cand_valid
    else:
        # existing-edge membership (presence sums over canonical pairs)
        b_e = seg.hash_buckets_for(_cap_hint(slab))
        tables = seg.build_hash_totals(
            slab.src, slab.dst, jnp.ones((cap,), jnp.float32), slab.alive,
            b_e)
        exists = seg.lookup_hash_totals(tables, cu, cv) > 0.0

        # first-occurrence-wins dedup among the candidates themselves
        b_c = seg.hash_buckets_for(k)
        h1 = seg._hash_mix(cu, cv, 0x9E3779B1, 0x85EBCA77, b_c)
        h2 = seg._hash_mix(cu, cv, 0x27D4EB2F, 0x165667B1, b_c)
        tag = jnp.arange(k, dtype=jnp.int32)
        live = cand_valid & ~exists
        big = jnp.int32(k)
        t1 = jnp.full((b_c + 1,), big, jnp.int32).at[
            jnp.where(live, h1, b_c)].min(tag, mode="drop")
        t2 = jnp.full((b_c + 1,), big, jnp.int32).at[
            jnp.where(live, h2, b_c)].min(tag, mode="drop")
        surv = live & ((t1[h1] == tag) | (t2[h2] == tag))

    # free-slot assignment: rank dead slots in slot order (prefix sum),
    # then invert rank -> slot with one scatter
    dead = ~slab.alive
    rank_dead = jnp.cumsum(dead.astype(jnp.int32)) - 1
    free_slots = jnp.full((cap,), cap, jnp.int32).at[
        jnp.where(dead, rank_dead, cap)].set(
        jnp.arange(cap, dtype=jnp.int32), mode="drop")
    n_free = jnp.sum(dead.astype(jnp.int32))
    rank = jnp.cumsum(surv.astype(jnp.int32)) - 1
    ok = surv & (rank < n_free)
    slot = jnp.where(ok, free_slots[jnp.clip(rank, 0, cap - 1)], cap)

    src = slab.src.at[slot].set(cu, mode="drop")
    dst = slab.dst.at[slot].set(cv, mode="drop")
    weight = slab.weight.at[slot].set(cand_w.astype(jnp.float32),
                                      mode="drop")
    alive = slab.alive.at[slot].set(True, mode="drop")
    n_dropped = jnp.sum(surv.astype(jnp.int32)) - \
        jnp.sum(ok.astype(jnp.int32))
    new_slab = dataclasses.replace(slab, src=src, dst=dst, weight=weight,
                                   alive=alive)
    return new_slab, n_dropped


def insert_edges(slab: GraphSlab,
                 cand_u: jax.Array,
                 cand_v: jax.Array,
                 cand_w: jax.Array,
                 cand_valid: jax.Array) -> Tuple[GraphSlab, jax.Array]:
    """Insert candidate edges into free slots, deduplicating.

    A candidate is dropped if its (u, v) already exists alive in the slab or
    appeared earlier in the candidate list; survivors fill dead slots in slot
    order.  Returns the new slab and the number of survivors dropped for lack
    of capacity (reported, never an error — the reference can grow its
    networkx graph unboundedly; we trade that for static shapes).
    """
    cap = slab.capacity
    k = cand_u.shape[0]
    n = slab.n_nodes

    all_u = jnp.concatenate([jnp.where(slab.alive, slab.src, n),
                             jnp.where(cand_valid, cand_u, n)]).astype(jnp.int32)
    all_v = jnp.concatenate([jnp.where(slab.alive, slab.dst, n),
                             jnp.where(cand_valid, cand_v, n)]).astype(jnp.int32)
    tag = jnp.concatenate([jnp.zeros((cap,), jnp.int32),
                           1 + jnp.arange(k, dtype=jnp.int32)])
    order = jnp.lexsort((tag, all_v, all_u))
    su, sv, st = all_u[order], all_v[order], tag[order]
    dup_prev = jnp.concatenate([
        jnp.zeros((1,), dtype=bool),
        (su[1:] == su[:-1]) & (sv[1:] == sv[:-1]),
    ])
    surviving_sorted = (~dup_prev) & (st > 0) & (su < n)
    # map survival back to candidate order via the unique tags 1..k
    surv = jnp.zeros((k + 1,), dtype=bool).at[st].set(surviving_sorted,
                                                      mode="drop")[1:]

    free_slots = jnp.argsort(slab.alive, stable=True)  # dead slots first
    n_free = jnp.sum((~slab.alive).astype(jnp.int32))
    rank = jnp.cumsum(surv.astype(jnp.int32)) - 1
    ok = surv & (rank < n_free)
    slot = jnp.where(ok, free_slots[jnp.clip(rank, 0, cap - 1)], cap)

    src = slab.src.at[slot].set(cand_u.astype(jnp.int32), mode="drop")
    dst = slab.dst.at[slot].set(cand_v.astype(jnp.int32), mode="drop")
    weight = slab.weight.at[slot].set(cand_w.astype(jnp.float32), mode="drop")
    alive = slab.alive.at[slot].set(True, mode="drop")
    n_dropped = jnp.sum(surv.astype(jnp.int32)) - jnp.sum(ok.astype(jnp.int32))
    new_slab = dataclasses.replace(slab, src=src, dst=dst, weight=weight,
                                   alive=alive)
    return new_slab, n_dropped


def singleton_candidates(slab: GraphSlab, prev: GraphSlab
                         ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Reconnect isolated nodes to their strongest previous-round neighbor.

    The reference reattaches via the *lowest*-weight previous edge (ascending
    sort then ``[0]``, fc:193-195) while its docstring says maximum
    (mc:94); we implement the documented/paper intent — maximum — and note the
    deviation (SURVEY.md §2.22.11).  Returns candidate (u, v, w, valid) arrays
    of length n_nodes, to be passed to insert_edges.
    """
    n = slab.n_nodes
    isolated = slab.degrees() == 0

    psrc, pdst, pw, pad = prev.directed()
    pseg = jnp.where(pad, psrc, n)
    neg_inf = jnp.float32(-jnp.inf)
    best_w = jax.ops.segment_max(jnp.where(pad, pw, neg_inf), pseg,
                                 num_segments=n + 1)[:-1]
    at_best = pad & (pw == best_w[jnp.clip(pseg, 0, n - 1)]) & (pseg < n)
    partner = jax.ops.segment_max(jnp.where(at_best, pdst, -1), pseg,
                                  num_segments=n + 1)[:-1]

    nodes = jnp.arange(n, dtype=jnp.int32)
    valid = isolated & (partner >= 0)
    # Exact self-dedup: two isolated nodes that pick each other both
    # propose the same canonical pair — keep the lower node's proposal.
    # With this, repair candidates are UNIQUE and (one endpoint being
    # isolated) cannot already exist in the slab, so the insert may take
    # the exact unique_new path: a repair must never be lost to a hash
    # collision (the reference guarantees reattachment, fc:193-195).
    p_c = jnp.clip(partner, 0, n - 1)
    mutual = valid & (partner < nodes) & valid[p_c] & (partner[p_c] == nodes)
    valid = valid & ~mutual
    u = jnp.minimum(nodes, partner)
    v = jnp.maximum(nodes, partner)
    w = jnp.where(jnp.isfinite(best_w), best_w, 0.0)
    return jnp.where(valid, u, 0), jnp.where(valid, v, 0), w, valid
