"""Jitted building blocks of the consensus round.

Each op re-expresses one phase of the reference's consensus loop
(``fast_consensus.py:129-411``) as a static-shape array program over the
GraphSlab:

* co-membership accumulation  (reference fc:150-159)  -> comembership_counts
* tau-thresholding            (fc:163-168)            -> threshold_weights
* delta-convergence           (fc:17-37)              -> convergence_stats
* triadic closure             (fc:175-191)            -> sample_wedges + insert_edges
* singleton repair            (fc:193-195)            -> singleton_candidates

The consensus matrix never materializes: co-membership counts are a gather +
compare + sum along the partition axis of a labels[n_p, N] array, restricted
to the edge slab (the paper's sparse-consensus trick, arXiv:1902.04014).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from fastconsensus_tpu.graph import GraphSlab


def comembership_counts(labels: jax.Array, src: jax.Array, dst: jax.Array
                        ) -> jax.Array:
    """Per-edge count of partitions whose endpoints share a community.

    ``labels`` is int32[n_p, N]; returns float32[E] in [0, n_p].  This is the
    one-op replacement for the reference's O(E * n_p) Python dict loops
    (fc:150-159 louvain, fc:213-221 leiden, fc:273-280 infomap/lpm).
    """
    agree = labels[:, src] == labels[:, dst]            # bool[n_p, E]
    return jnp.sum(agree, axis=0, dtype=jnp.float32)


def update_weights(slab: GraphSlab, counts: jax.Array, n_p: int) -> GraphSlab:
    """New round weights: co-membership counts, skipping converged edges.

    Edges whose weight already equals n_p (all partitions agreed last round)
    keep it — the intended "skip already-converged edges" semantics that
    fast_consensus.py's louvain branch garbles (else-misattachment, fc:156-159)
    and new/merged_consensus implement (nc:157-163, mc:185-192).
    """
    frozen = slab.weight >= jnp.float32(n_p)
    new_w = jnp.where(frozen, slab.weight, counts)
    return slab.with_weights(jnp.where(slab.alive, new_w, 0.0))


def threshold_weights(slab: GraphSlab, tau: float, n_p: int) -> GraphSlab:
    """Kill edges with weight < tau * n_p (strict, matching fc:163-168)."""
    keep = slab.alive & (slab.weight >= jnp.float32(tau) * jnp.float32(n_p))
    return slab.with_weights(jnp.where(keep, slab.weight, 0.0), alive=keep)


class ConvergenceStats(NamedTuple):
    converged: jax.Array      # bool[]
    n_unconverged: jax.Array  # int32[]  alive edges with 0 < w < n_p
    n_alive: jax.Array        # int32[]


def convergence_stats(slab: GraphSlab, n_p: int, delta: float
                      ) -> ConvergenceStats:
    """Converged iff #(alive edges with weight not in {0, n_p}) <= delta*|E|.

    Matches check_consensus_graph (fc:17-37): weight-0 edges (closure edges no
    partition agreed on) count in the denominator but not the numerator.
    """
    mid = slab.alive & (slab.weight > 0) & (slab.weight < jnp.float32(n_p))
    n_mid = jnp.sum(mid.astype(jnp.int32))
    n_alive = jnp.sum(slab.alive.astype(jnp.int32))
    converged = n_mid.astype(jnp.float32) <= jnp.float32(delta) * \
        n_alive.astype(jnp.float32)
    return ConvergenceStats(converged, n_mid, n_alive)


class CSR(NamedTuple):
    """Sorted-by-source view of the alive directed edges.

    ``neighbors[offsets[n]:offsets[n+1]]`` are node n's alive neighbors.
    Static shape 2*capacity; dead entries sort to the tail.
    """

    offsets: jax.Array    # int32[n_nodes + 1]
    neighbors: jax.Array  # int32[2 * capacity]


def build_csr(slab: GraphSlab) -> CSR:
    srcd, dstd, _, ad = slab.directed()
    key = jnp.where(ad, srcd, slab.n_nodes)
    order = jnp.argsort(key)
    sorted_key = key[order]
    offsets = jnp.searchsorted(
        sorted_key, jnp.arange(slab.n_nodes + 1, dtype=jnp.int32)
    ).astype(jnp.int32)
    return CSR(offsets=offsets, neighbors=dstd[order])


def sample_wedges(key: jax.Array, csr: CSR, n_nodes: int, n_samples: int
                  ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Triadic-closure wedge sampling (reference fc:175-191).

    L times: pick a uniform random node; if it has >= 2 alive neighbors, pick
    two distinct ones uniformly.  Returns canonical candidate endpoints
    (u, v) with a validity mask; candidates may duplicate existing edges —
    insert_edges dedups (the reference's ``has_edge`` test, fc:183).
    """
    k_node, k_i, k_j = jax.random.split(key, 3)
    anchors = jax.random.randint(k_node, (n_samples,), 0, n_nodes,
                                 dtype=jnp.int32)
    left = csr.offsets[anchors]
    deg = csr.offsets[anchors + 1] - left
    valid = deg >= 2
    degf = jnp.maximum(deg, 2).astype(jnp.float32)
    i = jnp.floor(jax.random.uniform(k_i, (n_samples,)) * degf).astype(jnp.int32)
    j = jnp.floor(jax.random.uniform(k_j, (n_samples,)) * (degf - 1.0)
                  ).astype(jnp.int32)
    i = jnp.minimum(i, deg - 1)
    j = jnp.minimum(j, deg - 2)
    j = j + (j >= i)  # distinct pair, uniform over ordered pairs
    a = csr.neighbors[jnp.clip(left + i, 0, csr.neighbors.shape[0] - 1)]
    b = csr.neighbors[jnp.clip(left + j, 0, csr.neighbors.shape[0] - 1)]
    u = jnp.minimum(a, b)
    v = jnp.maximum(a, b)
    valid = valid & (u != v)
    return u, v, valid


def insert_edges(slab: GraphSlab,
                 cand_u: jax.Array,
                 cand_v: jax.Array,
                 cand_w: jax.Array,
                 cand_valid: jax.Array) -> Tuple[GraphSlab, jax.Array]:
    """Insert candidate edges into free slots, deduplicating.

    A candidate is dropped if its (u, v) already exists alive in the slab or
    appeared earlier in the candidate list; survivors fill dead slots in slot
    order.  Returns the new slab and the number of survivors dropped for lack
    of capacity (reported, never an error — the reference can grow its
    networkx graph unboundedly; we trade that for static shapes).
    """
    cap = slab.capacity
    k = cand_u.shape[0]
    n = slab.n_nodes

    all_u = jnp.concatenate([jnp.where(slab.alive, slab.src, n),
                             jnp.where(cand_valid, cand_u, n)]).astype(jnp.int32)
    all_v = jnp.concatenate([jnp.where(slab.alive, slab.dst, n),
                             jnp.where(cand_valid, cand_v, n)]).astype(jnp.int32)
    tag = jnp.concatenate([jnp.zeros((cap,), jnp.int32),
                           1 + jnp.arange(k, dtype=jnp.int32)])
    order = jnp.lexsort((tag, all_v, all_u))
    su, sv, st = all_u[order], all_v[order], tag[order]
    dup_prev = jnp.concatenate([
        jnp.zeros((1,), dtype=bool),
        (su[1:] == su[:-1]) & (sv[1:] == sv[:-1]),
    ])
    surviving_sorted = (~dup_prev) & (st > 0) & (su < n)
    # map survival back to candidate order via the unique tags 1..k
    surv = jnp.zeros((k + 1,), dtype=bool).at[st].set(surviving_sorted,
                                                      mode="drop")[1:]

    free_slots = jnp.argsort(slab.alive, stable=True)  # dead slots first
    n_free = jnp.sum((~slab.alive).astype(jnp.int32))
    rank = jnp.cumsum(surv.astype(jnp.int32)) - 1
    ok = surv & (rank < n_free)
    slot = jnp.where(ok, free_slots[jnp.clip(rank, 0, cap - 1)], cap)

    src = slab.src.at[slot].set(cand_u.astype(jnp.int32), mode="drop")
    dst = slab.dst.at[slot].set(cand_v.astype(jnp.int32), mode="drop")
    weight = slab.weight.at[slot].set(cand_w.astype(jnp.float32), mode="drop")
    alive = slab.alive.at[slot].set(True, mode="drop")
    n_dropped = jnp.sum(surv.astype(jnp.int32)) - jnp.sum(ok.astype(jnp.int32))
    new_slab = dataclasses.replace(slab, src=src, dst=dst, weight=weight,
                                   alive=alive)
    return new_slab, n_dropped


def singleton_candidates(slab: GraphSlab, prev: GraphSlab
                         ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Reconnect isolated nodes to their strongest previous-round neighbor.

    The reference reattaches via the *lowest*-weight previous edge (ascending
    sort then ``[0]``, fc:193-195) while its docstring says maximum
    (mc:94); we implement the documented/paper intent — maximum — and note the
    deviation (SURVEY.md §2.22.11).  Returns candidate (u, v, w, valid) arrays
    of length n_nodes, to be passed to insert_edges.
    """
    n = slab.n_nodes
    isolated = slab.degrees() == 0

    psrc, pdst, pw, pad = prev.directed()
    pseg = jnp.where(pad, psrc, n)
    neg_inf = jnp.float32(-jnp.inf)
    best_w = jax.ops.segment_max(jnp.where(pad, pw, neg_inf), pseg,
                                 num_segments=n + 1)[:-1]
    at_best = pad & (pw == best_w[jnp.clip(pseg, 0, n - 1)]) & (pseg < n)
    partner = jax.ops.segment_max(jnp.where(at_best, pdst, -1), pseg,
                                  num_segments=n + 1)[:-1]

    nodes = jnp.arange(n, dtype=jnp.int32)
    valid = isolated & (partner >= 0)
    u = jnp.minimum(nodes, partner)
    v = jnp.maximum(nodes, partner)
    w = jnp.where(jnp.isfinite(best_w), best_w, 0.0)
    return jnp.where(valid, u, 0), jnp.where(valid, v, 0), w, valid
