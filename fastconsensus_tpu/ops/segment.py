"""Sorted-run segment machinery: per-(node, label) weighted aggregation.

This is the workhorse op of the whole framework.  Every base-detection kernel
(label propagation's neighbor vote, Louvain/Leiden's per-community in-weights
k_i_in(C), Infomap's module statistics) reduces to the same primitive:

    given directed edges (node -> neighbor) with weights, and a label per
    neighbor, compute  sum of weights per (node, neighbor-label) pair,

i.e. a sparse histogram whose support is bounded by the number of directed
edges.  The reference computes these with Python dict loops per edge per
partition (e.g. ``fast_consensus.py:150-159``, ``:273-280``); here it is a
lexicographic sort + segmented scan with fully static shapes, which XLA
compiles to one fused batched sort + a couple of segment reductions — the
standard data-parallel re-expression (cf. GPU Louvain, arXiv:1805.10904).

Shapes: all run arrays have length E (the directed-edge count).  There are at
most E distinct (node, label) pairs, so runs never overflow; unused run slots
are masked with ``valid=False`` and node id ``n_nodes``.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class Runs(NamedTuple):
    """Aggregated (node, label) runs.  All arrays length E, masked by valid."""

    node: jax.Array    # int32[E]; n_nodes for invalid runs
    label: jax.Array   # int32[E]
    total: jax.Array   # float32[E]; sum of values within the run
    valid: jax.Array   # bool[E]


def node_label_runs(node: jax.Array,
                    label: jax.Array,
                    value: jax.Array,
                    valid: jax.Array,
                    n_nodes: int) -> Runs:
    """Aggregate ``value`` per distinct (node, label) pair.

    Invalid entries sort to the end (node := n_nodes) and never merge with
    real runs.
    """
    e = node.shape[0]
    node_m = jnp.where(valid, node, n_nodes).astype(jnp.int32)
    label_m = jnp.where(valid, label, 0).astype(jnp.int32)
    value_m = jnp.where(valid, value, 0.0).astype(jnp.float32)

    order = jnp.lexsort((label_m, node_m))
    ns = node_m[order]
    ls = label_m[order]
    vs = value_m[order]

    new_run = jnp.concatenate([
        jnp.ones((1,), dtype=bool),
        (ns[1:] != ns[:-1]) | (ls[1:] != ls[:-1]),
    ])
    run_id = jnp.cumsum(new_run.astype(jnp.int32)) - 1

    total = jax.ops.segment_sum(vs, run_id, num_segments=e,
                                indices_are_sorted=True)
    count = jax.ops.segment_sum(jnp.ones_like(vs), run_id, num_segments=e,
                                indices_are_sorted=True)
    run_node = jax.ops.segment_max(ns, run_id, num_segments=e,
                                   indices_are_sorted=True)
    run_label = jax.ops.segment_max(ls, run_id, num_segments=e,
                                    indices_are_sorted=True)
    run_valid = (count > 0) & (run_node < n_nodes)
    run_node = jnp.where(run_valid, run_node, n_nodes)
    return Runs(node=run_node, label=jnp.where(run_valid, run_label, 0),
                total=jnp.where(run_valid, total, 0.0), valid=run_valid)


class HashTables(NamedTuple):
    """Two independent open-addressed sum tables over (node, label) pairs.

    The sort-free alternative to :func:`node_label_runs` for the per-sweep
    aggregation: each (node, label) candidate's weight scatter-adds into two
    hash tables; :func:`lookup_hash_totals` reads back ``min(t1[h1], t2[h2])``,
    which equals the exact per-pair total unless the pair collides with
    another live pair in *both* tables — probability ~(E/B)^2 per pair, and a
    collision only ever *overstates* a candidate's in-weight by one other
    run's total.  On TPU this replaces a 10M-element minor-axis sort per
    sweep with a few O(E) scatters (the sweeps are where >90% of detection
    time goes on skewed-degree graphs; see models/louvain.py path notes).
    """

    t1: jax.Array  # float32[B]
    t2: jax.Array  # float32[B]
    n_buckets: int


def _hash_mix(node: jax.Array, label: jax.Array, c1: int, c2: int,
              n_buckets: int) -> jax.Array:
    """Multiply-xorshift mix of a (node, label) pair into [0, n_buckets)."""
    m = (node.astype(jnp.uint32) * jnp.uint32(c1)
         + label.astype(jnp.uint32) * jnp.uint32(c2))
    m = m ^ (m >> 15)
    m = m * jnp.uint32(0x2C1B3C6D)
    m = m ^ (m >> 12)
    return (m & jnp.uint32(n_buckets - 1)).astype(jnp.int32)


def build_hash_totals(node: jax.Array, label: jax.Array, value: jax.Array,
                      valid: jax.Array, n_buckets: int) -> HashTables:
    """Scatter-add ``value`` per (node, label) into both tables.

    ``n_buckets`` must be a power of two; invalid entries drop out.
    """
    w = jnp.where(valid, value, 0.0).astype(jnp.float32)
    h1 = _hash_mix(node, label, 0x9E3779B1, 0x85EBCA77, n_buckets)
    h2 = _hash_mix(node, label, 0x27D4EB2F, 0x165667B1, n_buckets)
    t1 = jnp.zeros((n_buckets,), jnp.float32).at[
        jnp.where(valid, h1, n_buckets)].add(w, mode="drop")
    t2 = jnp.zeros((n_buckets,), jnp.float32).at[
        jnp.where(valid, h2, n_buckets)].add(w, mode="drop")
    return HashTables(t1=t1, t2=t2, n_buckets=n_buckets)


def lookup_hash_totals(tables: HashTables, node: jax.Array, label: jax.Array
                       ) -> jax.Array:
    """Per-entry total for each queried (node, label) pair (see HashTables)."""
    h1 = _hash_mix(node, label, 0x9E3779B1, 0x85EBCA77, tables.n_buckets)
    h2 = _hash_mix(node, label, 0x27D4EB2F, 0x165667B1, tables.n_buckets)
    return jnp.minimum(tables.t1[h1], tables.t2[h2])


def hash_buckets_for(n_entries: int, cap: int = 1 << 26) -> int:
    """Power-of-two table size ~4x the live-pair bound (load factor <= 0.25).

    ``cap`` (default 64M buckets = 256 MB/table) bounds the two tables' HBM
    footprint; a graph large enough to hit it (> ~16M live pairs) loses the
    documented ~(E/B)^2 collision bound, so the cap engaging is logged —
    quality on such graphs should be validated against an exact path.
    """
    b = 1
    while b < 4 * max(1, n_entries):
        b <<= 1
    if b > cap:
        import logging  # local: this module is imported on cold paths

        logging.getLogger("fastconsensus_tpu").warning(
            "hash table capped at %d buckets for %d entries (load factor "
            "%.2f > 0.25): collision rate exceeds the documented bound",
            cap, n_entries, n_entries / cap)
        return cap
    return b


def scatter_argmax_label(node: jax.Array, score: jax.Array, label: jax.Array,
                         valid: jax.Array, n_nodes: int
                         ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Sort-free :func:`argmax_label_per_node`: two scatter-max passes.

    Pass 1 scatter-maxes each node's best score; pass 2 scatter-maxes the
    label among entries matching that score (exact float equality — same
    value), breaking ties toward the larger label like the sorted variant.
    """
    neg_inf = jnp.float32(-jnp.inf)
    seg = jnp.where(valid, node, n_nodes).astype(jnp.int32)
    masked = jnp.where(valid, score, neg_inf)
    best = jnp.full((n_nodes + 1,), neg_inf).at[seg].max(
        masked, mode="drop")[:-1]
    is_best = valid & (masked == best[jnp.clip(seg, 0, n_nodes - 1)]) & \
        (seg < n_nodes)
    best_label = jnp.full((n_nodes + 1,), -1, jnp.int32).at[
        jnp.where(is_best, seg, n_nodes)].max(
        jnp.where(is_best, label, -1), mode="drop")[:-1]
    has_any = jnp.isfinite(best)
    return jnp.where(has_any, best_label, -1), \
        jnp.where(has_any, best, neg_inf), has_any


def argmax_label_per_node(runs_node: jax.Array,
                          score: jax.Array,
                          label: jax.Array,
                          valid: jax.Array,
                          n_nodes: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Per node, the label of the max-score run.

    Ties break toward the larger label (deterministic); callers wanting random
    tie-breaks add keyed jitter to ``score`` first.

    Returns ``(best_label, best_score, has_any)``; nodes with no valid run get
    label -1, score -inf, has_any False.
    """
    neg_inf = jnp.float32(-jnp.inf)
    seg = jnp.where(valid, runs_node, n_nodes).astype(jnp.int32)
    masked_score = jnp.where(valid, score, neg_inf)
    best = jax.ops.segment_max(masked_score, seg, num_segments=n_nodes + 1)[:-1]
    is_best = valid & (masked_score == best[jnp.clip(seg, 0, n_nodes - 1)]) \
        & (seg < n_nodes)
    best_label = jax.ops.segment_max(
        jnp.where(is_best, label, -1), seg, num_segments=n_nodes + 1)[:-1]
    has_any = jnp.isfinite(best)
    best_label = jnp.where(has_any, best_label, -1)
    best = jnp.where(has_any, best, neg_inf)
    return best_label, best, has_any


def pair_jitter(key: jax.Array, node: jax.Array, label: jax.Array,
                scale) -> jax.Array:
    """Keyed tie-break noise in [0, scale), derived from the (node, label)
    *content* rather than the array slot.

    :func:`uniform_jitter` draws per-position noise, which silently depends
    on array layout: growing the edge slab (graph.grow_slab) shifts the
    second orientation half of the directed arrays by the capacity delta, so
    tied candidates would win differently before and after growth.  Hashing
    the pair (salted per call from ``key``) makes the draw
    position-independent — and gives duplicate candidates (several edges
    from one node into the same community) identical noise, which is the
    correct tie-break semantics anyway.
    """
    salt = jax.random.bits(key, (2,), jnp.uint32)
    m = (node.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
         + label.astype(jnp.uint32) * jnp.uint32(0x85EBCA77) + salt[0])
    m = m ^ (m >> 15)
    m = m * jnp.uint32(0x2C1B3C6D) + salt[1]
    m = m ^ (m >> 13)
    # top 24 bits -> exact float32 in [0, 1)
    return (m >> 8).astype(jnp.float32) * (scale / jnp.float32(1 << 24))


def gumbel_from_uniform(u: jax.Array) -> jax.Array:
    """Standard Gumbel noise from uniform draws in [0, 1).

    argmax(gain + theta * G) over candidates samples one with probability
    proportional to exp(gain / theta) — the Gumbel-max reformulation of
    leidenalg's theta-randomized merge distribution, usable inside the
    existing per-candidate argmax machinery.
    """
    u = jnp.clip(u, 1e-7, 1.0 - 1e-7)
    return -jnp.log(-jnp.log(u))


def uniform_jitter(key: jax.Array, shape, scale: float = 1e-3) -> jax.Array:
    """Keyed tie-break noise, strictly inside [0, scale).

    Safe when genuine score gaps are >= 1 (integer vote totals), where it
    randomizes ties without reordering distinct scores.
    """
    return jax.random.uniform(key, shape, dtype=jnp.float32) * scale


def compact_labels(labels: jax.Array, n_nodes: int) -> jax.Array:
    """Relabel to dense 0..k-1 ids ordered by original label id.

    Jittable replacement for the host-side dict relabeling the reference does
    implicitly via dict insertion order (``fast_consensus.py:55-71``).
    """
    present = jnp.zeros((n_nodes + 1,), dtype=jnp.int32).at[
        jnp.clip(labels, 0, n_nodes)].max(1, mode="drop")
    # rank of each label among used labels
    rank = jnp.cumsum(present) - present
    return jnp.where(labels >= 0, rank[jnp.clip(labels, 0, n_nodes)], -1)
