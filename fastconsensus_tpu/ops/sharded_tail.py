"""Edge-local consensus tail: the round's post-detection phases under
``jax.shard_map``.

Round 2 sharded the edge slab over the mesh's ``"e"`` axis but left the
tail (co-membership -> threshold -> convergence -> closure -> repair) to
GSPMD, whose partitioning of the tail's sorts, concatenates and scatters
re-gathers the whole slab onto every device — 19-20 capacity-sized
all-gathers per round (parallel/sharding.py module notes; pinned in
tests/test_parallel.py).  The axis sharded *storage* without reducing the
round's peak *working* memory, which is the reason it exists (SURVEY.md
§2.24: the 100k-edge-and-up configs).

This module instead writes the tail the explicit SPMD way: every phase is
a per-shard computation over the device's LOCAL slab chunk, communicating
only

* ``psum("p")`` of per-edge agreement counts (the co-membership
  contraction — the round's one inherent collective),
* ``psum``/``pmax("e")`` of node-indexed ``[N]`` vectors (degrees,
  random-partner priorities, strongest-previous-neighbor),
* ``psum("e")`` of the hash membership tables and of scalar stats,
* one tiny ``all_gather("e")`` of per-shard free-slot counts.

The slab's raw per-edge arrays never cross the interconnect; the largest
remaining collectives are the two membership tables of the closure insert
(~4x the edge-count in buckets — proportional to graph size but
independent of the shard count; kept global rather than per-shard-OR'ed
so sharded and unsharded insertion see the identical collision pattern).
Every reduction is integer-valued (counts, psums of 0/1) or order-free
(max), so the sharded tail is **bit-identical** to
:func:`consensus.consensus_tail` on the same inputs — asserted by
tests/test_parallel.py parity tests.

Reference context: the whole tail replaces ``fast_consensus.py:150-195``
(dict loops on one process); the reference has no distributed story at
all, so this file is where the framework's edge-scale axis becomes real.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from fastconsensus_tpu.graph import GraphSlab
from fastconsensus_tpu.ops import segment as seg

# axis names must match parallel/sharding.py (imported lazily there to
# avoid a cycle; the literals are part of the mesh contract)
ENSEMBLE_AXIS = "p"
EDGE_AXIS = "e"


def _node_psum(vals: jax.Array, segs: jax.Array, valid: jax.Array,
               n: int) -> jax.Array:
    """Cross-shard segment-sum into a replicated [n] vector (int/exact)."""
    s = jnp.where(valid, segs, n)
    local = jnp.zeros((n + 1,), vals.dtype).at[s].add(
        jnp.where(valid, vals, jnp.zeros((), vals.dtype)), mode="drop")[:-1]
    return jax.lax.psum(local, EDGE_AXIS)


def _node_argmax(score: jax.Array, segs: jax.Array, label: jax.Array,
                 valid: jax.Array, n: int
                 ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Cross-shard :func:`segment.scatter_argmax_label`: per node, the
    label of the globally max-score entry, ties toward the larger label —
    the same rule as the unsharded op, realized as two pmax passes."""
    neg_inf = jnp.float32(-jnp.inf)
    s = jnp.where(valid, segs, n).astype(jnp.int32)
    masked = jnp.where(valid, score, neg_inf)
    best_local = jnp.full((n + 1,), neg_inf).at[s].max(
        masked, mode="drop")[:-1]
    best = jax.lax.pmax(best_local, EDGE_AXIS)
    is_best = valid & (masked == best[jnp.clip(s, 0, n - 1)]) & (s < n)
    lab_local = jnp.full((n + 1,), -1, jnp.int32).at[
        jnp.where(is_best, s, n)].max(
        jnp.where(is_best, label, -1), mode="drop")[:-1]
    lab = jax.lax.pmax(lab_local, EDGE_AXIS)
    has = jnp.isfinite(best)
    return jnp.where(has, lab, -1), jnp.where(has, best, neg_inf), has


def _degrees(slab: GraphSlab) -> jax.Array:
    """Replicated alive-degree [n] from the local shard (graph.degrees)."""
    n = slab.n_nodes
    ones = jnp.ones((slab.capacity,), jnp.int32)
    return _node_psum(ones, slab.src, slab.alive, n) + \
        _node_psum(ones, slab.dst, slab.alive, n)


def _comembership(labels: jax.Array, u: jax.Array, v: jax.Array
                  ) -> jax.Array:
    """Partition-agreement counts, contracted over the ensemble axis."""
    agree = labels[:, u] == labels[:, v]
    return jax.lax.psum(jnp.sum(agree, axis=0, dtype=jnp.float32),
                        ENSEMBLE_AXIS)


def _conv_stats(slab: GraphSlab, n_p: int, delta: float):
    mid = slab.alive & (slab.weight > 0) & \
        (slab.weight < jnp.float32(n_p))
    n_mid = jax.lax.psum(jnp.sum(mid.astype(jnp.int32)), EDGE_AXIS)
    n_alive = jax.lax.psum(jnp.sum(slab.alive.astype(jnp.int32)),
                           EDGE_AXIS)
    converged = n_mid.astype(jnp.float32) <= jnp.float32(delta) * \
        n_alive.astype(jnp.float32)
    return converged, n_mid, n_alive


def _num_alive(slab: GraphSlab) -> jax.Array:
    return jax.lax.psum(jnp.sum(slab.alive.astype(jnp.int32)), EDGE_AXIS)


def _sample_wedges(key: jax.Array, slab: GraphSlab, n_samples: int):
    """consensus_ops.sample_wedges_scatter with the partner argmax taken
    across shards (same content-keyed priorities => same winners)."""
    from fastconsensus_tpu.ops.consensus_ops import partner_draw_batches

    n = slab.n_nodes
    srcd = jnp.concatenate([slab.src, slab.dst])  # local concat: no comm
    dstd = jnp.concatenate([slab.dst, slab.src])
    ad = jnp.concatenate([slab.alive, slab.alive])
    valid_e = ad & (srcd != dstd)
    # same draw engine as the unsharded sampler (bit-identical winners);
    # only the argmax is the cross-shard pmax variant.  capacity here is
    # the LOCAL chunk — the [G*(n+1)] argmax bound inside the helper keeps
    # per-device temporaries shard-count-independent.
    u, v, ok = partner_draw_batches(key, srcd, dstd, valid_e, n,
                                    slab.capacity, n_samples, _node_argmax)
    return jnp.where(ok, u, 0), jnp.where(ok, v, 0), ok


def _insert_edges(slab: GraphSlab, cand_u, cand_v, cand_w, cand_valid,
                  cap_hint: int, unique_new: bool = False):
    """consensus_ops.insert_edges_hash with shard-local tables and slots.

    Membership tables are psum("e")-combined (sums of ones — exact);
    candidate dedup is computed identically on every shard (candidates are
    replicated); free slots are assigned in GLOBAL slot order — shard s
    owns the contiguous chunk [s*cap_local, (s+1)*cap_local), matching the
    unsharded argsort(alive)-equivalent order bit-exactly — and each
    survivor is written by exactly the shard owning its slot.
    """
    cap_l = slab.capacity
    k = cand_u.shape[0]
    cu = cand_u.astype(jnp.int32)
    cv = cand_v.astype(jnp.int32)

    if unique_new:
        # singleton repair: candidates are pairwise-distinct and absent
        # from the slab by construction — exact, no hash involvement
        surv = cand_valid
    else:
        # existing-edge membership (canonical pairs, two-table scheme)
        b_e = seg.hash_buckets_for(cap_hint)
        h1e = seg._hash_mix(slab.src, slab.dst, 0x9E3779B1, 0x85EBCA77,
                            b_e)
        h2e = seg._hash_mix(slab.src, slab.dst, 0x27D4EB2F, 0x165667B1,
                            b_e)
        one = jnp.ones((cap_l,), jnp.float32)
        t1 = jax.lax.psum(jnp.zeros((b_e + 1,), jnp.float32).at[
            jnp.where(slab.alive, h1e, b_e)].add(one, mode="drop"),
            EDGE_AXIS)
        t2 = jax.lax.psum(jnp.zeros((b_e + 1,), jnp.float32).at[
            jnp.where(slab.alive, h2e, b_e)].add(one, mode="drop"),
            EDGE_AXIS)
        h1c = seg._hash_mix(cu, cv, 0x9E3779B1, 0x85EBCA77, b_e)
        h2c = seg._hash_mix(cu, cv, 0x27D4EB2F, 0x165667B1, b_e)
        exists = jnp.minimum(t1[h1c], t2[h2c]) > 0.0

        # first-occurrence dedup among candidates (replicated computation)
        b_c = seg.hash_buckets_for(k)
        g1 = seg._hash_mix(cu, cv, 0x9E3779B1, 0x85EBCA77, b_c)
        g2 = seg._hash_mix(cu, cv, 0x27D4EB2F, 0x165667B1, b_c)
        tag = jnp.arange(k, dtype=jnp.int32)
        live = cand_valid & ~exists
        big = jnp.int32(k)
        d1 = jnp.full((b_c + 1,), big, jnp.int32).at[
            jnp.where(live, g1, b_c)].min(tag, mode="drop")
        d2 = jnp.full((b_c + 1,), big, jnp.int32).at[
            jnp.where(live, g2, b_c)].min(tag, mode="drop")
        surv = live & ((d1[g1] == tag) | (d2[g2] == tag))

    # global free-slot assignment
    dead = ~slab.alive
    local_free_count = jnp.sum(dead.astype(jnp.int32))
    counts = jax.lax.all_gather(local_free_count, EDGE_AXIS)  # [n_shards]
    me = jax.lax.axis_index(EDGE_AXIS)
    offset = jnp.sum(jnp.where(
        jnp.arange(counts.shape[0]) < me, counts, 0))
    n_free = jax.lax.psum(local_free_count, EDGE_AXIS)
    rank = jnp.cumsum(surv.astype(jnp.int32)) - 1
    ok = surv & (rank < n_free)
    mine = ok & (rank >= offset) & (rank < offset + local_free_count)
    local_rank = jnp.cumsum(dead.astype(jnp.int32)) - 1
    local_free = jnp.full((cap_l,), cap_l, jnp.int32).at[
        jnp.where(dead, local_rank, cap_l)].set(
        jnp.arange(cap_l, dtype=jnp.int32), mode="drop")
    lslot = jnp.where(mine, local_free[jnp.clip(rank - offset, 0,
                                                cap_l - 1)], cap_l)

    import dataclasses

    new_slab = dataclasses.replace(
        slab,
        src=slab.src.at[lslot].set(cu, mode="drop"),
        dst=slab.dst.at[lslot].set(cv, mode="drop"),
        weight=slab.weight.at[lslot].set(cand_w.astype(jnp.float32),
                                         mode="drop"),
        alive=slab.alive.at[lslot].set(True, mode="drop"))
    n_dropped = jnp.sum(surv.astype(jnp.int32)) - \
        jnp.sum(ok.astype(jnp.int32))
    return new_slab, n_dropped


def _singleton_candidates(slab: GraphSlab, prev: GraphSlab):
    """consensus_ops.singleton_candidates with cross-shard reductions."""
    n = slab.n_nodes
    isolated = _degrees(slab) == 0

    psrc = jnp.concatenate([prev.src, prev.dst])
    pdst = jnp.concatenate([prev.dst, prev.src])
    pw = jnp.concatenate([prev.weight, prev.weight])
    pad = jnp.concatenate([prev.alive, prev.alive])
    pseg = jnp.where(pad, psrc, n)
    neg_inf = jnp.float32(-jnp.inf)
    bw_local = jnp.full((n + 1,), neg_inf).at[pseg].max(
        jnp.where(pad, pw, neg_inf), mode="drop")[:-1]
    best_w = jax.lax.pmax(bw_local, EDGE_AXIS)
    at_best = pad & (pw == best_w[jnp.clip(pseg, 0, n - 1)]) & (pseg < n)
    partner_local = jnp.full((n + 1,), -1, jnp.int32).at[
        jnp.where(at_best, pseg, n)].max(
        jnp.where(at_best, pdst, -1), mode="drop")[:-1]
    partner = jax.lax.pmax(partner_local, EDGE_AXIS)

    nodes = jnp.arange(n, dtype=jnp.int32)
    valid = isolated & (partner >= 0)
    # exact self-dedup of mutual pairs (consensus_ops.singleton_candidates)
    p_c = jnp.clip(partner, 0, n - 1)
    mutual = valid & (partner < nodes) & valid[p_c] & \
        (partner[p_c] == nodes)
    valid = valid & ~mutual
    u = jnp.minimum(nodes, partner)
    v = jnp.maximum(nodes, partner)
    w = jnp.where(jnp.isfinite(best_w), best_w, 0.0)
    return jnp.where(valid, u, 0), jnp.where(valid, v, 0), w, valid


def _tail_local(slab: GraphSlab, labels: jax.Array, k_closure: jax.Array,
                prev_labels: jax.Array,
                *, n_p: int, tau: float, delta: float, n_closure: int,
                cap_hint: int, hybrid_gate: bool, agg_gate: bool,
                closure_tau=None):
    """The per-shard tail program; see the module docstring.

    ``prev_labels`` is the previous round's labels (member-sharded like
    ``labels``), consumed only by the fcqual churn metric; ``agg_gate``
    is ``graph.agg_compaction_active`` evaluated by the caller on the
    GLOBAL slab (the local chunk's capacity would mis-evaluate it).
    """
    from fastconsensus_tpu.consensus import RoundStats

    from fastconsensus_tpu.ops import consensus_ops as cops

    n = slab.n_nodes
    counts = _comembership(labels, slab.src, slab.dst)
    prev = slab
    # purely elementwise over the local chunk: the unsharded ops apply
    # verbatim (single source for the skip-converged-edges rule)
    slab = cops.update_weights(slab, counts, n_p)
    slab = cops.threshold_weights(slab, tau, n_p)
    mid_converged, mid_n_mid, mid_n_alive = _conv_stats(slab, n_p, delta)

    def do_closure(slab):
        n0 = _num_alive(slab)
        cu, cv, cvalid = _sample_wedges(k_closure, slab, n_closure)
        cw = _comembership(labels, cu, cv)
        if closure_tau is not None:
            # threshold-at-insert (ConsensusConfig.closure_tau); same rule
            # as consensus_tail — parity contract
            cvalid = cvalid & (cw >= jnp.float32(closure_tau) *
                               jnp.float32(n_p))
        slab, dropped = _insert_edges(slab, cu, cv, cw, cvalid, cap_hint)
        n1 = _num_alive(slab)
        su, sv, sw, svalid = _singleton_candidates(slab, prev)
        slab, dropped2 = _insert_edges(slab, su, sv, sw, svalid, cap_hint,
                                       unique_new=True)
        return slab, n1 - n0, _num_alive(slab) - n1, dropped + dropped2

    def skip_closure(slab):
        return slab, jnp.int32(0), jnp.int32(0), jnp.int32(0)

    slab, n_closed, n_repaired, n_dropped = jax.lax.cond(
        mid_converged, skip_closure, do_closure, slab)
    end_converged, end_n_mid, end_n_alive = _conv_stats(slab, n_p, delta)
    deg = _degrees(slab)
    if slab.d_cap > 0:
        n_overflow = jnp.sum(
            jnp.maximum(deg - slab.d_cap, 0).astype(jnp.int32))
    else:
        n_overflow = jnp.int32(0)
    if hybrid_gate:
        hub_mass = jnp.sum(jnp.where(deg > slab.d_hyb, deg, 0)
                           .astype(jnp.int32))
        n_hub_overflow = jnp.maximum(hub_mass - slab.hub_cap, 0)
    else:
        n_hub_overflow = jnp.int32(0)
    if agg_gate:
        # upper bound on what graph.compact_alive drops next round —
        # mirrors consensus_tail's n_agg_overflow (global alive count)
        n_agg_overflow = jnp.maximum(end_n_alive - slab.agg_cap, 0)
    else:
        n_agg_overflow = jnp.int32(0)

    # --- fcqual quality bundle: the sharded mirror of obs/quality
    # .tail_quality.  Same formulas, cross-shard reductions kept node-/
    # scalar-/[n_p]-sized so the slab-sized-all-gather HLO pin
    # (tests/test_parallel.py) still holds.  Float sums reduce in shard
    # order — quality metrics are observability-only and never compared
    # bit-wise across sharding layouts (only against a NumPy reference
    # on the unsharded path, tests/test_quality.py).
    f_np = jnp.float32(n_p)
    alive = slab.alive
    w_alive = jnp.where(alive, slab.weight, 0.0)
    n_w_zero = jax.lax.psum(jnp.sum(
        (alive & (slab.weight <= 0.0)).astype(jnp.int32)), EDGE_AXIS)
    n_w_full = jax.lax.psum(jnp.sum(
        (alive & (slab.weight >= f_np)).astype(jnp.int32)), EDGE_AXIS)
    mid_end = alive & (slab.weight > 0) & (slab.weight < f_np)
    one_mid = mid_end.astype(jnp.int32)
    hits = _node_psum(one_mid, slab.src, mid_end, n) + \
        _node_psum(one_mid, slab.dst, mid_end, n)
    n_frontier = jnp.sum((hits > 0).astype(jnp.int32))
    if n_p > 1:
        # mean pairwise agreement over round-START alive edges, from the
        # counts the update phase already contracted over "p"
        pair = counts * (counts - 1.0) + \
            (f_np - counts) * (f_np - counts - 1.0)
        tot = jax.lax.psum(jnp.sum(jnp.where(prev.alive, pair, 0.0)),
                           EDGE_AXIS)
        n_start = jax.lax.psum(
            jnp.sum(prev.alive.astype(jnp.int32)), EDGE_AXIS)
        agreement = tot / (jnp.maximum(n_start.astype(jnp.float32), 1.0) *
                           f_np * (f_np - 1.0))
    else:
        agreement = jnp.float32(1.0)
    # per-member churn / modularity: member-local compute, one tiny
    # tiled [n_p] all_gather over "p" to replicate the vectors
    churn_local = jnp.sum((labels != prev_labels).astype(jnp.int32),
                          axis=1)
    labels_changed = jax.lax.all_gather(churn_local, ENSEMBLE_AXIS,
                                        tiled=True)
    total_w = jax.lax.psum(jnp.sum(w_alive), EDGE_AXIS)
    w_safe = jnp.maximum(total_w, jnp.float32(1e-30))
    str_n = _node_psum(w_alive, slab.src, alive, n) + \
        _node_psum(w_alive, slab.dst, alive, n)
    agree_m = labels[:, slab.src] == labels[:, slab.dst]
    intra = jax.lax.psum(
        jnp.sum(jnp.where(agree_m, w_alive[None, :], 0.0), axis=1),
        EDGE_AXIS)

    def _penalty(lab):
        d_c = jnp.zeros((n,), jnp.float32).at[lab].add(str_n)
        return jnp.sum((d_c / (2.0 * w_safe)) ** 2)

    q_local = intra / w_safe - jax.vmap(_penalty)(labels)
    q_local = jnp.where(total_w > 0.0, q_local, jnp.zeros_like(q_local))
    member_modularity = jax.lax.all_gather(q_local, ENSEMBLE_AXIS,
                                           tiled=True)

    stats = RoundStats(
        converged=mid_converged | end_converged,
        n_alive=end_n_alive,
        n_unconverged=end_n_mid,
        n_closure_added=n_closed,
        n_repaired=n_repaired,
        n_dropped=n_dropped,
        n_overflow=n_overflow,
        n_hub_overflow=n_hub_overflow,
        n_agg_overflow=n_agg_overflow,
        cold=jnp.bool_(False),
        n_w_zero=n_w_zero,
        n_w_full=n_w_full,
        n_frontier=n_frontier,
        labels_changed=labels_changed,
        member_modularity=member_modularity,
        agreement=agreement,
    )
    return slab, stats


def sharded_consensus_tail(slab: GraphSlab, labels: jax.Array,
                           k_closure: jax.Array, n_p: int, tau: float,
                           delta: float, n_closure: int, mesh,
                           closure_tau=None, prev_labels=None
                           ) -> Tuple[GraphSlab, "object"]:
    """Run the tail edge-locally over ``mesh`` (axes "p" x "e").

    In/out shardings: slab leaves split over "e", labels over "p", stats
    replicated.  Bit-identical to :func:`consensus.consensus_tail` (see
    module docstring); with a 1-sized edge axis every "e" collective is a
    no-op and only the co-membership psum("p") remains.

    ``prev_labels`` ([n_p, N], member-sharded like ``labels``) feeds the
    fcqual churn metric only; None (round 0 / legacy callers) measures
    churn against the singleton baseline, materialized here so the
    shard_map operand list stays fixed-arity.
    """
    from fastconsensus_tpu.graph import agg_compaction_active
    from fastconsensus_tpu.models.louvain import _cap_hint, select_move_path

    if prev_labels is None:
        prev_labels = jnp.broadcast_to(
            jnp.arange(slab.n_nodes, dtype=jnp.int32), labels.shape)
    local = functools.partial(
        _tail_local, n_p=n_p, tau=tau, delta=delta,
        n_closure=n_closure, cap_hint=_cap_hint(slab),
        hybrid_gate=select_move_path(slab) == "hybrid",
        agg_gate=agg_compaction_active(slab),
        closure_tau=closure_tau)
    specs = dict(mesh=mesh,
                 in_specs=(P(EDGE_AXIS), P(ENSEMBLE_AXIS, None), P(),
                           P(ENSEMBLE_AXIS, None)),
                 out_specs=(P(EDGE_AXIS), P()))
    sm = getattr(jax, "shard_map", None)
    if sm is None:  # jax 0.4.x: experimental location
        from jax.experimental.shard_map import shard_map as sm
    # the replication-check kwarg was renamed check_rep -> check_vma
    # independently of the move to top-level; key on the actual signature
    import inspect

    if "check_vma" in inspect.signature(sm).parameters:
        fn = sm(local, check_vma=False, **specs)
    else:
        fn = sm(local, check_rep=False, **specs)
    return fn(slab, labels, k_closure, prev_labels)
