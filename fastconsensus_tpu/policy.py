"""Warm-loop control policy: stagnation, limit-cycle and alignment rules.

ONE implementation consumed by BOTH sides of the fused/per-round parity
contract (round-3 VERDICT Weak #6: every rule used to live twice):

* the host driver (``consensus.run_consensus``) evaluates the rules with
  ``xp = numpy`` between device calls, and
* the fused round block (``consensus.consensus_rounds_block``) evaluates
  the *same functions* with ``xp = jax.numpy`` inside ``lax.while_loop``.

Fused and per-round execution must take identical cold/warm/align
decisions, so every rule here is **division-free**: all comparisons are
built from IEEE-754 float32 multiplies and compares, which NumPy and XLA
round identically on every backend.  f32 *division* carries no such
guarantee — XLA may lower it via reciprocal approximation on TPU, and a
1-ULP difference against the host's NumPy divide could flip a refresh or
alignment decision and silently break parity (round-3 ADVICE, medium).
The running unconverged-fraction minimum is therefore tracked as the exact
integer pair ``(u_min, a_min)`` and compared by cross-multiplication, not
as a floating quotient.

The rules themselves are measurement-driven; the history behind each
threshold is documented on the consuming config fields
(``consensus.ConsensusConfig``) and in BASELINE.md.

Why the rules exist (measured, round 3):

* **stall** — warm members lock into diverse local optima: each is at its
  own fixpoint, so disagreement stops falling while triadic closure
  densifies the graph (warm leiden on lfr10k grew ~30k edges/round without
  converging).  The cure is a COLD round: re-derive every member from the
  current weights with independent keys (on SBM-100k this collapsed the
  unconverged fraction 0.99 -> 0.31 in one round where the aligned grind
  moved it 0.003/round).
* **stale** — warm LIMIT CYCLES: an ensemble can oscillate (karate,
  measured: 26 -> 34 -> 28 -> 31 -> ... for 64 rounds) without ever
  tripping the one-step rule, and alignment does not break the cycle —
  only a cold refresh does.  The FRACTION (not the count) is tracked so
  healthy densifying runs — absolute mid-weight count growing with the
  graph while the fraction falls (lfr10k 0.97 -> 0.24) — never trigger.
* **align** — near the end, members disagree mostly on
  modularity-degenerate ties; sharing one detection key (with
  content-keyed tie-break jitter, ``louvain._community_reps``) collapses
  exactly those (lfr10k: NMI 0.524 full-alignment vs 0.482 late-alignment
  vs divergence without).
"""

from __future__ import annotations

from typing import List, NamedTuple

# Rounds without a strict new unconverged-FRACTION minimum before the
# stale refresh fires.
# Sensitivity (round-5 A/B, runs/policy_ab, per-cell-fresh traces):
# STALE_ROUNDS 3 and 4 are a plateau on karate's limit-cycle dynamics
# (±1 round, ±0.004 NMI over 2 seeds) while 6 detects the cycle too
# late — one seed burned its whole 24-round budget unconverged.
# FACTOR_WARM is inert within ±0.05 everywhere tested.  Monotone
# trajectories (bounded-6 lfr10k) never engage either rule.  A family
# oscillating at period > STALE_ROUNDS would still evade the stale
# rule — the A/B bounds sensitivity, not universality.
STALE_ROUNDS = 4

# One-step relative-progress factors: a warm round must shrink the
# unconverged fraction by >= 10% (>= 5% when the round ran aligned —
# aligned rounds legitimately progress more slowly, but a 0.3%-per-round
# aligned grind must still hand over to a cold re-derivation; measured on
# SBM-100k, BASELINE.md r3).
FACTOR_WARM = 0.9
FACTOR_ALIGNED = 0.95

# Mid-weight floors under which stagnation rules do not apply (see
# stall_floor): the one-step rule keeps 64; the stale/limit-cycle rule
# uses 16 — tiny graphs' whole mid-weight band is ~30 edges (karate) and
# a 64 floor silently disabled every refresh there (measured: a warm limit
# cycle ground 64 rounds).
STALL_ABS = 64.0
STALE_ABS = 16.0


class PolicyState(NamedTuple):
    """Stagnation state carried between rounds.

    Host side: Python ints.  Device side: int32 scalars (stacked into the
    fused block's loop carry).  ``u2/a2`` are the PREVIOUS round's
    unconverged/alive counts (-1 = unknown or preceding round was cold),
    ``u1/a1`` the last round's (-1 = no round yet), ``(u_min, a_min)`` the
    exact running minimum of the unconverged fraction since the last cold
    round (sentinel (2, 1): every real fraction <= 1 < 2/1 improves it),
    and ``scount`` the number of rounds since that minimum last improved.
    """

    u2: object
    a2: object
    u1: object
    a1: object
    u_min: object
    a_min: object
    scount: object


INITIAL = PolicyState(u2=-1, a2=-1, u1=-1, a1=-1, u_min=2, a_min=1,
                      scount=0)


def _f32(xp, x):
    return xp.asarray(x, xp.float32)


def stall_floor(xp, delta: float, n_alive, absolute: float):
    """Minimum mid-weight edge count for a stagnation rule to apply.

    A relative rule alone misfires at endgame granularity (12 -> 11
    unconverged is an 8% "stall") and near the convergence bar, where a
    cold restart would blow away nearly-converged state.  Stagnation
    therefore requires the count to still sit at >= 4x the ``delta``
    convergence bar AND >= ``absolute`` (delta=0 runs).  f32 multiplies
    only.
    """
    bar = _f32(xp, 4.0) * _f32(xp, delta) * _f32(xp, n_alive)
    return xp.maximum(_f32(xp, absolute), bar)


def frac_improved(xp, u, a, u_min, a_min):
    """Is u/a a strict new minimum vs u_min/a_min?  Division-free:
    u/a < u_min/a_min  <=>  u * a_min < u_min * a  (a, a_min >= 1)."""
    return _f32(xp, u) * _f32(xp, a_min) < _f32(xp, u_min) * _f32(xp, a)


def observe(xp, state: PolicyState, cold, u, a) -> PolicyState:
    """Fold one completed round's stats into the state.

    ``cold`` rounds reset the one-step window (u2/a2 sentinel) and restart
    the fraction minimum at this round's own fraction — the incremental
    form both the host (via :func:`state_from_history`, replayed from the
    full history) and the fused block (this function with ``xp = jnp``
    inside the loop carry) maintain.  All branches are ``xp.where``-style
    selects so the same code traces under jit.
    """
    a_c = xp.maximum(a, 1)
    improved = cold | frac_improved(xp, u, a_c, state.u_min, state.a_min)
    neg = xp.asarray(-1, _int_dtype(xp))
    return PolicyState(
        u2=xp.where(cold, neg, state.u1),
        a2=xp.where(cold, neg, state.a1),
        u1=u, a1=a,
        u_min=xp.where(improved, u, state.u_min),
        a_min=xp.where(improved, a_c, state.a_min),
        scount=xp.where(improved, xp.asarray(0, _int_dtype(xp)),
                        state.scount + 1))


def _int_dtype(xp):
    return xp.int32


def stalled(xp, delta: float, state: PolicyState, aligned):
    """One-step stagnation: the last warm round failed to shrink the
    unconverged fraction by >= 10% (5% aligned) while the count sits above
    the floor.  False when either window endpoint is unknown (after a
    cold round).  Division-free: f1 >= factor*f2 cross-multiplied."""
    have = (xp.asarray(state.u2) >= 0) & (xp.asarray(state.u1) >= 0)
    u1f, a1f = _f32(xp, state.u1), _f32(xp, state.a1)
    u2f, a2f = _f32(xp, state.u2), _f32(xp, state.a2)
    factor = xp.where(xp.asarray(aligned), _f32(xp, FACTOR_ALIGNED),
                      _f32(xp, FACTOR_WARM))
    floor_ok = u1f >= stall_floor(xp, delta, xp.maximum(state.a1, 1),
                                  STALL_ABS)
    return have & floor_ok & (u1f * a2f >= factor * (u2f * a1f))


def stale(xp, delta: float, state: PolicyState):
    """Limit-cycle rule: no strict new unconverged-fraction minimum for
    STALE_ROUNDS rounds while the count sits above the (smaller) floor;
    fires regardless of alignment."""
    have = xp.asarray(state.u1) >= 0
    floor_ok = _f32(xp, state.u1) >= stall_floor(
        xp, delta, xp.maximum(state.a1, 1), STALE_ABS)
    return have & (xp.asarray(state.scount) >= STALE_ROUNDS) & floor_ok


def align_now(xp, align_frac: float, state: PolicyState):
    """Endgame alignment: engage once the last round's unconverged count
    is within ``align_frac`` of the alive count.  f32 multiply only."""
    have = xp.asarray(state.u1) >= 0
    return have & (_f32(xp, state.u1) <=
                   _f32(xp, align_frac) * _f32(xp, xp.maximum(state.a1, 1)))


def budgets_stale(xp, n_overflow, n_hub_overflow, d_cap: int,
                  hub_cap: int, n_nodes: int,
                  n_alive=0, agg_cap: int = 0):
    """Are the static move-candidate budgets starving under densification?

    The dense/hybrid detection paths drop move candidates beyond their
    pack-time budgets (graph.derive_dense_sizing / derive_hybrid_sizing);
    triadic closure grows degrees every round, so a fixed budget starves —
    measured on lfr100k, ``n_hub_overflow`` grew 34k -> 3.26M over 8
    rounds while the unconverged count *rose* after round 4 (VERDICT r3
    Weak #4).  Fires when a round's overflow exceeds 1/8 of the static
    budget it overflowed; the driver then re-derives the budgets from the
    live degree histogram (one recompile) and the next round detects with
    complete candidate rows.

    Thresholds compare against the STATIC budgets (hub_cap, n_nodes *
    d_cap) — not live degree mass — so the fused block can evaluate the
    identical rule in-loop with zero extra device work and stop at the
    breach round (fused and per-round execution must re-size at the same
    round or their trajectories diverge).  Integer arithmetic only.
    """
    hub = (xp.asarray(n_hub_overflow) * 8 > hub_cap) if hub_cap > 0 \
        else xp.asarray(False)
    dense = (xp.asarray(n_overflow) * 8 > n_nodes * d_cap) if d_cap > 0 \
        else xp.asarray(False)
    # Compacted-aggregate starvation (graph.derive_agg_sizing): distinct
    # aggregate pairs <= n_alive, so a loss is only *possible* past
    # agg_cap.  The standalone threshold is deliberately loose (25% past
    # the budget — by then the compaction win is gone anyway): every
    # dense/hub firing re-derives agg_cap for free, so mild agg staleness
    # between firings never costs a recompile of its own.
    agg = (xp.asarray(n_alive) * 4 > agg_cap * 5) if agg_cap > 0 \
        else xp.asarray(False)
    return hub | dense | agg


def state_from_history(history: List[dict]) -> PolicyState:
    """Host-side reconstruction of the state from the run history — the
    batch form of :func:`observe`, used when (re)entering the loop (resume
    from a checkpoint, or seeding a fused block's carry)."""
    import numpy as np

    state = PolicyState(*(np.int32(v) for v in INITIAL))
    for h in history:
        state = observe(np, state, np.bool_(bool(h.get("cold"))),
                        np.int32(h["n_unconverged"]), np.int32(h["n_alive"]))
    return state
