"""fastconsensus_tpu: TPU-native fast consensus clustering.

A from-scratch JAX/XLA re-design of fast consensus clustering (Tandon et al.,
Phys. Rev. E 2019, arXiv:1902.04014) with the capabilities of the reference
implementation (ytabatabaee/fastconsensus): run a base community-detection
algorithm n_p times, accumulate per-edge co-membership counts, threshold weak
edges at tau*n_p, densify by triadic closure, iterate to delta-convergence.

Design (SURVEY.md §7): the graph is a static-shape COO slab resident in HBM;
the n_p ensemble runs are a vmapped batch axis (sharded over the device mesh);
the consensus round is one jitted function built from segment reductions.
"""

from fastconsensus_tpu.version import __version__

__all__ = ["GraphSlab", "pack_edges", "host_edges", "fast_consensus",
           "run_consensus", "run_consensus_batch", "ConsensusConfig",
           "get_detector", "__version__"]


def __getattr__(name):
    # Lazy top-level API: importing the package must stay JAX-FREE (not
    # just cheap) — CLI --help, host-only tooling (obs/history,
    # bench_report) and the fcserve thin client (cli.py --server via
    # serve/client.py + utils/io.py) all import under this package and
    # must not pay (or even require) the jax import.  graph.py imports
    # jax at module level, so even the slab names resolve lazily here.
    if name in ("GraphSlab", "pack_edges", "host_edges"):
        from fastconsensus_tpu import graph

        return getattr(graph, name)
    if name in ("fast_consensus", "run_consensus", "run_consensus_batch",
                "ConsensusConfig"):
        from fastconsensus_tpu import consensus

        return getattr(consensus, name)
    if name == "get_detector":
        from fastconsensus_tpu.models.registry import get_detector

        return get_detector
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
