"""The jitted consensus engine: one round, fused round blocks, chunked
detection.

Everything in this module is device-side program construction — jittable
functions over the static-shape GraphSlab plus their lru-cached ``jax.jit``
wrappers.  The host-side loop driver (resume, sizing, stagnation policy,
checkpointing) lives in ``consensus.py``; the control rules both sides
share live in ``policy.py``.  Split out of consensus.py in round 4
(VERDICT r3 Weak #6).

One consensus round (reference ``fast_consensus.py:138-201``):

    detect (vmapped over n_p keys)          fc:148 / :211 / :268-270 / :324-335
    -> co-membership counts per edge        fc:150-159
    -> tau-threshold                        fc:163-168
    -> convergence check                    fc:172 (-> fc:17-37)
    -> triadic closure (skipped if converged)  fc:175-191
    -> singleton repair                     fc:193-195
    -> convergence check                    fc:201
"""

from __future__ import annotations

import functools
import logging
import os
import time
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from fastconsensus_tpu import policy
from fastconsensus_tpu.graph import GraphSlab
from fastconsensus_tpu.models.base import Detector
from fastconsensus_tpu.obs import counters as obs_counters
from fastconsensus_tpu.obs import quality as obs_quality
from fastconsensus_tpu.obs.tracer import get_tracer
from fastconsensus_tpu.ops import consensus_ops as cops
from fastconsensus_tpu.utils import prng

_logger = logging.getLogger("fastconsensus_tpu")


class RoundStats(NamedTuple):
    converged: jax.Array       # bool[]
    n_alive: jax.Array         # int32[] edges after the round
    n_unconverged: jax.Array   # int32[] alive edges with 0 < w < n_p
    n_closure_added: jax.Array # int32[] triadic-closure edges inserted
    n_repaired: jax.Array      # int32[] singleton-repair edges inserted
    n_dropped: jax.Array       # int32[] survivors dropped for capacity
    n_overflow: jax.Array      # int32[] directed edges beyond d_cap, i.e.
                               # dropped from dense move-candidate rows
    n_hub_overflow: jax.Array  # int32[] hub directed edges beyond hub_cap,
                               # i.e. dropped from the hybrid path's hashed
                               # move candidates (ops/dense_adj.build_hybrid)
    n_agg_overflow: jax.Array  # int32[] upper bound on alive aggregate
                               # edges graph.compact_alive will silently
                               # drop next round (0 when the aggregate
                               # compaction is provably lossless or off;
                               # see graph.agg_compaction_active)
    cold: jax.Array            # bool[] this round ran full-sweep singleton
                               # -start detection (round 0 / cold mode /
                               # stagnation refresh); drives the stall
                               # reset and is recorded in history
    # --- fcqual quality bundle (obs/quality.py) -------------------------
    n_w_zero: jax.Array        # int32[] alive edges at weight 0
    n_w_full: jax.Array        # int32[] alive edges at weight >= n_p
    n_frontier: jax.Array      # int32[] vertices on >= 1 mid-band edge —
                               # the active-frontier estimate
    labels_changed: jax.Array  # int32[n_p] per-member label churn vs the
                               # previous round's labels
    member_modularity: jax.Array  # float32[n_p] per-member Newman Q on
                               # the end-of-round weighted slab
    agreement: jax.Array       # float32[] mean pairwise co-membership
                               # agreement over round-start alive edges


def consensus_tail(slab: GraphSlab,
                   labels: jax.Array,
                   k_closure: jax.Array,
                   n_p: int,
                   tau: float,
                   delta: float,
                   n_closure: int,
                   sampler: str = "scatter",
                   closure_tau: Optional[float] = None,
                   prev_labels: Optional[jax.Array] = None
                   ) -> Tuple[GraphSlab, RoundStats]:
    """Everything after detection: co-membership -> threshold -> convergence
    -> closure -> repair.  Jittable; shared by the one-call
    :func:`consensus_round` and the split-phase driver loop.

    ``sampler`` selects the wedge-sampling lowering (static; see
    ConsensusConfig.closure_sampler): "csr" is the single-chip fast path,
    "scatter" the edge-local engine the shard_map tail shares bit-exactly.

    ``prev_labels`` ([n_p, N]) is the previous round's labels, consumed
    only by the fcqual churn metric (obs/quality.py); None (round 0 /
    legacy callers) measures churn against the singleton baseline.  It
    never influences the slab or control flow — results are invariant.
    """
    counts = cops.comembership_counts(labels, slab.src, slab.dst)
    prev = slab  # round-start weights; used by singleton repair (fc:194)
    slab = cops.update_weights(slab, counts, n_p)
    slab = cops.threshold_weights(slab, tau, n_p)
    st_mid = cops.convergence_stats(slab, n_p, delta)

    def do_closure(slab):
        n0 = slab.num_alive()
        if sampler == "csr":
            csr = cops.build_csr(slab)
            cu, cv, cvalid = cops.sample_wedges(k_closure, csr,
                                                slab.n_nodes, n_closure)
        else:
            # sort-free engine: required under an edge-sharded mesh, where
            # the CSR argsort re-gathers the whole slab
            # (sample_wedges_scatter docstring)
            cu, cv, cvalid = cops.sample_wedges_scatter(k_closure, slab,
                                                        n_closure)
        cw = cops.comembership_counts(labels, cu, cv)
        if closure_tau is not None:
            # threshold-at-insert (ConsensusConfig.closure_tau)
            cvalid = cvalid & (cw >= jnp.float32(closure_tau) *
                               jnp.float32(n_p))
        slab, dropped = cops.insert_edges_hash(slab, cu, cv, cw, cvalid)
        n1 = slab.num_alive()
        su, sv, sw, svalid = cops.singleton_candidates(slab, prev)
        # repair candidates are unique + absent by construction: exact
        # insert — a reattachment must never be lost to a hash collision
        slab, dropped2 = cops.insert_edges_hash(slab, su, sv, sw, svalid,
                                                unique_new=True)
        return slab, n1 - n0, slab.num_alive() - n1, dropped + dropped2

    def skip_closure(slab):
        return slab, jnp.int32(0), jnp.int32(0), jnp.int32(0)

    slab, n_closed, n_repaired, n_dropped = jax.lax.cond(
        st_mid.converged, skip_closure, do_closure, slab)
    st_end = cops.convergence_stats(slab, n_p, delta)
    if slab.d_cap > 0:
        # candidates the dense kernels will not see next round (ops/dense_adj)
        n_overflow = jnp.sum(
            jnp.maximum(slab.degrees() - slab.d_cap, 0).astype(jnp.int32))
    else:
        n_overflow = jnp.int32(0)
    from fastconsensus_tpu.models.louvain import select_move_path
    if select_move_path(slab) == "hybrid":
        # same count build_hybrid would drop next round: total degree of
        # hub nodes beyond the static prefix budget (ADVICE round 2 —
        # consensus rounds can outgrow the pack-time hub_cap silently).
        # Gated on the *selected* path: slabs can carry hybrid sizing yet
        # take the matmul/dense path, where nothing is ever dropped.
        deg = slab.degrees()
        hub_mass = jnp.sum(jnp.where(deg > slab.d_hyb, deg, 0)
                           .astype(jnp.int32))
        n_hub_overflow = jnp.maximum(hub_mass - slab.hub_cap, 0)
    else:
        n_hub_overflow = jnp.int32(0)
    from fastconsensus_tpu.graph import agg_compaction_active
    if agg_compaction_active(slab):
        # upper bound on alive aggregate edges compact_alive will rank
        # past agg_cap next round (distinct aggregate pairs <= alive
        # consensus edges, so 0 here means provably lossless)
        n_agg_overflow = jnp.maximum(st_end.n_alive - slab.agg_cap, 0)
    else:
        n_agg_overflow = jnp.int32(0)
    qual = obs_quality.tail_quality(prev.alive, counts, slab, labels,
                                    prev_labels, n_p)
    stats = RoundStats(
        converged=st_mid.converged | st_end.converged,
        n_alive=st_end.n_alive,
        n_unconverged=st_end.n_unconverged,
        n_closure_added=n_closed,
        n_repaired=n_repaired,
        n_dropped=n_dropped,
        n_overflow=n_overflow,
        n_hub_overflow=n_hub_overflow,
        n_agg_overflow=n_agg_overflow,
        cold=jnp.bool_(False),  # the caller (driver / block body) knows
        n_w_zero=qual.n_w_zero,
        n_w_full=qual.n_w_full,
        n_frontier=qual.n_frontier,
        labels_changed=qual.labels_changed,
        member_modularity=qual.member_modularity,
        agreement=qual.agreement,
    )
    return slab, stats


def _maybe_align_keys(keys: jax.Array, align) -> jax.Array:
    """Give every ensemble member member 0's key when ``align`` is true.

    ``align`` may be a Python bool (static short-circuit) or a traced bool
    scalar (both variants live in one executable — select on the raw key
    data; typed PRNG key arrays have no jnp.where).
    """
    if isinstance(align, bool) and not align:
        return keys
    aligned = keys[jnp.zeros((keys.shape[0],), jnp.int32)]
    return jax.random.wrap_key_data(
        jnp.where(align, jax.random.key_data(aligned),
                  jax.random.key_data(keys)))


def consensus_round(slab: GraphSlab,
                    key: jax.Array,
                    detect: Detector,
                    n_p: int,
                    tau: float,
                    delta: float,
                    n_closure: int,
                    ensemble_sharding=None,
                    init_labels: Optional[jax.Array] = None,
                    align: bool = False,
                    sampler: str = "scatter",
                    closure_tau: Optional[float] = None,
                    prev_labels: Optional[jax.Array] = None,
                    active: Optional[jax.Array] = None
                    ) -> Tuple[GraphSlab, jax.Array, RoundStats]:
    """One full consensus round.  Jittable; all shapes static.

    Returns (next_slab, labels[n_p, N], stats).  ``n_closure`` is L, the
    original edge count (the reference re-reads it from the *input* graph
    every round, fc:144/:175 — so it is static).

    ``init_labels`` ([n_p, N]) warm-starts detection from the previous
    round's labels — the consensus graph changes little between rounds, so
    warm members converge in a few sweeps instead of re-deriving the
    partition from singletons every round (the driver threads this;
    None = from-scratch, the reference's only mode, fc:148).

    ``align`` shares member 0's detection key with every member (endgame
    tie-break alignment, ConsensusConfig.align_frac; requires warm
    init_labels to keep members distinct).  May be a traced bool scalar —
    flipping it never recompiles the round.

    ``ensemble_sharding`` (a ``NamedSharding`` with spec ``P("p")``) pins the
    per-partition keys and labels to the mesh's ensemble axis; XLA then runs
    each chip's shard of the ensemble locally and contracts the n_p axis of
    the co-membership count with one ``psum`` — the round's only collective.

    ``active`` (traced bool[N], fcdelta) freezes vertices OUTSIDE the mask:
    after detection their labels are clamped back to the round-entering
    ``prev_labels`` under a ``where`` — shapes stay static, so an all-True
    mask is the identity program and full runs share executables with
    frontier-restricted incremental re-runs.  Requires ``prev_labels``;
    not supported under ``ensemble_sharding`` (the mesh path never serves
    delta jobs).  ``None`` (static) compiles no ``where`` at all.
    """
    if active is not None and ensemble_sharding is not None:
        raise ValueError("active mask is not supported on the mesh path")
    if active is not None and prev_labels is None:
        raise ValueError("active mask requires prev_labels (the freeze "
                         "source for masked-out vertices)")
    k_detect, k_closure = jax.random.split(key)
    keys = _maybe_align_keys(prng.partition_keys(k_detect, n_p), align)
    if ensemble_sharding is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        from fastconsensus_tpu.parallel.sharding import (constrain_keys,
                                                         replicate_slab)

        keys = constrain_keys(keys, ensemble_sharding)
        labels_sharding = NamedSharding(
            ensemble_sharding.mesh,
            PartitionSpec(*ensemble_sharding.spec, None))
        # detection-side replicated view of the slab (the tail below keeps
        # the edge-sharded one) — see parallel.sharding.replicate_slab
        det_slab = replicate_slab(slab, ensemble_sharding.mesh)
        if init_labels is not None:
            init_labels = jax.lax.with_sharding_constraint(
                init_labels, labels_sharding)
            raw = detect(det_slab, keys, init_labels)
        else:
            raw = detect(det_slab, keys)
        labels = jax.lax.with_sharding_constraint(raw, labels_sharding)
    elif init_labels is not None:
        labels = detect(slab, keys, init_labels)
    else:
        labels = detect(slab, keys)
    if active is not None:
        # frontier restriction: frozen vertices keep their round-entering
        # labels no matter what the detector's sweeps did — the move phase
        # is skipped for them by construction of the consensus input
        labels = jnp.where(active[None, :], labels, prev_labels)
    if ensemble_sharding is not None:
        # explicit edge-local tail: GSPMD re-gathers the tail's scatters
        # and concatenates capacity-wide (ops/sharded_tail.py docstring);
        # bit-identical to the unsharded tail below
        from fastconsensus_tpu.ops import sharded_tail as stail

        slab, stats = stail.sharded_consensus_tail(
            slab, labels, k_closure, n_p, tau, delta, n_closure,
            ensemble_sharding.mesh, closure_tau=closure_tau,
            prev_labels=prev_labels)
    else:
        slab, stats = consensus_tail(slab, labels, k_closure, n_p, tau,
                                     delta, n_closure, sampler=sampler,
                                     closure_tau=closure_tau,
                                     prev_labels=prev_labels)
    return slab, labels, stats


@functools.lru_cache(maxsize=128)
def _jitted_round(detect: Detector, n_p: int, tau: float, delta: float,
                  n_closure: int, ensemble_sharding,
                  sampler: str = "scatter",
                  closure_tau: Optional[float] = None):
    """Cache jitted round steps across run_consensus calls.

    ``jax.jit`` keys its executable cache on the *function object*; wrapping a
    fresh ``functools.partial`` per run would recompile every round step on
    every call (measured: ~18s/run on the TPU tunnel).  Detectors from the
    registry are module-level singletons, so they hash stably here.
    ``align`` stays a call-time (traced) argument for the same reason.
    """
    return jax.jit(functools.partial(
        consensus_round, detect=detect, n_p=n_p, tau=tau, delta=delta,
        n_closure=n_closure, ensemble_sharding=ensemble_sharding,
        sampler=sampler, closure_tau=closure_tau))


@functools.lru_cache(maxsize=64)
def _jitted_detect(detect: Detector):
    return jax.jit(detect)


def consensus_rounds_block(slab: GraphSlab,
                           key: jax.Array,
                           labels0: jax.Array,
                           start_round: jax.Array,
                           max_iters: jax.Array,
                           align0: jax.Array,
                           pstate0: policy.PolicyState,
                           watch0: jax.Array,
                           noop0: jax.Array,
                           active0: jax.Array,
                           warm0: jax.Array,
                           detect: Detector,
                           detect_warm: Detector,
                           detect_refresh: Detector,
                           n_p: int,
                           tau: float,
                           delta: float,
                           n_closure: int,
                           block: int,
                           warm: bool,
                           align_frac: float = 0.0,
                           sampler: str = "scatter",
                           closure_tau: Optional[float] = None
                           ) -> Tuple[GraphSlab, jax.Array, RoundStats,
                                      jax.Array]:
    """Up to ``min(block, max_iters)`` consensus rounds in ONE device call.

    On small graphs a round's device time is a few hundred ms, so the
    per-round host round-trip (dispatch + stats readback over the TPU
    tunnel) dominates the driver loop; a ``lax.while_loop`` over whole
    rounds amortizes it ``block``-fold.  Stops early on delta-convergence.
    ``max_iters`` is traced (the driver's remaining-round budget never
    triggers a recompile).  Returns (slab, n_rounds_done, stacked
    stats[block], last_labels); stats entries past n_rounds_done are garbage
    and must be ignored.  ``key`` is the run key: per-round keys are derived
    from (key, start_round + i) exactly as the one-round driver derives
    them, so block size never changes results.

    ``labels0`` [n_p, N] seeds the first round's detection when ``warm``
    (consensus_round init_labels); each later round warm-starts from its
    predecessor's labels via the loop carry.  Absolute round 0 runs the
    full-sweep ``detect``; later rounds the capped-sweep ``detect_warm``
    (an in-block ``lax.cond``; see louvain.warm_sweep_budget).  With
    ``warm=False`` the carry still tracks labels (for the caller's next
    block / final detection) but detection always cold-starts via
    ``detect``.

    ``align0`` (traced bool) is the endgame-alignment state entering the
    block (ConsensusConfig.align_frac); each in-block round re-derives it
    from its own stats, so fused and per-round execution stay bit-identical
    — the contract above.  ``align_frac=0`` keeps alignment off (the
    driver passes 0 for detectors without content-keyed tie-breaks).

    ``watch0`` (traced bool) and ``noop0`` (traced int32[2]) gate the
    budget early-stop: the block stops at a budget-starved round only
    when the host would act on it — auto_grow on, and the overflow
    exceeding the levels of the last no-op re-derivation (noop0; (-1,-1)
    = none).  Without the gate a persistently-stale run (--no-grow, or a
    histogram whose derived sizing cannot change) would degrade every
    block to one round (round-4 review).

    ``pstate0`` (a ``policy.PolicyState`` of traced int32 scalars) is the
    stagnation state entering the block.  Each in-block round evaluates
    the SAME division-free rules the host driver evaluates between device
    calls — ``policy.stalled`` (one-step relative progress), ``policy.
    stale`` (limit cycle) — with ``xp = jnp`` instead of numpy; a firing
    rule makes the next round re-detect COLD (singleton init, full sweeps,
    independent keys), and ``policy.observe`` folds each round's stats
    into the carried state exactly as the host's ``record()`` does.

    ``active0`` (traced bool[N]) and ``warm0`` (traced bool) are the
    fcdelta incremental-consensus inputs, ALWAYS passed so full and delta
    runs share one executable per bucket: ``active0`` freezes vertices
    outside the changed-edge neighborhood (all-True = the identity
    program, the full-run posture) and ``warm0`` makes absolute round 0
    run the capped-sweep ``detect_warm`` from ``labels0`` (the parent
    run's partitions) instead of the full-sweep singleton cold start.
    Stagnation refresh still re-detects cold mid-run either way.
    """
    def empty_stats():
        z = jnp.zeros((block,), jnp.int32)
        zp = jnp.zeros((block, n_p), jnp.int32)
        return RoundStats(converged=jnp.zeros((block,), bool), n_alive=z,
                          n_unconverged=z, n_closure_added=z, n_repaired=z,
                          n_dropped=z, n_overflow=z, n_hub_overflow=z,
                          n_agg_overflow=z,
                          cold=jnp.zeros((block,), bool),
                          n_w_zero=z, n_w_full=z, n_frontier=z,
                          labels_changed=zp,
                          member_modularity=zp.astype(jnp.float32),
                          agreement=jnp.zeros((block,), jnp.float32))

    def cond(carry):
        _, i, conv, _, _, _, _, need = carry
        # `need` stops the block at a budget-starved round (after it is
        # recorded): the host re-derives the candidate budgets and the
        # next block runs with complete rows.  Per-round execution
        # evaluates the identical rule after each round, so fused and
        # unfused trajectories re-size at the same round.
        return (~conv) & (~need) & (i < block) & (i < max_iters)

    def body(carry):
        slab, i, _, buf, labels, aligned, pst, _ = carry
        k = prng.stream(key, prng.STREAM_ROUND, start_round + i)
        if warm:
            # `aligned` is exactly "this round will run aligned"
            stall = policy.stalled(jnp, delta, pst, aligned)
            stale = policy.stale(jnp, delta, pst)
            # warm0 (fcdelta) downgrades the absolute-round-0 cold start
            # to a warm round seeded from labels0 (the parent ensemble);
            # stagnation refreshes still re-detect cold
            cold = ((start_round + i == 0) & ~warm0) | stale | stall

            def run_singleton(d):
                def go(op):
                    s, kk, lab, _ = op
                    sing = jnp.broadcast_to(
                        jnp.arange(lab.shape[1], dtype=jnp.int32),
                        lab.shape)
                    return consensus_round(
                        s, kk, detect=d, n_p=n_p, tau=tau, delta=delta,
                        n_closure=n_closure, init_labels=sing,
                        align=False, sampler=sampler,
                        closure_tau=closure_tau, prev_labels=lab,
                        active=active0)
                return go

            def run_cold(op):
                # round 0: the theta-randomized base detector (ensemble
                # diversity); stagnation refresh: the low-variance
                # refresh variant (models/leiden.py refresh_variant)
                if detect_refresh is detect:
                    return run_singleton(detect)(op)
                return jax.lax.cond(
                    start_round + i == 0, run_singleton(detect),
                    run_singleton(detect_refresh), op)

            def run_warm(op):
                s, kk, lab, al = op
                return consensus_round(
                    s, kk, detect=detect_warm, n_p=n_p, tau=tau,
                    delta=delta, n_closure=n_closure, init_labels=lab,
                    align=al, sampler=sampler, closure_tau=closure_tau,
                    prev_labels=lab, active=active0)

            slab, labels, st = jax.lax.cond(
                cold, run_cold, run_warm, (slab, k, labels, aligned))
            st = st._replace(cold=cold)
        else:
            prev_lab = labels
            slab, labels, st = consensus_round(
                slab, k, detect=detect, n_p=n_p, tau=tau, delta=delta,
                n_closure=n_closure, init_labels=None, align=False,
                sampler=sampler, closure_tau=closure_tau,
                prev_labels=prev_lab, active=active0)
            st = st._replace(cold=jnp.bool_(True))
        # fold the round into the carried stagnation state — the same
        # policy.observe the host's record() applies, so fused and
        # per-round execution see identical rule inputs
        pst = policy.observe(jnp, pst, st.cold, st.n_unconverged,
                             st.n_alive)
        buf = jax.tree.map(lambda b, s: b.at[i].set(s), buf, st)
        if warm and align_frac > 0:
            aligned = policy.align_now(jnp, align_frac, pst)
        else:
            aligned = jnp.bool_(False)
        need = policy.budgets_stale(jnp, st.n_overflow, st.n_hub_overflow,
                                    slab.d_cap, slab.hub_cap,
                                    slab.n_nodes, st.n_alive,
                                    slab.agg_cap) & \
            jnp.asarray(watch0) & \
            ((st.n_overflow > noop0[0]) | (st.n_hub_overflow > noop0[1]) |
             (st.n_alive > noop0[2]))
        return (slab, i + 1, st.converged, buf, labels, aligned, pst, need)

    active0 = jnp.asarray(active0, bool)
    warm0 = jnp.asarray(warm0, bool)
    pst0 = policy.PolicyState(*(jnp.asarray(v, jnp.int32)
                                for v in pstate0))
    slab, done, _, buf, labels, _, _, _ = jax.lax.while_loop(
        cond, body,
        (slab, jnp.int32(0), jnp.bool_(False), empty_stats(), labels0,
         jnp.asarray(align0, bool), pst0, jnp.bool_(False)))
    return slab, done, buf, labels


@functools.lru_cache(maxsize=128)
def _jitted_rounds_block(detect: Detector, detect_warm: Detector,
                         detect_refresh: Detector, n_p: int,
                         tau: float, delta: float, n_closure: int,
                         block: int, warm: bool, align_frac: float = 0.0,
                         sampler: str = "scatter",
                         closure_tau: Optional[float] = None):
    return jax.jit(functools.partial(
        consensus_rounds_block, detect=detect, detect_warm=detect_warm,
        detect_refresh=detect_refresh, n_p=n_p, tau=tau, delta=delta,
        n_closure=n_closure, block=block, warm=warm,
        align_frac=align_frac, sampler=sampler, closure_tau=closure_tau))


def consensus_batch_block(slab: GraphSlab,
                          key: jax.Array,
                          labels0: jax.Array,
                          start_round: jax.Array,
                          max_iters: jax.Array,
                          align0: jax.Array,
                          pstate0: policy.PolicyState,
                          watch0: jax.Array,
                          noop0: jax.Array,
                          detect: Detector,
                          n_p: int,
                          tau: float,
                          delta: float,
                          n_closure: int,
                          block: int,
                          mode: str,
                          align_frac: float = 0.0,
                          sampler: str = "scatter",
                          closure_tau: Optional[float] = None
                          ) -> Tuple[GraphSlab, jax.Array, RoundStats,
                                     jax.Array]:
    """One GRAPH's rounds for the cross-request batch path — vmapped over
    a leading batch axis by :func:`_jitted_rounds_batch`.

    :func:`consensus_rounds_block` decides cold/refresh/warm *in-loop*
    with ``lax.cond``; under ``vmap`` a batched predicate lowers every
    ``cond`` to ``select`` — BOTH detector branches execute for the whole
    batch every round, which on compute-bound backends eats the entire
    coalescing win (full-sweep cold detection costs a multiple of a
    capped warm round).  This variant therefore carries ONE static
    ``mode`` so the body traces exactly one detector path:

    * ``"warm"``    — every round runs the capped-sweep ``detect`` from
      the carried labels with the carried alignment flag; the loop STOPS
      (element freezes) when the stagnation policy says the next round
      must re-detect cold — the host driver splits that graph off to a
      solo ``run_consensus`` tail instead of paying a batched cold
      branch (consensus.run_consensus_batch).
    * ``"cold"``    — every round is a singleton-init full-sweep round
      (absolute round 0 of a warm run: uniform across the batch, so no
      per-element branch is needed).
    * ``"scratch"`` — every round cold-starts with no init (warm_start
      off / detectors without ``supports_init``), the fused analog of
      the unfused driver's ``warm=False`` path.

    Per-round keys derive from ``(key, start_round + i)`` exactly as the
    solo driver derives them, per-round policy folding is the same
    ``policy.observe``, and each non-deviating element's computation is
    the identical jaxpr per batch element — the bit-parity contract
    tests/test_serve_batch.py pins.  The ``need`` (budget-starvation)
    early stop mirrors :func:`consensus_rounds_block`; a stopped element
    is likewise split off to a solo tail by the driver.  Stats rows past
    each element's ``done`` count are garbage and must be ignored.
    """
    assert mode in ("warm", "cold", "scratch"), mode

    def empty_stats():
        z = jnp.zeros((block,), jnp.int32)
        zp = jnp.zeros((block, n_p), jnp.int32)
        return RoundStats(converged=jnp.zeros((block,), bool), n_alive=z,
                          n_unconverged=z, n_closure_added=z, n_repaired=z,
                          n_dropped=z, n_overflow=z, n_hub_overflow=z,
                          n_agg_overflow=z,
                          cold=jnp.zeros((block,), bool),
                          n_w_zero=z, n_w_full=z, n_frontier=z,
                          labels_changed=zp,
                          member_modularity=zp.astype(jnp.float32),
                          agreement=jnp.zeros((block,), jnp.float32))

    def cond(carry):
        _, i, conv, _, _, aligned, pst, need = carry
        go = (~conv) & (~need) & (i < block) & (i < max_iters)
        if mode == "warm":
            # stop BEFORE a round the solo driver would run cold
            # (round_mode "refresh"): the host splits this graph off
            refresh = policy.stale(jnp, delta, pst) | \
                policy.stalled(jnp, delta, pst, aligned)
            go = go & (~refresh)
        return go

    def body(carry):
        slab, i, _, buf, labels, aligned, pst, _ = carry
        k = prng.stream(key, prng.STREAM_ROUND, start_round + i)
        prev_lab = labels
        if mode == "warm":
            slab, labels, st = consensus_round(
                slab, k, detect=detect, n_p=n_p, tau=tau, delta=delta,
                n_closure=n_closure, init_labels=labels, align=aligned,
                sampler=sampler, closure_tau=closure_tau,
                prev_labels=prev_lab)
            st = st._replace(cold=jnp.bool_(False))
        else:
            init = None
            if mode == "cold":
                init = jnp.broadcast_to(
                    jnp.arange(labels.shape[1], dtype=jnp.int32),
                    labels.shape)
            slab, labels, st = consensus_round(
                slab, k, detect=detect, n_p=n_p, tau=tau, delta=delta,
                n_closure=n_closure, init_labels=init, align=False,
                sampler=sampler, closure_tau=closure_tau,
                prev_labels=prev_lab)
            st = st._replace(cold=jnp.bool_(True))
        pst = policy.observe(jnp, pst, st.cold, st.n_unconverged,
                             st.n_alive)
        buf = jax.tree.map(lambda b, s: b.at[i].set(s), buf, st)
        if mode == "warm" and align_frac > 0:
            aligned = policy.align_now(jnp, align_frac, pst)
        else:
            aligned = jnp.bool_(False)
        need = policy.budgets_stale(jnp, st.n_overflow, st.n_hub_overflow,
                                    slab.d_cap, slab.hub_cap,
                                    slab.n_nodes, st.n_alive,
                                    slab.agg_cap) & \
            jnp.asarray(watch0) & \
            ((st.n_overflow > noop0[0]) | (st.n_hub_overflow > noop0[1]) |
             (st.n_alive > noop0[2]))
        return (slab, i + 1, st.converged, buf, labels, aligned, pst, need)

    pst0 = policy.PolicyState(*(jnp.asarray(v, jnp.int32)
                                for v in pstate0))
    slab, done, _, buf, labels, _, _, _ = jax.lax.while_loop(
        cond, body,
        (slab, jnp.int32(0), jnp.bool_(False), empty_stats(), labels0,
         jnp.asarray(align0, bool), pst0, jnp.bool_(False)))
    return slab, done, buf, labels


@functools.lru_cache(maxsize=64)
def _jitted_rounds_batch(detect: Detector, n_p: int, tau: float,
                         delta: float, n_closure: int, block: int,
                         mode: str, align_frac: float = 0.0,
                         sampler: str = "scatter",
                         closure_tau: Optional[float] = None):
    """jit(vmap) of :func:`consensus_batch_block`: B same-bucket graphs'
    rounds in ONE device call.  Every argument batches over the leading
    axis; the batch width B is a call-time shape, so each rung of the
    serving ladder (serve/bucketer.BATCH_LADDER) compiles exactly one
    executable per (detector, config) through this one cached wrapper.
    """
    return jax.jit(jax.vmap(functools.partial(
        consensus_batch_block, detect=detect, n_p=n_p, tau=tau,
        delta=delta, n_closure=n_closure, block=block, mode=mode,
        align_frac=align_frac, sampler=sampler, closure_tau=closure_tau)))


@functools.lru_cache(maxsize=64)
def _jitted_detect_batch(detect: Detector, with_init: bool):
    """jit(vmap) of a detector over a leading graph-batch axis — the
    batched analog of :func:`_jitted_detect` for the final re-detection
    (each element computes ``detect(slab_b, keys_b[, init_b])``, the
    exact program the solo whole-ensemble dispatch runs)."""
    if with_init:
        return jax.jit(jax.vmap(
            lambda slab, keys, init: detect(slab, keys, init)))
    return jax.jit(jax.vmap(lambda slab, keys: detect(slab, keys)))


@functools.lru_cache(maxsize=128)
def _jitted_tail(n_p: int, tau: float, delta: float, n_closure: int,
                 mesh=None, sampler: str = "scatter",
                 closure_tau: Optional[float] = None):
    if mesh is not None:
        from fastconsensus_tpu.ops import sharded_tail as stail

        return jax.jit(functools.partial(
            stail.sharded_consensus_tail, n_p=n_p, tau=tau, delta=delta,
            n_closure=n_closure, mesh=mesh, closure_tau=closure_tau))
    return jax.jit(functools.partial(
        consensus_tail, n_p=n_p, tau=tau, delta=delta, n_closure=n_closure,
        sampler=sampler, closure_tau=closure_tau))


def _detect_chunked(detect: Detector, slab: GraphSlab, keys: jax.Array,
                    members: int,
                    cache_dir: Optional[str] = None,
                    cache_tag: str = "",
                    init_labels: Optional[jax.Array] = None,
                    ensemble_sharding=None,
                    timings: Optional[list] = None) -> jax.Array:
    """Run detection as ceil(n_p / members) separate device calls.

    Labels stay on device; only the dispatches are split.  Chunks reuse one
    compiled executable; an uneven remainder compiles a second shape once.

    ``cache_dir``: elastic recovery for long runs.  Each completed chunk's
    labels are persisted as ``{cache_dir}/{cache_tag}_c{i}.npy``; a
    restarted run (the TPU tunnel wedges multi-hundred-call sequences, see
    utils/trace.py notes) skips straight past finished chunks instead of
    redetecting them.  Results are identical either way — chunk keys are
    position-derived — *provided the detector is per-key independent*
    (member i's labels depend only on (slab, keys[i])).  Every ensemble()
    lift satisfies this; a custom Detector that mixes information across
    the keys axis would silently change results under chunking (see the
    Detector protocol docstring).
    """
    n_p = keys.shape[0]
    tracer = get_tracer()
    obs_reg = obs_counters.get_registry()
    jd = _jitted_detect(detect)
    if ensemble_sharding is not None:
        # detection-side replicated slab view (parallel.sharding
        # .replicate_slab rationale); host-side, so one device_put
        # shared by every chunk below
        from jax.sharding import NamedSharding, PartitionSpec

        slab = jax.device_put(slab, NamedSharding(
            ensemble_sharding.mesh, PartitionSpec()))

    def call(ks, init):
        if ensemble_sharding is not None:
            # pin each chunk to the mesh's ensemble axis (chunk sizes are
            # rounded to a multiple of it by setup_executables)
            from jax.sharding import NamedSharding, PartitionSpec

            from fastconsensus_tpu.parallel.sharding import put_keys

            ks = put_keys(ks, ensemble_sharding)
            if init is not None:
                init = jax.device_put(init, NamedSharding(
                    ensemble_sharding.mesh,
                    PartitionSpec(*ensemble_sharding.spec, None)))
        return jd(slab, ks) if init is None else jd(slab, ks, init)

    if members >= n_p:
        # whole-ensemble dispatch: labels stay on device (no sync here),
        # so the span measures dispatch/trace time only — the execute
        # lands in the caller's round/tail span
        with tracer.span("detect_dispatch", members=n_p):
            return call(keys, init_labels)
    # Pad to a whole number of equal chunks: one compiled shape for every
    # call (a ragged remainder would pay a second multi-minute remote
    # compile for at most `members-1` members of work).
    n_calls = -(-n_p // members)
    pad = n_calls * members - n_p
    if pad:
        # gather (typed PRNG key arrays don't implement .repeat)
        idx = jnp.concatenate([jnp.arange(n_p, dtype=jnp.int32),
                               jnp.full((pad,), n_p - 1, jnp.int32)])
        keys = keys[idx]
        if init_labels is not None:
            init_labels = init_labels[idx]
    parts = []
    computed = 0  # chunks actually executed (not cache-loaded) this call
    for i in range(n_calls):
        path = None
        if cache_dir:
            path = os.path.join(cache_dir, f"{cache_tag}_c{i}.npy")
            if os.path.exists(path):
                cached = np.load(path)
                if cached.shape != (members, slab.n_nodes) or \
                        cached.dtype != np.int32:
                    raise ValueError(
                        f"stale detect-chunk cache {path}: shape "
                        f"{cached.shape} dtype {cached.dtype}, expected "
                        f"{(members, slab.n_nodes)} int32; clean the "
                        f"cache dir")
                parts.append(jnp.asarray(cached))
                obs_reg.inc("detect.chunks_cached")
                _logger.debug("detect call %d/%d: loaded from %s",
                              i + 1, n_calls, path)
                continue
        t0 = time.perf_counter()
        sl = slice(i * members, (i + 1) * members)
        # tag carries the round (cache_tag embeds "_r{round}"), so a
        # merged host+device timeline attributes each chunk to its round
        # even where the enclosing step annotation is unavailable
        with tracer.span("detect_chunk", chunk=i, members=members,
                         tag=cache_tag):
            out = call(keys[sl],
                       None if init_labels is None else init_labels[sl])
            # fcheck: ok=sync-in-loop (deliberate: the per-chunk barrier
            # IS the timing measurement call sizing feeds on, and
            # chunking IS the split-dispatch feature)
            out.block_until_ready()
        obs_counters.host_sync("detect_chunk")
        dt = time.perf_counter() - t0
        obs_reg.inc("detect.chunks")
        obs_reg.observe("detect.call_s", dt)
        _logger.debug("detect call %d/%d (%d members): %.1fs",
                      i + 1, n_calls, members, dt)
        if timings is not None and computed > 0:
            # the first *executed* chunk of a new shape pays the compile
            # (on a cache-assisted restart that may be chunk k, not chunk
            # 0); later executions measure the pure execute rate (the
            # quantity call sizing needs)
            timings.append(dt / members)
        computed += 1
        if path is not None:
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:  # np.save would append .npy to tmp
                # fcheck: ok=sync-in-loop (per-chunk elastic-recovery
                # persistence — the cache write is the point)
                np.save(fh, np.asarray(out))
            os.replace(tmp, path)
        parts.append(out)
    return jnp.concatenate(parts, axis=0)[:n_p]

