"""fcheck-fault: exception-flow & resource-lifecycle analysis.

PRs 4-13 built the failure-isolation contracts the serving stack lives
by — per-job error absorption in ``server.py`` (a bad graph fails as
itself, never as its batch), cordon + requeue-with-exclusion in
``pool.py``, watchdog post-mortem bundles, SIGTERM drain — and nothing
proved those contracts cover every raise site.  The concurrency pass
(PR 7) audits who may touch what; the contracts pass (PR 14) audits
what things are called; this pass audits the third axis: where errors
GO.  Which exception types can reach which boundaries, which handlers
eat errors the observability stack can never see, and which resources
leak on exactly the path nobody tested.

Whole-program like concurrency.py: ``lint_paths`` hands it the
complete scanned source set, and per-function raise sets propagate
through the same name-based call resolution (local defs, ``from``
imports, ``self`` methods, and the deliberately type-blind
receiver-identifier/class-name containment fallback).  Over-approximate
on purpose — extra propagation edges mean extra findings, never missed
ones, and the pragma convention absorbs the occasional false positive.

The raise set of a function is: its explicit ``raise`` statements
(including handler re-raises, which re-throw the handler's caught
types), everything escaping its callees, plus a curated builtin-raiser
table (``urlopen`` -> URLError/HTTPError, ``socket.*`` -> OSError,
``np.load`` -> OSError/ValueError, ``json.loads`` -> JSONDecodeError,
``open`` -> OSError, ...).  Escape = not caught by any lexically
enclosing handler at the raise/call site, resolved through a merged
exception hierarchy: the builtin tree plus every scanned ``class
X(SomeError)`` definition; unknown types are assumed direct Exception
subclasses, so ``except Exception`` absorbs them and nothing narrower
does.  ``NotImplementedError`` and ``AssertionError`` are excluded
from the escape rules (abstract-method stubs and invariant checks are
supposed to be loud), as are BaseException-only types
(KeyboardInterrupt / SystemExit — the drain path handles those by
design, not by handler).

Four rules:

``escape-thread-root``
    An exception type reachable from a ``threading.Thread`` target
    that no handler absorbs before ``Thread.run``.  CPython prints the
    traceback to stderr and the thread dies — no cordon, no counter,
    no flight event, and for the dispatcher no pool.  Every thread
    root must route failures to the death machinery
    (``_Worker._die``-style) or carry a pragma saying why dying
    silently is acceptable.

``swallowed-error``
    An ``except`` body with no outlet: it neither re-raises, returns,
    records an error value (any assignment counts — binding a fallback
    IS the handled result), stamps an fcobs counter
    (``inc``/``gauge``/``observe``), records a flight event
    (``record``/``mark``), nor routes to the failure machinery
    (``_die``/``cordon``/``_fail*``/``send*``).  Logging is NOT an
    outlet — the obs stack cannot see a log line, and the one thing
    PRs 12-13 guarantee is that failures are visible in ``/metricsz``
    and the flight recorder.

``unmapped-http-error``
    An exception type reachable from an HTTP handler body
    (``do_GET``/``do_POST``/...) with no mapping to a status code.
    ``BaseHTTPRequestHandler`` turns an escaped exception into a
    silently dropped connection (or a 500 with a raw traceback) — the
    client sees a hang, not the 4xx/5xx + JSON error body the wire
    contract promises.

``resource-leak``
    Lifecycle holes on the error path: a ``threading.Thread`` started
    without ``daemon=`` and never joined; ``.acquire()`` with no
    ``.release()`` in a ``finally``; a file/socket/tempdir opened
    outside ``with`` whose close/cleanup is skipped when an exception
    fires between open and close.  Returning the resource (ownership
    transfer) and class-attribute bindings closed by any method of the
    class (object lifetime) are compliant.

The runtime half closes the loop the way ``analysis/lockorder.py``
does for the lock-order rule: ``--emit-fault-inventory`` writes
``runs/faults_r19.json`` — every raise site in ``serve/`` plus the
boundary this pass claims absorbs it — and ``serve/faultinject.py``
(``FCTPU_FAULT_INJECT=<site_id>``) patches any inventoried site to
throw on demand, so the ci_check injection campaign can assert per
site that the claimed contract actually holds against a live pool.

All rules honor ``# fcheck: ok=<rule>: <reason>`` pragmas
(diagnostics.parse_pragmas), counted like every other suppression.
"""

from __future__ import annotations

import ast
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from fastconsensus_tpu.analysis.diagnostics import (Diagnostic,
                                                    apply_pragmas)

FAULT_RULES = ("escape-thread-root", "swallowed-error",
               "unmapped-http-error", "resource-leak")

EXTERNAL_BOUNDARY = "<external>"

# The builtin exception tree, child -> parent, restricted to what the
# codebase's raise sites and the raiser table below can produce.  The
# project's own ``class X(SomeError)`` definitions are merged on top at
# collect time; anything still unknown is treated as a direct Exception
# subclass.
_EXC_PARENTS: Dict[str, str] = {
    "Exception": "BaseException",
    "KeyboardInterrupt": "BaseException",
    "SystemExit": "BaseException",
    "GeneratorExit": "BaseException",
    "ArithmeticError": "Exception",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "AssertionError": "Exception",
    "AttributeError": "Exception",
    "ImportError": "Exception",
    "ModuleNotFoundError": "ImportError",
    "LookupError": "Exception",
    "KeyError": "LookupError",
    "IndexError": "LookupError",
    "MemoryError": "Exception",
    "NameError": "Exception",
    "OSError": "Exception",
    "IOError": "OSError",
    "FileNotFoundError": "OSError",
    "FileExistsError": "OSError",
    "PermissionError": "OSError",
    "IsADirectoryError": "OSError",
    "NotADirectoryError": "OSError",
    "InterruptedError": "OSError",
    "BlockingIOError": "OSError",
    "ConnectionError": "OSError",
    "ConnectionResetError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "ConnectionAbortedError": "ConnectionError",
    "BrokenPipeError": "ConnectionError",
    "TimeoutError": "OSError",
    "URLError": "OSError",
    "HTTPError": "URLError",
    "RuntimeError": "Exception",
    "NotImplementedError": "RuntimeError",
    "RecursionError": "RuntimeError",
    "StopIteration": "Exception",
    "TypeError": "Exception",
    "ValueError": "Exception",
    "UnicodeError": "ValueError",
    "UnicodeDecodeError": "UnicodeError",
    "UnicodeEncodeError": "UnicodeError",
    "JSONDecodeError": "ValueError",
}

# Curated builtin raisers, (module prefix, function) -> raised types.
# Deliberately short: explicit ``raise`` statements dominate the
# project's fault surface; this table covers the I/O edges whose
# failures arrive from outside the process.
_RAISERS_QUALIFIED: Dict[Tuple[str, str], Tuple[str, ...]] = {
    ("urllib.request", "urlopen"): ("URLError", "HTTPError"),
    ("socket", "create_connection"): ("OSError",),
    ("socket", "socket"): ("OSError",),
    ("numpy", "load"): ("OSError", "ValueError"),
    ("numpy", "save"): ("OSError",),
    ("json", "loads"): ("JSONDecodeError",),
    ("json", "load"): ("JSONDecodeError", "OSError"),
    ("os", "makedirs"): ("OSError",),
    ("os", "replace"): ("OSError",),
    ("os", "remove"): ("OSError",),
    ("os", "unlink"): ("OSError",),
    ("os", "rename"): ("OSError",),
    ("shutil", "rmtree"): ("OSError",),
    ("tempfile", "mkdtemp"): ("OSError",),
}
_RAISERS_BARE: Dict[str, Tuple[str, ...]] = {
    "open": ("OSError",),
}

# Types the escape rules ignore (module docstring: stubs and invariant
# checks are supposed to be loud; BaseException-only types are the
# drain path's business).
_ESCAPE_IGNORED = {"NotImplementedError", "AssertionError",
                   "KeyboardInterrupt", "SystemExit", "GeneratorExit",
                   "StopIteration", "MemoryError"}

_HTTP_HANDLER_NAMES = {"do_GET", "do_POST", "do_PUT", "do_DELETE",
                       "do_PATCH", "do_HEAD"}

# except-body call names that count as an outlet (terminal attr/func
# name, underscores stripped): the fcobs registry verbs, the flight
# recorder verbs, and the serving stack's failure machinery.
_OUTLET_CALL_NAMES = {"inc", "gauge", "observe", "record", "mark",
                      "cordon", "write_bundle", "die", "fail",
                      "fail_job", "requeue_pending", "on_worker_death",
                      "set_exception", "abort"}

# resource factories for the leak rule: (resolved module, name) or
# bare-name builtins -> human kind
_RESOURCE_QUALIFIED: Dict[Tuple[str, str], str] = {
    ("socket", "socket"): "socket",
    ("socket", "create_connection"): "socket",
    ("tempfile", "mkdtemp"): "tempdir",
    ("tempfile", "TemporaryDirectory"): "tempdir",
    ("tempfile", "NamedTemporaryFile"): "tempfile",
}
_RESOURCE_BARE: Dict[str, str] = {"open": "file"}

# verbs that end a resource's life, for the leak rule's close scan
_CLOSE_VERBS = {"close", "cleanup", "rmtree", "unlink", "remove",
                "shutdown", "terminate"}


def _call_name(node: ast.Call) -> Tuple[Optional[str], str]:
    """(dotted qualifier, attr/function name) of a call target — the
    same shape concurrency.py uses, so the two passes resolve calls
    identically."""
    f = node.func
    if isinstance(f, ast.Name):
        return None, f.id
    if isinstance(f, ast.Attribute):
        parts = []
        v = f.value
        while isinstance(v, ast.Attribute):
            parts.append(v.attr)
            v = v.value
        if isinstance(v, ast.Name):
            parts.append(v.id)
            return ".".join(reversed(parts)), f.attr
        return None, f.attr
    return None, ""


def _module_name(path: str) -> str:
    from fastconsensus_tpu.analysis import _module_name as shared

    return shared(path)


def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # noqa: BLE001 — display-only fallback
        return "<expr>"


def _handler_types(h: ast.ExceptHandler) -> Tuple[str, ...]:
    """Terminal type names an except clause catches; ``*`` = bare
    except (or an unresolvable type expression, same effect)."""
    def term(e: ast.AST) -> str:
        if isinstance(e, ast.Name):
            return e.id
        if isinstance(e, ast.Attribute):
            return e.attr
        return "*"

    if h.type is None:
        return ("*",)
    if isinstance(h.type, ast.Tuple):
        return tuple(term(el) for el in h.type.elts) or ("*",)
    return (term(h.type),)


class _ExceptInfo:
    """One except clause: what it catches, whether its body has an
    outlet, where it is."""

    def __init__(self, types: Tuple[str, ...], node: ast.ExceptHandler,
                 filename: str) -> None:
        self.types = types
        self.node = node
        self.filename = filename
        self.has_outlet = _body_has_outlet(node.body)


def _body_has_outlet(body: List[ast.stmt]) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Raise, ast.Return)):
                return True
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign, ast.NamedExpr)):
                return True
            if isinstance(node, ast.Call):
                _, name = _call_name(node)
                if name.lstrip("_").lower() in _OUTLET_CALL_NAMES or \
                        name.lstrip("_").lower().startswith("send"):
                    return True
    return False


class _FnFault:
    """Per-function fault summary (one pass over the body)."""

    def __init__(self, module: str, cls: Optional[str], name: str,
                 node: ast.FunctionDef, filename: str) -> None:
        self.module = module
        self.cls = cls
        self.name = name
        self.node = node
        self.filename = filename
        self.ref = f"{module}.{cls}.{name}" if cls else f"{module}.{name}"
        self.qualname = f"{cls}.{name}" if cls else name
        # explicit raise sites: (exc type name, line, coverage stack)
        self.raises: List[Tuple[str, int,
                                Tuple[FrozenSet[str], ...]]] = []
        # every call: (qual, name, line, coverage stack)
        self.calls: List[Tuple[Optional[str], str, int,
                               Tuple[FrozenSet[str], ...]]] = []
        self.handlers: List[_ExceptInfo] = []
        self.thread_targets: List[str] = []    # Thread(target=...) refs
        # Thread(...) constructions: (line, daemon given, binding)
        self.thread_news: List[Tuple[int, bool, Optional[str]]] = []
        # resource factory calls: (kind, line, call id, binding)
        self.resources: List[Tuple[str, int, int, Optional[str]]] = []
        # .acquire() sites: (receiver text, line)
        self.acquires: List[Tuple[str, int]] = []
        # lifecycle verbs seen: (verb, target text, inside a finally)
        self.closes: List[Tuple[str, str, bool]] = []
        self.daemon_sets: Set[str] = set()     # ``x.daemon = True``
        self.returned: Set[str] = set()        # names returned
        self.with_ctx_ids: Set[int] = set()    # Call nodes used as ctx
        self.with_names: Set[str] = set()      # ``with f:`` names
        self.chained_close_ids: Set[int] = set()
        self.is_ctx_helper = any(
            isinstance(d, (ast.Name, ast.Attribute)) and
            _unparse(d).rsplit(".", 1)[-1] in ("contextmanager",
                                               "asynccontextmanager")
            for d in node.decorator_list)


class _ModFault:
    def __init__(self, module: str, filename: str, source: str) -> None:
        self.module = module
        self.filename = filename
        self.source = source
        self.functions: Dict[str, _FnFault] = {}
        self.classes: Dict[str, Dict[str, _FnFault]] = {}
        self.alias_modules: Dict[str, str] = {}
        self.from_imports: Dict[str, Tuple[str, str]] = {}


class FaultAnalyzer:
    """Whole-program exception-flow pass over a ``{filename: source}``
    set."""

    def __init__(self, sources: Dict[str, str]) -> None:
        self.sources = sources
        self.modules: Dict[str, _ModFault] = {}
        self.diags: List[Diagnostic] = []
        # merged hierarchy: builtin tree + scanned class definitions
        self.exc_parents: Dict[str, str] = dict(_EXC_PARENTS)
        self.esc: Dict[str, Set[str]] = {}

    # ---------------- collection ----------------

    def collect(self) -> None:
        for filename, source in self.sources.items():
            try:
                tree = ast.parse(source, filename=filename)
            # fcheck: ok=swallowed-error (astlint reports the syntax
            # error itself; this pass just skips the unparsable file)
            except SyntaxError:
                continue  # astlint reports the syntax error itself
            mod = _ModFault(_module_name(filename), filename, source)
            self._collect_imports(tree, mod)
            for node in tree.body:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    fn = _FnFault(mod.module, None, node.name, node,
                                  filename)
                    self._summarize(fn, mod)
                    mod.functions[node.name] = fn
                elif isinstance(node, ast.ClassDef):
                    self._collect_class_exc(node)
                    methods: Dict[str, _FnFault] = {}
                    for sub in node.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            fn = _FnFault(mod.module, node.name,
                                          sub.name, sub, filename)
                            self._summarize(fn, mod)
                            methods[sub.name] = fn
                    mod.classes[node.name] = methods
            self.modules[mod.module] = mod

    @staticmethod
    def _collect_imports(tree: ast.Module, mod: _ModFault) -> None:
        for stmt in tree.body:
            if isinstance(stmt, ast.Import):
                for a in stmt.names:
                    if a.asname:
                        mod.alias_modules[a.asname] = a.name
                    else:
                        mod.alias_modules.setdefault(a.name, a.name)
            elif isinstance(stmt, ast.ImportFrom) and stmt.level == 0 \
                    and stmt.module:
                for a in stmt.names:
                    alias = a.asname or a.name
                    mod.alias_modules.setdefault(
                        alias, f"{stmt.module}.{a.name}")
                    mod.from_imports[alias] = (stmt.module, a.name)

    def _collect_class_exc(self, node: ast.ClassDef) -> None:
        """Project exception hierarchy: every scanned class whose base
        chain might be an exception contributes child -> first base.
        Harmless for non-exception classes (only consulted on names
        that appear in raise/except position)."""
        for base in node.bases:
            if isinstance(base, ast.Name):
                self.exc_parents.setdefault(node.name, base.id)
                break
            if isinstance(base, ast.Attribute):
                self.exc_parents.setdefault(node.name, base.attr)
                break

    # ---------------- per-function summary ----------------

    def _summarize(self, fn: _FnFault, mod: _ModFault) -> None:
        self._walk(list(fn.node.body), fn, mod, coverage=(),
                   handler_types=(), handler_name=None,
                   in_finally=False)

    def _walk(self, stmts: List[ast.stmt], fn: _FnFault,
              mod: _ModFault, coverage: Tuple[FrozenSet[str], ...],
              handler_types: Tuple[str, ...],
              handler_name: Optional[str], in_finally: bool) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue  # nested defs run on their own schedule
            if isinstance(stmt, ast.Try):
                group = frozenset(
                    t for h in stmt.handlers for t in _handler_types(h))
                self._walk(stmt.body, fn, mod,
                           coverage + ((group,) if group else ()),
                           handler_types, handler_name, in_finally)
                for h in stmt.handlers:
                    htypes = _handler_types(h)
                    fn.handlers.append(
                        _ExceptInfo(htypes, h, fn.filename))
                    # the handler's own body is NOT covered by its try
                    self._walk(h.body, fn, mod, coverage, htypes,
                               h.name, in_finally)
                self._walk(stmt.orelse, fn, mod, coverage,
                           handler_types, handler_name, in_finally)
                self._walk(stmt.finalbody, fn, mod, coverage,
                           handler_types, handler_name, True)
                continue
            if isinstance(stmt, ast.Raise):
                for expr in (stmt.exc, stmt.cause):
                    if expr is not None:
                        self._scan_expr(expr, fn, mod, coverage,
                                        in_finally)
                for exc in self._raise_types(stmt, handler_types,
                                             handler_name):
                    fn.raises.append((exc, stmt.lineno, coverage))
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Call):
                        fn.with_ctx_ids.add(id(ce))
                        # ``closing(open(...))``-style wrappers manage
                        # their direct call arguments too
                        for a in ce.args:
                            if isinstance(a, ast.Call):
                                fn.with_ctx_ids.add(id(a))
                    elif isinstance(ce, ast.Name):
                        fn.with_names.add(ce.id)
                    self._scan_expr(ce, fn, mod, coverage, in_finally)
                self._walk(stmt.body, fn, mod, coverage, handler_types,
                           handler_name, in_finally)
                continue
            if isinstance(stmt, ast.Return):
                if isinstance(stmt.value, ast.Name):
                    fn.returned.add(stmt.value.id)
                elif isinstance(stmt.value, ast.Call):
                    fn.returned.add(f"<call:{id(stmt.value)}>")
                if stmt.value is not None:
                    self._scan_expr(stmt.value, fn, mod, coverage,
                                    in_finally)
                continue
            if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                self._note_binding(stmt.value, targets, fn)
                for t in targets:
                    # ``x.daemon = True`` keeps a non-daemon Thread
                    # from blocking interpreter exit
                    if isinstance(t, ast.Attribute) and \
                            t.attr == "daemon":
                        fn.daemon_sets.add(_unparse(t.value))
                    self._scan_expr(t, fn, mod, coverage, in_finally)
                if stmt.value is not None:
                    self._scan_expr(stmt.value, fn, mod, coverage,
                                    in_finally)
                continue
            # generic statement: scan its expression fields, recurse
            # into its statement-list fields
            for _field, value in ast.iter_fields(stmt):
                if isinstance(value, ast.expr):
                    self._scan_expr(value, fn, mod, coverage,
                                    in_finally)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.stmt):
                            self._walk([v], fn, mod, coverage,
                                       handler_types, handler_name,
                                       in_finally)
                        elif isinstance(v, ast.expr):
                            self._scan_expr(v, fn, mod, coverage,
                                            in_finally)
                        elif hasattr(ast, "match_case") and \
                                isinstance(v, ast.match_case):
                            self._walk(v.body, fn, mod, coverage,
                                       handler_types, handler_name,
                                       in_finally)

    def _note_binding(self, value: Optional[ast.AST],
                      targets: List[ast.AST], fn: _FnFault) -> None:
        """Remember which name/attr a resource or Thread call binds to
        so the leak rule can look for its close/join later."""
        if not isinstance(value, ast.Call) or len(targets) != 1:
            return
        t = targets[0]
        binding: Optional[str] = None
        if isinstance(t, ast.Name):
            binding = t.id
        elif isinstance(t, ast.Attribute):
            binding = _unparse(t)
        if binding is not None:
            self._pending_binding = (id(value), binding)

    def _raise_types(self, stmt: ast.Raise,
                     handler_types: Tuple[str, ...],
                     handler_name: Optional[str]) -> List[str]:
        if stmt.exc is None:
            # bare ``raise``: re-throws whatever the enclosing handler
            # caught (``*`` from a bare except re-throws anything)
            return [t if t != "*" else "Exception"
                    for t in handler_types] or ["Exception"]
        node = stmt.exc
        if isinstance(node, ast.Call):
            node = node.func
        if isinstance(node, ast.Attribute):
            return [node.attr]
        if isinstance(node, ast.Name):
            if handler_name is not None and node.id == handler_name:
                return [t if t != "*" else "Exception"
                        for t in handler_types]
            if node.id[:1].isupper():
                return [node.id]
            return ["Exception"]  # some variable: type unknown
        return ["Exception"]

    def _scan_expr(self, expr: ast.AST, fn: _FnFault, mod: _ModFault,
                   coverage: Tuple[FrozenSet[str], ...],
                   in_finally: bool) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            qual, name = _call_name(node)
            fn.calls.append((qual, name, node.lineno, coverage))
            if name == "Thread":
                daemon = any(kw.arg == "daemon"
                             for kw in node.keywords)
                binding = None
                pend = getattr(self, "_pending_binding", None)
                if pend is not None and pend[0] == id(node):
                    binding = pend[1]
                fn.thread_news.append((node.lineno, daemon, binding))
                for kw in node.keywords:
                    if kw.arg == "target":
                        ref = self._target_ref(kw.value, fn, mod)
                        if ref is not None:
                            fn.thread_targets.append(ref)
            kind = self._resource_kind(qual, name, mod)
            if kind is not None:
                binding = None
                pend = getattr(self, "_pending_binding", None)
                if pend is not None and pend[0] == id(node):
                    binding = pend[1]
                fn.resources.append((kind, node.lineno, id(node),
                                     binding))
            if name == "acquire" and isinstance(node.func,
                                                ast.Attribute):
                fn.acquires.append((_unparse(node.func.value),
                                    node.lineno))
            if name in _CLOSE_VERBS or name == "release" or \
                    name == "join":
                target = None
                if isinstance(node.func, ast.Attribute):
                    target = _unparse(node.func.value)
                    if isinstance(node.func.value, ast.Call):
                        # ``open(...).close()``: closed on the spot,
                        # no exception path between open and close
                        fn.chained_close_ids.add(id(node.func.value))
                elif node.args:
                    # ``rmtree(path)`` / ``os.remove(path)`` style
                    target = _unparse(node.args[0])
                if target is not None:
                    fn.closes.append((name, target, in_finally))

    def _target_ref(self, expr: ast.AST, fn: _FnFault,
                    mod: _ModFault) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and fn.cls is not None:
            return f"{mod.module}.{fn.cls}.{expr.attr}"
        if isinstance(expr, ast.Name):
            if expr.id in mod.functions:
                return f"{mod.module}.{expr.id}"
            tgt = mod.from_imports.get(expr.id)
            if tgt is not None:
                return f"{tgt[0]}.{tgt[1]}"
        return None

    def _resource_kind(self, qual: Optional[str], name: str,
                       mod: _ModFault) -> Optional[str]:
        if qual is None:
            hit = _RESOURCE_BARE.get(name)
            if hit is not None:
                return hit
            tgt = mod.from_imports.get(name)
            if tgt is not None:
                return _RESOURCE_QUALIFIED.get((tgt[0], tgt[1]))
            return None
        base = mod.alias_modules.get(qual, qual)
        for (m, n), kind in _RESOURCE_QUALIFIED.items():
            if name == n and (base == m or base.startswith(m + ".") or
                              base.endswith("." + m)):
                return kind
        return None

    def _raiser_types(self, qual: Optional[str], name: str,
                      mod: _ModFault) -> Tuple[str, ...]:
        if qual is None:
            hit = _RAISERS_BARE.get(name)
            if hit is not None:
                return hit
            tgt = mod.from_imports.get(name)
            if tgt is not None:
                for (m, n), types in _RAISERS_QUALIFIED.items():
                    if n == tgt[1] and (tgt[0] == m or
                                        tgt[0].startswith(m + ".")):
                        return types
            return ()
        base = mod.alias_modules.get(qual, qual)
        for (m, n), types in _RAISERS_QUALIFIED.items():
            if name == n and (base == m or base.startswith(m + ".") or
                              base.endswith("." + m)):
                return types
        return ()

    # ---------------- hierarchy / coverage ----------------

    def _catches(self, group: FrozenSet[str], exc: str) -> bool:
        """Does any type in a handler group catch ``exc``?  Unknown
        types are assumed direct Exception subclasses."""
        if "*" in group or "BaseException" in group:
            return True
        seen: Set[str] = set()
        cur: Optional[str] = exc
        while cur is not None and cur not in seen:
            if cur in group:
                return True
            seen.add(cur)
            if cur in ("Exception", "BaseException"):
                cur = self.exc_parents.get(cur)
            else:
                cur = self.exc_parents.get(cur, "Exception")
        return False

    def _covered(self, coverage: Tuple[FrozenSet[str], ...],
                 exc: str) -> bool:
        return any(self._catches(g, exc) for g in coverage)

    # ---------------- cross-function resolution ----------------

    def _all_fns(self):
        for mod in self.modules.values():
            yield from mod.functions.values()
            for methods in mod.classes.values():
                yield from methods.values()

    def _build_tables(self) -> None:
        self.by_ref: Dict[str, _FnFault] = {}
        self.by_method: Dict[str, List[_FnFault]] = {}
        for fn in self._all_fns():
            self.by_ref[fn.ref] = fn
            self.by_method.setdefault(fn.name, []).append(fn)

    def _resolve(self, caller: _FnFault, qual: Optional[str],
                 name: str) -> List[_FnFault]:
        """Callees a call may reach — the concurrency pass's
        resolution, verbatim: local def, from-import, self method,
        alias/direct module, then the type-blind class-name
        containment fallback."""
        mod = self.modules[caller.module]
        if qual is None:
            local = self.by_ref.get(f"{caller.module}.{name}")
            if local is not None:
                return [local]
            tgt = mod.from_imports.get(name)
            if tgt is not None:
                hit = self.by_ref.get(f"{tgt[0]}.{tgt[1]}")
                return [hit] if hit is not None else []
            return []
        if qual == "self" and caller.cls is not None:
            own = self.by_ref.get(
                f"{caller.module}.{caller.cls}.{name}")
            if own is not None:
                return [own]
        base = mod.alias_modules.get(qual, qual)
        direct = self.by_ref.get(f"{base}.{name}")
        if direct is not None:
            return [direct]
        ident = qual.rsplit(".", 1)[-1].lstrip("_").lower()
        if not ident:
            return []
        out = []
        for cand in self.by_method.get(name, ()):
            if cand.cls is None:
                continue
            cname = cand.cls.lstrip("_").lower()
            if ident in cname or cname in ident:
                out.append(cand)
        return out

    # ---------------- escape fixpoint ----------------

    def _escape_sets(self) -> Dict[str, Set[str]]:
        """escape(fn) = locally uncaught raises | per-call-site
        (escape(callee) | builtin raisers) minus that site's handler
        coverage, to fixpoint."""
        esc: Dict[str, Set[str]] = {}
        for fn in self._all_fns():
            s: Set[str] = set()
            for exc, _line, cov in fn.raises:
                if not self._covered(cov, exc):
                    s.add(exc)
            esc[fn.ref] = s
        changed = True
        while changed:
            changed = False
            for fn in self._all_fns():
                mod = self.modules[fn.module]
                cur = esc[fn.ref]
                for qual, name, _line, cov in fn.calls:
                    incoming: Set[str] = set(
                        self._raiser_types(qual, name, mod))
                    for callee in self._resolve(fn, qual, name):
                        incoming.update(esc[callee.ref])
                    for exc in incoming:
                        if exc not in cur and \
                                not self._covered(cov, exc):
                            cur.add(exc)
                            changed = True
        return esc

    def _worker_roots(self) -> Set[str]:
        roots: Set[str] = set()
        for fn in self._all_fns():
            roots.update(fn.thread_targets)
        return roots

    # ---------------- rules ----------------

    def run(self) -> List[Diagnostic]:
        self.collect()
        self._build_tables()
        self.esc = self._escape_sets()
        self._rule_escape_thread_root()
        self._rule_unmapped_http()
        self._rule_swallowed()
        self._rule_resource_leak()
        return self.diags

    def _escapes_of(self, fn: _FnFault) -> List[str]:
        return sorted(e for e in self.esc.get(fn.ref, ())
                      if e not in _ESCAPE_IGNORED)

    # -- rule 1: escape-thread-root -----------------------------------

    def _rule_escape_thread_root(self) -> None:
        roots = self._worker_roots()
        for fn in self._all_fns():
            if fn.ref not in roots:
                continue
            for exc in self._escapes_of(fn):
                self.diags.append(Diagnostic(
                    rule="escape-thread-root",
                    message=f"{exc} can escape thread root "
                            f"{fn.qualname}() — Thread.run prints a "
                            "traceback and the thread dies with no "
                            "cordon, no counter, no flight event; "
                            "absorb it into the death machinery "
                            "(except Exception -> die/cordon + "
                            "counter) or pragma with why silent death "
                            "is acceptable",
                    file=fn.filename, line=fn.node.lineno,
                    col=fn.node.col_offset))

    # -- rule 2: unmapped-http-error ----------------------------------

    def _rule_unmapped_http(self) -> None:
        for fn in self._all_fns():
            if fn.cls is None or fn.name not in _HTTP_HANDLER_NAMES:
                continue
            for exc in self._escapes_of(fn):
                self.diags.append(Diagnostic(
                    rule="unmapped-http-error",
                    message=f"{exc} can escape HTTP handler "
                            f"{fn.qualname}() with no status-code "
                            "mapping — the client sees a dropped "
                            "connection or a raw-traceback 500 "
                            "instead of the promised JSON error body; "
                            "add an except arm mapping it to a "
                            "4xx/5xx response or pragma with why it "
                            "cannot fire",
                    file=fn.filename, line=fn.node.lineno,
                    col=fn.node.col_offset))

    # -- rule 3: swallowed-error --------------------------------------

    def _rule_swallowed(self) -> None:
        for fn in self._all_fns():
            for h in fn.handlers:
                if h.has_outlet:
                    continue
                types = ", ".join(h.types)
                self.diags.append(Diagnostic(
                    rule="swallowed-error",
                    message=f"except ({types}) in {fn.qualname}() "
                            "absorbs the error with no outlet: no "
                            "re-raise, no return, no error-value "
                            "assignment, no fcobs counter, no flight "
                            "event — the failure is invisible to "
                            "/metricsz and the flight recorder; stamp "
                            "a counter, record the event, or pragma "
                            "with why silence is correct",
                    file=h.filename, line=h.node.lineno,
                    col=h.node.col_offset))

    # -- rule 4: resource-leak ----------------------------------------

    def _class_lifecycle(self, mod: _ModFault, cls: str
                         ) -> Tuple[Set[str], Set[str]]:
        """(targets closed/joined by any method, targets daemon-set by
        any method) across a class — object-lifetime resources are
        compliant when ANY method ends them."""
        closed: Set[str] = set()
        daemon: Set[str] = set()
        for m in mod.classes.get(cls, {}).values():
            for _verb, target, _fin in m.closes:
                closed.add(target)
            daemon.update(m.daemon_sets)
        return closed, daemon

    def _rule_resource_leak(self) -> None:
        for fn in self._all_fns():
            if fn.is_ctx_helper:
                continue  # @contextmanager: cleanup lives past yield
            mod = self.modules[fn.module]
            cls_closed: Set[str] = set()
            cls_daemon: Set[str] = set()
            if fn.cls is not None:
                cls_closed, cls_daemon = self._class_lifecycle(
                    mod, fn.cls)
            # (a) threads without join-or-daemon
            for line, daemon, binding in fn.thread_news:
                if daemon:
                    continue
                ok = False
                if binding is not None:
                    if binding.startswith("self."):
                        ok = binding in cls_closed or \
                            binding in cls_daemon
                    else:
                        ok = binding in fn.daemon_sets or any(
                            verb == "join" and target == binding
                            for verb, target, _fin in fn.closes)
                if not ok:
                    what = f"bound to {binding}" if binding else \
                        "never bound"
                    self.diags.append(Diagnostic(
                        rule="resource-leak",
                        message="Thread created without daemon= and "
                                f"never joined ({what}): a non-daemon "
                                "thread blocks interpreter exit and "
                                "outlives SIGTERM drain — pass "
                                "daemon=True, join it, or pragma "
                                "with who owns its shutdown",
                        file=fn.filename, line=line))
            # (b) acquire() without release() in a finally
            for recv, line in fn.acquires:
                ok = any(verb == "release" and target == recv and fin
                         for verb, target, fin in fn.closes)
                if not ok:
                    self.diags.append(Diagnostic(
                        rule="resource-leak",
                        message=f"{recv}.acquire() with no "
                                f"{recv}.release() in a finally: an "
                                "exception between acquire and "
                                "release leaves the lock held forever "
                                "— use 'with', add try/finally, or "
                                "pragma with where the release lives",
                        file=fn.filename, line=line))
            # (c) files/sockets/tempdirs opened outside with
            for kind, line, call_id, binding in fn.resources:
                if call_id in fn.with_ctx_ids or \
                        call_id in fn.chained_close_ids or \
                        f"<call:{call_id}>" in fn.returned:
                    continue
                ok = False
                if binding is not None:
                    if binding in fn.returned or \
                            binding in fn.with_names:
                        ok = True  # ownership transferred / with-bound
                    elif binding.startswith("self."):
                        ok = binding in cls_closed
                    else:
                        ok = any(target == binding and fin
                                 for _verb, target, fin in fn.closes)
                if not ok:
                    what = f"bound to {binding}" if binding else \
                        "never bound"
                    self.diags.append(Diagnostic(
                        rule="resource-leak",
                        message=f"{kind} opened outside 'with' "
                                f"({what}) and not closed in a "
                                "finally: an exception on the path "
                                "between open and close leaks the "
                                f"{kind} — use 'with', add "
                                "try/finally, or pragma with who "
                                "closes it",
                        file=fn.filename, line=line))

    # ---------------- injection-site inventory ----------------

    def build_inventory(self, module_prefix: str =
                        "fastconsensus_tpu.serve") -> dict:
        """The committed injection-site inventory (runs/faults_r19.
        json): every raise site in ``serve/`` (explicit raise or
        curated builtin raiser) + the boundary this pass claims
        absorbs it.  ``injectable`` marks sites serve/faultinject.py
        can model faithfully: the exception leaves the raising
        function and every absorber is a real caller-side handler
        (entry injection raises before the function's own try blocks
        run, so self-absorbed sites cannot be exercised that way)."""
        if not self.esc:
            self.run()
        roots = self._worker_roots()
        # reverse call table: callee ref -> [(caller, site coverage)]
        rev: Dict[str, List[Tuple[_FnFault,
                                  Tuple[FrozenSet[str], ...]]]] = {}
        for fn in self._all_fns():
            for qual, name, _line, cov in fn.calls:
                for callee in self._resolve(fn, qual, name):
                    rev.setdefault(callee.ref, []).append((fn, cov))
        rows: Dict[Tuple[str, str], dict] = {}
        for fn in self._all_fns():
            if not fn.module.startswith(module_prefix) or \
                    fn.module.endswith(".faultinject"):
                continue
            mod = self.modules[fn.module]
            sites: List[Tuple[str, int, Tuple[FrozenSet[str], ...],
                              str]] = []
            for exc, line, cov in fn.raises:
                sites.append((exc, line, cov, "raise"))
            for qual, name, line, cov in fn.calls:
                for exc in self._raiser_types(qual, name, mod):
                    sites.append((exc, line, cov, "builtin-call"))
            for exc, line, cov, kind in sites:
                if exc in _ESCAPE_IGNORED or exc == "Exception":
                    continue
                key = (fn.ref, exc)
                row = rows.get(key)
                if row is None:
                    boundary, injectable = self._boundary(
                        fn, exc, cov, rev, roots)
                    row = {
                        "site_id": f"{fn.module}:{fn.qualname}:{exc}",
                        "file": fn.filename,
                        "function": fn.qualname,
                        "exception": exc,
                        "kind": kind,
                        "lines": [],
                        "boundary": boundary,
                        "injectable": injectable,
                    }
                    rows[key] = row
                if line not in row["lines"]:
                    row["lines"].append(line)
                if kind == "raise":
                    row["kind"] = "raise"
        for row in rows.values():
            row["lines"].sort()
        return {
            "tool": "fcheck-fault",
            "version": 1,
            "module_prefix": module_prefix,
            "sites": sorted(rows.values(),
                            key=lambda r: r["site_id"]),
        }

    def _boundary(self, fn: _FnFault, exc: str,
                  cov: Tuple[FrozenSet[str], ...],
                  rev: Dict[str, List[Tuple[_FnFault,
                                            Tuple[FrozenSet[str],
                                                  ...]]]],
                  roots: Set[str]) -> Tuple[List[str], bool]:
        """Who absorbs ``exc`` raised at a site in ``fn`` — BFS up the
        reverse call table from the raising function, stopping at the
        first covering handler per path; sentinels mark paths nobody
        absorbs ('<thread-root:ref>' / '<external>')."""
        if self._covered(cov, exc):
            return [fn.ref], False
        absorbers: Set[str] = set()
        visited: Set[str] = {fn.ref}
        frontier: List[str] = [fn.ref]
        while frontier:
            nxt: List[str] = []
            for ref in frontier:
                callers = rev.get(ref, [])
                if not callers:
                    if ref in roots:
                        absorbers.add(f"<thread-root:{ref}>")
                    else:
                        absorbers.add(EXTERNAL_BOUNDARY)
                    continue
                escaped_any = False
                for caller, site_cov in callers:
                    if self._covered(site_cov, exc):
                        absorbers.add(caller.ref)
                    elif caller.ref not in visited:
                        visited.add(caller.ref)
                        nxt.append(caller.ref)
                        escaped_any = True
                if ref in roots and escaped_any:
                    # an uncaught path ends at this thread root even
                    # though other callers absorb it
                    absorbers.add(f"<thread-root:{ref}>")
            frontier = nxt
        boundary = sorted(absorbers)
        injectable = bool(boundary) and \
            all(not b.startswith("<") for b in boundary)
        return boundary, injectable


def check_faults(sources: Dict[str, str]
                 ) -> Tuple[List[Diagnostic], int]:
    """Run the whole-program fault pass over ``{filename: source}``;
    returns (diagnostics, n_suppressed), pragmas already applied per
    file."""
    analyzer = FaultAnalyzer(sources)
    raw = analyzer.run()
    by_file: Dict[str, List[Diagnostic]] = {}
    for d in raw:
        by_file.setdefault(d.file, []).append(d)
    kept: List[Diagnostic] = []
    suppressed = 0
    for filename, diags in by_file.items():
        k, s = apply_pragmas(diags, sources.get(filename, ""))
        kept.extend(k)
        suppressed += s
    return kept, suppressed


def build_fault_inventory(sources: Dict[str, str]) -> dict:
    """The injection-site inventory over a source set (see
    FaultAnalyzer.build_inventory)."""
    analyzer = FaultAnalyzer(sources)
    analyzer.run()
    return analyzer.build_inventory()


def fault_inventory_from_paths(paths: List[str]) -> dict:
    """Load every ``.py`` under ``paths`` the way lint_paths does and
    build the injection-site inventory — the ``--emit-fault-inventory``
    entry point (scripts/ci_check.sh regenerates and diffs the
    committed runs/faults_r19.json through it)."""
    import os

    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", "build"))
                files.extend(os.path.join(root, f)
                             for f in sorted(names)
                             if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    sources: Dict[str, str] = {}
    for f in files:
        with open(f, encoding="utf-8") as fh:
            sources[f] = fh.read()
    return build_fault_inventory(sources)
