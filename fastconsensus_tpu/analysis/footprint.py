"""fcheck-footprint: static device-memory & executable-surface model of
the serving stack.

The serving layer compiles one executable per (entry kind x bucket rung
x batch rung x engine mode) and, until now, nothing modeled what any of
those executables *costs* before it ran: an over-budget ``--warm`` spec
or a new ladder rung was discovered as a runtime OOM on first traffic,
and a static-arg axis quietly multiplying the executable surface was
discovered as a compile-count regression after the fact.  This module is
the compile-time answer — the HBM-budget / compile-surface lint of a
training stack, specialized to the bucketed serving ladder:

1. **Liveness sweep** (:func:`peak_live_bytes`): an abstract interpreter
   over a traced jaxpr that computes peak live device bytes — program
   arguments (donation-aware: a donated invar dies at its last use, a
   non-donated one is pinned for the whole execution, which is XLA's
   buffer contract), outputs, and the high-water set of temporaries,
   recursing through pjit/while/cond/scan sub-jaxprs.  Exact for what
   the jaxpr says; deliberately blind to XLA fusion (fusion only ever
   *lowers* the true peak, so the model is a conservative ceiling).
2. **Surface enumeration** (:func:`surface_count`, jax-free): every
   executable a serving posture implies — the ``{2^k, 3*2^k}`` bucket
   ladder (serve/bucketer.py) x the batch ladder {1, 2, 4, 8} x the
   engine's static modes (warm/cold/scratch batch blocks, warm/scratch
   solo blocks, tail, final detect).  The static complement of the
   runtime ``CompileGuard``: a new static-arg axis multiplies this count
   at review time, not after a week of recompiles in production.
3. **The serving feedback** (:func:`derive_chip_ceiling`): the largest
   ladder bucket whose worst-case executable set fits a per-chip byte
   budget — what ``serve --chip-max-edges auto`` routes on, and what
   every ``--warm`` spec is validated against at server start.

Three fcheck rules ride on the model (all exposed via ``--only``):

* ``jaxpr-peak-bytes``  — some surface executable's modeled peak
  exceeds the per-chip budget (``--hbm-bytes``; the default is the
  CI-pinned synthetic budget below).  The peak is NOT globally monotone
  in bucket size: the detectors self-limit per-sweep temporaries with a
  per-graph ensemble-chunk budget (models/base.py) whose estimate
  tightens as buckets grow, so the worst executable sits at an
  *interior* bucket (and the batch path multiplies that per-graph
  budget by every batch lane — a fact this model surfaced).  The gate
  therefore SCANS the edge ladder at the two worst-case node rows — the
  densest-connected posture ``n = 2e`` and the isolated-node-padded
  posture ``n = max_nodes`` — with the dominant executable kind, then
  prices every kind at the scan winners and the matmul-path frontier.
  Within one detection-path regime at fixed chunking the peak IS
  monotone along the ladder (pinned by tests/test_footprint.py).
* ``surface-count``     — the enumerated executable count exceeds a
  pinned budget (``--surface-budget``).
* ``padding-waste``     — some bucket's padding exceeds a configured
  fraction of its worst-case member's payload (``--pad-waste-frac``):
  the ladder's geometry bounds waste below ~50%, and this rule is the
  tripwire for a ladder edit that silently breaks that bound.

**Fixture mode**: a scanned source file may define a module-level
``FOOTPRINT_SPEC = {...}`` literal (see :meth:`SurfaceSpec.from_mapping`
for the keys); the analyzer then evaluates the rules against *that*
posture instead of the repo default — this is how the bad_/ok_ fixtures
in tests/analysis_fixtures/ exercise each rule in isolation.

**Report / artifact schema** (the ``footprint`` block of the ``--json``
report, and the committed ``runs/footprint_rNN.json`` artifact rendered
and gated by ``scripts/bench_report.py``)::

    {
      "tool": "fcheck-footprint", "version": 1,
      "config":  {max_nodes, max_edges, max_batch, n_p, algorithm,
                  hbm_bytes, surface_budget, pad_waste_frac},
      "surface_count":      <int>,   # enumerated executables
      "surface_budget":     <int>,
      "chip_ceiling_edges": <int|null>,  # derive_chip_ceiling(hbm)
      "max_pad_frac":       <float>, # worst non-floor bucket
      "gate": [ {kind, bucket, batch, mode, peak_bytes} ... ],
      "buckets": [                    # the footprint table (e-spine)
        {bucket, n_class, e_class, capacity, batch,
         peak_bytes,        # batched block, max rung, worst mode
         solo_peak_bytes,   # solo rounds block (warm)
         arg_bytes, out_bytes, pad_frac} ... ]
    }

The jax-free half (enumeration, padding) mirrors ``sizing.grid_up`` /
``serve.bucketer`` constants locally so the pre-commit hook and the
``--only surface-count,padding-waste`` path never import jax; the
mirrors are pinned against the real functions by tests/test_footprint.py.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, \
    Tuple

from fastconsensus_tpu.analysis.diagnostics import Diagnostic

# --------------------------------------------------------------------
# CI-pinned budgets.
# --------------------------------------------------------------------

# Synthetic per-chip byte budget for the CPU CI gate.  The default
# serving surface's worst executable — the B=8 batched final detect at
# bucket n1048576_e262144, where the detector's per-graph
# ensemble-chunk budget (models/base.py, ~2 GiB of sweep temporaries)
# is multiplied by every one of the 8 batch lanes — models at
# ~21.7 GiB, so the repo passes with ~10% headroom; growing the peak
# past the budget (a new resident temporary, a looser chunk estimate)
# fails the gate.  Real deployments pass their chip's actual budget via
# --hbm-bytes and route what doesn't fit with --chip-max-edges auto.
CHIP_HBM_BYTES_DEFAULT = 24 << 30

# Enumerated-executable budget.  The default posture models 13,280
# executables (830 reachable buckets x 16 kinds); the pin leaves ~23%
# headroom for ladder growth while any new *static axis* (which
# multiplies the count) blows it at review time.
SURFACE_BUDGET_DEFAULT = 16384

# Worst-case padding fraction per bucket.  The {2^k, 3*2^k} grid bounds
# consecutive classes at a 3/2 ratio, so the worst member of any
# non-floor bucket pads < 50% of its payload; 0.55 passes that geometry
# and fails any ladder edit that opens a wider gap.
PAD_WASTE_FRAC_DEFAULT = 0.55

FOOTPRINT_RULES = ("jaxpr-peak-bytes", "surface-count", "padding-waste")

# --------------------------------------------------------------------
# jax-free mirrors of the ladder geometry (pinned by test_footprint.py
# against sizing.grid_up / serve.bucketer / graph.derive_agg_sizing —
# importing the real ones would pull jax into the pre-commit hook).
# --------------------------------------------------------------------

MIN_NODE_CLASS = 64          # serve.bucketer.MIN_NODE_CLASS
MIN_EDGE_CLASS = 64          # serve.bucketer.MIN_EDGE_CLASS
BATCH_RUNGS = (1, 2, 4, 8)   # serve.bucketer.BATCH_LADDER
MATMUL_MAX_N = 1024          # models.louvain.MATMUL_MAX_N (path flip)

# Engine executable kinds per bucket (mirrors the engine's lru-cached
# jit wrappers a served bucket compiles through): the solo set — the
# fused rounds block in its warm and scratch static variants
# (engine._jitted_rounds_block), the consensus tail (_jitted_tail) and
# the final whole-ensemble detect (_jitted_detect) — plus, per batch
# rung > 1, the three static batch-block modes (_jitted_rounds_batch:
# a vmapped lax.cond would run BOTH detector branches, so mode is a
# static) and the batched final detect (_jitted_detect_batch).
SOLO_KINDS = ("rounds[warm]", "rounds[scratch]", "tail", "detect")
BATCH_MODES = ("warm", "cold", "scratch")
KINDS_PER_RUNG = len(BATCH_MODES) + 1   # + the batched final detect


def grid_up(n: int, minimum: int = 1) -> int:
    """Smallest {2^k, 3*2^k} value >= n (mirror of sizing.grid_up)."""
    n = max(int(n), int(minimum), 1)
    p = 1
    while p < n:
        p *= 2
    q = (3 * p) // 4
    return q if p >= 4 and q >= n else p


def grid_values(lo: int, hi: int) -> List[int]:
    """Every grid class in [grid_up(lo), grid_up(hi)], ascending."""
    lo, hi = grid_up(lo), grid_up(hi)
    out, v = [], lo
    while v <= hi:
        out.append(v)
        v = grid_up(v + 1)
    return out


def prev_class(c: int, minimum: int) -> Optional[int]:
    """The grid class directly below ``c``, or None at the floor.

    Closed form — a 2^k class sits above 3*2^(k-2) (= 3c/4) and a
    3*2^k class above 2^(k+1) (= 2c/3); small classes (< 4) step by 1.
    """
    if c <= minimum:
        return None
    if c < 4:
        prev = c - 1
    elif c & (c - 1) == 0:               # power of two
        prev = (3 * c) // 4
    else:                                # 3 * 2^k
        prev = (2 * c) // 3
    return max(prev, minimum)


def bucket_capacity(e_class: int) -> int:
    """serve.bucketer.Bucket.capacity: pack_edges' default headroom."""
    return 2 * e_class + 16


def bucket_agg_cap(e_class: int) -> int:
    """serve.bucketer.Bucket.agg_cap = graph.derive_agg_sizing(cap)."""
    cap = bucket_capacity(e_class)
    want = cap + cap // 8 + 1024
    return ((want + 4095) // 4096) * 4096


def bucket_bytes(n_class: int, e_class: int) -> int:
    """Request-resident slab-state bytes for one bucket: 13 B per edge
    slot (src/dst/weight int32+int32+f32 + alive bool) plus 8 B per node
    (the per-node int32 working pair every reduction carries).  A proxy
    for *payload scale*, used only by the padding rule — the executable
    peak model measures real jaxprs, not this."""
    return 13 * bucket_capacity(e_class) + 8 * n_class


# --------------------------------------------------------------------
# Surface posture
# --------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SurfaceSpec:
    """One serving posture: what the analyzer enumerates and budgets.

    Defaults mirror ``serve.server.ServeConfig`` admission bounds and
    batch ladder (pinned by test_footprint.py) and the engine's default
    ensemble width.
    """

    max_nodes: int = 1 << 20
    max_edges: int = 1 << 22
    max_batch: int = 8
    n_p: int = 20                      # ConsensusConfig default
    algorithm: str = "louvain"
    hbm_bytes: int = CHIP_HBM_BYTES_DEFAULT
    surface_budget: int = SURFACE_BUDGET_DEFAULT
    pad_waste_frac: float = PAD_WASTE_FRAC_DEFAULT
    # Explicit edge-ladder override for the padding rule (fixture mode:
    # a broken ladder must be expressible without editing bucketer).
    grid: Optional[Tuple[int, ...]] = None
    # Restrict evaluation to these rules (fixture mode; None = all).
    rules: Optional[Tuple[str, ...]] = None
    origin: str = "<defaults>"         # file the spec came from
    origin_line: int = 0

    _KEYS = ("max_nodes", "max_edges", "max_batch", "n_p", "algorithm",
             "hbm_bytes", "surface_budget", "pad_waste_frac", "grid",
             "rules")

    @classmethod
    def from_mapping(cls, d: Dict, origin: str = "<spec>",
                     origin_line: int = 0) -> "SurfaceSpec":
        unknown = set(d) - set(cls._KEYS)
        if unknown:
            raise ValueError(
                f"{origin}: unknown FOOTPRINT_SPEC key(s) "
                f"{sorted(unknown)}; known: {list(cls._KEYS)}")
        kw = dict(d)
        for k in ("grid", "rules"):
            if kw.get(k) is not None:
                kw[k] = tuple(kw[k])
        if kw.get("rules"):
            bad = set(kw["rules"]) - set(FOOTPRINT_RULES)
            if bad:
                raise ValueError(
                    f"{origin}: FOOTPRINT_SPEC rules {sorted(bad)} are "
                    f"not footprint rules {list(FOOTPRINT_RULES)}")
        return cls(origin=origin, origin_line=origin_line, **kw)

    def wants(self, rule: str) -> bool:
        return self.rules is None or rule in self.rules


def find_specs(paths: Iterable[str]) -> List[SurfaceSpec]:
    """Module-level ``FOOTPRINT_SPEC = {...}`` literals in the scanned
    sources (fixture mode).  Non-literal or unknown-key specs raise
    ValueError — a typo'd fixture must not silently evaluate defaults.
    """
    import ast
    import os

    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", "build"))
                files.extend(os.path.join(root, f) for f in sorted(names)
                             if f.endswith(".py"))
        elif p.endswith(".py"):
            files.append(p)
    specs: List[SurfaceSpec] = []
    for f in files:
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=f)
        # fcheck: ok=swallowed-error (unreadable/unparsable
        # files are astlint's finding; the spec scan skips them)
        except (OSError, SyntaxError):
            continue
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "FOOTPRINT_SPEC"
                    for t in node.targets):
                d = ast.literal_eval(node.value)   # ValueError on junk
                if not isinstance(d, dict):
                    raise ValueError(
                        f"{f}:{node.lineno}: FOOTPRINT_SPEC must be a "
                        f"dict literal")
                specs.append(SurfaceSpec.from_mapping(
                    d, origin=f, origin_line=node.lineno))
    return specs


# --------------------------------------------------------------------
# Surface enumeration (jax-free)
# --------------------------------------------------------------------


def node_classes(spec: SurfaceSpec) -> List[int]:
    return grid_values(MIN_NODE_CLASS, spec.max_nodes)


def edge_classes(spec: SurfaceSpec) -> List[int]:
    return grid_values(MIN_EDGE_CLASS, spec.max_edges)


def min_member(c: int, minimum: int) -> int:
    """Smallest raw value that lands in class ``c`` (floor classes
    serve everything from 1 up)."""
    prev = prev_class(c, minimum)
    return 1 if prev is None else prev + 1


def reachable(n_class: int, e_class: int, spec: SurfaceSpec) -> bool:
    """Whether some admissible graph lands in bucket (n_class, e_class):
    there must exist n <= max_nodes with grid_up(n) == n_class and
    e <= min(max_edges, n*(n-1)/2) with grid_up(e) == e_class (a simple
    graph cannot carry more than the complete graph's edges)."""
    n_hi = min(n_class, spec.max_nodes)
    if grid_up(n_hi, MIN_NODE_CLASS) != n_class:
        return False
    e_lo = min_member(e_class, MIN_EDGE_CLASS)
    return e_lo <= min(spec.max_edges, n_hi * (n_hi - 1) // 2)


def surface_buckets(spec: SurfaceSpec) -> List[Tuple[int, int]]:
    return [(n, e) for n in node_classes(spec) for e in edge_classes(spec)
            if reachable(n, e, spec)]


def batch_rungs(max_batch: int) -> List[int]:
    return [b for b in BATCH_RUNGS if b <= max(int(max_batch), 1)]


def executables_per_bucket(spec: SurfaceSpec) -> int:
    """Distinct executables one served bucket implies (see SOLO_KINDS /
    BATCH_MODES): the solo set plus KINDS_PER_RUNG per batch rung > 1."""
    n_rungs = len([b for b in batch_rungs(spec.max_batch) if b > 1])
    return len(SOLO_KINDS) + KINDS_PER_RUNG * n_rungs


def surface_count(spec: SurfaceSpec) -> int:
    return len(surface_buckets(spec)) * executables_per_bucket(spec)


def check_surface(spec: SurfaceSpec) -> List[Diagnostic]:
    count = surface_count(spec)
    if count <= spec.surface_budget:
        return []
    n_buckets = len(surface_buckets(spec))
    return [Diagnostic(
        rule="surface-count", file=spec.origin, line=spec.origin_line,
        message=f"the serving posture (max_nodes={spec.max_nodes}, "
                f"max_edges={spec.max_edges}, max_batch={spec.max_batch})"
                f" implies {count} compiled executables ({n_buckets} "
                f"reachable buckets x {executables_per_bucket(spec)} "
                f"kinds) > budget {spec.surface_budget}: a static-arg "
                f"axis or ladder change exploded the compile surface "
                f"(the static complement of CompileGuard)")]


# --------------------------------------------------------------------
# Padding waste (jax-free)
# --------------------------------------------------------------------


def pad_fraction(n_class: int, e_class: int) -> Optional[float]:
    """Worst-case pad bytes / payload bytes for one bucket: the member
    with the fewest nodes AND edges that still lands here.  None for
    floor buckets — the MIN_*_CLASS floors deliberately trade unbounded
    small-graph padding for a single shared tiny-graph bucket."""
    if n_class <= MIN_NODE_CLASS or e_class <= MIN_EDGE_CLASS:
        return None
    n_min = min_member(n_class, MIN_NODE_CLASS)
    e_min = min_member(e_class, MIN_EDGE_CLASS)
    payload = bucket_bytes(n_min, e_min)
    return (bucket_bytes(n_class, e_class) - payload) / payload


def _grid_pad_fractions(grid: Sequence[int]) -> List[Tuple[int, float]]:
    """(class, worst pad fraction) per non-floor class of an explicit
    1-D ladder (the fixture-mode ``grid`` override): waste measured on
    edge-slot bytes between consecutive classes."""
    out = []
    for prev, cur in zip(grid, grid[1:]):
        payload = bucket_bytes(MIN_NODE_CLASS, prev + 1)
        waste = (bucket_bytes(MIN_NODE_CLASS, cur) - payload) / payload
        out.append((cur, waste))
    return out


def max_pad_fraction(spec: SurfaceSpec) -> float:
    if spec.grid is not None:
        fracs = [w for _, w in _grid_pad_fractions(spec.grid)]
    else:
        fracs = [f for n, e in surface_buckets(spec)
                 if (f := pad_fraction(n, e)) is not None]
    return max(fracs, default=0.0)


def check_padding(spec: SurfaceSpec) -> List[Diagnostic]:
    diags = []
    if spec.grid is not None:
        worst = [(f"e{c}", w) for c, w in _grid_pad_fractions(spec.grid)
                 if w > spec.pad_waste_frac]
    else:
        worst = [(f"n{n}_e{e}", f) for n, e in surface_buckets(spec)
                 if (f := pad_fraction(n, e)) is not None
                 and f > spec.pad_waste_frac]
    for key, frac in worst[:8]:    # cap the flood; one is already fatal
        diags.append(Diagnostic(
            rule="padding-waste", file=spec.origin, line=spec.origin_line,
            message=f"bucket {key}: worst-case member pads "
                    f"{frac:.0%} of its payload "
                    f"(> {spec.pad_waste_frac:.0%}): the ladder's "
                    f"class spacing broke the {{2^k, 3*2^k}} waste "
                    f"bound (~50%)"))
    return diags


# --------------------------------------------------------------------
# Liveness sweep (needs a traced jaxpr; jax itself only for tracing)
# --------------------------------------------------------------------


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    dt = getattr(aval, "dtype", None)
    try:
        import numpy as np

        item = np.dtype(dt).itemsize
    except TypeError:
        # extended dtypes (typed PRNG keys): key<fry> = 2 x uint32
        item = getattr(dt, "itemsize", 8)
    n = 1
    for d in shape:
        n *= int(d)
    return n * int(item)


def _sub_jaxprs(eqn) -> Iterable:
    for v in eqn.params.values():
        if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
            yield v
        elif isinstance(v, (tuple, list)):
            for el in v:
                if hasattr(el, "eqns") or hasattr(el, "jaxpr"):
                    yield el


def peak_live_bytes(jaxpr, donated: FrozenSet[int] = frozenset()
                    ) -> Dict[str, int]:
    """Liveness sweep over a (Closed)Jaxpr: ``{"peak", "arg_bytes",
    "out_bytes"}`` in bytes.

    The model: a non-donated input buffer is live for the whole
    execution (XLA preserves it); a donated one dies at its last use;
    every other value is born at its defining equation and dies after
    its last use; program outputs live to the end.  A primitive
    equation's execution moment holds its live set plus its outputs
    being materialized; a call/control-flow equation (pjit, while, cond,
    scan) holds the live set *minus its operands* plus the recursive
    peak of its worst sub-jaxpr (operands alias the callee's inputs —
    counted once, inside).  Fusion can only shrink this, so the result
    is a conservative ceiling on the executable's live HBM.
    """
    from jax.core import Var

    inner = getattr(jaxpr, "jaxpr", jaxpr)
    eqns = inner.eqns
    end = len(eqns)
    last_use: Dict = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if isinstance(v, Var):
                last_use[v] = i
    for v in inner.outvars:
        if isinstance(v, Var):
            last_use[v] = end
    for i, v in enumerate(inner.invars):
        if i not in donated:
            last_use[v] = end
    for v in inner.constvars:
        last_use[v] = end

    live: Dict = {}
    for v in list(inner.invars) + list(inner.constvars):
        live[v] = _aval_bytes(v.aval)
    cur = sum(live.values())
    peak = cur
    for i, eqn in enumerate(eqns):
        subs = list(_sub_jaxprs(eqn))
        out_bytes = sum(_aval_bytes(v.aval) for v in eqn.outvars)
        if subs:
            operands = {v for v in eqn.invars if isinstance(v, Var)}
            op_bytes = sum(live.get(v, 0) for v in operands)
            # callee inputs may all be reused inside (XLA aliases the
            # call frame), so they die at their inner last use
            inner_peak = max(
                peak_live_bytes(
                    s, donated=frozenset(
                        range(len(getattr(s, "jaxpr", s).invars))))["peak"]
                for s in subs)
            exec_bytes = cur - op_bytes + \
                max(inner_peak, op_bytes + out_bytes)
        else:
            exec_bytes = cur + out_bytes
        peak = max(peak, exec_bytes)
        for v in eqn.outvars:
            b = _aval_bytes(v.aval)
            live[v] = b
            cur += b
        for v in [u for u in live if last_use.get(u, -1) <= i]:
            cur -= live.pop(v)
    return {"peak": peak,
            "arg_bytes": sum(_aval_bytes(v.aval) for v in inner.invars),
            "out_bytes": sum(_aval_bytes(v.aval) for v in inner.outvars)}


# --------------------------------------------------------------------
# The traced surface model
# --------------------------------------------------------------------


def _trace_peak(kind: str, n_class: int, e_class: int, b: int, mode: str,
                spec: SurfaceSpec) -> Dict[str, int]:
    """Trace one surface executable (analysis/entrypoints.py owns the
    operand construction) and sweep it.  Memoized per process — the
    ceiling search and the gate revisit buckets."""
    return _trace_peak_cached(kind, n_class, e_class, b, mode,
                              spec.n_p, spec.algorithm)


def _trace_peak_cached(kind, n_class, e_class, b, mode, n_p, algorithm):
    import logging

    key = (kind, n_class, e_class, b, mode, n_p, algorithm)
    try:
        return _TRACE_CACHE[key]
    # fcheck: ok=swallowed-error (cache miss, not an error:
    # the trace below fills the entry)
    except KeyError:
        pass
    from fastconsensus_tpu.analysis import entrypoints as eps

    logger = logging.getLogger("fastconsensus_tpu")
    level = logger.level
    logger.setLevel(logging.ERROR)   # hash-cap warnings are expected at
    try:                             # frontier shapes; keep CI logs clean
        closed = eps.trace_serving_executable(
            kind, n_class, e_class, b=b, mode=mode, n_p=n_p,
            algorithm=algorithm)
    finally:
        logger.setLevel(level)
    res = peak_live_bytes(closed)
    _TRACE_CACHE[key] = res
    return res


_TRACE_CACHE: Dict[Tuple, Dict[str, int]] = {}


def _max_reachable_e(n_class: int, spec: SurfaceSpec) -> Optional[int]:
    cands = [e for e in edge_classes(spec) if reachable(n_class, e, spec)]
    return max(cands, default=None)


def _rep_kinds(spec: SurfaceSpec) -> List[Tuple[str, int, str]]:
    """The executable families the gate scans with: the warm batch
    block AND the batched final detect at the top rung — the committed
    r08 artifact shows detect-batch is the worst kind at the binding
    bucket, so a block-only scan would let an over-budget detect-batch
    at a non-winner bucket escape.  (The block's cold/scratch siblings
    model within a percent of warm — the scan winners get every kind
    priced exactly.)  Solo equivalents when batching is off."""
    top = batch_rungs(spec.max_batch)[-1]
    if top > 1:
        return [("batch", top, "warm"), ("detect-batch", top, "-")]
    return [("rounds", 1, "warm"), ("detect", 1, "-")]


def _worst_n_rows(e_class: int, spec: SurfaceSpec) -> List[int]:
    """The node classes the gate prices per edge class: the
    densest-connected posture (n = 2e — every edge touches two nodes)
    and the isolated-node-padded posture (n = max_nodes; admissible at
    ANY edge count, and the detector hash tables scale with n).
    Interior node classes can locally exceed both when the detector's
    chunk estimate steps, but by at most one chunk-budget quantum —
    documented model tolerance."""
    rows = {grid_up(min(2 * e_class, spec.max_nodes), MIN_NODE_CLASS),
            grid_up(spec.max_nodes, MIN_NODE_CLASS)}
    return sorted(n for n in rows if reachable(n, e_class, spec))


def scan_rows(spec: SurfaceSpec,
              stop_over_budget: int = 0) -> List[Dict]:
    """Representative-kind peaks per (edge class x worst node row),
    ascending in edge class.  ``stop_over_budget`` > 0 stops the scan
    after that many over-budget rows (the gate only needs existence; a
    deliberately tiny CI budget must fail fast, not trace the ladder)."""
    rows: List[Dict] = []
    over = 0
    for e_class in edge_classes(spec):
        for n_class in _worst_n_rows(e_class, spec):
            for kind, b, mode in _rep_kinds(spec):
                res = _trace_peak(kind, n_class, e_class, b, mode, spec)
                rows.append({"kind": kind,
                             "bucket": f"n{n_class}_e{e_class}",
                             "n_class": n_class, "e_class": e_class,
                             "batch": b, "mode": mode,
                             "peak_bytes": res["peak"],
                             "arg_bytes": res["arg_bytes"],
                             "out_bytes": res["out_bytes"]})
                if res["peak"] > spec.hbm_bytes:
                    over += 1
                    if stop_over_budget and over >= stop_over_budget:
                        return rows
    return rows


def _all_kind_rows(n_class: int, e_class: int, spec: SurfaceSpec
                   ) -> List[Dict]:
    """Every executable kind this bucket compiles, priced exactly."""
    rows: List[Dict] = []
    top_rung = batch_rungs(spec.max_batch)[-1]
    for kind, b, mode in (
            [("rounds", 1, "warm"), ("rounds", 1, "scratch"),
             ("tail", 1, "-"), ("detect", 1, "-")] +
            [("batch", top_rung, m) for m in BATCH_MODES
             if top_rung > 1] +
            ([("detect-batch", top_rung, "-")] if top_rung > 1 else [])):
        res = _trace_peak(kind, n_class, e_class, b, mode, spec)
        rows.append({"kind": kind, "bucket": f"n{n_class}_e{e_class}",
                     "n_class": n_class, "e_class": e_class,
                     "batch": b, "mode": mode,
                     "peak_bytes": res["peak"],
                     "arg_bytes": res["arg_bytes"],
                     "out_bytes": res["out_bytes"]})
    return rows


def _matmul_frontier(spec: SurfaceSpec) -> Optional[Tuple[int, int]]:
    """Largest matmul-path bucket (the lowering flips at MATMUL_MAX_N
    nodes, so this regime needs its own probe)."""
    ns = [n for n in node_classes(spec) if n <= MATMUL_MAX_N]
    if not ns:
        return None
    e = _max_reachable_e(max(ns), spec)
    return None if e is None else (max(ns), e)


def check_peak_bytes(spec: SurfaceSpec
                     ) -> Tuple[List[Diagnostic], List[Dict]]:
    """The jaxpr-peak-bytes gate: scan the ladder's worst node rows
    with the dominant kind, then price every kind at the scan winners
    and the matmul frontier."""
    MAX_FINDINGS = 6
    scanned = scan_rows(spec, stop_over_budget=4)
    winners = sorted(scanned, key=lambda r: -r["peak_bytes"])[:2]
    gate_rows: List[Dict] = list(scanned)
    seen: set = set()
    full_at = [(r["n_class"], r["e_class"]) for r in winners]
    mm = _matmul_frontier(spec)
    if mm is not None and mm not in full_at:
        full_at.append(mm)
    for n_class, e_class in full_at:
        if (n_class, e_class) in seen:
            continue
        seen.add((n_class, e_class))
        for row in _all_kind_rows(n_class, e_class, spec):
            if not any(r["bucket"] == row["bucket"]
                       and r["kind"] == row["kind"]
                       and r["mode"] == row["mode"] for r in gate_rows):
                gate_rows.append(row)
    diags: List[Diagnostic] = []
    for r in gate_rows:
        if r["peak_bytes"] > spec.hbm_bytes and len(diags) < MAX_FINDINGS:
            diags.append(Diagnostic(
                rule="jaxpr-peak-bytes", file=spec.origin,
                line=spec.origin_line,
                message=f"surface executable {r['kind']} at bucket "
                        f"{r['bucket']} (B={r['batch']}, "
                        f"mode={r['mode']}) models a peak of "
                        f"{r['peak_bytes']:,} live device bytes > "
                        f"the per-chip budget {spec.hbm_bytes:,} "
                        f"(--hbm-bytes): it OOMs on first traffic "
                        f"unless kept off-chip (--chip-max-edges / "
                        f"--max-nodes admission)"))
    return diags, gate_rows


def footprint_table(spec: SurfaceSpec,
                    max_rows: int = 12) -> List[Dict]:
    """The per-bucket footprint table (the report/artifact ``buckets``
    block): the e-spine sampled at power-of-two classes (plus the ladder
    floor and top), each bucket at its worst-case node class, modeling
    the batched block at the top rung plus the solo rounds block."""
    es = edge_classes(spec)
    spine = [e for e in es if e & (e - 1) == 0]   # powers of two
    for must in (es[0], es[-1]):
        if must not in spine:
            spine.append(must)
    spine = sorted(set(spine))
    if len(spine) > max_rows:                     # thin evenly, keep ends
        idx = {0, len(spine) - 1}
        step = (len(spine) - 1) / (max_rows - 1)
        idx |= {round(i * step) for i in range(max_rows)}
        spine = [spine[i] for i in sorted(idx)]
    rows: List[Dict] = []
    top_rung = batch_rungs(spec.max_batch)[-1]
    for e_class in spine:
        n_class = grid_up(min(2 * e_class, spec.max_nodes),
                          MIN_NODE_CLASS)
        if not reachable(n_class, e_class, spec):
            continue
        batch = _trace_peak("batch" if top_rung > 1 else "rounds",
                            n_class, e_class, top_rung,
                            "warm", spec)
        solo = _trace_peak("rounds", n_class, e_class, 1, "warm", spec)
        pad = pad_fraction(n_class, e_class)
        rows.append({
            "bucket": f"n{n_class}_e{e_class}",
            "n_class": n_class, "e_class": e_class,
            "capacity": bucket_capacity(e_class), "batch": top_rung,
            "peak_bytes": batch["peak"],
            "solo_peak_bytes": solo["peak"],
            "arg_bytes": batch["arg_bytes"],
            "out_bytes": batch["out_bytes"],
            "pad_frac": round(pad, 4) if pad is not None else None,
        })
    return rows


def derive_chip_ceiling(hbm_bytes: Optional[int] = None,
                        spec: Optional[SurfaceSpec] = None
                        ) -> Optional[int]:
    """The largest ladder edge class E such that EVERY edge class up to
    E fits ``hbm_bytes`` on one chip — what ``serve --chip-max-edges
    auto`` routes on, and the startup validator for ``--warm`` specs.

    Routing is by edge class only (serve/pool.py ``_is_huge``), so the
    ceiling must be a *prefix* property: the scan walks the ladder
    ascending and stops at the first edge class whose worst-case
    executable no longer fits (peaks are not monotone in bucket size —
    see :func:`check_peak_bytes` — so a binary search would lie).

    Worst case per edge class: the densest-connected posture
    ``n_class = grid_up(min(2 * e_class, max_nodes))`` at the top batch
    rung, across BOTH batched executables the bucket compiles — the
    rounds block and the batched final detect, whichever models bigger
    (the committed r08 artifact shows detect-batch IS the worst kind at
    the binding bucket, so pricing only the block would admit a bucket
    whose first batched job still OOMs).  A graph declaring far MORE
    isolated nodes than 2e is priced by the jaxpr-peak-bytes gate's
    ``n = max_nodes`` row and governed by ``--max-nodes`` admission —
    an edge ceiling cannot bound node-dominated padding, and pretending
    it could would derive a ceiling of "nothing fits" for every posture
    that admits million-node graphs.  The model prices the spec's
    ensemble width (``n_p`` — serve resolves it from the warm config);
    requests free to choose a much larger ``n_p`` scale past it.
    Returns None when not even the floor bucket fits (the budget cannot
    serve this posture at all).
    """
    spec = spec or SurfaceSpec()
    if hbm_bytes is None:
        hbm_bytes = spec.hbm_bytes
    kinds = _rep_kinds(spec)
    ceiling: Optional[int] = None
    for e_class in edge_classes(spec):
        n_class = grid_up(min(2 * e_class, spec.max_nodes),
                          MIN_NODE_CLASS)
        if not reachable(n_class, e_class, spec):
            continue
        peak = max(_trace_peak(k, n_class, e_class, b, m, spec)["peak"]
                   for k, b, m in kinds)
        if peak > hbm_bytes:
            break
        ceiling = e_class
    return ceiling


# --------------------------------------------------------------------
# Orchestration (what __main__ calls)
# --------------------------------------------------------------------


def evaluate(spec: SurfaceSpec, rules: Optional[Iterable[str]] = None,
             with_table: bool = False, with_ceiling: bool = False
             ) -> Tuple[List[Diagnostic], Dict]:
    """Run the selected footprint rules against one posture; returns
    (diagnostics, footprint report block — see the module docstring
    schema).  ``jaxpr-peak-bytes`` is the only rule that imports jax."""
    selected = set(rules) if rules is not None else set(FOOTPRINT_RULES)
    selected &= {r for r in FOOTPRINT_RULES if spec.wants(r)}
    diags: List[Diagnostic] = []
    block: Dict = {
        "tool": "fcheck-footprint",
        "version": 1,
        "config": {
            "max_nodes": spec.max_nodes, "max_edges": spec.max_edges,
            "max_batch": spec.max_batch, "n_p": spec.n_p,
            "algorithm": spec.algorithm, "hbm_bytes": spec.hbm_bytes,
            "surface_budget": spec.surface_budget,
            "pad_waste_frac": spec.pad_waste_frac,
        },
        "surface_count": surface_count(spec),
        "surface_budget": spec.surface_budget,
        "max_pad_frac": round(max_pad_fraction(spec), 4),
        "chip_ceiling_edges": None,
        "gate": [],
        "buckets": [],
    }
    if "surface-count" in selected:
        diags.extend(check_surface(spec))
    if "padding-waste" in selected:
        diags.extend(check_padding(spec))
    if "jaxpr-peak-bytes" in selected:
        peak_diags, gate_rows = check_peak_bytes(spec)
        diags.extend(peak_diags)
        block["gate"] = gate_rows
        if with_ceiling:
            block["chip_ceiling_edges"] = derive_chip_ceiling(
                spec.hbm_bytes, spec)
        if with_table:
            block["buckets"] = footprint_table(spec)
    return diags, block
