"""Canonical-shape registry of the jitted entry points fcheck audits.

One place that knows how to build *deterministic, small* inputs for every
jitted surface the engine exposes (ops/consensus_ops.py, ops/dense_adj.py,
ops/segment.py, ops/pallas_kernels.py, models/*, engine.py) — the jaxpr
audit (analysis/jaxpr_audit.py) traces each with ``jax.make_jaxpr`` and
the analyzer's CI gate keeps the whole surface traceable.

The canonical graph is structural, not random: a ring over N nodes plus
deterministic chords.  ``make_jaxpr`` only needs shapes/dtypes, but
deterministic *values* keep d_cap/d_hyb/hub_cap derivation (which reads
the degree histogram on the host) stable across runs, so the audited
lowerings never flap.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

import numpy as np

N_NODES = 48
N_P = 4


def canonical_edges(n: int = N_NODES) -> np.ndarray:
    """Ring + two deterministic chord families; simple, connected."""
    ring = np.stack([np.arange(n), (np.arange(n) + 1) % n], axis=1)
    chords3 = np.stack([np.arange(0, n, 3), (np.arange(0, n, 3) + 7) % n],
                       axis=1)
    chords5 = np.stack([np.arange(0, n, 5), (np.arange(0, n, 5) + 13) % n],
                       axis=1)
    return np.concatenate([ring, chords3, chords5], axis=0).astype(np.int64)


def canonical_slab():
    from fastconsensus_tpu.graph import pack_edges

    return pack_edges(canonical_edges(), N_NODES)


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    """``trace()`` returns the ClosedJaxpr of the op at canonical shapes."""

    name: str
    trace: Callable


def _keys(n_p: int = N_P):
    import jax

    from fastconsensus_tpu.utils import prng

    return prng.partition_keys(jax.random.key(0), n_p)


def entry_points() -> List[EntryPoint]:
    """Build the registry.  Imports live inside so ``--no-jaxpr`` lint
    runs never pay a jax import."""
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.engine import (consensus_batch_block,
                                          consensus_round, consensus_tail)
    from fastconsensus_tpu.models.registry import available, get_detector
    from fastconsensus_tpu.ops import consensus_ops as cops
    from fastconsensus_tpu.ops import dense_adj as da
    from fastconsensus_tpu.ops import pallas_kernels as pk
    from fastconsensus_tpu.ops import segment as seg

    slab = canonical_slab()
    n = slab.n_nodes
    cap = slab.capacity
    key = jax.random.key(1)
    labels = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32) % 7, (N_P, n))
    labels1 = jnp.arange(n, dtype=jnp.int32) % 7
    e2 = 2 * cap
    # run-shaped operands for the segment ops
    node = jnp.arange(e2, dtype=jnp.int32) % n
    lab = jnp.arange(e2, dtype=jnp.int32) % 9
    val = jnp.ones((e2,), jnp.float32)
    ok = jnp.arange(e2) % 3 != 0
    k_cand = 16
    cu = jnp.arange(k_cand, dtype=jnp.int32) % n
    cv = (jnp.arange(k_cand, dtype=jnp.int32) * 5 + 1) % n
    cw = jnp.ones((k_cand,), jnp.float32)
    cok = jnp.arange(k_cand) % 2 == 0
    adj = None  # built lazily below (host-side argsort at trace time)

    def mk(fn, *args, **kwargs) -> Callable:
        return lambda: jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)

    eps: List[EntryPoint] = [
        EntryPoint("ops.comembership_counts",
                   mk(cops.comembership_counts, labels, slab.src,
                      slab.dst)),
        EntryPoint("ops.update_weights",
                   mk(lambda s, c: cops.update_weights(s, c, N_P), slab,
                      jnp.ones((cap,), jnp.float32))),
        EntryPoint("ops.threshold_weights",
                   mk(lambda s: cops.threshold_weights(s, 0.2, N_P),
                      slab)),
        EntryPoint("ops.convergence_stats",
                   mk(lambda s: cops.convergence_stats(s, N_P, 0.02),
                      slab)),
        EntryPoint("ops.build_csr", mk(cops.build_csr, slab)),
        # per-trace subkeys via fold_in — the same single-tree discipline
        # the analyzer's key-reuse rule enforces on the engine
        EntryPoint("ops.sample_wedges",
                   mk(lambda k, s: cops.sample_wedges(
                       k, cops.build_csr(s), n, 32),
                      jax.random.fold_in(key, 1), slab)),
        EntryPoint("ops.sample_wedges_scatter",
                   mk(lambda k, s: cops.sample_wedges_scatter(k, s, 32),
                      jax.random.fold_in(key, 2), slab)),
        EntryPoint("ops.insert_edges",
                   mk(cops.insert_edges, slab, cu, cv, cw, cok)),
        EntryPoint("ops.insert_edges_hash",
                   mk(cops.insert_edges_hash, slab, cu, cv, cw, cok)),
        EntryPoint("ops.singleton_candidates",
                   mk(cops.singleton_candidates, slab, slab)),
        EntryPoint("ops.node_label_runs",
                   mk(lambda *a: seg.node_label_runs(*a, n_nodes=n),
                      node, lab, val, ok)),
        EntryPoint("ops.hash_totals",
                   mk(lambda nd, lb, vl, vd: seg.lookup_hash_totals(
                       seg.build_hash_totals(nd, lb, vl, vd, 1 << 12),
                       nd, lb), node, lab, val, ok)),
        EntryPoint("ops.scatter_argmax_label",
                   mk(lambda *a: seg.scatter_argmax_label(*a, n_nodes=n),
                      node, val, lab, ok)),
        EntryPoint("ops.argmax_label_per_node",
                   mk(lambda *a: seg.argmax_label_per_node(*a, n_nodes=n),
                      node, val, lab, ok)),
        EntryPoint("ops.compact_labels",
                   mk(lambda l: seg.compact_labels(l, n), labels1)),
        EntryPoint("ops.build_dense_adjacency",
                   mk(da.build_dense_adjacency, slab)),
        EntryPoint("ops.pallas_row_totals",
                   # interpret=True: audit the CPU-lowerable program (the
                   # TPU lowering is exercised by the kernels' own tests)
                   mk(lambda l, w: pk.row_totals(l, w, interpret=True),
                      jnp.zeros((16, 8), jnp.int32),
                      jnp.ones((16, 8), jnp.float32))),
        EntryPoint("engine.consensus_tail",
                   mk(lambda s, lb, k: consensus_tail(
                       s, lb, k, N_P, 0.2, 0.02, 32), slab, labels,
                      jax.random.fold_in(key, 3))),
    ]
    if slab.d_cap > 0:
        adj = da.build_dense_adjacency(slab)
        eps.append(EntryPoint(
            "ops.row_label_totals",
            mk(lambda a, l: da.row_label_totals(a, l, use_pallas=False),
               adj, labels1)))
    if slab.d_hyb > 0 and slab.hub_cap > 0:
        eps.append(EntryPoint("ops.build_hybrid", mk(da.build_hybrid,
                                                     slab)))

    for i, alg in enumerate(("louvain", "leiden", "lpm")):
        try:
            det = get_detector(alg)
        except (NotImplementedError, ValueError):
            continue
        eps.append(EntryPoint(
            f"models.{alg}", mk(det, slab, _keys())))
        eps.append(EntryPoint(
            f"engine.consensus_round[{alg}]",
            mk(lambda s, k, d=det: consensus_round(
                s, k, detect=d, n_p=N_P, tau=0.2, delta=0.02,
                n_closure=32), slab, jax.random.fold_in(key, 100 + i))))
    # The cross-request batch path (serve coalescing): the vmapped batch
    # block at the canonical B=2, warm mode — the shape every serving
    # rung lowers through, audited once here so the f64/device_put/
    # huge-gather rules cover the batched lowering too.
    import functools

    from fastconsensus_tpu import policy
    from fastconsensus_tpu.graph import stack_slabs

    det_b = get_detector("louvain")
    det_warm = getattr(det_b, "warm_variant", None) or det_b
    slab2 = stack_slabs([slab, slab])
    keys2 = jax.random.wrap_key_data(jnp.stack(
        [jax.random.key_data(jax.random.fold_in(key, 200 + j))
         for j in range(2)]))
    labels2 = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32), (2, N_P, n))
    pst2 = policy.PolicyState(*(jnp.zeros((2,), jnp.int32)
                                for _ in policy.PolicyState._fields))
    batch_fn = jax.vmap(functools.partial(
        consensus_batch_block, detect=det_warm, n_p=N_P, tau=0.2,
        delta=0.02, n_closure=32, block=2, mode="warm", align_frac=1.0,
        sampler="csr"))
    eps.append(EntryPoint(
        "engine.consensus_batch_block[B=2]",
        mk(batch_fn, slab2, keys2, labels2,
           jnp.ones((2,), jnp.int32), jnp.full((2,), 2, jnp.int32),
           jnp.zeros((2,), bool), pst2, jnp.zeros((2,), bool),
           jnp.full((2, 3), -1, jnp.int32))))
    # native cnm/infomap go through pure_callback (host C++) — they are
    # deliberately NOT device programs, so they are not audited here;
    # available() still decides whether their registry entries resolve.
    # The fcobs observability package (obs/) is likewise host-only by
    # design — stdlib spans/counters/exporters with zero jittable
    # surface — so it contributes no entry points; the AST lint still
    # covers it (lint_paths walks the whole package tree).  That stays
    # true for the PR-3 additions: obs/history.py (pure-stdlib bench
    # archaeology), obs/roundlog.py, and obs/device.py — the last one
    # *talks to* jax.profiler (TraceAnnotation wrappers, trace-file
    # merging) but builds no jittable programs, so there is nothing for
    # the jaxpr audit to trace; its host clock reads carry the same
    # sync-in-loop pragma discipline as the tracer.
    # The fcserve serving layer (serve/) is host-only by the same
    # reasoning: stdlib HTTP/threading/queue/cache machinery whose only
    # device contact is DRIVING run_consensus — already audited above
    # through the engine entry points it reuses (serve/bucketer.py even
    # canonicalizes slab statics so requests land on those exact audited
    # shapes).  It registers no entry points; the AST lint walks the
    # package tree (including serve/), and the server's deliberate host
    # syncs carry `# fcheck: ok=sync-in-loop` pragmas with reasons
    # (serve/server.py run_spec's partition readback loop).
    assert available()  # registry import sanity
    return eps


def entry_point_names() -> List[str]:
    return [ep.name for ep in entry_points()]
