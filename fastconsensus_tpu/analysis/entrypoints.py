"""Canonical-shape registry of the jitted entry points fcheck audits.

One place that knows how to build *deterministic, small* inputs for every
jitted surface the engine exposes (ops/consensus_ops.py, ops/dense_adj.py,
ops/segment.py, ops/pallas_kernels.py, models/*, engine.py) — the jaxpr
audit (analysis/jaxpr_audit.py) traces each with ``jax.make_jaxpr`` and
the analyzer's CI gate keeps the whole surface traceable.

The canonical graph is structural, not random: a ring over N nodes plus
deterministic chords.  ``make_jaxpr`` only needs shapes/dtypes, but
deterministic *values* keep d_cap/d_hyb/hub_cap derivation (which reads
the degree histogram on the host) stable across runs, so the audited
lowerings never flap.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional

import numpy as np

N_NODES = 48
N_P = 4


def canonical_edges(n: int = N_NODES) -> np.ndarray:
    """Ring + two deterministic chord families; simple, connected."""
    ring = np.stack([np.arange(n), (np.arange(n) + 1) % n], axis=1)
    chords3 = np.stack([np.arange(0, n, 3), (np.arange(0, n, 3) + 7) % n],
                       axis=1)
    chords5 = np.stack([np.arange(0, n, 5), (np.arange(0, n, 5) + 13) % n],
                       axis=1)
    return np.concatenate([ring, chords3, chords5], axis=0).astype(np.int64)


def canonical_slab():
    from fastconsensus_tpu.graph import pack_edges

    return pack_edges(canonical_edges(), N_NODES)


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    """``trace()`` returns the ClosedJaxpr of the op at canonical shapes."""

    name: str
    trace: Callable


def _keys(n_p: int = N_P):
    import jax

    from fastconsensus_tpu.utils import prng

    return prng.partition_keys(jax.random.key(0), n_p)


def entry_points() -> List[EntryPoint]:
    """Build the registry.  Imports live inside so ``--no-jaxpr`` lint
    runs never pay a jax import."""
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.engine import (consensus_batch_block,
                                          consensus_round, consensus_tail)
    from fastconsensus_tpu.models.registry import available, get_detector
    from fastconsensus_tpu.ops import consensus_ops as cops
    from fastconsensus_tpu.ops import dense_adj as da
    from fastconsensus_tpu.ops import pallas_kernels as pk
    from fastconsensus_tpu.ops import segment as seg

    slab = canonical_slab()
    n = slab.n_nodes
    cap = slab.capacity
    key = jax.random.key(1)
    labels = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32) % 7, (N_P, n))
    labels1 = jnp.arange(n, dtype=jnp.int32) % 7
    e2 = 2 * cap
    # run-shaped operands for the segment ops
    node = jnp.arange(e2, dtype=jnp.int32) % n
    lab = jnp.arange(e2, dtype=jnp.int32) % 9
    val = jnp.ones((e2,), jnp.float32)
    ok = jnp.arange(e2) % 3 != 0
    k_cand = 16
    cu = jnp.arange(k_cand, dtype=jnp.int32) % n
    cv = (jnp.arange(k_cand, dtype=jnp.int32) * 5 + 1) % n
    cw = jnp.ones((k_cand,), jnp.float32)
    cok = jnp.arange(k_cand) % 2 == 0
    adj = None  # built lazily below (host-side argsort at trace time)

    def mk(fn, *args, **kwargs) -> Callable:
        return lambda: jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)

    eps: List[EntryPoint] = [
        EntryPoint("ops.comembership_counts",
                   mk(cops.comembership_counts, labels, slab.src,
                      slab.dst)),
        EntryPoint("ops.update_weights",
                   mk(lambda s, c: cops.update_weights(s, c, N_P), slab,
                      jnp.ones((cap,), jnp.float32))),
        EntryPoint("ops.threshold_weights",
                   mk(lambda s: cops.threshold_weights(s, 0.2, N_P),
                      slab)),
        EntryPoint("ops.convergence_stats",
                   mk(lambda s: cops.convergence_stats(s, N_P, 0.02),
                      slab)),
        EntryPoint("ops.build_csr", mk(cops.build_csr, slab)),
        # per-trace subkeys via fold_in — the same single-tree discipline
        # the analyzer's key-reuse rule enforces on the engine
        EntryPoint("ops.sample_wedges",
                   mk(lambda k, s: cops.sample_wedges(
                       k, cops.build_csr(s), n, 32),
                      jax.random.fold_in(key, 1), slab)),
        EntryPoint("ops.sample_wedges_scatter",
                   mk(lambda k, s: cops.sample_wedges_scatter(k, s, 32),
                      jax.random.fold_in(key, 2), slab)),
        EntryPoint("ops.insert_edges",
                   mk(cops.insert_edges, slab, cu, cv, cw, cok)),
        EntryPoint("ops.insert_edges_hash",
                   mk(cops.insert_edges_hash, slab, cu, cv, cw, cok)),
        EntryPoint("ops.singleton_candidates",
                   mk(cops.singleton_candidates, slab, slab)),
        EntryPoint("ops.node_label_runs",
                   mk(lambda *a: seg.node_label_runs(*a, n_nodes=n),
                      node, lab, val, ok)),
        EntryPoint("ops.hash_totals",
                   mk(lambda nd, lb, vl, vd: seg.lookup_hash_totals(
                       seg.build_hash_totals(nd, lb, vl, vd, 1 << 12),
                       nd, lb), node, lab, val, ok)),
        EntryPoint("ops.scatter_argmax_label",
                   mk(lambda *a: seg.scatter_argmax_label(*a, n_nodes=n),
                      node, val, lab, ok)),
        EntryPoint("ops.argmax_label_per_node",
                   mk(lambda *a: seg.argmax_label_per_node(*a, n_nodes=n),
                      node, val, lab, ok)),
        EntryPoint("ops.compact_labels",
                   mk(lambda l: seg.compact_labels(l, n), labels1)),
        EntryPoint("ops.build_dense_adjacency",
                   mk(da.build_dense_adjacency, slab)),
        EntryPoint("ops.pallas_row_totals",
                   # interpret=True: audit the CPU-lowerable program (the
                   # TPU lowering is exercised by the kernels' own tests)
                   mk(lambda l, w: pk.row_totals(l, w, interpret=True),
                      jnp.zeros((16, 8), jnp.int32),
                      jnp.ones((16, 8), jnp.float32))),
        EntryPoint("engine.consensus_tail",
                   # prev_labels operand included: every production call
                   # site (consensus.py / serve) passes it since fcqual,
                   # so the audited trace is the served executable
                   mk(lambda s, lb, k, pl: consensus_tail(
                       s, lb, k, N_P, 0.2, 0.02, 32, prev_labels=pl),
                      slab, labels, jax.random.fold_in(key, 3), labels)),
    ]
    # fcqual (obs/quality.py): the one obs module WITH a device half —
    # the per-round quality bundle rides inside consensus_tail (already
    # audited above), but its pieces are also independently jittable, so
    # they get their own entry points: the f64/huge-gather/key-reuse
    # rules then cover them even if a future caller lifts one out of the
    # tail.
    from fastconsensus_tpu.obs import quality as obs_quality

    counts_aval = jnp.ones((cap,), jnp.float32)
    eps += [
        EntryPoint("obs.quality.frontier_mask",
                   mk(lambda s: obs_quality.frontier_mask(s, N_P), slab)),
        EntryPoint("obs.quality.member_modularity",
                   mk(obs_quality.member_modularity, slab, labels)),
        EntryPoint("obs.quality.tail_quality",
                   mk(lambda al, c, s, lb, pl: obs_quality.tail_quality(
                       al, c, s, lb, pl, N_P),
                      slab.alive, counts_aval, slab, labels, labels)),
    ]
    if slab.d_cap > 0:
        adj = da.build_dense_adjacency(slab)
        eps.append(EntryPoint(
            "ops.row_label_totals",
            mk(lambda a, l: da.row_label_totals(a, l, use_pallas=False),
               adj, labels1)))
    if slab.d_hyb > 0 and slab.hub_cap > 0:
        eps.append(EntryPoint("ops.build_hybrid", mk(da.build_hybrid,
                                                     slab)))

    for i, alg in enumerate(("louvain", "leiden", "lpm")):
        try:
            det = get_detector(alg)
        # fcheck: ok=swallowed-error (an unavailable detector is
        # a normal posture, not a failure: the audit runs over
        # whatever entry points this build actually has)
        except (NotImplementedError, ValueError):
            continue
        eps.append(EntryPoint(
            f"models.{alg}", mk(det, slab, _keys())))
        eps.append(EntryPoint(
            f"engine.consensus_round[{alg}]",
            mk(lambda s, k, d=det: consensus_round(
                s, k, detect=d, n_p=N_P, tau=0.2, delta=0.02,
                n_closure=32), slab, jax.random.fold_in(key, 100 + i))))
    # The cross-request batch path (serve coalescing): the vmapped batch
    # block at the canonical B=2, warm mode — the shape every serving
    # rung lowers through, audited once here so the f64/device_put/
    # huge-gather rules cover the batched lowering too.
    import functools

    from fastconsensus_tpu import policy
    from fastconsensus_tpu.graph import stack_slabs

    det_b = get_detector("louvain")
    det_warm = getattr(det_b, "warm_variant", None) or det_b
    slab2 = stack_slabs([slab, slab])
    keys2 = jax.random.wrap_key_data(jnp.stack(
        [jax.random.key_data(jax.random.fold_in(key, 200 + j))
         for j in range(2)]))
    labels2 = jnp.broadcast_to(
        jnp.arange(n, dtype=jnp.int32), (2, N_P, n))
    pst2 = policy.PolicyState(*(jnp.zeros((2,), jnp.int32)
                                for _ in policy.PolicyState._fields))
    batch_fn = jax.vmap(functools.partial(
        consensus_batch_block, detect=det_warm, n_p=N_P, tau=0.2,
        delta=0.02, n_closure=32, block=2, mode="warm", align_frac=1.0,
        sampler="csr"))
    eps.append(EntryPoint(
        "engine.consensus_batch_block[B=2]",
        mk(batch_fn, slab2, keys2, labels2,
           jnp.ones((2,), jnp.int32), jnp.full((2,), 2, jnp.int32),
           jnp.zeros((2,), bool), pst2, jnp.zeros((2,), bool),
           jnp.full((2, 3), -1, jnp.int32))))
    # native cnm/infomap go through pure_callback (host C++) — they are
    # deliberately NOT device programs, so they are not audited here;
    # available() still decides whether their registry entries resolve.
    # The fcobs observability package (obs/) is likewise host-only by
    # design — stdlib spans/counters/exporters with zero jittable
    # surface — so it contributes no entry points; the AST lint still
    # covers it (lint_paths walks the whole package tree).  That stays
    # true for the PR-3 additions: obs/history.py (pure-stdlib bench
    # archaeology), obs/roundlog.py, and obs/device.py — the last one
    # *talks to* jax.profiler (TraceAnnotation wrappers, trace-file
    # merging) but builds no jittable programs, so there is nothing for
    # the jaxpr audit to trace; its host clock reads carry the same
    # sync-in-loop pragma discipline as the tracer — and for the fclat
    # addition obs/latency.py: stdlib log2-bucket latency histograms and
    # rate trackers (deliberately jax-free so the report tooling can
    # load them with jax poisoned), pure host arithmetic with zero
    # jittable surface; its histogram/registry fields are lock-guarded,
    # which the concurrency pass (not the jaxpr audit) verifies.  The
    # fcqual addition obs/quality.py is the deliberate EXCEPTION to the
    # obs-is-host-only rule: its device half (the per-round quality
    # bundle) is registered as entry points above, while its host half
    # (summarize_history) stays stdlib-only so bench_report can load
    # history.py with jax poisoned.
    # The fcserve serving layer (serve/) is host-only by the same
    # reasoning: stdlib HTTP/threading/queue/cache machinery whose only
    # device contact is DRIVING run_consensus — already audited above
    # through the engine entry points it reuses (serve/bucketer.py even
    # canonicalizes slab statics so requests land on those exact audited
    # shapes).  It registers no entry points; the AST lint walks the
    # package tree (including serve/), and the server's deliberate host
    # syncs carry `# fcheck: ok=sync-in-loop` pragmas with reasons
    # (serve/server.py run_spec's partition readback loop).  The
    # fcshape addition serve/shaping.py is host-only by the same
    # reasoning taken further: pure stdlib admission-control arithmetic
    # (EDF deadlines, hold-window/fill prediction, Retry-After and shed
    # math over the fclat histograms) that deliberately never imports
    # jax — its batch-ladder mirror is pinned against bucketer by test
    # so the jax-free guarantee survives ladder changes — and whose
    # only mutable state (the estimate cache) is guarded by one leaf
    # lock the concurrency pass verifies without pragmas.  The fcflight
    # additions are host-only by construction: obs/flight.py (stdlib
    # per-thread event rings, one leaf lock per ring), obs/postmortem.py
    # (bundle writer + jax-free render/diff reader — it must load with
    # jax POISONED, the incident-analysis posture), and
    # serve/watchdog.py (stdlib heartbeat table + poll thread; its only
    # inputs are fclat service estimates and a clock).  None builds a
    # jittable program; the AST lint and the concurrency pass cover all
    # three, and the watchdog's device-call timing reads arrive through
    # the fclat registry rather than any device sync of its own.
    # The fcfleet tier (serve/router.py, serve/fleet.py) is host-only
    # by construction and STRICTLY jax-free (pinned by test with jax
    # poisoned): the router is stdlib HTTP + a sha1 consistent-hash
    # ring whose shape classes come from analysis/footprint.grid_up
    # (the jax-free mirror of the bucketer grid, pinned against it by
    # test), and the fleet manager only spawns/polls replica
    # SUBPROCESSES — every device touch happens across an HTTP
    # boundary in a replica already covered above.  Both register no
    # entry points; the AST lint walks them and the concurrency pass
    # verifies the router's single lock discipline (outbound HTTP
    # deliberately outside the lock).
    assert available()  # registry import sanity
    return eps


def entry_point_names() -> List[str]:
    return [ep.name for ep in entry_points()]


# ---------------------------------------------------------------------
# Serving-surface metadata: how to trace any executable of the bucketed
# serving ladder at an ARBITRARY bucket, for the footprint model
# (analysis/footprint.py).  Operands are jax.ShapeDtypeStruct only —
# tracing needs avals, not data, so modeling the n1048576_e4194304
# frontier bucket costs ~1 s and zero device memory.  The slab statics
# are the bucket-canonical ones serve/bucketer.pad_to_bucket pins
# (d_cap/d_hyb/hub_cap = 0, cap_hint = capacity, agg_cap derived), so
# the traced program IS the one a served request of that bucket runs.
# ---------------------------------------------------------------------

SERVING_KINDS = ("rounds", "batch", "tail", "detect", "detect-batch")


def _bucket_slab_struct(n_class: int, e_class: int,
                        batch: Optional[int] = None):
    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu.graph import GraphSlab, derive_agg_sizing

    cap = 2 * e_class + 16           # bucketer.Bucket.capacity
    lead = () if batch is None else (batch,)

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(lead + shape, dtype)

    return GraphSlab(
        src=sds((cap,), jnp.int32), dst=sds((cap,), jnp.int32),
        weight=sds((cap,), jnp.float32), alive=sds((cap,), jnp.bool_),
        n_nodes=n_class, d_cap=0, cap_hint=cap, d_hyb=0, hub_cap=0,
        agg_cap=derive_agg_sizing(cap))


def trace_serving_executable(kind: str, n_class: int, e_class: int,
                             b: int = 1, mode: str = "warm",
                             n_p: int = 20, algorithm: str = "louvain"):
    """ClosedJaxpr of one serving-surface executable at bucket
    (n_class, e_class).

    ``kind`` is one of :data:`SERVING_KINDS`, mirroring the engine's
    lru-cached jit wrappers a served bucket compiles through:
    ``"rounds"`` — the solo fused rounds block
    (engine._jitted_rounds_block; ``mode`` "warm"/"scratch" selects the
    static warm flag); ``"batch"`` — the B-vmapped batch block
    (engine._jitted_rounds_batch; ``mode`` warm/cold/scratch, ``b`` the
    rung); ``"tail"`` / ``"detect"`` / ``"detect-batch"`` — the
    consensus tail and the final whole-ensemble detection (solo and
    B-vmapped).  ``n_closure`` is the bucket-canonical e_class, exactly
    as serve/server.py passes it.
    """
    import functools

    import jax
    import jax.numpy as jnp

    from fastconsensus_tpu import policy
    from fastconsensus_tpu.engine import (consensus_batch_block,
                                          consensus_rounds_block,
                                          consensus_tail)
    from fastconsensus_tpu.models.registry import get_detector

    if kind not in SERVING_KINDS:
        raise ValueError(f"unknown serving kind {kind!r}; one of "
                         f"{SERVING_KINDS}")
    det = get_detector(algorithm)
    det_warm = getattr(det, "warm_variant", None) or det
    det_refresh = getattr(det, "refresh_variant", None) or det
    key_aval = jax.eval_shape(lambda: jax.random.key(0))
    tau, delta, block = 0.2, 0.02, 8
    n, L = n_class, e_class

    def sds(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype)

    if kind == "rounds":
        assert mode in ("warm", "scratch"), mode
        slab = _bucket_slab_struct(n_class, e_class)
        pst = policy.PolicyState(*(sds((), jnp.int32)
                                   for _ in policy.PolicyState._fields))
        fn = functools.partial(
            consensus_rounds_block, detect=det, detect_warm=det_warm,
            detect_refresh=det_refresh, n_p=n_p, tau=tau, delta=delta,
            n_closure=L, block=block, warm=(mode == "warm"),
            align_frac=1.0, sampler="csr")
        return jax.make_jaxpr(fn)(
            slab, sds((), key_aval.dtype), sds((n_p, n), jnp.int32),
            sds((), jnp.int32), sds((), jnp.int32), sds((), jnp.bool_),
            pst, sds((), jnp.bool_), sds((3,), jnp.int32),
            # fcdelta traced inputs: active mask + warm-round-0 flag
            sds((n,), jnp.bool_), sds((), jnp.bool_))
    if kind == "batch":
        assert mode in ("warm", "cold", "scratch"), mode
        d = det_warm if mode == "warm" else det
        slab = _bucket_slab_struct(n_class, e_class, batch=b)
        pst = policy.PolicyState(*(sds((b,), jnp.int32)
                                   for _ in policy.PolicyState._fields))
        fn = jax.vmap(functools.partial(
            consensus_batch_block, detect=d, n_p=n_p, tau=tau,
            delta=delta, n_closure=L, block=block, mode=mode,
            align_frac=1.0 if mode == "warm" else 0.0, sampler="csr"))
        return jax.make_jaxpr(fn)(
            slab, sds((b,), key_aval.dtype),
            sds((b, n_p, n), jnp.int32), sds((b,), jnp.int32),
            sds((b,), jnp.int32), sds((b,), jnp.bool_), pst,
            sds((b,), jnp.bool_), sds((b, 3), jnp.int32))
    if kind == "tail":
        slab = _bucket_slab_struct(n_class, e_class)
        # prev_labels is a real operand of the served tail executable
        # since fcqual (consensus.py always passes it), so the modeled
        # footprint must carry it too
        fn = functools.partial(consensus_tail, n_p=n_p, tau=tau,
                               delta=delta, n_closure=L, sampler="csr")
        return jax.make_jaxpr(
            lambda s, lb, k, pl: fn(s, lb, k, prev_labels=pl))(
            slab, sds((n_p, n), jnp.int32), sds((), key_aval.dtype),
            sds((n_p, n), jnp.int32))
    if kind == "detect":
        slab = _bucket_slab_struct(n_class, e_class)
        return jax.make_jaxpr(
            lambda s, k, i: det_warm(s, k, i))(
            slab, sds((n_p,), key_aval.dtype), sds((n_p, n), jnp.int32))
    # detect-batch: the B-vmapped final re-detection
    slab = _bucket_slab_struct(n_class, e_class, batch=b)
    return jax.make_jaxpr(
        jax.vmap(lambda s, k, i: det_warm(s, k, i)))(
        slab, sds((b, n_p), key_aval.dtype),
        sds((b, n_p, n), jnp.int32))
